"""Live cluster services: the Coordinator wired to real sockets and clocks.

The reference composes Discovery (PeerFinder), MasterService (single-threaded
state-update queue), ClusterApplierService (apply committed states locally)
and the Coordinator around the shared TransportService (ref: node/Node.java
:595-605 DiscoveryModule wiring, cluster/service/MasterService.java:186,
ClusterApplierService.java, discovery/PeerFinder.java:44). This module is
that composition for live nodes; the SAME Coordinator state machine runs
under the deterministic simulation in tests (SURVEY §4 tier 3).

Pieces:
  * ThreadScheduler — wall-clock `schedule_at` for the Coordinator.
  * CoordinationTransport — Coordinator messages over the framed TCP action
    "internal:cluster/coordination/msg", with an address book fed by
    discovery handshakes. Node NAMES are the coordination-layer node ids
    (the bootstrap contract: cluster.initial_master_nodes lists names,
    ref: ClusterBootstrapService.java).
  * PeerFinder — probes seed hosts, learns (name, address) pairs.
  * ClusterFormationService — owns the Coordinator + MasterService semantics:
    leaders compute and publish new states; followers forward updates to the
    leader (TransportMasterNodeAction analog) and apply committed states.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster.coordination import (
    Coordinator, CoordinationError, PublishedState,
)
from elasticsearch_tpu.cluster.gateway import PersistedCoordinationState
from elasticsearch_tpu.common.errors import ElasticsearchTpuError
from elasticsearch_tpu.transport.service import TransportService


class _Handle:
    def __init__(self, timer: threading.Timer):
        self._timer = timer

    def cancel(self) -> None:
        self._timer.cancel()


class ThreadScheduler:
    """schedule_at(delay_ms, fn) on wall clock (threading.Timer)."""

    def __init__(self):
        self._stopped = False

    def schedule_at(self, delay_ms: float, fn: Callable[[], None]) -> _Handle:
        t = threading.Timer(max(delay_ms, 1.0) / 1000.0, self._run, args=(fn,))
        t.daemon = True
        t.start()
        return _Handle(t)

    def _run(self, fn) -> None:
        if not self._stopped:
            try:
                fn()
            except Exception:      # noqa: BLE001 — scheduler must survive
                pass

    def stop(self) -> None:
        self._stopped = True


class CoordinationTransport:
    """Adapter: Coordinator's async send API -> framed TCP round trips.

    Each send runs on a short-lived thread (the coordination fan-out is a
    handful of peers at election/publish cadence, not the data path)."""

    def __init__(self, transport: TransportService, self_name: str):
        self.transport = transport
        self.self_name = self_name
        self.addresses: Dict[str, Tuple[str, int]] = {}
        self._local_handler: Optional[Callable] = None

    def set_address(self, name: str, host: str, port: int) -> None:
        self.addresses[name] = (host, port)

    def register_local(self, handler: Callable) -> None:
        """handler(sender, msg, reply_fn) — the Coordinator's handle_message."""
        self._local_handler = handler
        self.transport.register_request_handler(
            "internal:cluster/coordination/msg", self._on_rpc)

    def _on_rpc(self, req) -> dict:
        out: dict = {}

        def reply(msg: dict) -> None:
            out.update(msg)

        if self._local_handler is not None:
            self._local_handler(req.payload["from"], req.payload["msg"], reply)
        return out

    def send(self, sender: str, to: str, msg: dict,
             on_reply: Callable[[dict], None],
             on_error: Optional[Callable[[], None]] = None) -> None:
        addr = self.addresses.get(to)
        if addr is None:
            if on_error is not None:
                on_error()
            return

        def run():
            try:
                resp = TransportService.send_remote(
                    addr[0], addr[1], "internal:cluster/coordination/msg",
                    {"from": sender, "msg": msg}, source_node=sender,
                    timeout=10.0)
            except Exception:      # noqa: BLE001 — network failure
                if on_error is not None:
                    on_error()
                return
            if resp:               # empty dict = handler chose not to reply
                on_reply(resp)

        threading.Thread(target=run, daemon=True).start()


class PeerFinder:
    """Seed-host probing (ref: discovery/PeerFinder.java:44,
    SettingsBasedSeedHostsProvider.java): periodically handshake every seed
    address, learn (node name, bound address), feed the address book."""

    PROBE_INTERVAL_S = 1.0

    def __init__(self, self_name: str, transport: TransportService,
                 seed_hosts: List[Tuple[str, int]],
                 on_peer: Callable[[str, str, int], None]):
        self.self_name = self_name
        self.transport = transport
        self.seed_hosts = list(seed_hosts)
        self.on_peer = on_peer
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        transport.register_request_handler(
            "internal:discovery/handshake",
            lambda req: {"node": self.self_name,
                         "port": self.transport.bound_port})

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            for host, port in list(self.seed_hosts):
                try:
                    resp = TransportService.send_remote(
                        host, port, "internal:discovery/handshake", {},
                        source_node=self.self_name, timeout=2.0)
                    name = resp.get("node")
                    if name and name != self.self_name:
                        self.on_peer(name, host, port)
                except Exception:  # noqa: BLE001 — seed not up yet
                    pass
            self._stop.wait(self.PROBE_INTERVAL_S)

    def stop(self) -> None:
        self._stop.set()


class ClusterFormationService:
    """Coordinator + master-service + applier for one live node.

    State value on the wire is the serialized ClusterState dict; the
    Coordinator replicates it, this service applies commits locally and
    exposes `submit_state_update` with leader-forwarding semantics."""

    def __init__(self, node_name: str, transport: TransportService,
                 initial_value: dict, voting_config: List[str],
                 data_path: Optional[str],
                 on_committed: Callable[[dict], None]):
        self.node_name = node_name
        self.transport = transport
        self.on_committed = on_committed
        self.scheduler = ThreadScheduler()
        self.coord_transport = CoordinationTransport(transport, node_name)
        self._update_lock = threading.Lock()
        self._persist = PersistedCoordinationState(data_path)
        restored = self._persist.load()
        config = frozenset(voting_config)
        initial = PublishedState(term=0, version=0, value=initial_value,
                                 config=config, last_committed_config=config)
        self.coordinator = Coordinator(
            node_name, initial, self.coord_transport, self.scheduler,
            random.Random(hash(node_name) & 0xFFFF),
            on_commit=self._on_commit,
            persistor=self._persist.store,
            restored=restored,
        )
        self.coord_transport.register_local(self.coordinator.handle_message)
        transport.register_request_handler(
            "internal:cluster/state/update", self._on_forwarded_update)
        self.peer_finder: Optional[PeerFinder] = None

    # ---- lifecycle ----

    def start(self, seed_hosts: List[Tuple[str, int]]) -> None:
        self.peer_finder = PeerFinder(
            self.node_name, self.transport, seed_hosts, self._on_peer)
        self.peer_finder.start()
        self.coordinator.start()

    def stop(self) -> None:
        if self.peer_finder is not None:
            self.peer_finder.stop()
        self.coordinator.stop()
        self.scheduler.stop()

    def _on_peer(self, name: str, host: str, port: int) -> None:
        self.coord_transport.set_address(name, host, port)

    # ---- mode / introspection ----

    @property
    def is_leader(self) -> bool:
        return self.coordinator.mode == "LEADER"

    @property
    def leader_name(self) -> Optional[str]:
        return self.coordinator.leader_id

    def committed_value(self) -> dict:
        return self.coordinator.state.accepted.value

    def await_leader(self, timeout: float = 30.0) -> str:
        """Block until some node is known to lead (local mode or leader id)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.coordinator.mode == "LEADER":
                return self.node_name
            if self.coordinator.mode == "FOLLOWER" and self.coordinator.leader_id:
                return self.coordinator.leader_id
            time.sleep(0.05)
        raise TimeoutError(f"[{self.node_name}] no leader after {timeout}s")

    # ---- state updates (MasterService.submitStateUpdateTask analog) ----

    def submit_state_update(self, updater: Callable[[dict], dict],
                            timeout: float = 30.0) -> dict:
        """Run updater(current_value) -> new_value through consensus.

        On the leader: compute + publish + wait for local commit. On a
        follower: forward to the leader (TransportMasterNodeAction). The
        wire-forwarded form re-runs the updater by name on the leader — so
        remote callers instead send the ALREADY-COMPUTED update via
        `_on_forwarded_update` payloads carrying a value diff description."""
        if self.is_leader:
            with self._update_lock:
                new_value = updater(self.coordinator.state.accepted.value)
                pub_term, pub_version = self.coordinator.publish(new_value)
            self._await_commit(pub_term, pub_version, timeout)
            return self.coordinator.state.accepted.value
        raise NotMasterError(self.leader_name)

    def _await_commit(self, pub_term: int, pub_version: int, timeout: float) -> None:
        """Wait for THE publication identified by (term, version) to commit.

        Waiting for any commit would ack a write that a new leader's
        unrelated commit satisfied (ref: MasterService publication listeners
        are per-publication; a term bump fails in-flight publications)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.coordinator.state
            if st.last_committed_version >= pub_version \
                    and st.accepted.term == pub_term:
                return
            if st.current_term != pub_term:
                raise ElasticsearchTpuError(
                    "cluster state publication failed: term changed "
                    f"({pub_term} -> {st.current_term})")
            time.sleep(0.02)
        raise ElasticsearchTpuError("cluster state publication timed out")

    def _on_forwarded_update(self, req) -> dict:
        """Leader-side handler for follower-forwarded whole-value updates."""
        if not self.is_leader:
            raise NotMasterError(self.leader_name)
        new_value = req.payload["value"]
        with self._update_lock:
            pub_term, pub_version = self.coordinator.publish(new_value)
        self._await_commit(pub_term, pub_version, 30.0)
        return {"ok": True}

    def _on_commit(self, st: PublishedState) -> None:
        try:
            self.on_committed(st.value)
        except Exception:          # noqa: BLE001 — applier must not kill consensus
            pass


class NotMasterError(ElasticsearchTpuError):
    status = 503
    error_type = "not_master_exception"

    def __init__(self, leader: Optional[str]):
        super().__init__(f"not the elected master (leader: {leader})")
        self.leader = leader

"""Durable coordination state: term, vote, accepted cluster state.

The reference persists consensus-critical state in a local Lucene index
(ref: gateway/PersistedClusterStateService.java:111, GatewayMetaState.java:68)
so a restarted node cannot vote twice in one term or forget an accepted-but-
uncommitted publication. Here the same contract is a fsynced JSON document
with atomic replace — the state is small (term, vote, one cluster state) and
write frequency is election/publication cadence, not the data path.
"""

from __future__ import annotations

import json
import os
from typing import Optional


class PersistedCoordinationState:
    """Load/store one node's (current_term, join_vote_term, accepted state,
    last_committed_version)."""

    FILENAME = "_coordination_state.json"

    def __init__(self, data_path: Optional[str]):
        self.path = os.path.join(data_path, self.FILENAME) if data_path else None

    def load(self) -> Optional[dict]:
        if self.path is None or not os.path.exists(self.path):
            return None
        with open(self.path) as f:
            return json.load(f)

    def store(self, doc: dict) -> None:
        if self.path is None:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        # fsync the directory so the rename itself is durable — without it a
        # crash can forget a cast vote, the exact contract this module exists
        # to provide (ref: gateway/PersistedClusterStateService.java fsyncs
        # the state directory after commit)
        dir_fd = os.open(os.path.dirname(self.path), os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

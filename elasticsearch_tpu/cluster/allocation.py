"""Shard allocation: assigning shard copies to data nodes.

Re-designs the reference allocation layer (ref:
cluster/routing/allocation/AllocationService.java — reroute() applies
deciders then the balanced allocator;
allocation/allocator/BalancedShardsAllocator.java;
allocation/decider/SameShardAllocationDecider.java;
allocation/decider/FilterAllocationDecider.java) as a deterministic
functional step over the immutable ClusterState:

  * `reroute` assigns UNASSIGNED copies to the least-loaded eligible data
    node (same-shard exclusion: never two copies of one shard on one node),
    marking them INITIALIZING with a fresh allocation id; it then applies
    the maintenance deciders — draining nodes named by
    `cluster.routing.allocation.exclude._name` and rebalancing shard
    counts onto under-loaded (newly joined) nodes — both bounded by the
    concurrent-relocations cap;
  * a relocation is a linked pair: the source flips STARTED -> RELOCATING
    (still serving) and a target copy INITIALIZING is born with a fresh
    allocation id, each naming the other via `relocating_node_id` (ref:
    ShardRouting.relocate/initializeTargetRelocatingShard). Target
    started commits the move (in-sync swap, source removed); target
    failure cancels it (source reverts to STARTED);
  * `disassociate_dead_nodes` removes a departed node's copies — a lost
    primary is replaced by promoting an in-sync STARTED replica (primary
    term bump, ref: IndexMetadata.primaryTerm fencing) and a replacement
    replica goes back to UNASSIGNED, stamped with a delayed-allocation
    deadline (ref: UnassignedInfo.delayed) so a bounced node can rejoin
    and reclaim its own copies;
  * shard-started / shard-failed transitions mirror the master-side
    routing state machine (ref: ShardStateAction.java).

Pure functions of state -> state: the master applies them inside its
single-threaded update queue, publishes, and node-local appliers react.
An injectable clock keeps the delayed-allocation deadline fake-clock
testable.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import replace
from typing import Callable, Dict, List, Optional, Set, Tuple

from elasticsearch_tpu.cluster.state import ClusterState, ShardRouting

# dynamic cluster settings consulted by the deciders (set through
# PUT /_cluster/settings and replicated inside ClusterState.settings)
EXCLUDE_NAME_SETTING = "cluster.routing.allocation.exclude._name"
CONCURRENT_RELOC_SETTING = \
    "cluster.routing.allocation.cluster_concurrent_rebalance"
DEFAULT_CONCURRENT_RELOCATIONS = 2


def _new_allocation_id() -> str:
    return uuid.uuid4().hex[:20]


def _data_nodes(state: ClusterState) -> List[str]:
    return sorted(nid for nid, n in state.nodes.items() if "data" in n.roles)


def _excluded_nodes(state: ClusterState) -> Set[str]:
    """Nodes being drained: exclude._name matches node name or id."""
    raw = state.settings.get(EXCLUDE_NAME_SETTING, "")
    names = {p.strip() for p in raw.split(",") if p.strip()}
    if not names:
        return set()
    out: Set[str] = set()
    for nid, n in state.nodes.items():
        if nid in names or n.name in names:
            out.add(nid)
    # a drained node may have already left; keep raw names so its copies
    # (if any remain) are still treated as excluded
    return out | names


def _relocation_cap(state: ClusterState) -> int:
    raw = state.settings.get(CONCURRENT_RELOC_SETTING)
    try:
        return int(raw) if raw is not None else DEFAULT_CONCURRENT_RELOCATIONS
    except ValueError:
        return DEFAULT_CONCURRENT_RELOCATIONS


def _relocations_in_flight(state: ClusterState) -> int:
    return sum(1 for shards in state.routing.values()
               for r in shards if r.state == "RELOCATING")


def _shard_counts(state: ClusterState) -> Dict[str, int]:
    """Copies per node for balance decisions. A moving copy counts at its
    target (where it will land), not at its RELOCATING source — so one
    reroute pass doesn't schedule the same shard twice."""
    counts = {nid: 0 for nid in _data_nodes(state)}
    for shards in state.routing.values():
        for r in shards:
            if r.node_id in counts and r.state in ("INITIALIZING", "STARTED"):
                counts[r.node_id] += 1
    return counts


def _occupied_nodes(shards: List[ShardRouting], shard_id: int) -> Set[str]:
    return {r.node_id for r in shards
            if r.shard_id == shard_id and r.node_id is not None
            and r.state != "UNASSIGNED"}


class AllocationService:
    """Master-side routing computations (pure state transitions)."""

    def __init__(self, clock: Optional[Callable[[], int]] = None):
        # wall-clock in ms; injectable for fake-clock delayed-allocation
        # tests (the master update queue owns the real timer)
        self._clock = clock or (lambda: int(time.time() * 1000))

    def now_ms(self) -> int:
        return self._clock()

    def reroute(self, state: ClusterState,
                now_ms: Optional[int] = None) -> ClusterState:
        """Assign unassigned copies, then run the maintenance deciders
        (drain + rebalance) bounded by the concurrent-relocations cap."""
        if now_ms is None:
            now_ms = self._clock()
        state = self._allocate_unassigned(state, now_ms)
        state = self._drain_excluded(state)
        state = self._rebalance(state)
        return state

    # ---- unassigned allocation (balanced allocator) ----

    def _allocate_unassigned(self, state: ClusterState,
                             now_ms: int) -> ClusterState:
        counts = _shard_counts(state)
        if not counts:
            return state
        excluded = _excluded_nodes(state)
        changed = False
        new_routing: Dict[str, List[ShardRouting]] = {}
        for index, shards in state.routing.items():
            remaining = list(shards)
            out: List[ShardRouting] = []
            # node ids already holding a copy, per shard id
            occupied: Dict[int, Set[str]] = {}
            for r in remaining:
                if r.node_id is not None and r.state != "UNASSIGNED":
                    occupied.setdefault(r.shard_id, set()).add(r.node_id)
                    if r.relocating_node_id and r.state == "RELOCATING":
                        occupied[r.shard_id].add(r.relocating_node_id)
            # primaries first: a replica can only initialize against a
            # started primary (ref: ReplicaShardAllocator waits for primary)
            for want_primary in (True, False):
                for r in list(remaining):
                    if r.primary != want_primary or r.state != "UNASSIGNED":
                        continue
                    if not r.primary:
                        primary = next(
                            (p for p in remaining + out
                             if p.shard_id == r.shard_id and p.primary), None)
                        if primary is None or primary.state not in (
                                "STARTED", "RELOCATING"):
                            continue
                    taken = occupied.get(r.shard_id, set())
                    candidates = [n for n in counts
                                  if n not in taken and n not in excluded]
                    delayed = (r.delayed_until_ms is not None
                               and r.delayed_until_ms > now_ms)
                    if delayed:
                        # inside the window the copy only goes back to the
                        # node that last held it — rejoin reuse, no storm
                        candidates = [n for n in candidates
                                      if n == r.last_node_id]
                    if not candidates:
                        continue
                    target = min(candidates, key=lambda n: (counts[n], n))
                    counts[target] += 1
                    occupied.setdefault(r.shard_id, set()).add(target)
                    remaining.remove(r)
                    out.append(ShardRouting(
                        index=index, shard_id=r.shard_id, node_id=target,
                        primary=r.primary, state="INITIALIZING",
                        allocation_id=_new_allocation_id()))
                    changed = True
            out.extend(remaining)
            out.sort(key=lambda r: (r.shard_id, not r.primary, r.allocation_id))
            new_routing[index] = out
        if not changed:
            return state
        st = state
        for index, entries in new_routing.items():
            st = st.with_routing_updates(index, entries)
        return st

    # ---- relocation state machine ----

    def initiate_relocation(self, state: ClusterState, index: str,
                            shard_id: int, allocation_id: str,
                            target_node: str) -> ClusterState:
        """STARTED copy -> RELOCATING source + INITIALIZING target pair
        (ref: RoutingNodes.relocateShard). Returns state unchanged when
        the move is not legal (missing copy, target already holds one,
        target unknown/excluded-same-shard)."""
        shards = list(state.routing.get(index, []))
        source = next((r for r in shards
                       if r.shard_id == shard_id
                       and r.allocation_id == allocation_id
                       and r.state == "STARTED"), None)
        if source is None or source.node_id == target_node:
            return state
        if target_node not in state.nodes:
            return state
        if target_node in _occupied_nodes(shards, shard_id):
            return state
        i = shards.index(source)
        shards[i] = replace(source, state="RELOCATING",
                            relocating_node_id=target_node)
        shards.append(ShardRouting(
            index=index, shard_id=shard_id, node_id=target_node,
            primary=source.primary, state="INITIALIZING",
            allocation_id=_new_allocation_id(),
            relocating_node_id=source.node_id))
        shards.sort(key=lambda r: (r.shard_id, not r.primary, r.allocation_id))
        return state.with_routing_updates(index, shards)

    def _relocation_pair(self, shards: List[ShardRouting],
                         r: ShardRouting) -> Optional[ShardRouting]:
        """The other half of a relocation: source <-> target."""
        if r.relocating_node_id is None:
            return None
        want_state = "INITIALIZING" if r.state == "RELOCATING" \
            else "RELOCATING"
        for other in shards:
            if (other.shard_id == r.shard_id
                    and other.state == want_state
                    and other.node_id == r.relocating_node_id
                    and other.relocating_node_id == r.node_id):
                return other
        return None

    def _cancel_relocation(self, state: ClusterState, index: str,
                           shards: List[ShardRouting],
                           target: ShardRouting) -> Tuple[List[ShardRouting],
                                                          ClusterState]:
        """Target failed/lost: drop it and revert the source to STARTED
        (still serving — nothing was lost)."""
        from elasticsearch_tpu.common.relocation import count
        shards.remove(target)
        source = self._relocation_pair(shards, target)
        if source is not None:
            shards[shards.index(source)] = replace(
                source, state="STARTED", relocating_node_id=None)
        count("cancels")
        return shards, state

    def apply_started_shard(self, state: ClusterState, index: str,
                            shard_id: int, allocation_id: str) -> ClusterState:
        """INITIALIZING -> STARTED; add to the in-sync set (ref:
        ShardStateAction.ShardStartedClusterStateTaskExecutor +
        IndexMetadataUpdater.applyChanges adds the allocation id). A
        relocation target completing commits the move: the source leaves
        routing and the in-sync set in the same update."""
        shards = list(state.routing.get(index, []))
        started = next((r for r in shards
                        if r.shard_id == shard_id
                        and r.allocation_id == allocation_id
                        and r.state == "INITIALIZING"), None)
        if started is None:
            return state
        source = self._relocation_pair(shards, started)
        removed_aid: Optional[str] = None
        if started.relocating_node_id is not None and source is not None:
            from elasticsearch_tpu.common.relocation import count
            shards.remove(source)
            removed_aid = source.allocation_id
            count("moves")
        shards[shards.index(started)] = replace(
            started, state="STARTED", relocating_node_id=None,
            delayed_until_ms=None, last_node_id=None)
        st = state.with_routing_updates(index, shards)
        meta = st.indices[index]
        in_sync = set(meta.in_sync_allocations.get(shard_id, ()))
        in_sync.add(allocation_id)
        if removed_aid is not None:
            in_sync.discard(removed_aid)
        return st.with_index_metadata(
            meta.with_in_sync(shard_id, tuple(sorted(in_sync))))

    def apply_failed_shard(self, state: ClusterState, index: str,
                           shard_id: int, allocation_id: str) -> ClusterState:
        """Remove a failed copy from routing and the in-sync set, then leave
        an UNASSIGNED replacement (ref: ShardStateAction shard-failed).
        Relocation halves fail specially: a failed target cancels the move
        (source reverts, keeps serving, no replacement); a failed source
        takes its half-recovered target down with it."""
        shards = list(state.routing.get(index, []))
        failed = next((r for r in shards
                       if r.shard_id == shard_id
                       and r.allocation_id == allocation_id), None)
        if failed is None:
            return state
        st = state
        if (failed.state == "INITIALIZING"
                and failed.relocating_node_id is not None):
            pair = self._relocation_pair(shards, failed)
            shards, st = self._cancel_relocation(st, index, shards, failed)
            if pair is None:
                # orphaned target (source already gone): plain removal
                shards.append(ShardRouting(
                    index=index, shard_id=shard_id, node_id=None,
                    primary=False, state="UNASSIGNED"))
            st = st.with_routing_updates(index, shards)
            return self.reroute(st)
        removed = [failed]
        shards.remove(failed)
        if failed.state == "RELOCATING":
            target = self._relocation_pair(shards, failed)
            if target is not None:
                shards.remove(target)
                removed.append(target)
        if failed.primary:
            shards, st = _promote_replacement(st, index, shard_id, shards)
        shards.append(ShardRouting(index=index, shard_id=shard_id,
                                   node_id=None, primary=False,
                                   state="UNASSIGNED"))
        st = st.with_routing_updates(index, shards)
        meta = st.indices[index]
        in_sync = set(meta.in_sync_allocations.get(shard_id, ()))
        for r in removed:
            in_sync.discard(r.allocation_id)
        st = st.with_index_metadata(
            meta.with_in_sync(shard_id, tuple(sorted(in_sync))))
        return self.reroute(st)

    def disassociate_dead_nodes(self, state: ClusterState, dead: Set[str],
                                delayed_ms: Optional[int] = None,
                                ) -> ClusterState:
        """Node-left: drop the node, promote replicas for its primaries,
        queue replacements (ref: NodeRemovalClusterStateTaskExecutor ->
        AllocationService.disassociateDeadNodes). Replacement replicas are
        stamped with a delayed-allocation deadline so a bounced node can
        rejoin and recover its own copies; in-flight relocations touching
        a dead node resolve (dead target -> source reverts; dead source ->
        target dies with it, promotion covers the shard)."""
        if delayed_ms is None:
            from elasticsearch_tpu.common.settings import knob
            delayed_ms = knob("ES_TPU_DELAYED_ALLOC_MS")
        now = self._clock()
        st = state
        for nid in dead:
            st = st.without_node(nid)
        for index in list(st.routing):
            shards = list(st.routing[index])
            lost = [r for r in shards if r.node_id in dead]
            if not lost:
                continue
            # resolve relocations first: a dead target is a clean cancel
            # (the source still serves — no copy was lost)
            for r in list(lost):
                if (r.state == "INITIALIZING"
                        and r.relocating_node_id is not None):
                    shards, st = self._cancel_relocation(st, index, shards, r)
                    lost.remove(r)
            for r in lost:
                shards.remove(r)
            meta = st.indices[index]
            for r in lost:
                if r.state == "RELOCATING":
                    # dead source: the target can't finish recovering from
                    # it — drop the half-built target too
                    target = self._relocation_pair(shards, r)
                    if target is not None:
                        shards.remove(target)
                        in_sync = set(meta.in_sync_allocations.get(
                            r.shard_id, ()))
                        in_sync.discard(target.allocation_id)
                        meta = meta.with_in_sync(
                            r.shard_id, tuple(sorted(in_sync)))
                        st = st.with_index_metadata(meta)
                if r.primary:
                    shards, st = _promote_replacement(st, index, r.shard_id,
                                                      shards)
                    meta = st.indices[index]
                shards.append(ShardRouting(
                    index=index, shard_id=r.shard_id, node_id=None,
                    primary=False, state="UNASSIGNED",
                    delayed_until_ms=(now + delayed_ms) if delayed_ms > 0
                    else None,
                    last_node_id=r.node_id))
            for r in lost:
                in_sync = set(meta.in_sync_allocations.get(r.shard_id, ()))
                # the departed copy leaves the in-sync set only if a live
                # copy remains to serve as primary; otherwise keeping it
                # records which copy a future allocate-stale must find
                survivors = [s for s in shards
                             if s.shard_id == r.shard_id and s.serving]
                if survivors:
                    in_sync.discard(r.allocation_id)
                    meta = meta.with_in_sync(r.shard_id, tuple(sorted(in_sync)))
            st = st.with_index_metadata(meta)
            st = st.with_routing_updates(index, shards)
        return self.reroute(st)

    # ---- maintenance deciders ----

    def _drain_excluded(self, state: ClusterState) -> ClusterState:
        """FilterAllocationDecider analog: relocate STARTED copies off
        nodes named by cluster.routing.allocation.exclude._name."""
        excluded = _excluded_nodes(state)
        if not excluded:
            return state
        budget = _relocation_cap(state) - _relocations_in_flight(state)
        if budget <= 0:
            return state
        counts = _shard_counts(state)
        st = state
        for index in sorted(st.routing):
            for r in sorted(st.routing[index],
                            key=lambda r: (r.shard_id, not r.primary)):
                if budget <= 0:
                    return st
                if r.state != "STARTED" or r.node_id not in excluded:
                    continue
                taken = _occupied_nodes(st.routing[index], r.shard_id)
                candidates = [n for n in counts
                              if n not in taken and n not in excluded]
                if not candidates:
                    continue
                target = min(candidates, key=lambda n: (counts[n], n))
                moved = self.initiate_relocation(
                    st, index, r.shard_id, r.allocation_id, target)
                if moved is not st:
                    counts[target] += 1
                    budget -= 1
                    st = moved
        return st

    def _rebalance(self, state: ClusterState) -> ClusterState:
        """Shard-count rebalancer: move copies from the most- to the
        least-loaded data node while the spread is >= 2 (a newly joined
        empty node attracts copies without thrashing a balanced pair)."""
        budget = _relocation_cap(state) - _relocations_in_flight(state)
        st = state
        excluded = _excluded_nodes(st)
        while budget > 0:
            counts = _shard_counts(st)
            eligible = {n: c for n, c in counts.items() if n not in excluded}
            if len(eligible) < 2:
                return st
            lo = min(eligible, key=lambda n: (eligible[n], n))
            hi = max(eligible, key=lambda n: (eligible[n], n))
            if eligible[hi] - eligible[lo] < 2:
                return st
            moved_any = False
            for index in sorted(st.routing):
                for r in sorted(st.routing[index],
                                key=lambda r: (r.shard_id, not r.primary)):
                    if (r.state != "STARTED" or r.node_id != hi
                            or lo in _occupied_nodes(st.routing[index],
                                                     r.shard_id)):
                        continue
                    moved = self.initiate_relocation(
                        st, index, r.shard_id, r.allocation_id, lo)
                    if moved is not st:
                        st = moved
                        budget -= 1
                        moved_any = True
                        break
                if moved_any:
                    break
            if not moved_any:
                return st
        return st


def _promote_replacement(state: ClusterState, index: str, shard_id: int,
                         shards: List[ShardRouting]):
    """Pick the in-sync STARTED replica to promote to primary; bump the
    shard's primary term (ref: RoutingNodes.promoteActiveReplicaShardToPrimary
    + IndexMetadataUpdater primary-term increment)."""
    meta = state.indices[index]
    in_sync = set(meta.in_sync_allocations.get(shard_id, ()))
    candidates = [r for r in shards
                  if r.shard_id == shard_id and not r.primary
                  and r.state == "STARTED" and r.allocation_id in in_sync]
    if not candidates:
        return shards, state     # red shard: no safe copy to promote
    chosen = sorted(candidates, key=lambda r: r.allocation_id)[0]
    shards[shards.index(chosen)] = ShardRouting(
        index=index, shard_id=shard_id, node_id=chosen.node_id,
        primary=True, state="STARTED", allocation_id=chosen.allocation_id)
    state = state.with_index_metadata(meta.with_primary_term_bump(shard_id))
    return shards, state

"""Shard allocation: assigning shard copies to data nodes.

Re-designs the reference allocation layer (ref:
cluster/routing/allocation/AllocationService.java — reroute() applies
deciders then the balanced allocator;
allocation/allocator/BalancedShardsAllocator.java;
allocation/decider/SameShardAllocationDecider.java) as a deterministic
functional step over the immutable ClusterState:

  * `reroute` assigns UNASSIGNED copies to the least-loaded eligible data
    node (same-shard exclusion: never two copies of one shard on one node),
    marking them INITIALIZING with a fresh allocation id;
  * `disassociate_dead_nodes` removes a departed node's copies — a lost
    primary is replaced by promoting an in-sync STARTED replica (primary
    term bump, ref: IndexMetadata.primaryTerm fencing) and a replacement
    replica goes back to UNASSIGNED;
  * shard-started / shard-failed transitions mirror the master-side
    routing state machine (ref: ShardStateAction.java).

Pure functions of state -> state: the master applies them inside its
single-threaded update queue, publishes, and node-local appliers react.
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional, Set

from elasticsearch_tpu.cluster.state import ClusterState, ShardRouting


def _new_allocation_id() -> str:
    return uuid.uuid4().hex[:20]


def _data_nodes(state: ClusterState) -> List[str]:
    return sorted(nid for nid, n in state.nodes.items() if "data" in n.roles)


def _shard_counts(state: ClusterState) -> Dict[str, int]:
    counts = {nid: 0 for nid in _data_nodes(state)}
    for shards in state.routing.values():
        for r in shards:
            if r.node_id in counts and r.state in ("INITIALIZING", "STARTED"):
                counts[r.node_id] += 1
    return counts


class AllocationService:
    """Master-side routing computations (pure state transitions)."""

    def reroute(self, state: ClusterState) -> ClusterState:
        """Assign unassigned copies; balanced by shard count per node."""
        counts = _shard_counts(state)
        if not counts:
            return state
        changed = False
        new_routing: Dict[str, List[ShardRouting]] = {}
        for index, shards in state.routing.items():
            remaining = list(shards)
            out: List[ShardRouting] = []
            # node ids already holding a copy, per shard id
            occupied: Dict[int, Set[str]] = {}
            for r in remaining:
                if r.node_id is not None and r.state != "UNASSIGNED":
                    occupied.setdefault(r.shard_id, set()).add(r.node_id)
            # primaries first: a replica can only initialize against a
            # started primary (ref: ReplicaShardAllocator waits for primary)
            for want_primary in (True, False):
                for r in list(remaining):
                    if r.primary != want_primary or r.state != "UNASSIGNED":
                        continue
                    if not r.primary:
                        primary = next(
                            (p for p in remaining + out
                             if p.shard_id == r.shard_id and p.primary), None)
                        if primary is None or primary.state != "STARTED":
                            continue
                    taken = occupied.get(r.shard_id, set())
                    candidates = [n for n in counts if n not in taken]
                    if not candidates:
                        continue
                    target = min(candidates, key=lambda n: (counts[n], n))
                    counts[target] += 1
                    occupied.setdefault(r.shard_id, set()).add(target)
                    remaining.remove(r)
                    out.append(ShardRouting(
                        index=index, shard_id=r.shard_id, node_id=target,
                        primary=r.primary, state="INITIALIZING",
                        allocation_id=_new_allocation_id()))
                    changed = True
            out.extend(remaining)
            out.sort(key=lambda r: (r.shard_id, not r.primary, r.allocation_id))
            new_routing[index] = out
        if not changed:
            return state
        st = state
        for index, entries in new_routing.items():
            st = st.with_routing_updates(index, entries)
        return st

    def apply_started_shard(self, state: ClusterState, index: str,
                            shard_id: int, allocation_id: str) -> ClusterState:
        """INITIALIZING -> STARTED; add to the in-sync set (ref:
        ShardStateAction.ShardStartedClusterStateTaskExecutor +
        IndexMetadataUpdater.applyChanges adds the allocation id)."""
        shards = list(state.routing.get(index, []))
        changed = False
        for i, r in enumerate(shards):
            if (r.shard_id == shard_id and r.allocation_id == allocation_id
                    and r.state == "INITIALIZING"):
                shards[i] = ShardRouting(
                    index=index, shard_id=shard_id, node_id=r.node_id,
                    primary=r.primary, state="STARTED",
                    allocation_id=allocation_id)
                changed = True
        if not changed:
            return state
        st = state.with_routing_updates(index, shards)
        meta = st.indices[index]
        in_sync = set(meta.in_sync_allocations.get(shard_id, ()))
        in_sync.add(allocation_id)
        return st.with_index_metadata(
            meta.with_in_sync(shard_id, tuple(sorted(in_sync))))

    def apply_failed_shard(self, state: ClusterState, index: str,
                           shard_id: int, allocation_id: str) -> ClusterState:
        """Remove a failed copy from routing and the in-sync set, then leave
        an UNASSIGNED replacement (ref: ShardStateAction shard-failed)."""
        shards = list(state.routing.get(index, []))
        failed = next((r for r in shards
                       if r.shard_id == shard_id
                       and r.allocation_id == allocation_id), None)
        if failed is None:
            return state
        shards.remove(failed)
        st = state
        if failed.primary:
            shards, st = _promote_replacement(st, index, shard_id, shards)
        shards.append(ShardRouting(index=index, shard_id=shard_id,
                                   node_id=None, primary=False,
                                   state="UNASSIGNED"))
        st = st.with_routing_updates(index, shards)
        meta = st.indices[index]
        in_sync = set(meta.in_sync_allocations.get(shard_id, ()))
        in_sync.discard(allocation_id)
        st = st.with_index_metadata(
            meta.with_in_sync(shard_id, tuple(sorted(in_sync))))
        return self.reroute(st)

    def disassociate_dead_nodes(self, state: ClusterState,
                                dead: Set[str]) -> ClusterState:
        """Node-left: drop the node, promote replicas for its primaries,
        queue replacements (ref: NodeRemovalClusterStateTaskExecutor ->
        AllocationService.disassociateDeadNodes)."""
        st = state
        for nid in dead:
            st = st.without_node(nid)
        for index in list(st.routing):
            shards = list(st.routing[index])
            lost = [r for r in shards if r.node_id in dead]
            if not lost:
                continue
            for r in lost:
                shards.remove(r)
            for r in lost:
                if r.primary:
                    shards, st = _promote_replacement(st, index, r.shard_id,
                                                      shards)
                shards.append(ShardRouting(index=index, shard_id=r.shard_id,
                                           node_id=None, primary=False,
                                           state="UNASSIGNED"))
            meta = st.indices[index]
            for r in lost:
                in_sync = set(meta.in_sync_allocations.get(r.shard_id, ()))
                # the departed copy leaves the in-sync set only if a live
                # copy remains to serve as primary; otherwise keeping it
                # records which copy a future allocate-stale must find
                survivors = [s for s in shards
                             if s.shard_id == r.shard_id
                             and s.state == "STARTED"]
                if survivors:
                    in_sync.discard(r.allocation_id)
                    meta = meta.with_in_sync(r.shard_id, tuple(sorted(in_sync)))
            st = st.with_index_metadata(meta)
            st = st.with_routing_updates(index, shards)
        return self.reroute(st)


def _promote_replacement(state: ClusterState, index: str, shard_id: int,
                         shards: List[ShardRouting]):
    """Pick the in-sync STARTED replica to promote to primary; bump the
    shard's primary term (ref: RoutingNodes.promoteActiveReplicaShardToPrimary
    + IndexMetadataUpdater primary-term increment)."""
    meta = state.indices[index]
    in_sync = set(meta.in_sync_allocations.get(shard_id, ()))
    candidates = [r for r in shards
                  if r.shard_id == shard_id and not r.primary
                  and r.state == "STARTED" and r.allocation_id in in_sync]
    if not candidates:
        return shards, state     # red shard: no safe copy to promote
    chosen = sorted(candidates, key=lambda r: r.allocation_id)[0]
    shards[shards.index(chosen)] = ShardRouting(
        index=index, shard_id=shard_id, node_id=chosen.node_id,
        primary=True, state="STARTED", allocation_id=chosen.allocation_id)
    state = state.with_index_metadata(meta.with_primary_term_bump(shard_id))
    return shards, state

"""Cluster coordination: Raft-like consensus with voting configurations.

Re-designs the reference coordination layer (ref:
cluster/coordination/Coordinator.java:87, CoordinationState.java,
PreVoteCollector.java, ElectionSchedulerFactory.java, Publication.java,
FollowersChecker.java, LeaderChecker.java) as a transport-agnostic state
machine driven by an injected clock/scheduler, so the SAME code runs in
production (real transport + wall clock) and in the deterministic
simulation harness (virtual time + disruptable transport).

Safety core (CoordinationState):
  * terms: a node votes at most once per term; a candidate needs a quorum
    of joins in BOTH the last-committed and the last-accepted voting
    configurations (joint consensus for reconfiguration).
  * publish: two-phase — leader sends the new state; a quorum of accepts in
    both configs commits it; commits broadcast; followers apply on commit.
  * a join carries the voter's last accepted (term, version) and is only
    granted to candidates whose accepted state is at least as fresh.

Liveness: randomized election scheduling with backoff, pre-vote rounds to
avoid disrupting a live leader, leader/follower fault checkers.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Set


# --------------------------------------------------------------------------
# value + vote model
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class PublishedState:
    """The replicated value: an opaque payload + consensus bookkeeping."""

    term: int
    version: int
    value: Any
    config: frozenset            # committed voting configuration (node ids)
    last_committed_config: frozenset

    def quorum(self, votes: Set[str]) -> bool:
        """Joint consensus: quorum in BOTH configs (ref: VotingConfiguration
        + Reconfigurator joint requirement)."""
        return _has_quorum(votes, self.config) and _has_quorum(votes, self.last_committed_config)


def _has_quorum(votes: Set[str], config: frozenset) -> bool:
    return len(votes & config) * 2 > len(config)


@dataclass
class Join:
    voter: str
    target: str
    term: int
    last_accepted_term: int
    last_accepted_version: int


# --------------------------------------------------------------------------
# CoordinationState — the pure safety state machine
# --------------------------------------------------------------------------


class CoordinationError(Exception):
    pass


class CoordinationState:
    """Persisted consensus state of one node (ref: CoordinationState.java).

    `persistor(doc)` — when given — is invoked synchronously BEFORE any
    safety-critical transition returns (vote cast, publish accepted, commit):
    a restarted node must never vote twice in one term or forget an accepted
    publication (ref: gateway/GatewayMetaState.java persisted-state wrapper).
    """

    def __init__(self, node_id: str, initial: PublishedState, persistor=None):
        self.node_id = node_id
        self.current_term = initial.term
        self.accepted = initial               # last accepted (maybe uncommitted)
        self.last_committed_version = initial.version
        # the config of the last state that actually COMMITTED — the joint-
        # consensus base for any new publication. Chaining it off uncommitted
        # accepted states would let an isolated leader shrink its own quorum.
        self.committed_config: frozenset = initial.config
        self.join_vote_term = 0               # term we voted in
        self.election_won = False
        self.join_votes: Set[str] = set()
        self.publish_votes: Set[str] = set()
        self.persistor = persistor

    # ---- durability ----

    def to_doc(self) -> dict:
        return {"current_term": self.current_term,
                "join_vote_term": self.join_vote_term,
                "accepted": _state_to_wire(self.accepted),
                "last_committed_version": self.last_committed_version,
                "committed_config": sorted(self.committed_config)}

    @classmethod
    def from_doc(cls, node_id: str, doc: dict, persistor=None) -> "CoordinationState":
        st = cls(node_id, _state_from_wire(doc["accepted"]), persistor)
        st.current_term = doc["current_term"]
        st.join_vote_term = doc["join_vote_term"]
        st.last_committed_version = doc["last_committed_version"]
        st.committed_config = frozenset(doc["committed_config"])
        return st

    def _persist(self) -> None:
        if self.persistor is not None:
            self.persistor(self.to_doc())

    # ---- term/vote handling ----

    def handle_start_join(self, target: str, term: int) -> Join:
        """A candidate asked us to join its term: bump term, grant the vote."""
        if term <= self.current_term:
            raise CoordinationError(
                f"incoming term {term} not greater than {self.current_term}")
        self.current_term = term
        self.join_vote_term = term
        self.election_won = False
        self.join_votes = set()
        self.publish_votes = set()
        self._persist()     # the vote must be durable before it is cast
        return Join(voter=self.node_id, target=target, term=term,
                    last_accepted_term=self.accepted.term,
                    last_accepted_version=self.accepted.version)

    def handle_join(self, join: Join) -> bool:
        """Candidate side: absorb a join; True when the election is won."""
        if join.term != self.current_term:
            raise CoordinationError(
                f"join term {join.term} != current {self.current_term}")
        # the voter must not know a fresher accepted state than ours
        if (join.last_accepted_term, join.last_accepted_version) > (
                self.accepted.term, self.accepted.version):
            raise CoordinationError("joiner has fresher state")
        self.join_votes.add(join.voter)
        won = self.accepted.quorum(self.join_votes)
        if won and not self.election_won:
            self.election_won = True
        return self.election_won

    # ---- publication (leader) ----

    def handle_client_value(self, value: Any,
                            new_config: Optional[frozenset] = None) -> PublishedState:
        if not self.election_won:
            raise CoordinationError("not leader")
        st = PublishedState(
            term=self.current_term,
            version=self.accepted.version + 1,
            value=value,
            config=new_config if new_config is not None else self.accepted.config,
            last_committed_config=self.committed_config,
        )
        self.publish_votes = set()
        self.accepted = st
        self._persist()
        return st

    # ---- publication (any node) ----

    def handle_publish_request(self, st: PublishedState) -> "PublishResponse":
        if st.term != self.current_term:
            raise CoordinationError(
                f"publish term {st.term} != current {self.current_term}")
        if st.term == self.accepted.term and st.version <= self.accepted.version:
            raise CoordinationError(
                f"publish version {st.version} not newer than accepted "
                f"{self.accepted.version}")
        self.accepted = st
        self._persist()     # accepted state must survive restart before ack
        return PublishResponse(node_id=self.node_id, term=st.term, version=st.version)

    def handle_publish_response(self, resp: "PublishResponse") -> bool:
        """Leader side: True when this publication reached commit quorum."""
        if resp.term != self.current_term:
            raise CoordinationError("stale publish response")
        if resp.version != self.accepted.version:
            return False
        self.publish_votes.add(resp.node_id)
        return self.accepted.quorum(self.publish_votes)

    def handle_commit(self, term: int, version: int) -> PublishedState:
        if term != self.accepted.term or version != self.accepted.version:
            raise CoordinationError(
                f"commit for {term}/{version} but accepted is "
                f"{self.accepted.term}/{self.accepted.version}")
        self.last_committed_version = version
        self.committed_config = self.accepted.config
        committed = replace(self.accepted, last_committed_config=self.accepted.config)
        self.accepted = committed
        self._persist()
        return committed


@dataclass
class PublishResponse:
    node_id: str
    term: int
    version: int


# --------------------------------------------------------------------------
# Coordinator — modes, elections, fault detection
# --------------------------------------------------------------------------

CANDIDATE, LEADER, FOLLOWER = "CANDIDATE", "LEADER", "FOLLOWER"


class Coordinator:
    """One node's coordination behavior (ref: Coordinator.java modes).

    transport: send(to_node_id, message: dict, on_reply, on_error)
    scheduler: schedule_at(delay_ms, fn) -> handle with .cancel()
    on_commit: callback(PublishedState) when a state commits locally.
    """

    ELECTION_INITIAL_MS = 100
    ELECTION_BACKOFF_MS = 100
    ELECTION_MAX_MS = 10_000
    ELECTION_DURATION_MS = 300
    FOLLOWER_CHECK_INTERVAL_MS = 1000
    FOLLOWER_CHECK_RETRIES = 3
    LEADER_CHECK_INTERVAL_MS = 1000
    LEADER_CHECK_RETRIES = 3
    PUBLISH_TIMEOUT_MS = 30_000

    def __init__(self, node_id: str, initial: PublishedState, transport,
                 scheduler, rng, on_commit: Callable[[PublishedState], None],
                 persistor=None, restored: Optional[dict] = None):
        self.node_id = node_id
        if restored is not None:
            self.state = CoordinationState.from_doc(node_id, restored, persistor)
        else:
            self.state = CoordinationState(node_id, initial, persistor)
        self.transport = transport
        self.scheduler = scheduler
        self.rng = rng
        self.on_commit = on_commit
        self.mode = CANDIDATE
        self.leader_id: Optional[str] = None
        self.last_known_peers: Set[str] = set(initial.config)
        self._election_attempt = 0
        self._election_handle = None
        self._checker_handle = None
        self._follower_failures: Dict[str, int] = {}
        self.stopped = False

    # ---- lifecycle ----

    def start(self) -> None:
        self._become_candidate("startup")

    def stop(self) -> None:
        self.stopped = True
        self._cancel_timers()

    def _cancel_timers(self) -> None:
        for h in (self._election_handle, self._checker_handle):
            if h is not None:
                h.cancel()
        self._election_handle = self._checker_handle = None

    # ---- mode transitions ----

    def _become_candidate(self, reason: str) -> None:
        self.mode = CANDIDATE
        self.leader_id = None
        self.state.election_won = False
        self._cancel_timers()
        self._schedule_election()

    def _become_leader(self) -> None:
        self.mode = LEADER
        self.leader_id = self.node_id
        self._cancel_timers()
        self._follower_failures = {}
        self._schedule_follower_checks()
        # republish the current state so the new term commits a state
        # (ref: Coordinator.becomeLeader -> publishes a no-op join state)
        self.publish(self.state.accepted.value)

    def _become_follower(self, leader_id: str) -> None:
        if self.mode == FOLLOWER and self.leader_id == leader_id:
            return
        self.mode = FOLLOWER
        self.leader_id = leader_id
        self._cancel_timers()
        self._schedule_leader_checks()

    # ---- elections ----

    def _schedule_election(self) -> None:
        if self.stopped:
            return
        self._election_attempt += 1
        backoff = min(self.ELECTION_MAX_MS,
                      self.ELECTION_INITIAL_MS
                      + self.ELECTION_BACKOFF_MS * self._election_attempt)
        delay = self.rng.random() * backoff + 10
        self._election_handle = self.scheduler.schedule_at(delay, self._start_prevote)

    def _start_prevote(self) -> None:
        if self.stopped or self.mode != CANDIDATE:
            return
        # pre-vote round (ref: PreVoteCollector): ask peers whether they'd
        # vote for us — avoids term inflation when partitioned
        votes: Set[str] = {self.node_id}
        acc = self.state.accepted
        responded = {"won": False}

        def on_reply(peer, reply):
            leader_hint = reply.get("leader")
            if leader_hint and leader_hint != self.node_id and self.mode == CANDIDATE:
                # a live leader exists: ask it to take us (back) in rather
                # than disrupting it with an election
                self.transport.send(self.node_id, leader_hint,
                                    {"type": "request_rejoin"}, lambda r: None)
            if reply.get("grant") and not responded["won"]:
                votes.add(peer)
                if acc.quorum(votes):
                    responded["won"] = True
                    self._start_election()

        for peer in self._peers():
            self.transport.send(
                self.node_id, peer,
                {"type": "pre_vote", "term": self.state.current_term,
                 "last_accepted_term": acc.term, "last_accepted_version": acc.version},
                lambda reply, peer=peer: on_reply(peer, reply))
        if acc.quorum(votes):          # single-node cluster
            self._start_election()
        if self.mode == CANDIDATE:     # retry with backoff until a leader exists
            self._schedule_election()

    def _start_election(self) -> None:
        if self.stopped or self.mode != CANDIDATE:
            return
        term = self.state.current_term + 1
        try:
            own_join = self.state.handle_start_join(self.node_id, term)
            self._on_join(own_join)
        except CoordinationError:
            return
        for peer in self._peers():
            self.transport.send(
                self.node_id, peer,
                {"type": "start_join", "term": term},
                self._on_join_reply)

    def _on_join_reply(self, reply: dict) -> None:
        if self.stopped or reply.get("type") != "join":
            return
        join = Join(**{k: reply[k] for k in
                       ("voter", "target", "term", "last_accepted_term",
                        "last_accepted_version")})
        self._on_join(join)

    def _on_join(self, join: Join) -> None:
        if join.term != self.state.current_term or join.target != self.node_id:
            return
        if self.mode == LEADER:
            self.state.join_votes.add(join.voter)
            return
        try:
            won = self.state.handle_join(join)
        except CoordinationError:
            return
        if won and self.mode == CANDIDATE:
            self._become_leader()

    # ---- inbound messages ----

    def handle_message(self, sender: str, msg: dict, reply: Callable[[dict], None]) -> None:
        if self.stopped:
            return
        t = msg["type"]
        if t == "pre_vote":
            acc = self.state.accepted
            grant = (msg["term"] >= self.state.current_term
                     and (msg["last_accepted_term"], msg["last_accepted_version"])
                     >= (acc.term, acc.version)
                     and (self.mode != FOLLOWER or self.leader_id is None))
            # leader hint lets an ousted/rejoining candidate find the live
            # leader and ask to be re-added (ref: JoinHelper discovery)
            leader_hint = self.leader_id if self.mode in (FOLLOWER, LEADER) else None
            if self.mode == LEADER:
                leader_hint = self.node_id
            reply({"type": "pre_vote_response", "grant": grant,
                   "leader": leader_hint})
        elif t == "request_rejoin":
            if self.mode == LEADER:
                self.on_node_joined(sender)
        elif t == "start_join":
            try:
                join = self.state.handle_start_join(sender, msg["term"])
            except CoordinationError:
                return
            if self.mode != CANDIDATE:
                self._become_candidate("saw higher term")
            reply({"type": "join", "voter": join.voter, "target": sender,
                   "term": join.term,
                   "last_accepted_term": join.last_accepted_term,
                   "last_accepted_version": join.last_accepted_version})
        elif t == "publish":
            st = _state_from_wire(msg["state"])
            if st.term > self.state.current_term:
                # implicit join of the newer term
                try:
                    self.state.handle_start_join(sender, st.term)
                except CoordinationError:
                    return
            try:
                resp = self.state.handle_publish_request(st)
            except CoordinationError:
                # idempotent re-ack when the leader re-sends the state we
                # already accepted (catch-up of a follower that accepted a
                # version but missed its commit — without this the commit is
                # never re-sent and the follower lags forever)
                if (st.term == self.state.current_term
                        and st.term == self.state.accepted.term
                        and st.version == self.state.accepted.version):
                    self._become_follower(sender)
                    reply({"type": "publish_response", "node_id": self.node_id,
                           "term": st.term, "version": st.version})
                return
            self._become_follower(sender)
            reply({"type": "publish_response", "node_id": resp.node_id,
                   "term": resp.term, "version": resp.version})
        elif t == "commit":
            try:
                committed = self.state.handle_commit(msg["term"], msg["version"])
            except CoordinationError:
                return
            self.last_known_peers = set(committed.config)
            self.on_commit(committed)
            reply({"type": "commit_response"})
        elif t == "follower_check":
            if msg["term"] == self.state.current_term and self.mode == FOLLOWER:
                reply({"type": "follower_check_response", "ok": True,
                       "last_committed_version": self.state.last_committed_version,
                       "last_committed_term": self.state.accepted.term})
            elif msg["term"] >= self.state.current_term:
                # not yet following this leader: adopt its term first, else
                # our stale-term leader_checks would bounce us straight back
                # to candidate (ref: Coordinator.onFollowerCheckRequest calls
                # ensureTermAtLeast before becomeFollower)
                if msg["term"] > self.state.current_term:
                    try:
                        self.state.handle_start_join(sender, msg["term"])
                    except CoordinationError:
                        pass
                self._become_follower(sender)
                reply({"type": "follower_check_response", "ok": True,
                       "last_committed_version": self.state.last_committed_version,
                       "last_committed_term": self.state.accepted.term})
            else:
                reply({"type": "follower_check_response", "ok": False})
        elif t == "leader_check":
            ok = self.mode == LEADER and msg["term"] == self.state.current_term
            reply({"type": "leader_check_response", "ok": ok})

    # ---- publication ----

    def publish(self, value: Any, new_config: Optional[frozenset] = None) -> tuple:
        """Leader: replicate a new state (ref: Coordinator.publish).

        Returns the publication's (term, version) so callers can await THIS
        publication's commit rather than any concurrent commit."""
        if self.mode != LEADER:
            raise CoordinationError("not the leader")
        st = self.state.handle_client_value(value, new_config)
        wire = _state_to_wire(st)
        committed = {"done": False}

        def on_publish_reply(reply: dict) -> None:
            if self.stopped or reply.get("type") != "publish_response":
                return
            resp = PublishResponse(reply["node_id"], reply["term"], reply["version"])
            try:
                ready = self.state.handle_publish_response(resp)
            except CoordinationError:
                return
            if ready and not committed["done"]:
                committed["done"] = True
                self._broadcast_commit(st)

        # handle_client_value already accepted st locally; record our own vote
        try:
            own = PublishResponse(self.node_id, st.term, st.version)
            ready = self.state.handle_publish_response(own)
        except CoordinationError:
            return (st.term, st.version)
        for peer in self._peers(st):
            self.transport.send(self.node_id, peer,
                                {"type": "publish", "state": wire},
                                on_publish_reply)
        if ready and not committed["done"]:
            committed["done"] = True
            self._broadcast_commit(st)

        def on_timeout():
            # a leader that cannot commit has lost its quorum: step down
            # (ref: Publication timeout -> Coordinator.becomeCandidate)
            if not committed["done"] and not self.stopped and self.mode == LEADER \
                    and self.state.accepted.version == st.version \
                    and self.state.current_term == st.term:
                self._become_candidate("publication timed out")

        self.scheduler.schedule_at(self.PUBLISH_TIMEOUT_MS, on_timeout)
        return (st.term, st.version)

    def _broadcast_commit(self, st: PublishedState) -> None:
        try:
            committed = self.state.handle_commit(st.term, st.version)
        except CoordinationError:
            return
        self.last_known_peers = set(committed.config)
        self.on_commit(committed)
        for peer in self._peers(st):
            self.transport.send(self.node_id, peer,
                                {"type": "commit", "term": st.term,
                                 "version": st.version}, lambda r: None)

    # ---- fault detection ----

    def _schedule_follower_checks(self) -> None:
        if self.stopped or self.mode != LEADER:
            return

        def tick():
            if self.stopped or self.mode != LEADER:
                return
            for peer in self._peers():
                self._check_follower(peer)
            self._schedule_follower_checks()

        self._checker_handle = self.scheduler.schedule_at(
            self.FOLLOWER_CHECK_INTERVAL_MS, tick)

    def _check_follower(self, peer: str) -> None:
        def on_reply(reply):
            if reply.get("ok"):
                self._follower_failures[peer] = 0
                # lag detection: a healed/rejoined follower that missed
                # publishes gets the current committed state pushed directly
                # (ref: LagDetector + full-state PublicationTransportHandler)
                if reply.get("last_committed_version", 1 << 62) \
                        < self.state.last_committed_version:
                    self._catch_up(peer)
            else:
                self._note_follower_failure(peer)

        self.transport.send(
            self.node_id, peer,
            {"type": "follower_check", "term": self.state.current_term},
            on_reply, on_error=lambda: self._note_follower_failure(peer))

    def _catch_up(self, peer: str) -> None:
        """Re-send the latest committed state to one lagging follower."""
        st = self.state.accepted
        if st.version != self.state.last_committed_version:
            return   # a publication is in flight; it will cover the gap

        def on_reply(reply):
            if reply.get("type") == "publish_response" and not self.stopped:
                self.transport.send(self.node_id, peer,
                                    {"type": "commit", "term": st.term,
                                     "version": st.version}, lambda r: None)

        self.transport.send(self.node_id, peer,
                            {"type": "publish", "state": _state_to_wire(st)},
                            on_reply)

    def _note_follower_failure(self, peer: str) -> None:
        if self.mode != LEADER:
            return
        n = self._follower_failures.get(peer, 0) + 1
        self._follower_failures[peer] = n
        if n >= self.FOLLOWER_CHECK_RETRIES:
            self._follower_failures[peer] = 0
            self.on_node_failed(peer)

    def on_node_failed(self, peer: str) -> None:
        """Auto-reconfiguration on failure (ref: Reconfigurator +
        NodeRemovalClusterStateTaskExecutor): shrink the voting config so the
        cluster survives further sequential failures. Joint consensus makes
        the shrink itself safe — the publish needs a quorum of BOTH the old
        committed config and the new one."""
        if self.mode != LEADER:
            return
        cfg = self.state.accepted.config
        if peer not in cfg or len(cfg) <= 1:
            return
        new_cfg = frozenset(cfg - {peer})
        try:
            self.publish(self.state.accepted.value, new_config=new_cfg)
        except CoordinationError:
            pass

    def on_node_joined(self, peer: str) -> None:
        """A previously-removed node came back: grow the voting config."""
        if self.mode != LEADER:
            return
        cfg = self.state.accepted.config
        if peer in cfg:
            return
        try:
            self.publish(self.state.accepted.value,
                         new_config=frozenset(cfg | {peer}))
        except CoordinationError:
            pass

    def _schedule_leader_checks(self) -> None:
        if self.stopped or self.mode != FOLLOWER:
            return
        failures = {"n": 0}

        def on_reply(reply):
            if reply.get("ok"):
                failures["n"] = 0
            else:
                note_failure()

        def note_failure():
            failures["n"] += 1
            if failures["n"] >= self.LEADER_CHECK_RETRIES:
                self._become_candidate("leader unresponsive")

        def tick():
            if self.stopped or self.mode != FOLLOWER or self.leader_id is None:
                return
            self.transport.send(self.node_id, self.leader_id,
                                {"type": "leader_check",
                                 "term": self.state.current_term},
                                on_reply, on_error=note_failure)
            self._checker_handle = self.scheduler.schedule_at(
                self.LEADER_CHECK_INTERVAL_MS, tick)

        self._checker_handle = self.scheduler.schedule_at(
            self.LEADER_CHECK_INTERVAL_MS, tick)

    # ---- helpers ----

    def _peers(self, st: Optional[PublishedState] = None) -> List[str]:
        cfg = set((st or self.state.accepted).config) | \
            set((st or self.state.accepted).last_committed_config) | \
            self.last_known_peers
        return sorted(cfg - {self.node_id})


def _state_to_wire(st: PublishedState) -> dict:
    return {"term": st.term, "version": st.version, "value": st.value,
            "config": sorted(st.config),
            "last_committed_config": sorted(st.last_committed_config)}


def _state_from_wire(d: dict) -> PublishedState:
    return PublishedState(term=d["term"], version=d["version"], value=d["value"],
                          config=frozenset(d["config"]),
                          last_committed_config=frozenset(d["last_committed_config"]))

from elasticsearch_tpu.cluster.state import ClusterState, IndexMetadata, DiscoveryNode

__all__ = ["ClusterState", "IndexMetadata", "DiscoveryNode"]

"""Immutable cluster state model.

Re-designs the reference's ClusterState/Metadata/IndexMetadata/RoutingTable
(ref: cluster/ClusterState.java, cluster/metadata/Metadata.java:1609,
IndexMetadata.java, cluster/routing/RoutingTable.java) as frozen dataclasses
with copy-on-write updaters. State changes go through a single-threaded
master task queue (cluster/service/MasterService.java analog lives in
cluster/coordination.py) and are versioned; appliers react to diffs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.settings import Settings


@dataclass(frozen=True)
class DiscoveryNode:
    node_id: str
    name: str
    address: str = "127.0.0.1:9300"
    roles: tuple = ("master", "data", "ingest")

    def to_dict(self) -> dict:
        return {"node_id": self.node_id, "name": self.name,
                "address": self.address, "roles": list(self.roles)}

    @staticmethod
    def from_dict(d: dict) -> "DiscoveryNode":
        return DiscoveryNode(node_id=d["node_id"], name=d["name"],
                             address=d.get("address", ""),
                             roles=tuple(d.get("roles", ())))


@dataclass(frozen=True)
class ShardRouting:
    """Ref: cluster/routing/ShardRouting.java — one shard copy's assignment."""

    index: str
    shard_id: int
    node_id: Optional[str]
    primary: bool
    state: str = "STARTED"     # UNASSIGNED | INITIALIZING | STARTED | RELOCATING
    allocation_id: str = ""
    # relocation linkage (ref: ShardRouting.relocatingNodeId): on the
    # RELOCATING source this names the target node; on the INITIALIZING
    # target it names the source node.
    relocating_node_id: Optional[str] = None
    # delayed allocation (ref: UnassignedInfo.delayed): an UNASSIGNED
    # replacement left behind by node-left is not allocatable before this
    # wall-clock deadline, giving the bounced node a window to rejoin.
    delayed_until_ms: Optional[int] = None
    # the node that last held this copy — a rejoining node reclaims its
    # own delayed copies instead of triggering a copy storm
    last_node_id: Optional[str] = None

    @property
    def serving(self) -> bool:
        """A copy that answers reads: STARTED, or a RELOCATING source that
        keeps serving until the target takes over."""
        return self.state in ("STARTED", "RELOCATING")

    def to_dict(self) -> dict:
        d = {"index": self.index, "shard_id": self.shard_id,
             "node_id": self.node_id, "primary": self.primary,
             "state": self.state, "allocation_id": self.allocation_id}
        if self.relocating_node_id is not None:
            d["relocating_node_id"] = self.relocating_node_id
        if self.delayed_until_ms is not None:
            d["delayed_until_ms"] = self.delayed_until_ms
        if self.last_node_id is not None:
            d["last_node_id"] = self.last_node_id
        return d

    @staticmethod
    def from_dict(d: dict) -> "ShardRouting":
        return ShardRouting(index=d["index"], shard_id=d["shard_id"],
                            node_id=d.get("node_id"), primary=d["primary"],
                            state=d.get("state", "STARTED"),
                            allocation_id=d.get("allocation_id", ""),
                            relocating_node_id=d.get("relocating_node_id"),
                            delayed_until_ms=d.get("delayed_until_ms"),
                            last_node_id=d.get("last_node_id"))


@dataclass(frozen=True)
class IndexMetadata:
    index: str
    uuid: str
    settings: Settings
    mappings: dict
    aliases: Dict[str, dict] = field(default_factory=dict)
    state: str = "open"
    creation_date: int = field(default_factory=lambda: int(time.time() * 1000))
    version: int = 1
    # per-shard primary terms, bumped on every primary failover (ref:
    # IndexMetadata.primaryTerm — the fencing token replicas check)
    primary_terms: tuple = ()
    # per-shard in-sync allocation ids (ref: IndexMetadata
    # in_sync_allocations — the copies a promoted primary may come from)
    in_sync_allocations: Dict[int, tuple] = field(default_factory=dict)

    @property
    def number_of_shards(self) -> int:
        return int(self.settings.raw("index.number_of_shards", 1))

    @property
    def number_of_replicas(self) -> int:
        return int(self.settings.raw("index.number_of_replicas", 1))

    def primary_term(self, shard_id: int) -> int:
        if shard_id < len(self.primary_terms):
            return self.primary_terms[shard_id]
        return 1

    def with_primary_term_bump(self, shard_id: int) -> "IndexMetadata":
        terms = list(self.primary_terms) or [1] * self.number_of_shards
        while len(terms) <= shard_id:
            terms.append(1)
        terms[shard_id] += 1
        return replace(self, version=self.version + 1, primary_terms=tuple(terms))

    def with_in_sync(self, shard_id: int, allocation_ids: tuple) -> "IndexMetadata":
        in_sync = dict(self.in_sync_allocations)
        in_sync[shard_id] = tuple(allocation_ids)
        return replace(self, version=self.version + 1, in_sync_allocations=in_sync)

    def to_dict(self) -> dict:
        return {"index": self.index, "uuid": self.uuid,
                "settings": self.settings.as_dict(), "mappings": self.mappings,
                "aliases": self.aliases, "state": self.state,
                "creation_date": self.creation_date, "version": self.version,
                "primary_terms": list(self.primary_terms),
                "in_sync_allocations": {str(k): list(v) for k, v in
                                        self.in_sync_allocations.items()}}

    @staticmethod
    def from_dict(d: dict) -> "IndexMetadata":
        return IndexMetadata(
            index=d["index"], uuid=d["uuid"], settings=Settings(d["settings"]),
            mappings=d.get("mappings", {}), aliases=d.get("aliases", {}),
            state=d.get("state", "open"),
            creation_date=d.get("creation_date", 0),
            version=d.get("version", 1),
            primary_terms=tuple(d.get("primary_terms", ())),
            in_sync_allocations={int(k): tuple(v) for k, v in
                                 d.get("in_sync_allocations", {}).items()})


@dataclass(frozen=True)
class ClusterState:
    cluster_name: str = "elasticsearch-tpu"
    version: int = 0
    term: int = 0
    master_node_id: Optional[str] = None
    nodes: Dict[str, DiscoveryNode] = field(default_factory=dict)
    indices: Dict[str, IndexMetadata] = field(default_factory=dict)
    routing: Dict[str, List[ShardRouting]] = field(default_factory=dict)
    # cluster-wide persistent settings (ref: Metadata persistentSettings) —
    # allocation filters like cluster.routing.allocation.exclude._name live
    # here so every master sees the same drain intent
    settings: Dict[str, str] = field(default_factory=dict)

    # ---- functional updaters ----

    def with_settings(self, updates: Dict[str, Optional[str]]) -> "ClusterState":
        merged = dict(self.settings)
        for k, v in updates.items():
            if v is None or v == "":
                merged.pop(k, None)
            else:
                merged[k] = str(v)
        return replace(self, version=self.version + 1, settings=merged)

    def with_index(self, meta: IndexMetadata, routing: List[ShardRouting]) -> "ClusterState":
        indices = dict(self.indices)
        indices[meta.index] = meta
        rt = dict(self.routing)
        rt[meta.index] = routing
        return replace(self, version=self.version + 1, indices=indices, routing=rt)

    def without_index(self, index: str) -> "ClusterState":
        indices = dict(self.indices)
        indices.pop(index, None)
        rt = dict(self.routing)
        rt.pop(index, None)
        return replace(self, version=self.version + 1, indices=indices, routing=rt)

    def with_node(self, node: DiscoveryNode) -> "ClusterState":
        nodes = dict(self.nodes)
        nodes[node.node_id] = node
        return replace(self, version=self.version + 1, nodes=nodes)

    def without_node(self, node_id: str) -> "ClusterState":
        nodes = dict(self.nodes)
        nodes.pop(node_id, None)
        master = self.master_node_id if self.master_node_id != node_id else None
        return replace(self, version=self.version + 1, nodes=nodes,
                       master_node_id=master)

    def with_routing_updates(self, index: str,
                             entries: List[ShardRouting]) -> "ClusterState":
        rt = dict(self.routing)
        rt[index] = entries
        return replace(self, version=self.version + 1, routing=rt)

    def with_index_metadata(self, meta: IndexMetadata) -> "ClusterState":
        indices = dict(self.indices)
        indices[meta.index] = meta
        return replace(self, version=self.version + 1, indices=indices)

    def shard_copies(self, index: str, shard_id: int) -> List[ShardRouting]:
        return [r for r in self.routing.get(index, []) if r.shard_id == shard_id]

    def primary_of(self, index: str, shard_id: int) -> Optional[ShardRouting]:
        # during primary relocation two entries carry the primary flag
        # (RELOCATING source + INITIALIZING target); the serving one is
        # authoritative for writes until the swap commits
        best: Optional[ShardRouting] = None
        for r in self.routing.get(index, []):
            if r.shard_id == shard_id and r.primary:
                if r.serving:
                    return r
                if best is None:
                    best = r
        return best

    def entries_on_node(self, node_id: str) -> List[ShardRouting]:
        return [r for shards in self.routing.values() for r in shards
                if r.node_id == node_id]

    def node_by_name(self, name: str) -> Optional[DiscoveryNode]:
        for n in self.nodes.values():
            if n.name == name:
                return n
        return None

    # ---- wire form (the consensus-replicated value) ----

    def to_dict(self) -> dict:
        return {
            "cluster_name": self.cluster_name,
            "version": self.version,
            "term": self.term,
            "master_node_id": self.master_node_id,
            "nodes": {nid: n.to_dict() for nid, n in self.nodes.items()},
            "indices": {name: m.to_dict() for name, m in self.indices.items()},
            "routing": {name: [r.to_dict() for r in shards]
                        for name, shards in self.routing.items()},
            "settings": dict(self.settings),
        }

    @staticmethod
    def from_dict(d: dict) -> "ClusterState":
        return ClusterState(
            cluster_name=d.get("cluster_name", "elasticsearch-tpu"),
            version=d.get("version", 0),
            term=d.get("term", 0),
            master_node_id=d.get("master_node_id"),
            nodes={nid: DiscoveryNode.from_dict(n)
                   for nid, n in d.get("nodes", {}).items()},
            indices={name: IndexMetadata.from_dict(m)
                     for name, m in d.get("indices", {}).items()},
            routing={name: [ShardRouting.from_dict(r) for r in shards]
                     for name, shards in d.get("routing", {}).items()},
            settings=dict(d.get("settings", {})),
        )

    def resolve_indices(self, expression: str) -> List[str]:
        """Index-name expression resolution: names, aliases, wildcards, _all
        (ref: cluster/metadata/IndexNameExpressionResolver.java)."""
        import fnmatch

        if expression in ("_all", "*", ""):
            return sorted(self.indices)
        out: List[str] = []
        for part in expression.split(","):
            part = part.strip()
            if not part:
                continue
            matched = False
            if "*" in part or "?" in part:
                for name in sorted(self.indices):
                    if fnmatch.fnmatchcase(name, part) and name not in out:
                        out.append(name)
                        matched = True
                if not matched:
                    matched = True  # wildcard with no match is not an error
            else:
                if part in self.indices:
                    out.append(part)
                    matched = True
                else:
                    for name, meta in self.indices.items():
                        if part in meta.aliases and name not in out:
                            out.append(name)
                            matched = True
        return out

    def health(self, now_ms: Optional[int] = None) -> dict:
        """Ref: cluster health computation — green/yellow/red from routing.

        RELOCATING sources still serve reads and writes, so they count as
        active; red means some shard has NO serving primary (neither
        STARTED nor RELOCATING)."""
        if now_ms is None:
            now_ms = int(time.time() * 1000)
        active_primary = 0
        active = 0
        unassigned = 0
        initializing = 0
        relocating = 0
        delayed = 0
        served: Dict[Any, bool] = {}
        for index, shards in self.routing.items():
            for s in shards:
                key = (index, s.shard_id)
                served.setdefault(key, False)
                if s.state == "RELOCATING":
                    relocating += 1
                if s.serving:
                    active += 1
                    if s.primary:
                        active_primary += 1
                        served[key] = True
                elif s.state == "INITIALIZING":
                    # a relocation target is the move's other half — the
                    # RELOCATING source already counts as active, so the
                    # target neither drives yellow nor inflates totals
                    if s.relocating_node_id is None:
                        initializing += 1
                else:
                    unassigned += 1
                    if (s.delayed_until_ms is not None
                            and s.delayed_until_ms > now_ms):
                        delayed += 1
        if any(not ok for ok in served.values()):
            status = "red"
        elif unassigned or initializing:
            status = "yellow"
        else:
            status = "green"
        total = active + unassigned + initializing
        return {
            "cluster_name": self.cluster_name,
            "status": status,
            "timed_out": False,
            "number_of_nodes": len(self.nodes),
            "number_of_data_nodes": sum(1 for n in self.nodes.values() if "data" in n.roles),
            "active_primary_shards": active_primary,
            "active_shards": active,
            "relocating_shards": relocating,
            "initializing_shards": initializing,
            "unassigned_shards": unassigned,
            "delayed_unassigned_shards": delayed,
            "number_of_pending_tasks": 0,
            "number_of_in_flight_fetch": 0,
            "task_max_waiting_in_queue_millis": 0,
            "active_shards_percent_as_number": (100.0 * active / total) if total else 100.0,
        }

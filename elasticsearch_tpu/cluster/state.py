"""Immutable cluster state model.

Re-designs the reference's ClusterState/Metadata/IndexMetadata/RoutingTable
(ref: cluster/ClusterState.java, cluster/metadata/Metadata.java:1609,
IndexMetadata.java, cluster/routing/RoutingTable.java) as frozen dataclasses
with copy-on-write updaters. State changes go through a single-threaded
master task queue (cluster/service/MasterService.java analog lives in
cluster/coordination.py) and are versioned; appliers react to diffs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.settings import Settings


@dataclass(frozen=True)
class DiscoveryNode:
    node_id: str
    name: str
    address: str = "127.0.0.1:9300"
    roles: tuple = ("master", "data", "ingest")


@dataclass(frozen=True)
class ShardRouting:
    """Ref: cluster/routing/ShardRouting.java — one shard copy's assignment."""

    index: str
    shard_id: int
    node_id: Optional[str]
    primary: bool
    state: str = "STARTED"     # UNASSIGNED | INITIALIZING | STARTED | RELOCATING
    allocation_id: str = ""


@dataclass(frozen=True)
class IndexMetadata:
    index: str
    uuid: str
    settings: Settings
    mappings: dict
    aliases: Dict[str, dict] = field(default_factory=dict)
    state: str = "open"
    creation_date: int = field(default_factory=lambda: int(time.time() * 1000))
    version: int = 1

    @property
    def number_of_shards(self) -> int:
        return int(self.settings.raw("index.number_of_shards", 1))

    @property
    def number_of_replicas(self) -> int:
        return int(self.settings.raw("index.number_of_replicas", 1))


@dataclass(frozen=True)
class ClusterState:
    cluster_name: str = "elasticsearch-tpu"
    version: int = 0
    term: int = 0
    master_node_id: Optional[str] = None
    nodes: Dict[str, DiscoveryNode] = field(default_factory=dict)
    indices: Dict[str, IndexMetadata] = field(default_factory=dict)
    routing: Dict[str, List[ShardRouting]] = field(default_factory=dict)

    # ---- functional updaters ----

    def with_index(self, meta: IndexMetadata, routing: List[ShardRouting]) -> "ClusterState":
        indices = dict(self.indices)
        indices[meta.index] = meta
        rt = dict(self.routing)
        rt[meta.index] = routing
        return replace(self, version=self.version + 1, indices=indices, routing=rt)

    def without_index(self, index: str) -> "ClusterState":
        indices = dict(self.indices)
        indices.pop(index, None)
        rt = dict(self.routing)
        rt.pop(index, None)
        return replace(self, version=self.version + 1, indices=indices, routing=rt)

    def with_node(self, node: DiscoveryNode) -> "ClusterState":
        nodes = dict(self.nodes)
        nodes[node.node_id] = node
        return replace(self, version=self.version + 1, nodes=nodes)

    def resolve_indices(self, expression: str) -> List[str]:
        """Index-name expression resolution: names, aliases, wildcards, _all
        (ref: cluster/metadata/IndexNameExpressionResolver.java)."""
        import fnmatch

        if expression in ("_all", "*", ""):
            return sorted(self.indices)
        out: List[str] = []
        for part in expression.split(","):
            part = part.strip()
            if not part:
                continue
            matched = False
            if "*" in part or "?" in part:
                for name in sorted(self.indices):
                    if fnmatch.fnmatchcase(name, part) and name not in out:
                        out.append(name)
                        matched = True
                if not matched:
                    matched = True  # wildcard with no match is not an error
            else:
                if part in self.indices:
                    out.append(part)
                    matched = True
                else:
                    for name, meta in self.indices.items():
                        if part in meta.aliases and name not in out:
                            out.append(name)
                            matched = True
        return out

    def health(self) -> dict:
        """Ref: cluster health computation — green/yellow/red from routing."""
        active_primary = 0
        active = 0
        unassigned = 0
        initializing = 0
        for shards in self.routing.values():
            for s in shards:
                if s.state == "STARTED":
                    active += 1
                    if s.primary:
                        active_primary += 1
                elif s.state == "INITIALIZING":
                    initializing += 1
                else:
                    unassigned += 1
        if any(s.primary and s.state != "STARTED"
               for shards in self.routing.values() for s in shards):
            status = "red"
        elif unassigned or initializing:
            status = "yellow"
        else:
            status = "green"
        total = active + unassigned + initializing
        return {
            "cluster_name": self.cluster_name,
            "status": status,
            "timed_out": False,
            "number_of_nodes": len(self.nodes),
            "number_of_data_nodes": sum(1 for n in self.nodes.values() if "data" in n.roles),
            "active_primary_shards": active_primary,
            "active_shards": active,
            "relocating_shards": 0,
            "initializing_shards": initializing,
            "unassigned_shards": unassigned,
            "delayed_unassigned_shards": 0,
            "number_of_pending_tasks": 0,
            "number_of_in_flight_fetch": 0,
            "task_max_waiting_in_queue_millis": 0,
            "active_shards_percent_as_number": (100.0 * active / total) if total else 100.0,
        }

"""Distributed search: query-then-fetch scatter-gather over the transport.

Re-designs the reference's search coordination (ref:
action/search/AbstractSearchAsyncAction.java:188 per-shard query fan-out,
action/search/FetchSearchPhase.java:94 fetch of winning docs from owning
shards, action/search/SearchPhaseController.java:397 reduced merge;
SearchTransportService.java:70 action names). The per-shard executor is the
device path (query_phase over TPU segments); this module is the host
control plane moving ids and scores between nodes.

Wire format: shard query results serialize hits as plain dicts; aggregation
partials (numpy-bearing monoid objects) travel through the DATA-ONLY tagged
codec (common/wire.py — deserialization never executes code, closing
ADVICE r4's pickle finding), the same principle the reference applies with
its named-writeable registry.

Coordinator memory is BOUNDED (ref P6 / VERDICT r4 weak #6;
action/search/QueryPhaseResultConsumer.java:52,96): shard results reduce
incrementally every `batched_reduce_size` arrivals — hit windows truncate
to from+size and aggregation partials fold into one — with the pending
partials' byte estimate reserved on the coordinator's request breaker.

Shard FAILOVER (ref: AbstractSearchAsyncAction.onShardFailure ->
performPhaseOnShard(nextShard)): a shard-query failure retries the shard on
the next-best STARTED copy — excluded-node tracking, bounded by
``ES_TPU_SEARCH_SHARD_RETRIES`` — and the shard only counts failed when
every copy is exhausted, with per-shard reasons in `_shards.failures`.
Consecutive transport failures to a node open a `NodeTransportHealth`
circuit (common/health.py) that replica routing skips; the request
`timeout` travels in the shard payload and bounds each RPC
(``ES_TPU_RPC_TIMEOUT_MS`` floor) so a hung node yields `timed_out: true`
partials at the coordinator instead of wedging the pool.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    CircuitBreakingError, ElasticsearchTpuError, IndexNotFoundError,
    SearchPhaseExecutionError,
)
from elasticsearch_tpu.cluster.remote import ACTION_REMOTE_SEARCH
from elasticsearch_tpu.cluster.state import ClusterState
from elasticsearch_tpu.common import metrics, tracing
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.indices.shard_service import DistributedShardService
from elasticsearch_tpu.search.fetch_phase import execute_fetch_phase
from elasticsearch_tpu.search.query_phase import (
    QuerySearchResult, ShardHit, _sort_key, execute_query_phase, parse_sort,
)
from elasticsearch_tpu.search.reader_context import ReaderContextRegistry
from elasticsearch_tpu.tasks import task_manager as _taskmgr
from elasticsearch_tpu.threadpool import scheduler
from elasticsearch_tpu.transport.channels import (
    NodeChannels, NodeUnavailableError, RpcTimeoutError,
)
from elasticsearch_tpu.transport.service import TransportService

ACTION_QUERY = "indices:data/read/search[phase/query]"
ACTION_FETCH = "indices:data/read/search[phase/fetch/id]"
ACTION_FREE = "indices:data/read/search[free_context]"
ACTION_CAN_MATCH = "indices:data/read/search[can_match]"
_PRE_FILTER_SHARD_SIZE = 4   # ref default is 128; our meshes are smaller


# ---- coordinator resilience counters (node-wide; `tpu_coordinator`
#      section of GET /_nodes/stats) ----

_COORD_LOCK = threading.Lock()
_COORD_COUNTERS: Dict[str, int] = {  # guarded by: _COORD_LOCK
    "shard_retries": 0,        # failover attempts on a next-best copy
    "node_circuit_open": 0,    # candidates skipped on an open node circuit
    "rpc_timeouts": 0,         # RPCs abandoned past their deadline
    "fetch_failures": 0,       # shards dropped in the fetch phase
    "can_match_reroutes": 0,   # pre-filter targets demoted as unreachable
    "deadline_expired": 0,     # shards not attempted: request deadline hit
    "overload_reroutes": 0,    # rankings where a pressured copy was demoted
}


def _count_coord(key: str, n: int = 1) -> None:
    with _COORD_LOCK:
        _COORD_COUNTERS[key] += n


def coordinator_stats() -> dict:
    """`tpu_coordinator` stats: resilience counters + transport circuits."""
    from elasticsearch_tpu.common.health import node_transport_health_stats

    with _COORD_LOCK:
        out: dict = dict(_COORD_COUNTERS)
    out["transport"] = node_transport_health_stats()
    return out


def _is_transport_error(e: BaseException) -> bool:
    """Transport-level failures feed the node circuit; application errors
    from a reachable node (parse errors, missing shard) do not."""
    return isinstance(e, (NodeUnavailableError, RpcTimeoutError))


@dataclasses.dataclass
class _ShardTarget:
    """One shard to query, with its failover candidates in routing order."""

    index: str
    sid: int
    candidates: List[str]      # STARTED copy holders, best first


def _py(v):
    """numpy scalar -> python for JSON transport."""
    if hasattr(v, "item"):
        return v.item()
    return v


def _merge_suggests(parts: List[dict]) -> dict:
    """Coordinator-side suggest merge (ref: SearchPhaseController
    mergeSuggest): entries align positionally (every shard analyzed the
    same text), options dedupe by (text, _id) — term frequencies SUM
    across shards, scores keep the max — and re-rank."""
    out: Dict[str, list] = {}
    names = {n for p in parts for n in p}
    for name in sorted(names):
        entries: List[dict] = []
        cap = 0
        for p in parts:
            for i, e in enumerate(p.get(name) or []):
                cap = max(cap, len(e["options"]))
                if i >= len(entries):
                    entries.append({k: v for k, v in e.items()
                                    if k != "options"} | {"options": []})
                entries[i]["options"].extend(e["options"])
        for e in entries:
            by_key: Dict[tuple, dict] = {}
            for o in e["options"]:
                key = (o.get("text"), o.get("_id"))
                cur = by_key.get(key)
                if cur is None:
                    by_key[key] = dict(o)
                elif "freq" in o:
                    cur["freq"] = cur.get("freq", 0) + o["freq"]
                    cur["score"] = max(cur["score"], o["score"])
                else:
                    cur["score"] = max(cur["score"], o["score"])
            e["options"] = sorted(
                by_key.values(),
                key=lambda o: (-o.get("score", 0.0), -o.get("freq", 0),
                               o.get("text", "")))[: max(cap, 1)]
        out[name] = entries
    return out


class _QueryPhaseResultConsumer:
    """Bounded incremental coordinator reduce (ref P6;
    action/search/QueryPhaseResultConsumer.java:52,96): shard results fold
    every `batched_reduce_size` arrivals, so coordinator memory holds at
    most batch x (hits + one agg partial) regardless of shard count, and
    pending aggregation partials are accounted on the request breaker."""

    def __init__(self, body: dict, sort, k: int, breaker=None):
        self.body = body
        self.sort = sort
        self.k = k
        self.collapse = (body.get("collapse") or {}).get("field")
        self.batch = max(2, int(body.get("batched_reduce_size", 512)))
        self.breaker = breaker
        self.window: List[Tuple[int, dict]] = []    # sorted, <= k
        self._pend_hits: List[Tuple[int, dict]] = []
        self._pend_aggs: List = []
        self.agg_state = None
        self.total = 0
        self.relation = "eq"
        self._reserved = 0
        self._n_pending = 0
        self.n_reduce_steps = 0

    def consume(self, si: int, resp: dict) -> None:
        from elasticsearch_tpu.common.wire import wire_size_estimate

        self.total += resp["total"]
        if resp["relation"] == "gte":
            self.relation = "gte"
        self._pend_hits.extend((si, h) for h in resp["hits"])
        if resp.get("aggs") is not None:
            est = wire_size_estimate(resp["aggs"])
            if self.breaker is not None:
                self.breaker.add_estimate_bytes_and_maybe_break(
                    est, "<reduce_aggs>")
            self._reserved += est
            self._pend_aggs.append(resp["aggs"])
        self._n_pending += 1
        if self._n_pending >= self.batch:
            self._reduce_step()

    def _key(self, t):
        si, h = t
        if self.sort:
            return _sort_key(
                ShardHit(h["leaf_idx"], h["ord"], h["score"],
                         h["global_ord"], h["sort_values"]),
                self.sort) + (si, h["global_ord"])
        return (-h["score"], si, h["global_ord"])

    def _reduce_step(self) -> None:
        if self._pend_hits:
            allh = self.window + self._pend_hits
            allh.sort(key=self._key)
            if self.collapse:
                seen = set()
                out = []
                for t in allh:
                    v = t[1].get("collapse")
                    if v is not None:
                        key = (type(v).__name__, v)
                        if key in seen:
                            continue
                        seen.add(key)
                    out.append(t)
                    if len(out) >= self.k:
                        break
                allh = out
            self.window = allh[: self.k]
            self._pend_hits = []
        if self._pend_aggs:
            from elasticsearch_tpu.common.wire import decode_value
            from elasticsearch_tpu.search.aggregations import (
                parse_aggs, reduce_partials,
            )

            spec = (self.body.get("aggs")
                    or self.body.get("aggregations") or {})
            aggs, _ = parse_aggs(spec)
            parts = [decode_value(x) for x in self._pend_aggs]
            if self.agg_state is not None:
                parts.append(self.agg_state)
            self.agg_state = reduce_partials(aggs, parts)
            self._pend_aggs = []
            if self.breaker is not None and self._reserved:
                self.breaker.release(self._reserved)
            self._reserved = 0
        self._n_pending = 0
        self.n_reduce_steps += 1

    def finish(self):
        """(window [(si, hit)], reduced agg state)."""
        self._reduce_step()
        if self.breaker is not None and self._reserved:
            self.breaker.release(self._reserved)
            self._reserved = 0
        return self.window, self.agg_state

    def release(self) -> None:
        """Error-path cleanup: drop the pending agg reservation without
        reducing (ref: QueryPhaseResultConsumer implements Releasable so the
        breaker bytes never outlive the request)."""
        if self.breaker is not None and self._reserved:
            self.breaker.release(self._reserved)
        self._reserved = 0
        self._pend_aggs = []


class SearchActionService:
    """Shard-level query/fetch handlers + the coordinator entrypoint."""

    def __init__(self, transport: TransportService, channels: NodeChannels,
                 shard_service: DistributedShardService, breakers=None,
                 thread_pool=None, tasks=None, overload=None, remotes=None):
        from elasticsearch_tpu.common.breaker import (
            HierarchyCircuitBreakerService,
        )
        from elasticsearch_tpu.threadpool import ThreadPool

        self.channels = channels
        self.shards = shard_service
        # node TaskManager (tasks/task_manager.py): shard query/fetch
        # handlers register child tasks under the coordinator's
        # `_parent_task` payload field when wired
        self.tasks = tasks
        self.breakers = breakers or HierarchyCircuitBreakerService()
        self.contexts = ReaderContextRegistry()
        # shard query/fetch phases run on the node's SEARCH pool —
        # bounded and isolated from the write stage (a worker of the
        # same pool re-enters inline, so a coordinator running on a
        # search worker serves its local shards without self-deadlock)
        self.thread_pool = thread_pool or ThreadPool()
        transport.register_request_handler(
            ACTION_QUERY,
            lambda req: self.thread_pool.execute(
                "search", self._on_shard_query, req))
        transport.register_request_handler(
            ACTION_FETCH,
            lambda req: self.thread_pool.execute(
                "search", self._on_shard_fetch, req))
        transport.register_request_handler(ACTION_FREE, self._on_free_context)
        transport.register_request_handler(ACTION_CAN_MATCH,
                                           self._on_can_match)
        # cross-cluster plane (PR 20): the registry of named remote
        # clusters this coordinator may fan out to, and the handler that
        # answers a REMOTE coordinator's one-RPC-per-cluster search leg
        self.remotes = remotes
        transport.register_request_handler(ACTION_REMOTE_SEARCH,
                                           self._on_remote_search)
        # adaptive replica selection state: EWMA of per-node shard-query
        # service time (ref: OperationRouting.java:34 rankShardsAndUpdateStats
        # / ResponseCollectorService)
        self._node_ewma_ms: Dict[str, float] = {}
        # per-target-node transport circuits (common/health.py): consecutive
        # transport failures quarantine the node from replica routing until
        # a half-open probe readmits it
        self._node_health: Dict[str, "NodeTransportHealth"] = {}
        # overload controller (common/overload.py): transport admission on
        # the data-node side, retry budget + piggybacked peer pressure on
        # the coordinator side
        self.overload = overload
        # node -> (level, monotonic ts) from `_overload` piggybacks
        self._node_pressure: Dict[str, tuple] = {}

    def _overload(self):
        if self.overload is None:
            from elasticsearch_tpu.common.overload import default_overload

            self.overload = default_overload()
        return self.overload

    # ---------------- shard-level handlers (data node) ----------------

    class _ShardView:
        """IndexService-shaped adapter over one ShardInstance so the
        serving fast path (search/serving.ServingContext) runs per shard."""

        def __init__(self, inst):
            self.shards = [inst.engine]
            self.mapper = inst.mapper
            self.name = inst.index

    def _shard_serving(self, inst):
        ctx = getattr(inst, "_serving_ctx", None)
        if ctx is None:
            from elasticsearch_tpu.search.serving import ServingContext

            ctx = ServingContext(self._ShardView(inst))
            inst._serving_ctx = ctx
        return ctx

    def _shard_slowlog(self, phase: str, index: str, shard_id, took_ms: float,
                       body: dict, tc) -> None:
        """Data-node slowlog: check this shard's phase timing against the
        index's effective thresholds (cluster-state settings) and append a
        structured record when over."""
        meta = self.shards.state.indices.get(index)
        if meta is None:
            return
        th = tracing.slowlog_thresholds(meta.settings).get(phase) or {}
        level = tracing.slowlog_check(phase, took_ms, th)
        if level is not None:
            tracing.slowlog_record(
                phase, level, index, took_ms,
                source=body.get("query"), node=self.shards.node_name,
                shard=shard_id, tc=tc)

    def _on_shard_query(self, req) -> dict:
        p = req.payload
        self._admit_shard_request(p, f"[{p['index']}][{p['shard_id']}]")
        tc = tracing.child_from_wire(p.get("_trace"),
                                     node=self.shards.node_name,
                                     kind="shard_query")
        child = self._register_child(ACTION_QUERY, p, tc)
        t0 = time.monotonic()
        try:
            with tracing.activate(tc), \
                    scheduler.activate_tier(p.get("_sla")), \
                    _taskmgr.activate(child):
                if child is not None:
                    # ban raced this registration: die before any dispatch
                    child.check()
                    child.note_dispatch(phase="query")
                out = self._shard_query_inner(req)
        finally:
            if child is not None:
                self.tasks.unregister(child)
        q_ms = (time.monotonic() - t0) * 1e3
        metrics.observe("query", q_ms)
        if tc is not None:
            tc.add_span("query", q_ms, index=p["index"], shard=p["shard_id"])
            tracing.record_trace(tc)
            out["_trace_spans"] = tc.span_dicts()
        self._shard_slowlog("query", p["index"], p["shard_id"], q_ms,
                            p["body"], tc)
        ov = self.overload
        if ov is not None:
            # pressure propagation: piggyback this data node's level on
            # the response payload (popped by the coordinator, never
            # surfaced in a body) so ARS can route around brownout
            out["_overload"] = ov.stats()["level"]
        return out

    def _admit_shard_request(self, p: dict, where: str) -> None:
        """Transport-side admission (data node): the coordinator's `_sla`
        tier rides the payload; bulk-tier shard work sheds at YELLOW,
        interactive at RED. A shed raises 429 back through the RPC — the
        coordinator fails over to a less-loaded copy."""
        ov = self.overload
        if ov is None:
            return
        tier = p.get("_sla") or scheduler.TIER_INTERACTIVE
        retry_after = ov.admit(tier)
        if retry_after is None:
            return
        from elasticsearch_tpu.threadpool import EsRejectedExecutionError

        raise EsRejectedExecutionError(
            f"[{self.shards.node_name}] overload shed "
            f"({ov.stats()['level']}): {tier}-tier shard request {where}",
            node=self.shards.node_name, tier=tier,
            retry_after_s=retry_after)

    def _shard_query_inner(self, req) -> dict:
        p = req.payload
        inst = self.shards.get_shard(p["index"], p["shard_id"])
        searcher = inst.engine.acquire_searcher()
        # shard-level serving fast path (SURVEY §7 step 4 / VERDICT r4
        # item 10: the flagship engines compose with the mesh THROUGH the
        # transport scatter-gather — each data node serves its shard on
        # its own Turbo/BlockMax engine, shard-local stats, coordinator
        # fetch/reduce unchanged)
        qr: QuerySearchResult | None = None
        if not knob("ES_TPU_DISABLE_SHARD_SERVING"):
            try:
                qr = self._shard_serving(inst).try_query_phase(p["body"])
            except Exception:  # noqa: BLE001 — fast path never fails a query
                qr = None
        if qr is None:
            qr = execute_query_phase(searcher, inst.mapper, p["body"])
        ctx = self.contexts.create(searcher, inst.mapper, p["index"],
                                   p["shard_id"])
        collapse_field = (p["body"].get("collapse") or {}).get("field")
        hits_wire = []
        for h in qr.hits:
            wh = {"leaf_idx": h.leaf_idx, "ord": h.ord,
                  "score": _py(h.score), "global_ord": h.global_ord,
                  "sort_values": [_py(v) for v in h.sort_values]
                  if h.sort_values is not None else None}
            if collapse_field:
                from elasticsearch_tpu.search.query_phase import collapse_value

                wh["collapse"] = _py(collapse_value(
                    searcher.views[h.leaf_idx].segment, h.ord, collapse_field))
            hits_wire.append(wh)
        aggs_wire = None
        if qr.aggregations is not None:
            from elasticsearch_tpu.common.wire import encode_value

            aggs_wire = encode_value(qr.aggregations)
        suggest_out = None
        if p["body"].get("suggest") is not None:
            from elasticsearch_tpu.search.suggest import execute_suggest

            suggest_out = execute_suggest(searcher.views, inst.mapper,
                                          p["body"]["suggest"])
        return {"total": qr.total, "relation": qr.relation,
                "max_score": _py(qr.max_score), "hits": hits_wire,
                "context_id": ctx.context_id, "aggs": aggs_wire,
                "suggest": suggest_out, "profile": qr.profile,
                "timed_out": bool(getattr(qr, "timed_out", False))}

    def _register_child(self, action: str, p: dict, tc):
        """Shard-side child task linked by the coordinator's `_parent_task`
        payload field (next to `_trace`/`_sla` — never in the body, which
        would break extract_plan's allowed-keys fast path). Returns None
        when the node has no TaskManager wired or no parent was sent."""
        if self.tasks is None or not p.get("_parent_task"):
            return None
        where = f"[{p['index']}][{p['shard_id']}]" if "index" in p \
            else f"[ctx {p.get('context_id')}]"
        return self.tasks.register(
            action, f"shard {where}", parent_task_id=p["_parent_task"],
            trace_id=tc.trace_id if tc is not None else None,
            sla=p.get("_sla"))

    def _on_shard_fetch(self, req) -> dict:
        p = req.payload
        tc = tracing.child_from_wire(p.get("_trace"),
                                     node=self.shards.node_name,
                                     kind="shard_fetch")
        child = self._register_child(ACTION_FETCH, p, tc)
        ctx = self.contexts.get(p["context_id"])
        hits = [ShardHit(leaf_idx=h["leaf_idx"], ord=h["ord"],
                         score=h["score"], global_ord=h["global_ord"],
                         sort_values=h.get("sort_values"))
                for h in p["hits"]]
        t0 = time.monotonic()
        try:
            with tracing.activate(tc), _taskmgr.activate(child):
                if child is not None:
                    child.check()
                    child.note_dispatch(phase="fetch")
                fetched = execute_fetch_phase(ctx.searcher, hits, p["body"],
                                              ctx.index, mapper=ctx.mapper)
        finally:
            if child is not None:
                self.tasks.unregister(child)
        f_ms = (time.monotonic() - t0) * 1e3
        metrics.observe("fetch", f_ms)
        out = {"hits": fetched}
        if tc is not None:
            tc.add_span("fetch", f_ms, index=ctx.index, hits=len(hits))
            tracing.record_trace(tc)
            out["_trace_spans"] = tc.span_dicts()
        self._shard_slowlog("fetch", ctx.index, None, f_ms, p["body"], tc)
        return out

    def _on_free_context(self, req) -> dict:
        freed = self.contexts.release(req.payload["context_id"])
        return {"freed": freed}

    def _on_can_match(self, req) -> dict:
        """Lightweight shard pre-filter (ref:
        action/search/CanMatchPreFilterSearchPhase.java): no scoring — just
        'could any document here match?'. Cheap dictionary/column-bound
        checks against every required term of the query."""
        p = req.payload
        try:
            inst = self.shards.get_shard(p["index"], p["shard_id"])
        except Exception:  # noqa: BLE001 — unknown shard: let query phase fail
            return {"can_match": True}
        terms = p.get("required_terms") or []
        if not terms:
            return {"can_match": True}
        searcher = inst.engine.acquire_searcher()
        for field, term in terms:
            ft = inst.mapper.field_type(field)
            if ft is None or ft.family not in ("inverted", "keyword"):
                continue   # column-served fields have no postings to probe
            if not any(v.segment.term_stats(field, term)[0] > 0
                       for v in searcher.views):
                return {"can_match": False}
        return {"can_match": True}

    def _on_remote_search(self, req) -> dict:
        """Answer a REMOTE coordinator's cross-cluster search leg (PR 20):
        one RPC per remote cluster (ref: ccs_minimize_roundtrips) — this
        node runs the full local query-then-fetch for the pattern and
        returns the merged per-cluster response. `_trace`/`_sla` crossed
        the cluster boundary in the payload, so the leg's spans parent
        into the caller's trace and its shard dispatches keep the
        caller's SLA tier."""
        p = req.payload
        tc = tracing.child_from_wire(p.get("_trace"),
                                     node=self.shards.node_name,
                                     kind="remote_search")
        with tracing.activate(tc), scheduler.activate_tier(p.get("_sla")):
            return self.execute_search(p.get("index") or "_all",
                                       dict(p.get("body") or {}))

    @staticmethod
    def _required_terms(body: dict) -> List[Tuple[str, str]]:
        """(field, term) pairs every match must contain — conservative: only
        top-level term queries and bool.must/filter term queries qualify."""
        if body.get("knn") is not None:
            # knn hits union with query hits (query_phase mask | knn mask):
            # a shard with no query-term match can still contribute neighbors
            return []
        query = body.get("query") or {}
        out: List[Tuple[str, str]] = []

        def leaf(spec):
            if not isinstance(spec, dict):
                return
            if "term" in spec and isinstance(spec["term"], dict):
                for f, v in spec["term"].items():
                    out.append((f, str(v["value"] if isinstance(v, dict)
                                       else v)))
        leaf(query)
        b = query.get("bool") or {}
        for clause in list(b.get("must", [])) + list(b.get("filter", [])):
            leaf(clause)
        return out

    # ---------------- coordinator (any node) ----------------

    def _free_contexts(self, shard_results: List[dict]) -> None:
        """Release the reader contexts a query phase created."""
        for r in shard_results:
            try:
                self.channels.request(
                    r["_node"], ACTION_FREE,
                    {"context_id": r["context_id"]},
                    source=self.shards.node_name)
            except Exception:  # noqa: BLE001 — reaper collects leftovers
                pass

    # ---- failover plumbing ----

    def _node_circuit(self, node: str):
        h = self._node_health.get(node)
        if h is None:
            from elasticsearch_tpu.common.health import NodeTransportHealth

            h = NodeTransportHealth(f"{self.shards.node_name}->{node}")
            self._node_health[node] = h
        return h

    def _record_transport_outcome(self, node: str,
                                  err: Optional[BaseException] = None) -> None:
        """Feed the node circuit: transport failures count against it; a
        REACHABLE node answering with an application error proves the
        transport edge healthy (and completes any half-open probe)."""
        h = self._node_circuit(node)
        if err is None or not _is_transport_error(err):
            h.record_success()
        else:
            h.record_fault(err)

    def _penalize_node(self, node: str) -> None:
        # penalize the node so ARS stops preferring a failing copy
        prev = self._node_ewma_ms.get(node, 0.0)
        self._node_ewma_ms[node] = 0.7 * prev + 0.3 * 5000.0

    def _note_node_ok(self, node: str, took_ms: float) -> None:
        prev = self._node_ewma_ms.get(node, took_ms)
        self._node_ewma_ms[node] = 0.7 * prev + 0.3 * took_ms
        # age every OTHER node's stat toward zero so a once-bad node is
        # retried eventually (ref: ResponseCollectorService adjusts stats
        # for unselected nodes)
        for other in self._node_ewma_ms:
            if other != node:
                self._node_ewma_ms[other] *= 0.98

    def _note_node_pressure(self, node: str, level: str) -> None:
        """Piggybacked data-node pressure (`_overload` on the shard-query
        response payload): remembered with a timestamp so ARS ranking can
        demote browned-out copies until the signal goes stale."""
        self._node_pressure[node] = (level, time.monotonic())

    def _pressure_rank(self, node: str) -> int:
        """0 green/unknown/stale, 1 yellow, 2 red. Signals age out after
        twice the hysteresis window (min 1s) — a node that stops answering
        stops telling us it is overloaded, and must not be shunned forever."""
        ent = self._node_pressure.get(node)
        if ent is None:
            return 0
        level, ts = ent
        ttl_s = max(1.0, 2 * int(knob("ES_TPU_OVERLOAD_HYSTERESIS_MS"))
                    / 1000.0)
        if time.monotonic() - ts > ttl_s:
            return 0
        return {"yellow": 1, "red": 2}.get(level, 0)

    def _rank_copies(self, copies) -> List[str]:
        """Replica-selection order for one shard's STARTED copies: the
        local copy is free; remote copies rank by service-time EWMA (ref:
        OperationRouting.java:34); copies on nodes that piggybacked an
        elevated overload level are demoted below green ones; quarantined
        nodes (open transport circuit) sink to last resort."""
        from elasticsearch_tpu.common.health import CLOSED

        def key(r):
            h = self._node_health.get(r.node_id)
            quarantined = 1 if h is not None and h.state != CLOSED else 0
            local = 0 if r.node_id == self.shards.node_name else 1
            return (quarantined, self._pressure_rank(r.node_id), local,
                    self._node_ewma_ms.get(r.node_id, 0.0), r.node_id)

        ranked = sorted(copies, key=key)
        if len(ranked) > 1 and any(
                self._pressure_rank(r.node_id) for r in ranked):
            _count_coord("overload_reroutes")
        return [r.node_id for r in ranked]

    @staticmethod
    def _failure_entry(index: str, sid: int, node: Optional[str],
                       err: BaseException, phase: str,
                       attempted: Optional[List[str]] = None) -> dict:
        reason = {"type": getattr(err, "error_type", type(err).__name__),
                  "reason": str(err), "phase": phase}
        if attempted:
            reason["attempted_nodes"] = list(attempted)
        return {"shard": sid, "index": index, "node": node,
                "status": "failed", "reason": reason}

    @staticmethod
    def _shard_body(body: dict, deadline) -> dict:
        """Deadline propagation: the shard query carries the REMAINING
        request budget, so the data node's own dispatch deadline shrinks as
        coordinator time is spent."""
        if deadline is None:
            return body
        rem = deadline.remaining_ms()
        shard_body = dict(body)
        shard_body["timeout"] = max(1, int(rem if rem is not None else 1))
        return shard_body

    def _rpc(self, node: str, action: str, payload: dict,
             deadline=None) -> dict:
        """One bounded RPC. The bound is the request deadline's remaining
        budget, floored at ``ES_TPU_RPC_TIMEOUT_MS`` (so a nearly-spent
        budget still gives the RPC a useful window); with no deadline the
        floor alone applies when set. Unbounded calls dispatch directly —
        no thread hop on the common path. A hung RPC is abandoned at the
        bound (`RpcTimeoutError`); its worker thread dies with the late
        reply instead of wedging a pool worker."""
        floor_ms = float(knob("ES_TPU_RPC_TIMEOUT_MS"))
        timeout_ms: Optional[float] = None
        if deadline is not None:
            rem = deadline.remaining_ms()
            if rem is not None and rem <= 0:
                raise RpcTimeoutError(
                    f"request deadline expired before [{action}] "
                    f"to [{node}]")
            if rem is not None:
                timeout_ms = max(rem, floor_ms)
            elif floor_ms > 0:
                timeout_ms = floor_ms
        elif floor_ms > 0:
            timeout_ms = floor_ms
        src = self.shards.node_name
        if timeout_ms is None:
            return self.channels.request(node, action, payload, source=src)
        box: dict = {}

        def run():
            try:
                box["r"] = self.channels.request(node, action, payload,
                                                 source=src)
            except BaseException as e:  # noqa: BLE001 — crosses the thread
                box["e"] = e

        t = threading.Thread(target=run, daemon=True, name=f"rpc[{node}]")
        t.start()
        t.join(timeout_ms / 1000.0)
        if t.is_alive():
            _count_coord("rpc_timeouts")
            raise RpcTimeoutError(
                f"[{action}] to [{node}] timed out after {timeout_ms:.0f}ms")
        if "e" in box:
            raise box["e"]
        return box["r"]

    def _query_shard_with_failover(self, target: _ShardTarget, body: dict,
                                   deadline, retries_max: int):
        """Query one shard, failing over to the next-best STARTED copy
        (ref: AbstractSearchAsyncAction.onShardFailure ->
        performPhaseOnShard(nextShard)). Attempted nodes are excluded from
        re-selection; open-circuit nodes are skipped unless every copy is
        quarantined (then the best one gets a forced probe). Returns
        (response, None) on success, (None, failure_entry) when the copies
        are exhausted."""
        attempted: List[str] = []
        quarantined: List[str] = []
        last_err: Optional[BaseException] = None
        budget = retries_max + 1

        def attempt(node: str):
            nonlocal last_err
            if attempted:
                _count_coord("shard_retries")
            attempted.append(node)
            tc = tracing.current()
            payload = {"index": target.index, "shard_id": target.sid,
                       "body": self._shard_body(body, deadline),
                       # the coordinator's SLA tier rides to the data
                       # node so its dispatch scheduler budgets the shard
                       # query like the coordinator would
                       "_sla": scheduler.current_tier()}
            ct = _taskmgr.current_task()
            if ct is not None:
                # parent linkage rides the payload next to _trace/_sla
                # (never the body): the data node registers its shard
                # task as a cancellable child of this coordinator
                payload["_parent_task"] = ct.task_id
            if tc is not None:
                # per-attempt propagation: every failover retry shares the
                # SAME trace id, so a recovered request shows both the
                # failed and the successful rpc_query span
                payload["_trace"] = tc.wire()
            t_q = time.monotonic()
            try:
                resp = self._rpc(node, ACTION_QUERY, payload, deadline)
            except CircuitBreakingError:
                # a breaker trip is a REQUEST error, not a shard failure —
                # swallowing it would return silently-wrong aggregations
                # under memory pressure
                raise
            except Exception as e:  # noqa: BLE001 — failover candidate
                last_err = e
                if tc is not None:
                    tc.add_span("rpc_query", (time.monotonic() - t_q) * 1e3,
                                node=node, index=target.index,
                                shard=target.sid, attempt=len(attempted),
                                error=type(e).__name__)
                self._penalize_node(node)
                self._record_transport_outcome(node, e)
                return None
            self._record_transport_outcome(node)
            rpc_ms = (time.monotonic() - t_q) * 1000.0
            self._note_node_ok(node, rpc_ms)
            self._overload().note_success()
            lvl = resp.pop("_overload", None)
            if lvl:
                self._note_node_pressure(node, lvl)
            if tc is not None:
                tc.add_span("rpc_query", rpc_ms, node=node,
                            index=target.index, shard=target.sid,
                            attempt=len(attempted))
            resp["_node"] = node
            resp["_index"] = target.index
            resp["_shard"] = target.sid
            return resp

        for node in target.candidates:
            if len(attempted) >= budget:
                break
            if deadline is not None and deadline.expired:
                break
            if attempted and not self._overload().retry_allowed(
                    "shard_failover"):
                # node-wide retry budget exhausted: fail fast with the
                # organic error instead of amplifying a brownout
                break
            h = self._node_health.get(node)
            if h is not None and not h.allow_request():
                _count_coord("node_circuit_open")
                quarantined.append(node)
                continue
            resp = attempt(node)
            if resp is not None:
                return resp, None
        if not attempted and quarantined \
                and not (deadline is not None and deadline.expired):
            # every copy quarantined: one forced probe beats failing the
            # shard with zero attempts
            resp = attempt(quarantined[0])
            if resp is not None:
                return resp, None
        if last_err is None:
            last_err = RpcTimeoutError(
                "request timeout expired before the shard query could run")
        node = attempted[-1] if attempted else \
            (quarantined[-1] if quarantined else None)
        return None, self._failure_entry(target.index, target.sid, node,
                                         last_err, "query",
                                         attempted=attempted)

    def _should_trace(self, body: dict,
                      state: Optional[ClusterState]) -> bool:
        """Coordinator-side trace enablement: profile requests, every-Nth
        sampling, or a slowlog threshold configured on any target index
        (slow queries must carry phase attribution)."""
        if body.get("profile"):
            return True
        if tracing.should_sample():
            return True
        st = state or self.shards.state
        for meta in st.indices.values():
            if tracing.slowlog_configured(meta.settings):
                return True
        return False

    def execute_search(self, index_expr: str, body: dict,
                       state: Optional[ClusterState] = None) -> dict:
        """query_then_fetch across every target shard's best copy, with
        replica failover, deadline propagation, and partial-results
        accounting (see module docstring). Registers a cancellable
        coordinator task when no REST-layer task is already active, and
        wraps the phase runner in a coordinator TraceContext when the
        flight recorder is on (an already-active trace — the REST
        layer's — is reused as-is)."""
        if self.tasks is not None and _taskmgr.current_task() is None:
            with self.tasks.task("indices:data/read/search",
                                 f"indices[{index_expr}]"):
                return self._execute_search_traced(index_expr, body, state)
        return self._execute_search_traced(index_expr, body, state)

    def _execute_search_traced(self, index_expr: str, body: dict,
                               state: Optional[ClusterState] = None) -> dict:
        tc = tracing.current()
        if tc is not None:
            return self._execute_search_phases(index_expr, body, state)
        if not self._should_trace(body, state):
            return self._execute_search_phases(index_expr, body, state)
        tc = tracing.TraceContext(node=self.shards.node_name,
                                  kind="coordinator")
        # the coordinator task registered before the trace existed —
        # backfill so /_tasks shows the same id the flight recorder does
        ct = _taskmgr.current_task()
        if ct is not None and ct.trace_id is None:
            ct.trace_id = tc.trace_id
        with tracing.activate(tc):
            resp = self._execute_search_phases(index_expr, body, state)
        tracing.record_trace(tc)
        return resp

    def _execute_search_phases(self, index_expr: str, body: dict,
                               state: Optional[ClusterState] = None) -> dict:
        from elasticsearch_tpu.tasks.task_manager import (
            Deadline, parse_timeout_ms,
        )

        # cross-cluster fan-out (PR 20): `remote:pattern` parts split off
        # into one search RPC per remote cluster; the purely-local leg
        # re-enters here under the same task/trace/tier
        if self.remotes is not None \
                and self.remotes.has_remote_parts(index_expr):
            local_parts, remote_groups = \
                self.remotes.split_expression(index_expr)
            return self.remotes.cross_cluster_search(
                body, local_parts, remote_groups,
                lambda expr, sub: self._execute_search_phases(
                    expr, sub, state))

        start = time.monotonic()
        state = state or self.shards.state
        indices = state.resolve_indices(index_expr)
        if not indices:
            raise IndexNotFoundError(index_expr)

        timeout_ms = parse_timeout_ms(body.get("timeout"))
        deadline = Deadline(timeout_ms) if timeout_ms is not None else None
        allow_partial = \
            body.get("allow_partial_search_results", True) is not False
        retries_max = max(0, knob("ES_TPU_SEARCH_SHARD_RETRIES"))

        targets: List[_ShardTarget] = []
        for index in indices:
            meta = state.indices[index]
            if meta.state == "close":
                from elasticsearch_tpu.common.errors import IndexClosedError

                raise IndexClosedError(f"closed index [{index}]")
            for sid in range(meta.number_of_shards):
                copies = [r for r in state.shard_copies(index, sid)
                          if r.serving and r.node_id is not None]
                if not copies:
                    raise ElasticsearchTpuError(
                        f"all shards failed: no started copy of "
                        f"[{index}][{sid}]")
                targets.append(
                    _ShardTarget(index, sid, self._rank_copies(copies)))

        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        sort = parse_sort(body.get("sort"))

        # ---- can_match pre-filter: skip shards that provably hold no
        # matches (ref: CanMatchPreFilterSearchPhase — only bothers when
        # there are enough shards for skipping to pay for the round) ----
        # ref: pre_filter_shard_size — below the threshold the extra
        # round-trip costs more than the skips save
        skipped = 0
        required = self._required_terms(body) \
            if len(targets) >= _PRE_FILTER_SHARD_SIZE else []
        if required:
            kept = []
            for t in targets:
                node = t.candidates[0]
                try:
                    r = self._rpc(node, ACTION_CAN_MATCH,
                                  {"index": t.index, "shard_id": t.sid,
                                   "required_terms": required}, deadline)
                    self._record_transport_outcome(node)
                    if r.get("can_match", True):
                        kept.append(t)
                    else:
                        skipped += 1
                except Exception as e:  # noqa: BLE001 — fail OPEN, but
                    # re-route: the unreachable node must not stay the
                    # query-phase target, so demote it to last resort and
                    # penalize its EWMA before the fan-out
                    self._penalize_node(node)
                    self._record_transport_outcome(node, e)
                    if len(t.candidates) > 1:
                        t.candidates = t.candidates[1:] + [node]
                    _count_coord("can_match_reroutes")
                    kept.append(t)
            targets = kept

        consumer = _QueryPhaseResultConsumer(
            body, sort, k=from_ + size,
            breaker=self.breakers.get_breaker("request"))
        shard_results: List[dict] = []
        failures: List[dict] = []
        failed = 0
        timed_out = False
        fetch_failed: set = set()
        fetched: Dict[Tuple[int, int], dict] = {}  # (shard_idx, pos) -> hit
        ct = _taskmgr.current_task()
        if ct is not None:
            ct.phase = "query"
        try:
            for t in targets:
                if ct is not None:
                    # per-shard fan-out boundary: a cancel (or a ban from
                    # a dead parent) stops the remaining shard lines here
                    ct.check()
                if deadline is not None and deadline.expired:
                    # budget exhausted mid-fan-out: remaining shards become
                    # timed-out partials, not an error (unless strict)
                    timed_out = True
                    _count_coord("deadline_expired")
                    failed += 1
                    failures.append(self._failure_entry(
                        t.index, t.sid, None, RpcTimeoutError(
                            "request timeout expired before the shard "
                            "query could run"), "query"))
                    continue
                resp, failure = self._query_shard_with_failover(
                    t, body, deadline, retries_max)
                if resp is None:
                    failed += 1
                    failures.append(failure)
                    if failure["reason"]["type"] == \
                            "receive_timeout_transport_exception":
                        timed_out = True
                    continue
                if resp.get("timed_out"):
                    timed_out = True
                shard_results.append(resp)
                consumer.consume(len(shard_results) - 1, resp)
                # the consumer owns hit windows + agg partials from here;
                # drop them from the retained metadata so coordinator
                # memory stays bounded by the batch size
                resp["hits"] = ()
                resp["aggs"] = None

            if not allow_partial and failed:
                raise SearchPhaseExecutionError(
                    f"{failed} of {len(targets)} shards failed and "
                    f"allow_partial_search_results=false: "
                    f"{failures[0]['reason']['reason']}",
                    failures=failures)

            # ---- reduce (ref: SearchPhaseController.reducedQueryPhase) ----
            # the incremental consumer already merged/deduped/truncated as
            # results arrived; finish() folds any remainder
            t_merge = time.monotonic()
            window_entries, agg_state = consumer.finish()
            merge_ms = (time.monotonic() - t_merge) * 1e3
            metrics.observe("merge", merge_ms)
            tc = tracing.current()
            if tc is not None:
                tc.add_span("merge", merge_ms, shards=len(shard_results))

            window = [(si, h, shard_results[si])
                      for si, h in window_entries][from_: from_ + size]

            # ---- fetch winning docs from their owning shards (per-shard
            # isolation: ONE failed fetch drops that shard's hits and gets
            # accounted in _shards.failures; the rest of the response — and
            # every reader context — survives) ----
            by_shard: Dict[int, List[dict]] = {}
            for si, h, r in window:
                by_shard.setdefault(si, []).append(h)
            if ct is not None:
                ct.phase = "fetch"
            for si, hits in by_shard.items():
                if ct is not None:
                    ct.check()
                r = shard_results[si]
                node = r["_node"]
                if deadline is not None and deadline.expired:
                    timed_out = True
                    _count_coord("deadline_expired")
                    fetch_failed.add(si)
                    failures.append(self._failure_entry(
                        r["_index"], r["_shard"], node, RpcTimeoutError(
                            "request timeout expired before the fetch "
                            "phase"), "fetch"))
                    continue
                fetch_payload = {"context_id": r["context_id"],
                                 "hits": hits, "body": body}
                ct_f = _taskmgr.current_task()
                if ct_f is not None:
                    fetch_payload["_parent_task"] = ct_f.task_id
                tc_f = tracing.current()
                if tc_f is not None:
                    fetch_payload["_trace"] = tc_f.wire()
                t_f = time.monotonic()
                try:
                    resp = self._rpc(node, ACTION_FETCH, fetch_payload,
                                     deadline)
                    self._record_transport_outcome(node)
                    if tc_f is not None:
                        tc_f.add_span("rpc_fetch",
                                      (time.monotonic() - t_f) * 1e3,
                                      node=node, index=r["_index"],
                                      shard=r["_shard"], hits=len(hits))
                except CircuitBreakingError:
                    raise
                except Exception as e:  # noqa: BLE001 — drop one shard
                    _count_coord("fetch_failures")
                    self._penalize_node(node)
                    self._record_transport_outcome(node, e)
                    fetch_failed.add(si)
                    failures.append(self._failure_entry(
                        r["_index"], r["_shard"], node, e, "fetch"))
                    if _is_transport_error(e) and \
                            isinstance(e, RpcTimeoutError):
                        timed_out = True
                    continue
                for h, out in zip(hits, resp["hits"]):
                    fetched[(si, h["global_ord"], h["leaf_idx"])] = out

            if not allow_partial and (fetch_failed or timed_out):
                reason = (failures[0]["reason"]["reason"] if failures
                          else "request timed out")
                raise SearchPhaseExecutionError(
                    f"partial results with "
                    f"allow_partial_search_results=false: {reason}",
                    failures=failures)
        except BaseException:
            # breaker trip (or any coordinator error) mid-request: the
            # consumer's pending agg reservation and every reader context
            # created so far must not outlive the request — without this the
            # breaker's _reserved bytes leak until process restart and the
            # contexts hold segments until the reaper collects them
            consumer.release()
            self._free_contexts(shard_results)
            raise
        total = consumer.total
        relation = consumer.relation
        collapse_field = consumer.collapse

        max_score = None
        if not sort:
            ms = [r["max_score"] for r in shard_results
                  if r["max_score"] is not None]
            if ms:
                max_score = max(ms)

        hits_out = []
        for si, h, r in window:
            out = fetched.get((si, h["global_ord"], h["leaf_idx"]))
            if out is None:
                continue
            if out.get("_score") is None and h.get("sort_values") is None:
                out["_score"] = h["score"]
            if collapse_field:
                out.setdefault("fields", {})[collapse_field] = [h.get("collapse")]
            hits_out.append(out)

        # ---- aggregations: finalize the incrementally-reduced state ----
        aggs_out = None
        if agg_state is not None:
            from elasticsearch_tpu.search.aggregations import (
                finalize_aggs, parse_aggs,
            )

            spec = body.get("aggs") or body.get("aggregations") or {}
            aggs, pipelines = parse_aggs(spec)
            aggs_out = finalize_aggs(aggs, pipelines, agg_state)

        # ---- suggest: merge shard suggestions ----
        suggest_out = None
        shard_suggests = [r.get("suggest") for r in shard_results
                          if r.get("suggest")]
        if shard_suggests:
            suggest_out = _merge_suggests(shard_suggests)

        # ---- release contexts ----
        self._free_contexts(shard_results)

        profile = None
        if body.get("profile"):
            shards_prof = []
            for r in shard_results:
                entry = {"id": f"[{r['_index']}][{r['_shard']}]",
                         "searches": [{"query": r.get("profile") or [],
                                       "rewrite_time": 0, "collector": []}]}
                spans = r.get("_trace_spans")
                if spans:
                    phases: Dict[str, float] = {}
                    for s in spans:
                        phases[s["name"]] = round(
                            phases.get(s["name"], 0.0) + s["duration_ms"], 3)
                    entry["tpu"] = {"node": r["_node"], "phases": phases,
                                    "spans": spans}
                shards_prof.append(entry)
            profile = {"shards": shards_prof}
            tc_p = tracing.current()
            if tc_p is not None:
                # took decomposition: coordinator-side phase totals (rpc
                # fan-out, reduce) keyed by the shared trace id
                profile["tpu"] = {"trace_id": tc_p.trace_id,
                                  "opaque_id": tc_p.opaque_id,
                                  "node": self.shards.node_name,
                                  "phases": tc_p.phase_totals()}
        if deadline is not None and deadline.expired:
            timed_out = True
        shards_section = {
            "total": len(targets) + skipped,
            "successful": len(shard_results) - len(fetch_failed) + skipped,
            "skipped": skipped,
            "failed": failed + len(fetch_failed),
        }
        if failures:
            # per-shard reasons — only for shards whose copies were
            # EXHAUSTED (or whose fetch failed); recovered failovers leave
            # no trace here, keeping failed-over responses bit-identical to
            # fault-free ones
            shards_section["failures"] = failures
        resp = {
            "took": int((time.monotonic() - start) * 1000),
            "timed_out": bool(timed_out),
            "_shards": shards_section,
            "hits": {"total": {"value": total, "relation": relation},
                     "max_score": max_score, "hits": hits_out},
        }
        from elasticsearch_tpu.search.response import finalize_hits_envelope

        finalize_hits_envelope(resp, body)
        if aggs_out is not None:
            resp["aggregations"] = aggs_out
        if suggest_out is not None:
            resp["suggest"] = suggest_out
        if profile is not None:
            resp["profile"] = profile
        return resp

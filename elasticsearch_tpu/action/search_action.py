"""Distributed search: query-then-fetch scatter-gather over the transport.

Re-designs the reference's search coordination (ref:
action/search/AbstractSearchAsyncAction.java:188 per-shard query fan-out,
action/search/FetchSearchPhase.java:94 fetch of winning docs from owning
shards, action/search/SearchPhaseController.java:397 reduced merge;
SearchTransportService.java:70 action names). The per-shard executor is the
device path (query_phase over TPU segments); this module is the host
control plane moving ids and scores between nodes.

Wire format: shard query results serialize hits as plain dicts; aggregation
partials (numpy-bearing monoid objects) travel pickled+base64 — they are
internal node-to-node payloads exactly like the reference's
InternalAggregations Writeables.
"""

from __future__ import annotations

import base64
import pickle
import time
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import ElasticsearchTpuError, IndexNotFoundError
from elasticsearch_tpu.cluster.state import ClusterState
from elasticsearch_tpu.indices.shard_service import DistributedShardService
from elasticsearch_tpu.search.fetch_phase import execute_fetch_phase
from elasticsearch_tpu.search.query_phase import (
    QuerySearchResult, ShardHit, _sort_key, execute_query_phase, parse_sort,
)
from elasticsearch_tpu.search.reader_context import ReaderContextRegistry
from elasticsearch_tpu.transport.channels import NodeChannels
from elasticsearch_tpu.transport.service import TransportService

ACTION_QUERY = "indices:data/read/search[phase/query]"
ACTION_FETCH = "indices:data/read/search[phase/fetch/id]"
ACTION_FREE = "indices:data/read/search[free_context]"
ACTION_CAN_MATCH = "indices:data/read/search[can_match]"
_PRE_FILTER_SHARD_SIZE = 4   # ref default is 128; our meshes are smaller


def _py(v):
    """numpy scalar -> python for JSON transport."""
    if hasattr(v, "item"):
        return v.item()
    return v


class SearchActionService:
    """Shard-level query/fetch handlers + the coordinator entrypoint."""

    def __init__(self, transport: TransportService, channels: NodeChannels,
                 shard_service: DistributedShardService):
        self.channels = channels
        self.shards = shard_service
        self.contexts = ReaderContextRegistry()
        transport.register_request_handler(ACTION_QUERY, self._on_shard_query)
        transport.register_request_handler(ACTION_FETCH, self._on_shard_fetch)
        transport.register_request_handler(ACTION_FREE, self._on_free_context)
        transport.register_request_handler(ACTION_CAN_MATCH,
                                           self._on_can_match)
        # adaptive replica selection state: EWMA of per-node shard-query
        # service time (ref: OperationRouting.java:34 rankShardsAndUpdateStats
        # / ResponseCollectorService)
        self._node_ewma_ms: Dict[str, float] = {}

    # ---------------- shard-level handlers (data node) ----------------

    def _on_shard_query(self, req) -> dict:
        p = req.payload
        inst = self.shards.get_shard(p["index"], p["shard_id"])
        searcher = inst.engine.acquire_searcher()
        qr: QuerySearchResult = execute_query_phase(
            searcher, inst.mapper, p["body"])
        ctx = self.contexts.create(searcher, inst.mapper, p["index"],
                                   p["shard_id"])
        collapse_field = (p["body"].get("collapse") or {}).get("field")
        hits_wire = []
        for h in qr.hits:
            wh = {"leaf_idx": h.leaf_idx, "ord": h.ord,
                  "score": _py(h.score), "global_ord": h.global_ord,
                  "sort_values": [_py(v) for v in h.sort_values]
                  if h.sort_values is not None else None}
            if collapse_field:
                from elasticsearch_tpu.search.query_phase import collapse_value

                wh["collapse"] = _py(collapse_value(
                    searcher.views[h.leaf_idx].segment, h.ord, collapse_field))
            hits_wire.append(wh)
        aggs_b64 = None
        if qr.aggregations is not None:
            aggs_b64 = base64.b64encode(
                pickle.dumps(qr.aggregations)).decode("ascii")
        return {"total": qr.total, "relation": qr.relation,
                "max_score": _py(qr.max_score), "hits": hits_wire,
                "context_id": ctx.context_id, "aggs": aggs_b64,
                "profile": qr.profile}

    def _on_shard_fetch(self, req) -> dict:
        p = req.payload
        ctx = self.contexts.get(p["context_id"])
        hits = [ShardHit(leaf_idx=h["leaf_idx"], ord=h["ord"],
                         score=h["score"], global_ord=h["global_ord"],
                         sort_values=h.get("sort_values"))
                for h in p["hits"]]
        fetched = execute_fetch_phase(ctx.searcher, hits, p["body"],
                                      ctx.index, mapper=ctx.mapper)
        return {"hits": fetched}

    def _on_free_context(self, req) -> dict:
        freed = self.contexts.release(req.payload["context_id"])
        return {"freed": freed}

    def _on_can_match(self, req) -> dict:
        """Lightweight shard pre-filter (ref:
        action/search/CanMatchPreFilterSearchPhase.java): no scoring — just
        'could any document here match?'. Cheap dictionary/column-bound
        checks against every required term of the query."""
        p = req.payload
        try:
            inst = self.shards.get_shard(p["index"], p["shard_id"])
        except Exception:  # noqa: BLE001 — unknown shard: let query phase fail
            return {"can_match": True}
        terms = p.get("required_terms") or []
        if not terms:
            return {"can_match": True}
        searcher = inst.engine.acquire_searcher()
        for field, term in terms:
            ft = inst.mapper.field_type(field)
            if ft is None or ft.family not in ("inverted", "keyword"):
                continue   # column-served fields have no postings to probe
            if not any(v.segment.term_stats(field, term)[0] > 0
                       for v in searcher.views):
                return {"can_match": False}
        return {"can_match": True}

    @staticmethod
    def _required_terms(body: dict) -> List[Tuple[str, str]]:
        """(field, term) pairs every match must contain — conservative: only
        top-level term queries and bool.must/filter term queries qualify."""
        if body.get("knn") is not None:
            # knn hits union with query hits (query_phase mask | knn mask):
            # a shard with no query-term match can still contribute neighbors
            return []
        query = body.get("query") or {}
        out: List[Tuple[str, str]] = []

        def leaf(spec):
            if not isinstance(spec, dict):
                return
            if "term" in spec and isinstance(spec["term"], dict):
                for f, v in spec["term"].items():
                    out.append((f, str(v["value"] if isinstance(v, dict)
                                       else v)))
        leaf(query)
        b = query.get("bool") or {}
        for clause in list(b.get("must", [])) + list(b.get("filter", [])):
            leaf(clause)
        return out

    # ---------------- coordinator (any node) ----------------

    def execute_search(self, index_expr: str, body: dict,
                       state: Optional[ClusterState] = None) -> dict:
        """query_then_fetch across every target shard's best copy."""
        start = time.monotonic()
        state = state or self.shards.state
        indices = state.resolve_indices(index_expr)
        if not indices:
            raise IndexNotFoundError(index_expr)

        targets: List[Tuple[str, str, int]] = []   # (node, index, shard_id)
        for index in indices:
            meta = state.indices[index]
            for sid in range(meta.number_of_shards):
                copies = [r for r in state.shard_copies(index, sid)
                          if r.state == "STARTED" and r.node_id is not None]
                if not copies:
                    raise ElasticsearchTpuError(
                        f"all shards failed: no started copy of "
                        f"[{index}][{sid}]")
                # adaptive replica selection: the local copy is free; among
                # remote copies, prefer the node with the best observed
                # service-time EWMA (ref: OperationRouting.java:34)
                local = next((r for r in copies
                              if r.node_id == self.shards.node_name), None)
                if local is not None:
                    chosen = local
                else:
                    chosen = min(
                        copies,
                        key=lambda r: (self._node_ewma_ms.get(
                            r.node_id, 0.0), r.node_id))
                targets.append((chosen.node_id, index, sid))

        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        sort = parse_sort(body.get("sort"))

        # ---- can_match pre-filter: skip shards that provably hold no
        # matches (ref: CanMatchPreFilterSearchPhase — only bothers when
        # there are enough shards for skipping to pay for the round) ----
        # ref: pre_filter_shard_size — below the threshold the extra
        # round-trip costs more than the skips save
        skipped = 0
        required = self._required_terms(body) \
            if len(targets) >= _PRE_FILTER_SHARD_SIZE else []
        if required:
            kept = []
            for node, index, sid in targets:
                try:
                    r = self.channels.request(
                        node, ACTION_CAN_MATCH,
                        {"index": index, "shard_id": sid,
                         "required_terms": required})
                    if r.get("can_match", True):
                        kept.append((node, index, sid))
                    else:
                        skipped += 1
                except Exception:  # noqa: BLE001 — fail open
                    kept.append((node, index, sid))
            targets = kept

        shard_results: List[dict] = []
        failed = 0
        for node, index, sid in targets:
            t_q = time.monotonic()
            try:
                resp = self.channels.request(
                    node, ACTION_QUERY,
                    {"index": index, "shard_id": sid, "body": body})
                resp["_node"] = node
                resp["_index"] = index
                resp["_shard"] = sid
                shard_results.append(resp)
                took_ms = (time.monotonic() - t_q) * 1000.0
                prev = self._node_ewma_ms.get(node, took_ms)
                self._node_ewma_ms[node] = 0.7 * prev + 0.3 * took_ms
                # age every OTHER node's stat toward zero so a once-bad
                # node is retried eventually (ref: ResponseCollectorService
                # adjusts stats for unselected nodes)
                for other in self._node_ewma_ms:
                    if other != node:
                        self._node_ewma_ms[other] *= 0.98
            except Exception:  # noqa: BLE001
                failed += 1
                # penalize the node so ARS stops preferring a failing copy
                prev = self._node_ewma_ms.get(node, 0.0)
                self._node_ewma_ms[node] = 0.7 * prev + 0.3 * 5000.0

        # ---- reduce (ref: SearchPhaseController.reducedQueryPhase) ----
        total = sum(r["total"] for r in shard_results)
        relation = "gte" if any(r["relation"] == "gte"
                                for r in shard_results) else "eq"
        merged: List[Tuple[int, dict, dict]] = []  # (shard_idx, hit, result)
        for si, r in enumerate(shard_results):
            for h in r["hits"]:
                merged.append((si, h, r))
        if sort:
            merged.sort(key=lambda t: _sort_key(
                ShardHit(t[1]["leaf_idx"], t[1]["ord"], t[1]["score"],
                         t[1]["global_ord"], t[1]["sort_values"]), sort)
                + (t[0], t[1]["global_ord"]))
        else:
            merged.sort(key=lambda t: (-t[1]["score"], t[0],
                                       t[1]["global_ord"]))
        collapse_field = (body.get("collapse") or {}).get("field")
        if collapse_field:
            # coordinator-level group dedup (shards collapsed locally; the
            # same key can still appear on several shards)
            seen_groups = set()
            deduped = []
            for t in merged:
                v = t[1].get("collapse")
                if v is not None:
                    key = (type(v).__name__, v)
                    if key in seen_groups:
                        continue
                    seen_groups.add(key)
                deduped.append(t)
            merged = deduped
        window = merged[from_: from_ + size]

        max_score = None
        if not sort:
            ms = [r["max_score"] for r in shard_results
                  if r["max_score"] is not None]
            if ms:
                max_score = max(ms)

        # ---- fetch winning docs from their owning shards ----
        by_shard: Dict[int, List[dict]] = {}
        for si, h, r in window:
            by_shard.setdefault(si, []).append(h)
        fetched: Dict[Tuple[int, int], dict] = {}  # (shard_idx, pos) -> hit
        for si, hits in by_shard.items():
            r = shard_results[si]
            resp = self.channels.request(
                r["_node"], ACTION_FETCH,
                {"context_id": r["context_id"], "hits": hits, "body": body})
            for h, out in zip(hits, resp["hits"]):
                fetched[(si, h["global_ord"], h["leaf_idx"])] = out

        hits_out = []
        for si, h, r in window:
            out = fetched.get((si, h["global_ord"], h["leaf_idx"]))
            if out is None:
                continue
            if out.get("_score") is None and h.get("sort_values") is None:
                out["_score"] = h["score"]
            if collapse_field:
                out.setdefault("fields", {})[collapse_field] = [h.get("collapse")]
            hits_out.append(out)

        # ---- aggregations: partial reduce then finalize (ref P6) ----
        aggs_out = None
        parts = [pickle.loads(base64.b64decode(r["aggs"]))
                 for r in shard_results if r.get("aggs")]
        if parts:
            from elasticsearch_tpu.search.aggregations import finalize_shard_aggs

            aggs_out = finalize_shard_aggs(body, parts)

        # ---- release contexts ----
        for r in shard_results:
            try:
                self.channels.request(
                    r["_node"], ACTION_FREE,
                    {"context_id": r["context_id"]})
            except Exception:  # noqa: BLE001 — reaper collects leftovers
                pass

        profile = None
        if body.get("profile"):
            profile = {"shards": [
                {"id": f"[{r['_index']}][{r['_shard']}]",
                 "searches": [{"query": r.get("profile") or [],
                               "rewrite_time": 0, "collector": []}]}
                for r in shard_results]}
        resp = {
            "took": int((time.monotonic() - start) * 1000),
            "timed_out": False,
            "_shards": {"total": len(targets) + skipped,
                        "successful": len(shard_results) + skipped,
                        "skipped": skipped, "failed": failed},
            "hits": {"total": {"value": total, "relation": relation},
                     "max_score": max_score, "hits": hits_out},
        }
        from elasticsearch_tpu.search.response import finalize_hits_envelope

        finalize_hits_envelope(resp, body)
        if aggs_out is not None:
            resp["aggregations"] = aggs_out
        if profile is not None:
            resp["profile"] = profile
        return resp

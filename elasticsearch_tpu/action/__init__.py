"""Distributed action layer (ref: server/.../action/)."""

"""Data-only wire codec for structured values (aggregation partials).

Extends the segment_io principle (JSON header + raw arrays, never pickle)
to ARBITRARY nested python/numpy values: aggregation partials are monoid
states built from dicts (sometimes with tuple keys — composite buckets),
lists, tuples, numpy arrays/scalars and primitives. Encoding tags each
node; decoding only CONSTRUCTS data — no code ever executes
(ADVICE r4: inter-node aggregation partials used to travel pickled).

Ref: the reference's StreamInput/StreamOutput named-writeable registry
(server/src/main/java/org/elasticsearch/common/io/stream/) — a closed,
code-free set of wire shapes.
"""

from __future__ import annotations

import base64
from typing import Any

import numpy as np


class WireError(ValueError):
    pass


def encode_value(obj: Any):
    """Value -> JSON-safe structure (data only)."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        if obj != obj or obj in (float("inf"), float("-inf")):
            return {"__t": "f", "v": repr(obj)}
        return obj
    if isinstance(obj, np.ndarray):
        return {"__t": "nd", "d": str(obj.dtype), "s": list(obj.shape),
                "b": base64.b64encode(np.ascontiguousarray(obj).tobytes())
                .decode("ascii")}
    if isinstance(obj, np.generic):
        return {"__t": "np", "d": str(obj.dtype),
                "v": encode_value(obj.item())}
    if isinstance(obj, tuple):
        return {"__t": "tu", "v": [encode_value(x) for x in obj]}
    if isinstance(obj, list):
        return {"__t": "li", "v": [encode_value(x) for x in obj]}
    if isinstance(obj, (set, frozenset)):
        return {"__t": "se", "v": [encode_value(x) for x in sorted(
            obj, key=repr)]}
    if isinstance(obj, dict):
        if all(isinstance(k, str) and k != "__t" for k in obj):
            return {"__t": "di",
                    "v": {k: encode_value(v) for k, v in obj.items()}}
        return {"__t": "dk",
                "v": [[encode_value(k), encode_value(v)]
                      for k, v in obj.items()]}
    if isinstance(obj, bytes):
        return {"__t": "by", "b": base64.b64encode(obj).decode("ascii")}
    raise WireError(f"non-wireable type {type(obj).__name__}")


def decode_value(enc: Any):
    """Inverse of encode_value; constructs data only."""
    if enc is None or isinstance(enc, (bool, int, float, str)):
        return enc
    if isinstance(enc, list):
        return [decode_value(x) for x in enc]
    if not isinstance(enc, dict):
        raise WireError(f"malformed wire value {type(enc).__name__}")
    t = enc.get("__t")
    if t == "f":
        return float(enc["v"])
    if t == "nd":
        arr = np.frombuffer(base64.b64decode(enc["b"]),
                            dtype=np.dtype(enc["d"]))
        return arr.reshape([int(x) for x in enc["s"]]).copy()
    if t == "np":
        return np.dtype(enc["d"]).type(decode_value(enc["v"]))
    if t == "tu":
        return tuple(decode_value(x) for x in enc["v"])
    if t == "li":
        return [decode_value(x) for x in enc["v"]]
    if t == "se":
        return set(decode_value(x) for x in enc["v"])
    if t == "di":
        return {k: decode_value(v) for k, v in enc["v"].items()}
    if t == "dk":
        return {decode_value(k): decode_value(v) for k, v in enc["v"]}
    if t == "by":
        return base64.b64decode(enc["b"])
    raise WireError(f"unknown wire tag {t!r}")


def wire_size_estimate(enc: Any) -> int:
    """Rough byte estimate of an ENCODED value (breaker accounting)."""
    if enc is None or isinstance(enc, (bool, int, float)):
        return 8
    if isinstance(enc, str):
        return 8 + len(enc)
    if isinstance(enc, list):
        return 8 + sum(wire_size_estimate(x) for x in enc)
    if isinstance(enc, dict):
        if enc.get("__t") in ("nd", "by"):
            return 16 + (len(enc["b"]) * 3) // 4
        return 8 + sum(8 + len(k) + wire_size_estimate(v)
                       for k, v in enc.items() if k != "__t")
    return 8

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuError,
    IndexNotFoundError,
    DocumentMissingError,
    VersionConflictError,
    CircuitBreakingError,
    IllegalArgumentError,
    ParsingError,
    ResourceAlreadyExistsError,
)
from elasticsearch_tpu.common.settings import Setting, Settings, ClusterSettings
from elasticsearch_tpu.common.breaker import CircuitBreaker, HierarchyCircuitBreakerService

__all__ = [
    "ElasticsearchTpuError",
    "IndexNotFoundError",
    "DocumentMissingError",
    "VersionConflictError",
    "CircuitBreakingError",
    "IllegalArgumentError",
    "ParsingError",
    "ResourceAlreadyExistsError",
    "Setting",
    "Settings",
    "ClusterSettings",
    "CircuitBreaker",
    "HierarchyCircuitBreakerService",
]

"""Overload control plane: adaptive admission, retry budgets, brownout ladder.

PR 12 built the pressure *signals* (pool queue depth and `queue_ewma_ms`,
scheduler lane occupancy via `AdaptiveDispatchScheduler.sample()`,
`hbm_ledger` headroom, parent breaker usage, indexing-pressure outstanding
bytes); this module makes the node *act* on them. A per-node
`OverloadController` folds the signals into a GREEN / YELLOW / RED pressure
level with hysteresis and feeds three consumers:

1. **Admission control** — the REST front door and the transport shard
   handlers call `admit(tier)`: bulk-tier requests shed at YELLOW with a 429
   + ``Retry-After``, interactive requests shed only at RED. Every shed is
   counted (`shed_interactive` / `shed_bulk` in `stats()`, `overload_shed`
   in Prometheus); nothing is silently dropped.
2. **Retry budgets** — `retry_allowed(site)` consults a token bucket
   (`RetryBudget`) refilled by successful requests
   (`ES_TPU_RETRY_BUDGET_RATIO` tokens per success, capped at
   `ES_TPU_RETRY_BUDGET_CAP`). The shard-failover loop, replication / bulk /
   recovery retries and the coalescer/scheduler poison solo retries each
   spend one token per retry; when the bucket is empty the original error
   fails fast instead of amplifying (counter `retry_budget_exhausted`,
   per-site in `stats()`).
3. **Pressure propagation** — data nodes piggyback their level on shard RPC
   responses (`_overload` in the payload, never the body) and the
   coordinator's `_rank_copies` penalizes overloaded replicas in ARS order.

Brownout changes *which* requests are admitted and *where* they run — never
their results: admitted queries stay bit-identical to an unloaded run.

Signal folding: backlog / memory-commitment signals (pool queue fraction,
parent breaker usage, indexing-pressure fraction) carry full weight, because
they only saturate when the node is genuinely behind. Occupancy-shaped
signals (scheduler lane busy-fraction, HBM residency, queue-wait EWMA)
saturate in *healthy* steady state too — double-buffered lanes run at 1.0
and a full column cache is good utilization — so they are advisory: scaled
by 0.5 they can lift the score toward YELLOW but can never force RED alone.

Deterministic pressure for tests rides the ``ES_TPU_FAULTS`` grammar via the
``overload_pressure`` site (`faults.injected_overload_level`): mode
``hang`` pins YELLOW, ``raise``/``oom`` pin RED. Each `evaluate()` consumes
one fault-clause call, so ``overload_pressure:raise@3x2`` sheds exactly the
3rd and 4th admission checks.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from elasticsearch_tpu.common import metrics
from elasticsearch_tpu.common.faults import injected_overload_level
from elasticsearch_tpu.common.settings import knob

GREEN = "green"
YELLOW = "yellow"
RED = "red"

_RANK = {GREEN: 0, YELLOW: 1, RED: 2}

# must match threadpool/scheduler.py TIER_* (overload stays import-light:
# metrics/settings/faults only, so the threadpool package can depend on it)
TIER_INTERACTIVE = "interactive"
TIER_BULK = "bulk"

# occupancy-shaped signals (lane busy-fraction, HBM residency, queue-wait
# EWMA) saturate in healthy steady state; cap their vote below the default
# RED threshold so they can never shed on their own
_ADVISORY_WEIGHT = 0.5

# queue-wait EWMA normalization: 2s of queue wait == fully saturated signal
_QUEUE_WAIT_FULL_MS = 2000.0

metrics.declare_gauge("tpu_overload.level",
                      "folded node pressure level (0=green 1=yellow 2=red)")
metrics.declare_gauge("tpu_overload.score",
                      "folded pressure score in [0,1] (pre-hysteresis)")
metrics.declare_counter("overload_shed",
                        "requests shed by overload admission control "
                        "(bulk at YELLOW, interactive at RED)")
metrics.declare_counter("retry_budget_exhausted",
                        "retries denied because the node-wide retry token "
                        "bucket was empty (the original error fails fast)")


class RetryBudget:
    """Node-wide retry token bucket (ref: the reference client's
    `RetryBudget` / Finagle-style retry budgets).

    Each retry spends one token; each *successful* request refills
    ``ES_TPU_RETRY_BUDGET_RATIO`` tokens, capped at
    ``ES_TPU_RETRY_BUDGET_CAP`` (also the initial fill, so cold starts can
    ride out a transient). Ratio <= 0 disables the budget: `allow` always
    grants, restoring the legacy unbounded-retry behavior.
    """

    def __init__(self):
        self._lock = threading.Lock()
        cap = max(1, int(knob("ES_TPU_RETRY_BUDGET_CAP")))
        self._tokens = float(cap)           # guarded by: _lock
        self._consumed = 0                  # guarded by: _lock
        self._refilled = 0.0                # guarded by: _lock
        self._exhausted: Dict[str, int] = {}  # per-site; guarded by: _lock

    def allow(self, site: str) -> bool:
        """True when a retry at `site` may proceed (spends one token)."""
        ratio = float(knob("ES_TPU_RETRY_BUDGET_RATIO"))
        if ratio <= 0:
            return True
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self._consumed += 1
                return True
            self._exhausted[site] = self._exhausted.get(site, 0) + 1
        metrics.counter_add("retry_budget_exhausted", 1)
        return False

    def note_success(self) -> None:
        """A request completed successfully: refill `ratio` tokens."""
        ratio = float(knob("ES_TPU_RETRY_BUDGET_RATIO"))
        if ratio <= 0:
            return
        cap = max(1, int(knob("ES_TPU_RETRY_BUDGET_CAP")))
        with self._lock:
            before = self._tokens
            self._tokens = min(float(cap), self._tokens + ratio)
            self._refilled += self._tokens - before

    def stats(self) -> dict:
        with self._lock:
            return {
                "tokens": round(self._tokens, 3),
                "consumed": self._consumed,
                "refilled": round(self._refilled, 3),
                "exhausted": dict(self._exhausted),
                "exhausted_total": sum(self._exhausted.values()),
            }


class OverloadController:
    """Folds node pressure signals into a green/yellow/red level and owns
    the node's retry budget.

    Level transitions copy the health-circuit idiom (common/health.py):
    upgrades (toward RED) apply immediately; downgrades only after the raw
    level has stayed below the current one continuously for
    ``ES_TPU_OVERLOAD_HYSTERESIS_MS`` — a square-wave load therefore holds
    the elevated level instead of flapping GREEN<->RED.
    """

    def __init__(self, name: str = "node", thread_pool=None, scheduler=None,
                 breakers=None, indexing_pressure=None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.thread_pool = thread_pool
        self.scheduler = scheduler
        self.breakers = breakers
        self.indexing_pressure = indexing_pressure
        self.budget = RetryBudget()
        self._clock = clock
        self._lock = threading.Lock()
        self._level = GREEN                     # guarded by: _lock
        self._below_since: Optional[float] = None  # guarded by: _lock
        self._transitions = deque(maxlen=16)    # guarded by: _lock
        self._shed_interactive = 0              # guarded by: _lock
        self._shed_bulk = 0                     # guarded by: _lock
        self._last_signals: Dict[str, float] = {}  # guarded by: _lock

    # ---- signals ---------------------------------------------------------

    def _compute_signals(self) -> Dict[str, float]:
        """Each signal normalized to [0, 1]; missing wiring reads as 0."""
        sig = {"pool_queue": 0.0, "queue_wait": 0.0, "scheduler": 0.0,
               "hbm": 0.0, "breaker": 0.0, "indexing": 0.0}
        tp = self.thread_pool
        if tp is not None:
            try:
                for st in tp.stats().values():
                    qcap = st.get("queue_size") or 0
                    if qcap > 0:
                        frac = st.get("queue", 0) / qcap
                        sig["pool_queue"] = max(sig["pool_queue"], frac)
                    wait = st.get("queue_ewma_ms", 0.0) / _QUEUE_WAIT_FULL_MS
                    sig["queue_wait"] = max(sig["queue_wait"], wait)
            except Exception:
                pass
        sched = self.scheduler
        if sched is not None:
            try:
                busy = sched.sample().get("lane_busy_fraction", {})
                if busy:
                    sig["scheduler"] = max(busy.values())
            except Exception:
                pass
        try:
            from elasticsearch_tpu.common.hbm_ledger import hbm_stats
            hbm = hbm_stats()
            budget = hbm.get("budget_bytes") or 0
            if budget > 0:
                sig["hbm"] = 1.0 - hbm.get("headroom_bytes", budget) / budget
        except Exception:
            pass
        br = self.breakers
        if br is not None:
            try:
                parent = br.parent
                if parent.limit_bytes > 0:
                    sig["breaker"] = parent.used_bytes / parent.limit_bytes
            except Exception:
                pass
        ip = self.indexing_pressure
        if ip is not None:
            try:
                mem = ip.stats()["memory"]
                limit = mem["limit_in_bytes"]
                if limit > 0:
                    sig["indexing"] = mem["current"]["all_in_bytes"] / limit
            except Exception:
                pass
        return {k: round(max(0.0, min(1.0, v)), 4) for k, v in sig.items()}

    @staticmethod
    def _fold(sig: Dict[str, float]) -> float:
        return max(sig["pool_queue"], sig["breaker"], sig["indexing"],
                   _ADVISORY_WEIGHT * sig["queue_wait"],
                   _ADVISORY_WEIGHT * sig["scheduler"],
                   _ADVISORY_WEIGHT * sig["hbm"])

    # ---- level -----------------------------------------------------------

    def evaluate(self) -> str:
        """Re-read signals + injection, apply hysteresis, return the level.
        Consumes one `overload_pressure` fault-clause call per invocation."""
        injected = injected_overload_level()
        sig = self._compute_signals()
        score = round(self._fold(sig), 4)
        yellow = float(knob("ES_TPU_OVERLOAD_YELLOW"))
        red = float(knob("ES_TPU_OVERLOAD_RED"))
        if injected == RED or score >= red:
            raw = RED
        elif injected == YELLOW or score >= yellow:
            raw = YELLOW
        else:
            raw = GREEN
        now = self._clock()
        with self._lock:
            self._last_signals = dict(sig, score=score,
                                      injected=injected or "")
            cur = self._level
            if _RANK[raw] >= _RANK[cur]:
                # upgrades (and steady state) apply immediately
                if raw != cur:
                    self._move(cur, raw)
                self._below_since = None
            else:
                hyst_ms = max(0, int(knob("ES_TPU_OVERLOAD_HYSTERESIS_MS")))
                if self._below_since is None:
                    self._below_since = now
                if (now - self._below_since) * 1000.0 >= hyst_ms:
                    self._move(cur, raw)
                    self._below_since = None
            level = self._level
        metrics.gauge_set("tpu_overload.level", _RANK[level])
        metrics.gauge_set("tpu_overload.score", score)
        return level

    def _move(self, a: str, b: str) -> None:  # tpulint: holds=_lock
        self._level = b
        self._transitions.append(f"{a}->{b}")

    def level(self) -> str:
        return self.evaluate()

    # ---- consumer 1: admission ------------------------------------------

    def admit(self, tier: Optional[str]) -> Optional[float]:
        """None when the request is admitted; Retry-After seconds when it
        must be shed (bulk tier at YELLOW, every tier at RED)."""
        level = self.evaluate()
        if level == GREEN:
            return None
        tier = tier if tier in (TIER_INTERACTIVE, TIER_BULK) else TIER_BULK
        if level == YELLOW and tier == TIER_INTERACTIVE:
            return None
        with self._lock:
            if tier == TIER_INTERACTIVE:
                self._shed_interactive += 1
            else:
                self._shed_bulk += 1
        metrics.counter_add("overload_shed", 1)
        return self.retry_after_s()

    def retry_after_s(self) -> float:
        """Backoff hint for shed responses: at least the hysteresis window
        (pressure cannot clear sooner), stretched by observed queue wait."""
        hyst_s = max(0, int(knob("ES_TPU_OVERLOAD_HYSTERESIS_MS"))) / 1000.0
        wait_s = 0.0
        tp = self.thread_pool
        if tp is not None:
            try:
                wait_s = max((st.get("queue_ewma_ms", 0.0)
                              for st in tp.stats().values()),
                             default=0.0) / 1000.0
            except Exception:
                pass
        return float(min(30, max(1, int(hyst_s + wait_s + 0.999))))

    # ---- consumer 2: retry budget ---------------------------------------

    def retry_allowed(self, site: str) -> bool:
        return self.budget.allow(site)

    def note_success(self) -> None:
        self.budget.note_success()

    # ---- observability ---------------------------------------------------

    def stats(self) -> dict:
        """`tpu_overload` node-stats section. Reports the cached level from
        the last `evaluate()` — it does NOT re-evaluate, so scraping never
        consumes a deterministic fault-injection fire."""
        with self._lock:
            return {
                "level": self._level,
                "score": self._last_signals.get("score", 0.0),
                "signals": dict(self._last_signals),
                "transitions": list(self._transitions),
                "shed": {
                    "interactive": self._shed_interactive,
                    "bulk": self._shed_bulk,
                    "total": self._shed_interactive + self._shed_bulk,
                },
                "retry_budget": self.budget.stats(),
            }


# ---------------------------------------------------------------------------
# process-default controller: consumers that predate per-node wiring
# (coalescer / scheduler poison retries) share one budget per process
# ---------------------------------------------------------------------------

_DEFAULT_LOCK = threading.Lock()
_default: Optional[OverloadController] = None  # guarded by: _DEFAULT_LOCK


def default_overload() -> OverloadController:
    global _default
    with _DEFAULT_LOCK:
        if _default is None:
            _default = OverloadController(name="process")
        return _default


def reset_default_for_tests() -> None:
    global _default
    with _DEFAULT_LOCK:
        _default = None

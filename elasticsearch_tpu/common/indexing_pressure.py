"""IndexingPressure: byte-accounted write backpressure (ref:
index/IndexingPressure.java:1 — the reference rejects indexing operations
once outstanding coordinating+primary+replica bytes exceed
`indexing_pressure.memory.limit`, 10% of heap by default, with 429
EsRejectedExecutionException).

Same accounting model here: a bulk's bytes are reserved for the stage's
lifetime (coordinating on the REST/coordinator node, primary/replica on
the shard write path; replica ops get the 1.5x headroom the reference
grants so replication never deadlocks behind coordinating traffic) and
released when the stage completes. A flood of bulk requests hits the
limit and bounces with 429 instead of accumulating unbounded host memory
ahead of refresh (VERDICT r4 weak #7)."""

from __future__ import annotations

import threading
from contextlib import contextmanager

from elasticsearch_tpu.common.errors import ElasticsearchTpuError

DEFAULT_LIMIT_BYTES = 512 << 20


class EsRejectedExecutionError(ElasticsearchTpuError):
    status = 429
    error_type = "es_rejected_execution_exception"


class IndexingPressure:
    def __init__(self, limit_bytes: int = DEFAULT_LIMIT_BYTES):
        self.limit = int(limit_bytes)
        self._lock = threading.Lock()
        self._coordinating = 0
        self._primary = 0
        self._replica = 0
        self._total_coordinating = 0
        self._total_primary = 0
        self._total_replica = 0
        self._rejections = {"coordinating": 0, "primary": 0, "replica": 0}

    # ---- stage guards ----

    @contextmanager
    def coordinating(self, bytes_: int):
        self._acquire("coordinating", bytes_, self.limit)
        try:
            yield
        finally:
            self._release("coordinating", bytes_)

    @contextmanager
    def primary(self, bytes_: int):
        self._acquire("primary", bytes_, self.limit)
        try:
            yield
        finally:
            self._release("primary", bytes_)

    @contextmanager
    def replica(self, bytes_: int):
        # replica writes get headroom so a saturated coordinating stage
        # cannot starve in-flight replication (ref: IndexingPressure.java
        # replicaLimits = 1.5 * limit)
        self._acquire("replica", bytes_, int(self.limit * 1.5))
        try:
            yield
        finally:
            self._release("replica", bytes_)

    # ---- internals ----

    def _acquire(self, stage: str, bytes_: int, limit: int) -> None:
        with self._lock:
            outstanding = self._coordinating + self._primary + self._replica
            if bytes_ > 0 and outstanding + bytes_ > limit:
                self._rejections[stage] += 1
                raise EsRejectedExecutionError(
                    f"rejected execution of {stage} operation ["
                    f"coordinating_and_primary_bytes="
                    f"{self._coordinating + self._primary}, "
                    f"replica_bytes={self._replica}, all_bytes={outstanding},"
                    f" {stage}_operation_bytes={bytes_}, "
                    f"max_{stage}_bytes={limit}]")
            setattr(self, f"_{stage}", getattr(self, f"_{stage}") + bytes_)
            setattr(self, f"_total_{stage}",
                    getattr(self, f"_total_{stage}") + bytes_)

    def _release(self, stage: str, bytes_: int) -> None:
        with self._lock:
            setattr(self, f"_{stage}", getattr(self, f"_{stage}") - bytes_)

    def stats(self) -> dict:
        with self._lock:
            return {"memory": {
                "current": {
                    "combined_coordinating_and_primary_in_bytes":
                        self._coordinating + self._primary,
                    "coordinating_in_bytes": self._coordinating,
                    "primary_in_bytes": self._primary,
                    "replica_in_bytes": self._replica,
                    "all_in_bytes": (self._coordinating + self._primary
                                     + self._replica),
                },
                "total": {
                    "coordinating_in_bytes": self._total_coordinating,
                    "primary_in_bytes": self._total_primary,
                    "replica_in_bytes": self._total_replica,
                    "coordinating_rejections":
                        self._rejections["coordinating"],
                    "primary_rejections": self._rejections["primary"],
                    "replica_rejections": self._rejections["replica"],
                },
                "limit_in_bytes": self.limit,
            }}

"""End-to-end data integrity plane: detect silent bit-rot on every leg.

The reference engine treats corruption as a first-class failure — every
Lucene file carries a footer checksum, `index.shard.check_on_startup`
verifies stores before they serve, and a CorruptIndexException fails the
copy so the master reallocates from a healthy replica. This module is the
shared core of our port of that posture, covering three legs:

  at rest   segment blobs carry a sha256 footer (index/segment_io.py);
            every read verifies; a failure raises `SegmentCorruptedError`,
            drops a ``corrupted-*`` marker in the shard data path, and the
            copy is shard-failed so the master reallocates it from a
            healthy peer (the marker blocks re-serving the corrupt store
            until a fresh recovery overwrites it)
  in flight peer-recovery / relocation segment payloads advertise their
            blob hash; the target verifies before `install_segment` and
            re-fetches on mismatch (indices/shard_service.py)
  in HBM    engines that pin columns register scrub regions here; a
            background scrubber re-downloads one region per tick,
            re-hashes it against the host-side fingerprint, re-uploads
            from the host copy on mismatch, and trips the engine-health
            circuit after repeated hits

Deterministic damage rides the PR 8 fault grammar: corruption sites
``segment_read`` / ``segment_transfer`` / ``hbm_region`` never raise at
the site — `faults.corruption_fires(part, site)` tells the caller to flip
a bit (see `bitflip`) and the plane must DETECT it downstream.

Counters surface as ``tpu_integrity`` in ``GET /_nodes/stats``
(`integrity_stats()`) and as Prometheus gauges via common/metrics.py.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import threading
import time
import uuid
import weakref
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from elasticsearch_tpu.common import metrics
from elasticsearch_tpu.common.settings import knob


class SegmentCorruptedError(Exception):
    """A segment blob failed checksum verification (at rest or on the
    recovery wire). The copy holding it must not serve: the shard is
    failed to the master, which reallocates from a healthy peer."""


# ---------------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_COUNTERS: Dict[str, int] = {
    # ---- at rest ----
    "segments_verified": 0,      # v3 blobs whose footer re-hash passed
    "bytes_verified": 0,         # total blob bytes covered by those passes
    "segments_corrupted": 0,     # footer mismatches (any leg)
    "legacy_blobs_read": 0,      # v2 blobs parsed without verification
    "markers_written": 0,        # corrupted-* markers dropped in data paths
    "markers_cleared": 0,        # markers removed after a clean recovery
    "shards_failed_corrupt": 0,  # copies shard-failed over corruption
    "copies_quarantined": 0,     # corrupt replica stores renamed aside
    "startup_checks": 0,         # ES_TPU_CHECK_ON_STARTUP full-store scans
    "startup_failures": 0,       # scans that found corruption
    # ---- in flight ----
    "transfer_hashes_verified": 0,  # recovery payloads that matched
    "transfer_corruptions": 0,      # advertised-hash mismatches at target
    "transfer_retries": 0,          # re-fetches burned on those mismatches
    # ---- in HBM ----
    "scrub_ticks": 0,            # regions examined by the scrubber
    "scrub_clean": 0,            # re-hash matched the fingerprint
    "scrub_baselined": 0,        # first sight of a device-built epoch
    "scrub_mismatches": 0,       # fingerprint mismatches detected
    "scrub_repairs": 0,          # regions re-uploaded / rebuilt
    "scrub_repaired_bytes": 0,   # bytes restored by those repairs
    "scrub_yields": 0,           # ticks skipped (overload not GREEN)
    # ---- snapshots ----
    "repo_verifies": 0,          # POST /_snapshot/{repo}/_verify runs
    "repo_corrupt_blobs": 0,     # corrupt blobs those runs reported
    "restore_cleanups": 0,       # partial indices deleted after a failure
}

for _name, _doc in (
        ("segments_verified", "segment blob footer verifications passed"),
        ("segments_corrupted", "segment blob checksum failures"),
        ("markers_written", "corrupted-* markers written"),
        ("shards_failed_corrupt", "shard copies failed over corruption"),
        ("transfer_corruptions", "recovery payload hash mismatches"),
        ("scrub_mismatches", "HBM scrub fingerprint mismatches"),
        ("scrub_repairs", "HBM regions repaired from host copies"),
):
    metrics.declare_counter(f"tpu_integrity.{_name}", _doc)
metrics.declare_gauge("tpu_integrity.scrub_regions",
                      "HBM regions registered with the scrubber")
_METRIC_KEYS = frozenset({
    "segments_verified", "segments_corrupted", "markers_written",
    "shards_failed_corrupt", "transfer_corruptions", "scrub_mismatches",
    "scrub_repairs",
})


def count(key: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTERS[key] += n
    if key in _METRIC_KEYS:
        metrics.counter_add(f"tpu_integrity.{key}", n)


def integrity_stats() -> dict:
    """`tpu_integrity` node-stats section: every counter above, plus the
    live scrub-registry size."""
    with _LOCK:
        out = dict(_COUNTERS)
    out["scrub_regions"] = scrub_registry_size()
    return out


def reset_for_tests() -> Dict[str, int]:
    with _LOCK:
        prev = dict(_COUNTERS)
        for k in _COUNTERS:
            _COUNTERS[k] = 0
    return prev


# ---------------------------------------------------------------------------
# deterministic damage
# ---------------------------------------------------------------------------

def bitflip(data: bytes) -> bytes:
    """Flip one bit in the middle of `data` — the canonical injected
    corruption for every `corruption_fires()` call site, far enough from
    headers/footers that only the checksum can catch it."""
    if not data:
        return data
    buf = bytearray(data)
    buf[len(buf) // 2] ^= 0x01
    return bytes(buf)


# ---------------------------------------------------------------------------
# corrupted-* markers (shard data path)
# ---------------------------------------------------------------------------
# Ref: Lucene's Store.markStoreCorrupted writes a corrupted_<uuid> file the
# allocator refuses to reuse. Ours is JSON so the runbook can read it.

def write_corruption_marker(data_path: str, reason: str,
                            segment: Optional[str] = None) -> str:
    os.makedirs(data_path, exist_ok=True)
    name = f"corrupted-{uuid.uuid4().hex[:12]}.json"
    path = os.path.join(data_path, name)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"reason": str(reason)[:500], "segment": segment,
                   "timestamp": time.time()}, f)
    os.replace(tmp, path)
    count("markers_written")
    return path


def corruption_marker(data_path: str) -> Optional[dict]:
    """First readable marker's content, or None when the store is clean."""
    for path in sorted(glob.glob(os.path.join(data_path, "corrupted-*.json"))):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"reason": f"unreadable marker {os.path.basename(path)}"}
    return None


def clear_corruption_markers(data_path: str) -> int:
    cleared = 0
    for path in glob.glob(os.path.join(data_path, "corrupted-*.json")):
        try:
            os.remove(path)
            cleared += 1
        except OSError:
            pass
    if cleared:
        count("markers_cleared", cleared)
    return cleared


# ---------------------------------------------------------------------------
# HBM scrub registry
# ---------------------------------------------------------------------------

class _ScrubRegion:
    """One device-resident region under scrub.

    Two flavors, by provenance of the truth the download is checked
    against:

      host-backed  `expected(owner)` returns the authoritative host numpy
                   array (the engine keeps it anyway, or retains it for
                   this purpose); repair re-uploads it
      baseline     the region is device-built (no host copy is cheap to
                   keep); `epoch(owner)` returns a token that changes on
                   every legitimate rebuild — the first scrub at an epoch
                   records the downloaded fingerprint as trusted, later
                   scrubs at the SAME epoch must match it; repair resets
                   the cache (dropping to a new epoch)

    All callables take the owner so the registry holds only a weakref —
    a dropped engine must not be pinned alive by its scrub entry."""

    def __init__(self, owner, name: str, get_device, expected, repair,
                 epoch):
        self.ref = weakref.ref(owner)
        self.key = (id(owner), name)
        self.name = name
        self.kind = type(owner).__name__
        self.get_device = get_device
        self.expected = expected
        self.repair = repair
        self.epoch = epoch
        self.baseline_epoch: Any = None
        self.baseline_digest: Optional[bytes] = None


_SCRUB_LOCK = threading.Lock()
_REGIONS: List[_ScrubRegion] = []      # guarded by: _SCRUB_LOCK
_HEALTH: Dict[int, Any] = {}           # id(owner) -> EngineHealth (weak)
_CURSOR = [0]                          # round-robin position


def register_scrub_region(owner, name: str,
                          get_device: Callable[[Any], Any], *,
                          expected: Optional[Callable[[Any], Any]] = None,
                          repair: Optional[Callable[[Any], None]] = None,
                          epoch: Optional[Callable[[Any], Any]] = None
                          ) -> None:
    """Register (or re-register) one region. Exactly one of `expected`
    (host-backed) or `epoch` (baseline) must be given."""
    if (expected is None) == (epoch is None):
        raise ValueError("exactly one of expected= / epoch= required")
    region = _ScrubRegion(owner, name, get_device, expected, repair, epoch)
    with _SCRUB_LOCK:
        _prune_locked()
        for i, r in enumerate(_REGIONS):
            if r.key == region.key:
                _REGIONS[i] = region
                break
        else:
            _REGIONS.append(region)
        metrics.gauge_set("tpu_integrity.scrub_regions", len(_REGIONS))


def attach_scrub_health(owner, health) -> None:
    """Wire an EngineHealth circuit to every region of `owner`: repeated
    scrub mismatches trip it exactly like repeated dispatch faults, so a
    persistently rotting engine stops serving from the device."""
    with _SCRUB_LOCK:
        _HEALTH[id(owner)] = health
        weakref.finalize(owner, _HEALTH.pop, id(owner), None)


def _prune_locked() -> None:  # tpulint: holds=_SCRUB_LOCK
    _REGIONS[:] = [r for r in _REGIONS if r.ref() is not None]


def scrub_registry_size() -> int:
    with _SCRUB_LOCK:
        _prune_locked()
        return len(_REGIONS)


def _host_bytes(arr) -> bytes:
    return np.ascontiguousarray(np.asarray(arr)).tobytes()


def scrub_once() -> Optional[dict]:
    """Scrub the next region (round-robin): download, re-hash, compare,
    repair on mismatch. Synchronous — the scrubber thread calls this once
    per tick; tests call it directly. Returns an outcome dict, or None
    when no regions are registered."""
    from elasticsearch_tpu.common import faults

    with _SCRUB_LOCK:
        _prune_locked()
        metrics.gauge_set("tpu_integrity.scrub_regions", len(_REGIONS))
        if not _REGIONS:
            return None
        region = _REGIONS[_CURSOR[0] % len(_REGIONS)]
        _CURSOR[0] += 1
        health = _HEALTH.get(region.key[0])
    owner = region.ref()
    if owner is None:
        return None
    count("scrub_ticks")
    outcome = {"region": f"{region.kind}.{region.name}", "result": "clean"}
    # baseline flavor: read the epoch token BEFORE the download — a
    # legitimate rebuild racing the scrub then re-baselines next pass
    # instead of false-mismatching
    ep = region.epoch(owner) if region.epoch is not None else None
    # the download IS the verification read; an injected hbm_region clause
    # damages this copy (the device never served it), which is exactly the
    # bit the fingerprint must catch
    data = _host_bytes(region.get_device(owner))
    if faults.corruption_fires(region.name, site="hbm_region"):
        data = bitflip(data)
    digest = hashlib.sha256(data).digest()
    if region.expected is not None:
        want = hashlib.sha256(_host_bytes(region.expected(owner))).digest()
    else:
        if ep != region.baseline_epoch or region.baseline_digest is None:
            # first sight of this epoch: trust the download as baseline
            region.baseline_epoch = ep
            region.baseline_digest = digest
            count("scrub_baselined")
            outcome["result"] = "baselined"
            return outcome
        want = region.baseline_digest
    if digest == want:
        count("scrub_clean")
        if health is not None:
            health.record_success()
        return outcome
    count("scrub_mismatches")
    err = SegmentCorruptedError(
        f"HBM scrub mismatch in {region.kind}.{region.name}")
    outcome["result"] = "mismatch"
    if region.repair is not None:
        region.repair(owner)
        region.baseline_epoch = None   # device-built: re-baseline next pass
        region.baseline_digest = None
        count("scrub_repairs")
        count("scrub_repaired_bytes", len(data))
        outcome["repaired"] = True
    if health is not None:
        health.record_fault(err)
    return outcome


def reset_scrub_for_tests() -> None:
    with _SCRUB_LOCK:
        _REGIONS.clear()
        _HEALTH.clear()
        _CURSOR[0] = 0


# ---------------------------------------------------------------------------
# background scrubber
# ---------------------------------------------------------------------------

class IntegrityScrubber:
    """Periodic HBM scrub driver (``ES_TPU_INTEGRITY_SCRUB_S``; 0 = off).

    One region per tick, executed on the node's MANAGEMENT pool so scrub
    downloads never contend with search/write workers for a stage slot;
    the tick is skipped entirely while the overload controller is not
    GREEN (reads the CACHED level — `stats()` — because `evaluate()`
    consumes a deterministic `overload_pressure` fault fire)."""

    def __init__(self, thread_pool=None, overload=None):
        self._thread_pool = thread_pool
        self._overload = overload
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> bool:
        period = float(knob("ES_TPU_INTEGRITY_SCRUB_S"))
        if period <= 0 or self._thread is not None:
            return False
        self._thread = threading.Thread(
            target=self._loop, args=(period,), daemon=True,
            name="es-tpu-integrity-scrub")
        self._thread.start()
        return True

    def _loop(self, period: float) -> None:
        while not self._stop.wait(period):
            try:
                self.tick()
            except Exception:   # noqa: BLE001 — scrub must never kill itself
                pass

    def tick(self) -> None:
        ol = self._overload
        if ol is not None and ol.stats().get("level", "green") != "green":
            count("scrub_yields")
            return
        if self._thread_pool is not None:
            self._thread_pool.execute("management", scrub_once)
        else:
            scrub_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

"""Device-memory residency ledger and compile-cache introspection (PR 12).

The column caches in ``parallel/turbo.py`` / ``parallel/spmd.py`` and the
BlockMax postings own almost all of the HBM this stack touches, yet until
now they evicted and re-uploaded silently.  This module is the host-side
set of books: every engine registers its device-resident regions here
(mirroring its ``hbm_bytes()`` arithmetic *exactly* — the cross-check test
holds the two to equality), eviction/zeroing churn is counted, and the
``turbo_eligible`` routing decision leaves an explainable trail instead of
a bare boolean.

A second set of books tracks the XLA compile cache by proxy: jit traces
happen lazily at the first dispatch of a new (engine kind, QC) shape, so
the first dispatch at an unseen shape is recorded as a *miss* (with wall
time — that IS the trace cost), later dispatches as *hits*, and
``extend_qc_sizes`` priming as *primed shapes*.  Warmup coverage — the
fraction of dispatches that landed on an already-traced shape — is the
number the scheduler bucket-ladder autotuning work needs.

Everything here is plain host bookkeeping guarded by one lock; nothing on
the device dispatch path blocks on device state.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common import metrics
from elasticsearch_tpu.common.settings import knob

# gauges/counters live in the shared metric registry so the Prometheus
# exposition and sampler ring pick them up like every other metric; the
# dotted tails below must stay surfaced in hbm_stats()/compile_stats()
# (tpulint TPU005)
metrics.declare_gauge("tpu_hbm.occupancy_bytes",
                      "device bytes currently registered by live engines")
metrics.declare_gauge("tpu_hbm.high_watermark_bytes",
                      "peak registered device bytes since process start")
metrics.declare_gauge("tpu_hbm.budget_bytes",
                      "ES_TPU_TURBO_HBM column-cache budget")
metrics.declare_gauge("tpu_hbm.headroom_bytes",
                      "budget minus occupancy (negative = over budget)")
metrics.declare_gauge("tpu_hbm.protected_peak_ratio",
                      "peak fraction of cache slots pinned by an in-flight "
                      "batch's protect set")
metrics.declare_gauge("tpu_hbm.engines", "live engines registered with the ledger")
metrics.declare_counter("tpu_hbm.evictions", "column-cache slot evictions")
metrics.declare_counter("tpu_hbm.churn_bytes",
                        "bytes freed by evictions and cache resets")
metrics.declare_counter("tpu_hbm.zeroed_tiles",
                        "cache tiles queued for zeroing after eviction")
metrics.declare_gauge("tpu_compile.primed_shapes",
                      "(engine kind, QC) shapes primed via extend_qc_sizes")
metrics.declare_gauge("tpu_compile.warmup_coverage_ratio",
                      "fraction of dispatches that hit an already-traced shape")
metrics.declare_counter("tpu_compile.hits",
                        "dispatches at an already-traced (kind, QC) shape")
metrics.declare_counter("tpu_compile.misses",
                        "first dispatches at a new (kind, QC) shape (one "
                        "XLA trace each)")
metrics.declare_counter("tpu_compile.retraces",
                        "misses whose shape was never primed — unplanned "
                        "serving-time traces")

_LOCK = threading.RLock()

_ENGINES: Dict[int, "_EngineEntry"] = {}  # guarded by: _LOCK
_SEQ = [0]                                # guarded by: _LOCK
_HIGH_WATERMARK = [0]                     # guarded by: _LOCK
_PROTECT_PEAK = [0.0]                     # guarded by: _LOCK
_EVICTIONS = [0]                          # guarded by: _LOCK
_CHURN_BYTES = [0]                        # guarded by: _LOCK
_ZEROED_TILES = [0]                       # guarded by: _LOCK

_PRIMED: set = set()                      # guarded by: _LOCK  (kind, shape)
_SEEN: set = set()                        # guarded by: _LOCK  (kind, shape)
_COMPILE_HITS = [0]                       # guarded by: _LOCK
_COMPILE_MISSES = [0]                     # guarded by: _LOCK
_COMPILE_RETRACES = [0]                   # guarded by: _LOCK
_COMPILE_EVENTS: List[dict] = []          # guarded by: _LOCK
_COMPILE_EVENT_CAP = 256

_ROUTING_LOG: List[dict] = []             # guarded by: _LOCK
_ROUTING_CAP = 64


class _EngineEntry:
    __slots__ = ("label", "kind", "devices", "regions", "protect_peak")

    def __init__(self, label: str, kind: str, devices: int) -> None:
        self.label = label
        self.kind = kind
        self.devices = max(1, int(devices))
        self.regions: Dict[str, int] = {}
        self.protect_peak = 0.0


def _occupancy_locked() -> int:
    return sum(sum(e.regions.values()) for e in _ENGINES.values())


def _publish_locked() -> None:  # tpulint: holds=_LOCK
    occ = _occupancy_locked()
    if occ > _HIGH_WATERMARK[0]:
        _HIGH_WATERMARK[0] = occ
    budget = int(knob("ES_TPU_TURBO_HBM"))
    metrics.gauge_set("tpu_hbm.occupancy_bytes", occ)
    metrics.gauge_set("tpu_hbm.high_watermark_bytes", _HIGH_WATERMARK[0])
    metrics.gauge_set("tpu_hbm.budget_bytes", budget)
    metrics.gauge_set("tpu_hbm.headroom_bytes", budget - occ)
    metrics.gauge_set("tpu_hbm.protected_peak_ratio", _PROTECT_PEAK[0])
    metrics.gauge_set("tpu_hbm.engines", len(_ENGINES))


def _drop_entry(key: int) -> None:
    with _LOCK:
        _ENGINES.pop(key, None)
        _publish_locked()


class LedgerHandle:
    """Per-engine view of the ledger. Engines call ``set_region`` with the
    exact ``.nbytes`` of each device buffer they hold, so the ledger's
    per-engine total stays byte-identical to the engine's ``hbm_bytes()``."""

    def __init__(self, key: int, label: str) -> None:
        self._key = key
        self.label = label

    def set_region(self, name: str, nbytes: int) -> None:
        with _LOCK:
            entry = _ENGINES.get(self._key)
            if entry is None:
                return
            entry.regions[name] = int(nbytes)
            _publish_locked()

    def drop_region(self, name: str) -> None:
        with _LOCK:
            entry = _ENGINES.get(self._key)
            if entry is not None and name in entry.regions:
                freed = entry.regions.pop(name)
                _CHURN_BYTES[0] += freed
                metrics.counter_add("tpu_hbm.churn_bytes", freed)
                _publish_locked()

    def note_eviction(self, count: int = 1, freed_bytes: int = 0) -> None:
        with _LOCK:
            _EVICTIONS[0] += count
            _CHURN_BYTES[0] += freed_bytes
        metrics.counter_add("tpu_hbm.evictions", count)
        if freed_bytes:
            metrics.counter_add("tpu_hbm.churn_bytes", freed_bytes)

    def note_zeroed_tiles(self, count: int) -> None:
        if count <= 0:
            return
        with _LOCK:
            _ZEROED_TILES[0] += count
        metrics.counter_add("tpu_hbm.zeroed_tiles", count)

    def note_protect_pressure(self, protected: int, capacity: int) -> None:
        if capacity <= 0:
            return
        ratio = min(1.0, protected / capacity)
        with _LOCK:
            entry = _ENGINES.get(self._key)
            if entry is not None and ratio > entry.protect_peak:
                entry.protect_peak = ratio
            if ratio > _PROTECT_PEAK[0]:
                _PROTECT_PEAK[0] = ratio
                _publish_locked()

    def total_bytes(self) -> int:
        with _LOCK:
            entry = _ENGINES.get(self._key)
            return sum(entry.regions.values()) if entry is not None else 0

    def close(self) -> None:
        _drop_entry(self._key)


def register_engine(obj: object, kind: str, devices: int = 1) -> LedgerHandle:
    """Register ``obj`` and return its handle. The entry is dropped when
    the engine is garbage-collected (or ``close()`` is called), so stale
    engines cannot pin phantom occupancy."""
    with _LOCK:
        _SEQ[0] += 1
        key = _SEQ[0]
        label = f"{kind}-{key}"
        _ENGINES[key] = _EngineEntry(label, kind, devices)
        _publish_locked()
    handle = LedgerHandle(key, label)
    try:
        weakref.finalize(obj, _drop_entry, key)
    except TypeError:  # __slots__ without __weakref__ — close() still works
        pass
    return handle


# --- compile-cache introspection ---------------------------------------------

def note_primed(kind: str, sizes) -> None:
    """Record bucket-ladder priming (extend_qc_sizes). Priming does not
    trace by itself — the trace still lands at the first dispatch — so
    primed shapes are tracked separately from seen shapes."""
    with _LOCK:
        for s in sizes:
            _PRIMED.add((kind, int(s)))
        metrics.gauge_set("tpu_compile.primed_shapes", len(_PRIMED))


def note_dispatch(kind: str, shape) -> bool:
    """Count one dispatch at ``(kind, shape)``. Returns True when this is
    the first dispatch at that shape (an XLA trace): the caller should
    time it and report the wall cost via ``note_compile_done``."""
    key = (kind, shape)
    with _LOCK:
        if key in _SEEN:
            first = retrace = False
            _COMPILE_HITS[0] += 1
        else:
            _SEEN.add(key)
            first = True
            retrace = key not in _PRIMED
            _COMPILE_MISSES[0] += 1
            if retrace:
                _COMPILE_RETRACES[0] += 1
        total = _COMPILE_HITS[0] + _COMPILE_MISSES[0]
        ratio = _COMPILE_HITS[0] / total if total else 0.0
        metrics.gauge_set("tpu_compile.warmup_coverage_ratio", ratio)
    if first:
        metrics.counter_add("tpu_compile.misses")
        if retrace:
            metrics.counter_add("tpu_compile.retraces")
    else:
        metrics.counter_add("tpu_compile.hits")
    return first


def hot_shapes() -> Dict[str, List[int]]:
    """The integer dispatch shapes this process has traced or primed, per
    engine kind — the payload a relocation source hands its target so the
    moved shard's bucket ladder covers the same widths (warm HBM handoff).
    Non-integer shape keys (e.g. blockmax tuple shapes) are skipped: only
    QC widths feed extend_qc_sizes."""
    out: Dict[str, set] = {}
    with _LOCK:
        for kind, shape in _SEEN | _PRIMED:
            if isinstance(shape, (int,)) and not isinstance(shape, bool):
                out.setdefault(kind, set()).add(int(shape))
    return {k: sorted(v) for k, v in sorted(out.items())}


def note_compile_done(kind: str, shape, wall_s: float) -> None:
    """Record the wall cost of a first-trace dispatch (the compile event)."""
    with _LOCK:
        _COMPILE_EVENTS.append({
            "engine": kind,
            "shape": str(shape),
            "wall_ms": round(float(wall_s) * 1000.0, 3),
            "primed": (kind, shape) in _PRIMED,
        })
        del _COMPILE_EVENTS[: max(0, len(_COMPILE_EVENTS) - _COMPILE_EVENT_CAP)]


# --- routing explainability ---------------------------------------------------

def note_routing(index: str, eligible: bool, reason: str,
                 need_bytes: int, budget_bytes: int) -> None:
    with _LOCK:
        _ROUTING_LOG.append({
            "index": index,
            "eligible": bool(eligible),
            "reason": reason,
            "need_bytes": int(need_bytes),
            "budget_bytes": int(budget_bytes),
            "occupancy_bytes": _occupancy_locked(),
        })
        del _ROUTING_LOG[: max(0, len(_ROUTING_LOG) - _ROUTING_CAP)]


def last_routing() -> Optional[dict]:
    with _LOCK:
        return dict(_ROUTING_LOG[-1]) if _ROUTING_LOG else None


def last_routing_reason() -> Optional[str]:
    last = last_routing()
    return last["reason"] if last else None


# --- stats surfaces ------------------------------------------------------------

def hbm_stats() -> dict:
    """The ``tpu_hbm`` section of GET /_nodes/stats."""
    with _LOCK:
        occ = _occupancy_locked()
        budget = int(knob("ES_TPU_TURBO_HBM"))
        return {
            "occupancy_bytes": occ,
            "high_watermark_bytes": _HIGH_WATERMARK[0],
            "budget_bytes": budget,
            "headroom_bytes": budget - occ,
            "protected_peak_ratio": round(_PROTECT_PEAK[0], 4),
            "evictions": _EVICTIONS[0],
            "churn_bytes": _CHURN_BYTES[0],
            "zeroed_tiles": _ZEROED_TILES[0],
            "engines": {
                e.label: {
                    "kind": e.kind,
                    "devices": e.devices,
                    "occupancy_bytes": sum(e.regions.values()),
                    "per_device_bytes": sum(e.regions.values()) // e.devices,
                    "protected_peak_ratio": round(e.protect_peak, 4),
                    "regions": dict(e.regions),
                } for e in _ENGINES.values()
            },
            "routing": {
                "last": dict(_ROUTING_LOG[-1]) if _ROUTING_LOG else None,
                "log": [dict(r) for r in _ROUTING_LOG],
            },
        }


def compile_stats() -> dict:
    """The ``tpu_compile`` section of GET /_nodes/stats."""
    with _LOCK:
        hits = _COMPILE_HITS[0]
        misses = _COMPILE_MISSES[0]
        total = hits + misses
        return {
            "primed_shapes": [f"{k}:{s}" for k, s in sorted(_PRIMED)],
            "seen_shapes": len(_SEEN),
            "hits": hits,
            "misses": misses,
            "retraces": _COMPILE_RETRACES[0],
            "warmup_coverage_ratio": round(hits / total, 4) if total else 0.0,
            "events": [dict(e) for e in _COMPILE_EVENTS],
        }


def reset_for_tests() -> None:
    with _LOCK:
        _ENGINES.clear()
        _HIGH_WATERMARK[0] = 0
        _PROTECT_PEAK[0] = 0.0
        _EVICTIONS[0] = 0
        _CHURN_BYTES[0] = 0
        _ZEROED_TILES[0] = 0
        _PRIMED.clear()
        _SEEN.clear()
        _COMPILE_HITS[0] = 0
        _COMPILE_MISSES[0] = 0
        _COMPILE_RETRACES[0] = 0
        _COMPILE_EVENTS.clear()
        _ROUTING_LOG.clear()

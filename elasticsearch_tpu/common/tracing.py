"""Per-request trace contexts for the search flight recorder.

A ``TraceContext`` is born at the REST layer (or at a coordinator entry for
the in-process cluster harness), rides the current thread via a thread-local,
hops threads through ``threadpool.pool`` (tasks capture the submitter's trace
and re-activate it in the worker), and crosses node boundaries as a small
``_trace`` dict inside the shard RPC payload — NEVER inside the search body
itself, which would trip ``extract_plan``'s allowed-key check and silently
kill the Turbo fast path.

Tracing is OFF by default: ``current()`` returns None, every recording site
degrades to one thread-local read, and responses are bit-identical to the
untraced build (differential-tested). It turns on per request when:

- the search body asks for ``profile``,
- ``ES_TPU_TRACE_SAMPLE`` = N samples every Nth search, or
- the target index has any ``index.search.slowlog.threshold.*`` configured
  (slow queries must carry phase attribution when they hit the slowlog).

Completed traces land in a bounded in-memory ring (``ES_TPU_TRACE_RING``);
over-threshold queries additionally append structured records to the slowlog
ring (``ES_TPU_SLOWLOG_RING``) served at ``GET /_tpu/slowlog``.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.settings import knob, parse_time_value

_tls = threading.local()


class TraceContext:
    """Spans for one search request on one node. Thread-safe: spans arrive
    from pool workers, coalescer leaders and RPC threads concurrently."""

    __slots__ = ("trace_id", "opaque_id", "node", "kind", "t0", "spans",
                 "_lock")

    def __init__(self, trace_id: Optional[str] = None,
                 opaque_id: Optional[str] = None,
                 node: str = "", kind: str = "coordinator"):
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.opaque_id = opaque_id
        self.node = node
        self.kind = kind
        self.t0 = time.monotonic()
        self._lock = threading.Lock()
        self.spans: List[dict] = []  # guarded by: _lock

    def add_span(self, name: str, duration_ms: float, **meta: Any) -> None:
        end_ms = (time.monotonic() - self.t0) * 1e3
        span = {"name": name,
                "start_ms": round(max(0.0, end_ms - duration_ms), 3),
                "duration_ms": round(duration_ms, 3)}
        if meta:
            span["meta"] = meta
        with self._lock:
            self.spans.append(span)

    @contextmanager
    def span(self, name: str, **meta: Any):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add_span(name, (time.monotonic() - t0) * 1e3, **meta)

    def span_dicts(self) -> List[dict]:
        with self._lock:
            return [dict(s) for s in self.spans]

    def phase_totals(self) -> Dict[str, float]:
        """Aggregate span durations by name (ms). rest_total is excluded —
        it envelopes every other phase and would double the sum."""
        out: Dict[str, float] = {}
        for s in self.span_dicts():
            if s["name"] == "rest_total":
                continue
            out[s["name"]] = round(out.get(s["name"], 0.0) + s["duration_ms"], 3)
        return out

    def wire(self) -> dict:
        """What crosses the RPC boundary (payload `_trace` key)."""
        return {"trace_id": self.trace_id, "opaque_id": self.opaque_id}

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "opaque_id": self.opaque_id,
                "node": self.node, "kind": self.kind,
                "spans": self.span_dicts()}


def current() -> Optional[TraceContext]:
    return getattr(_tls, "trace", None)


@contextmanager
def activate(tc: Optional[TraceContext]):
    """Install ``tc`` as the thread's current trace. activate(None) is a
    no-op pass-through so call sites need no branching."""
    if tc is None:
        yield None
        return
    prev = getattr(_tls, "trace", None)
    _tls.trace = tc
    try:
        yield tc
    finally:
        _tls.trace = prev


def child_from_wire(wire: Optional[dict], node: str = "",
                    kind: str = "shard") -> Optional[TraceContext]:
    """Data-node side of RPC propagation: rebuild a local context sharing
    the coordinator's trace id (or None when the request is untraced)."""
    if not wire:
        return None
    return TraceContext(trace_id=wire.get("trace_id"),
                        opaque_id=wire.get("opaque_id"),
                        node=node, kind=kind)


# --- sampling ---------------------------------------------------------------

_SAMPLE_LOCK = threading.Lock()
_SAMPLE = {"n": 0}  # guarded by: _SAMPLE_LOCK


def should_sample() -> bool:
    """Every-Nth sampling per ES_TPU_TRACE_SAMPLE (0 = off)."""
    every = knob("ES_TPU_TRACE_SAMPLE")
    if every <= 0:
        return False
    with _SAMPLE_LOCK:
        _SAMPLE["n"] += 1
        return _SAMPLE["n"] % every == 0


# --- flight-recorder ring ---------------------------------------------------

_RING_LOCK = threading.Lock()
_TRACES: deque = deque()  # guarded by: _RING_LOCK


def record_trace(tc: TraceContext) -> None:
    cap = max(1, knob("ES_TPU_TRACE_RING"))
    with _RING_LOCK:
        _TRACES.append(tc.to_dict())
        while len(_TRACES) > cap:
            _TRACES.popleft()


def recent_traces() -> List[dict]:
    with _RING_LOCK:
        return list(_TRACES)


# --- slowlog ----------------------------------------------------------------

_SLOWLOG_LOCK = threading.Lock()
_SLOWLOG: deque = deque()  # guarded by: _SLOWLOG_LOCK
_SLOWLOG_COUNTS = {"query_warn": 0, "query_info": 0,
                   "fetch_warn": 0, "fetch_info": 0}  # guarded by: _SLOWLOG_LOCK

_SLOWLOG_SETTING = "index.search.slowlog.threshold.{phase}.{level}"
_LEVELS = ("warn", "info")  # warn checked first: highest threshold wins


def slowlog_thresholds(settings) -> Dict[str, Dict[str, Optional[float]]]:
    """Effective per-phase thresholds (ms) from an index Settings object —
    {'query': {'warn': ms|None, 'info': ms|None}, 'fetch': {...}}.
    Unset or '-1' means disabled, matching the reference semantics."""
    out: Dict[str, Dict[str, Optional[float]]] = {}
    for phase in ("query", "fetch"):
        per: Dict[str, Optional[float]] = {}
        for level in _LEVELS:
            raw = settings.raw(_SLOWLOG_SETTING.format(phase=phase, level=level))
            ms: Optional[float] = None
            if raw not in (None, "", "-1", -1):
                try:
                    ms = float(raw)  # bare numbers are ms (reference convention)
                except (TypeError, ValueError):
                    try:
                        ms = parse_time_value(str(raw)) * 1000.0
                    except Exception:  # unparseable -> disabled, not fatal
                        ms = None
                if ms is not None and ms < 0:
                    ms = None
            per[level] = ms
        out[phase] = per
    return out


def slowlog_configured(settings) -> bool:
    th = slowlog_thresholds(settings)
    return any(v is not None for per in th.values() for v in per.values())


def slowlog_check(phase: str, took_ms: float,
                  thresholds: Dict[str, Optional[float]]) -> Optional[str]:
    """Highest matching level for one phase timing, or None."""
    for level in _LEVELS:
        ms = thresholds.get(level)
        if ms is not None and took_ms >= ms:
            return level
    return None


def slowlog_record(phase: str, level: str, index: str, took_ms: float,
                   source: Any = None, node: str = "", shard: Any = None,
                   tc: Optional[TraceContext] = None) -> None:
    entry = {
        "phase": phase,
        "level": level,
        "index": index,
        "shard": shard,
        "node": node,
        "took_ms": round(took_ms, 3),
        "source": source,
        "trace_id": tc.trace_id if tc is not None else None,
        "opaque_id": tc.opaque_id if tc is not None else None,
        "phases": tc.phase_totals() if tc is not None else {},
    }
    cap = max(1, knob("ES_TPU_SLOWLOG_RING"))
    key = f"{phase}_{level}"
    with _SLOWLOG_LOCK:
        if key in _SLOWLOG_COUNTS:
            _SLOWLOG_COUNTS[key] += 1
        _SLOWLOG.append(entry)
        while len(_SLOWLOG) > cap:
            _SLOWLOG.popleft()


def slowlog_entries() -> List[dict]:
    with _SLOWLOG_LOCK:
        return list(_SLOWLOG)


def slowlog_stats() -> dict:
    with _SLOWLOG_LOCK:
        return {**_SLOWLOG_COUNTS, "ring_entries": len(_SLOWLOG)}


def reset_for_tests() -> None:
    with _RING_LOCK:
        _TRACES.clear()
    with _SLOWLOG_LOCK:
        _SLOWLOG.clear()
        for k in _SLOWLOG_COUNTS:
            _SLOWLOG_COUNTS[k] = 0
    with _SAMPLE_LOCK:
        _SAMPLE["n"] = 0
    if getattr(_tls, "trace", None) is not None:
        _tls.trace = None

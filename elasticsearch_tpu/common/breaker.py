"""Circuit breakers: bounded memory accounting for request-scoped allocations.

Re-designs the reference's parent/child breaker hierarchy
(ref: common/breaker/CircuitBreaker.java,
indices/breaker/HierarchyCircuitBreakerService.java): each child breaker
tracks bytes for one concern (request, fielddata, in_flight_requests) and a
parent enforces the sum. On the TPU build this guards *host* memory (segment
staging buffers, reduce buffers); HBM budgeting is handled separately by the
segment registry, which knows device array sizes exactly.
"""

from __future__ import annotations

import threading

from elasticsearch_tpu.common.errors import CircuitBreakingError


class CircuitBreaker:
    def __init__(self, name: str, limit_bytes: int, overhead: float = 1.0, parent: "CircuitBreaker | None" = None):
        self.name = name
        self.limit_bytes = limit_bytes
        self.overhead = overhead
        self.parent = parent
        self._used = 0        # guarded by: _lock
        self._trip_count = 0  # guarded by: _lock
        self._lock = threading.Lock()

    @property
    def used_bytes(self) -> int:
        return self._used

    @property
    def trip_count(self) -> int:
        return self._trip_count

    def add_estimate_bytes_and_maybe_break(self, bytes_: int, label: str = "<unknown>") -> None:
        with self._lock:
            new_used = self._used + bytes_
            if bytes_ > 0 and new_used * self.overhead > self.limit_bytes:
                self._trip_count += 1
                raise CircuitBreakingError(
                    f"[{self.name}] Data too large, data for [{label}] would be "
                    f"[{new_used}b], wanted [{bytes_}b] on top of [{self._used}b] "
                    f"already used, which is larger than the limit of "
                    f"[{self.limit_bytes}b]",
                    bytes_wanted=bytes_,
                    bytes_used=self._used,
                    bytes_limit=self.limit_bytes,
                    durability="TRANSIENT",
                )
            self._used = new_used
        if self.parent is not None:
            try:
                self.parent.add_estimate_bytes_and_maybe_break(bytes_, label)
            except CircuitBreakingError:
                with self._lock:
                    self._used -= bytes_
                raise

    def add_without_breaking(self, bytes_: int) -> None:
        with self._lock:
            self._used += bytes_
        if self.parent is not None:
            self.parent.add_without_breaking(bytes_)

    def release(self, bytes_: int) -> None:
        self.add_without_breaking(-bytes_)

    def stats(self) -> dict:
        return {
            "limit_size_in_bytes": self.limit_bytes,
            "estimated_size_in_bytes": self._used,
            "overhead": self.overhead,
            "tripped": self._trip_count,
        }


class HierarchyCircuitBreakerService:
    """Parent breaker + named children (ref: HierarchyCircuitBreakerService.java)."""

    def __init__(self, total_limit_bytes: int = 4 << 30):
        self.parent = CircuitBreaker("parent", total_limit_bytes)
        self._breakers: dict[str, CircuitBreaker] = {}
        for name, fraction, overhead in (
            ("request", 0.6, 1.0),
            ("fielddata", 0.4, 1.03),
            ("in_flight_requests", 1.0, 2.0),
        ):
            self._breakers[name] = CircuitBreaker(
                name, int(total_limit_bytes * fraction), overhead, parent=self.parent
            )

    def get_breaker(self, name: str) -> CircuitBreaker:
        return self._breakers[name]

    def stats(self) -> dict:
        out = {name: b.stats() for name, b in self._breakers.items()}
        out["parent"] = self.parent.stats()
        return out

"""Log-bucketed latency histograms for the search flight recorder.

Node-wide distributions per search phase (queue wait, coalesce wait, device
sweep, demux, fetch, ...) plus coalescer batch-size / pad-ratio shapes.
Design constraints:

- **Fixed bucket boundaries** per kind so histograms merge across nodes by
  summing bucket counts (no per-node rescaling; see ``merge_summaries``).
- **Always-on and cheap**: one bisect + three integer bumps under a lock per
  observation. Span recording (tracing.py) is the gated/off-by-default part;
  histograms are the standing node-level distributions.
- Every histogram name must be declared here via ``declare_histogram`` so
  tpulint TPU005 can verify observation sites against the registry and the
  whole set surfaces in ``search_latency_stats()``.
"""

from __future__ import annotations

import re
import threading
import time
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from elasticsearch_tpu.common.settings import knob


def _log_ms_bounds() -> Tuple[float, ...]:
    """Geometric grid ~0.02 ms → ~120 s, two buckets per octave (sqrt-2
    ratio): fine enough that p99 quantization error stays under ~41%."""
    out: List[float] = []
    v = 0.02
    while v <= 130_000.0:
        out.append(round(v, 4))
        v *= 2 ** 0.5
    return tuple(out)


_BOUNDS_BY_KIND: Dict[str, Tuple[float, ...]] = {
    "ms": _log_ms_bounds(),
    # batch sizes: powers of two up to well past the largest qc bucket
    "count": tuple(float(1 << i) for i in range(13)),
    # ratios (pad waste): linear 0..1 in 5% steps
    "ratio": tuple(i / 20 for i in range(1, 21)),
}


class Histogram:
    """One fixed-boundary histogram. Thread-safe."""

    __slots__ = ("name", "kind", "bounds", "_lock", "counts", "n", "total", "vmax")

    def __init__(self, name: str, kind: str):
        self.name = name
        self.kind = kind
        self.bounds = _BOUNDS_BY_KIND[kind]
        self._lock = threading.Lock()
        # one slot per bound plus overflow
        self.counts = [0] * (len(self.bounds) + 1)  # guarded by: _lock
        self.n = 0  # guarded by: _lock
        self.total = 0.0  # guarded by: _lock
        self.vmax = 0.0  # guarded by: _lock

    def record(self, value: float) -> None:
        v = float(value)
        if v < 0.0:
            v = 0.0
        i = bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.n += 1
            self.total += v
            if v > self.vmax:
                self.vmax = v

    def _percentile_locked(self, q: float) -> float:
        """Upper bound of the bucket containing the q-quantile observation."""
        rank = max(1, int(q * self.n + 0.999999))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                return self.bounds[i] if i < len(self.bounds) else self.vmax
        return self.vmax

    def stats(self) -> dict:
        with self._lock:
            if self.n == 0:
                return {"count": 0, "buckets": 0, "mean": 0.0, "p50": 0.0,
                        "p90": 0.0, "p99": 0.0, "max": 0.0}
            return {
                "count": self.n,
                "buckets": sum(1 for c in self.counts if c),
                "mean": round(self.total / self.n, 4),
                "p50": self._percentile_locked(0.50),
                "p90": self._percentile_locked(0.90),
                "p99": self._percentile_locked(0.99),
                "max": round(self.vmax, 4),
            }

    def raw(self) -> dict:
        """Mergeable form: bucket counts against the kind's fixed bounds."""
        with self._lock:
            return {"kind": self.kind, "counts": list(self.counts),
                    "count": self.n, "total": self.total, "max": self.vmax}


def merge_summaries(raws: List[dict]) -> dict:
    """Merge ``Histogram.raw()`` dumps from several nodes into one summary.
    Only valid within one kind — the fixed boundaries make this a plain
    element-wise sum."""
    if not raws:
        return {"count": 0, "buckets": 0, "mean": 0.0, "p50": 0.0, "p90": 0.0,
                "p99": 0.0, "max": 0.0}
    kind = raws[0]["kind"]
    merged = Histogram("merged", kind)
    for r in raws:
        if r["kind"] != kind:
            raise ValueError(f"cannot merge histogram kinds {kind} and {r['kind']}")
        for i, c in enumerate(r["counts"]):
            merged.counts[i] += c
        merged.n += r["count"]
        merged.total += r["total"]
        merged.vmax = max(merged.vmax, r["max"])
    return merged.stats()


# --- registry ---------------------------------------------------------------

_REG_LOCK = threading.Lock()
DECLARED: Dict[str, Tuple[str, str]] = {}  # name -> (kind, doc); import-time only
_LIVE: Dict[str, Histogram] = {}  # guarded by: _REG_LOCK


def declare_histogram(name: str, kind: str, doc: str) -> None:
    if kind not in _BOUNDS_BY_KIND:
        raise ValueError(f"unknown histogram kind {kind!r}")
    DECLARED[name] = (kind, doc)


class UndeclaredHistogramError(KeyError):
    pass


def _hist(name: str) -> Histogram:
    h = _LIVE.get(name)
    if h is not None:
        return h
    if name not in DECLARED:
        raise UndeclaredHistogramError(
            f"histogram {name!r} is not declared in common/metrics.py")
    with _REG_LOCK:
        h = _LIVE.get(name)
        if h is None:
            h = Histogram(name, DECLARED[name][0])
            _LIVE[name] = h
        return h


def observe(name: str, value: float) -> None:
    """Record one observation. ``name`` must be declared (tpulint TPU005
    checks literal call sites against the declarations above)."""
    _hist(name).record(value)


def observe_if_declared(name: str, value: float) -> None:
    """For dynamically composed names (``queue_wait.<pool>``): silently skip
    names outside the registry so ad-hoc test pools don't blow up."""
    if name in DECLARED:
        _hist(name).record(value)


def summary(name: str) -> Optional[dict]:
    """Percentile summary for one declared histogram, or None if undeclared."""
    if name not in DECLARED:
        return None
    return _hist(name).stats()


def search_latency_stats() -> dict:
    """The ``tpu_search_latency`` section of GET /_nodes/stats — the stats()
    owner of every histogram declared below."""
    return {name: _hist(name).stats() for name in DECLARED}


def raw_dump(name: str) -> dict:
    """Mergeable bucket dump for cross-node aggregation (tests, future
    coordinator-side rollups)."""
    return _hist(name).raw()


def reset_for_tests() -> None:
    _SAMPLER_STOP.set()
    with _REG_LOCK:
        _LIVE.clear()
        _COUNTERS.clear()
        _GAUGES.clear()
    with _SAMPLE_LOCK:
        _SAMPLES.clear()


# --- counters & gauges (device telemetry plane, PR 12) -----------------------
# Scalar companions to the histograms above, with the same declare-first
# discipline: counters are monotonic totals (rates come from sampler-ring
# deltas), gauges are point-in-time levels. Gauges declared OUTSIDE this
# registry (common/hbm_ledger.py) must surface in the declaring module's
# stats() function — tpulint TPU005 enforces that, exactly like it ties
# observe() sites to declare_histogram.

DECLARED_COUNTERS: Dict[str, str] = {}  # name -> doc; import-time only
DECLARED_GAUGES: Dict[str, str] = {}    # name -> doc; import-time only
_COUNTERS: Dict[str, float] = {}        # guarded by: _REG_LOCK
_GAUGES: Dict[str, float] = {}          # guarded by: _REG_LOCK


class UndeclaredMetricError(KeyError):
    pass


def declare_counter(name: str, doc: str) -> None:
    DECLARED_COUNTERS[name] = doc


def declare_gauge(name: str, doc: str) -> None:
    DECLARED_GAUGES[name] = doc


def counter_add(name: str, delta: float = 1.0) -> None:
    if name not in DECLARED_COUNTERS:
        raise UndeclaredMetricError(f"counter {name!r} is not declared")
    with _REG_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0.0) + float(delta)


def gauge_set(name: str, value: float) -> None:
    if name not in DECLARED_GAUGES:
        raise UndeclaredMetricError(f"gauge {name!r} is not declared")
    with _REG_LOCK:
        _GAUGES[name] = float(value)


def counter_values() -> Dict[str, float]:
    """Every declared counter (unbumped ones read 0 so scrapes and rate
    computations never see a metric appear out of nowhere)."""
    with _REG_LOCK:
        return {n: _COUNTERS.get(n, 0.0) for n in DECLARED_COUNTERS}


def gauge_values() -> Dict[str, float]:
    with _REG_LOCK:
        return {n: _GAUGES.get(n, 0.0) for n in DECLARED_GAUGES}


# node-level scheduler occupancy, pushed by threadpool/scheduler.py as
# dispatch slots are taken/released; the sampler ring below turns them
# into busy fractions and flush rates without an external scraper
declare_gauge("sched_inflight",
              "device batches currently in flight across scheduler lanes")
declare_gauge("sched_lanes", "live (engine, k) scheduler lanes")
declare_counter("sched_flushes",
                "adaptive-scheduler batch flushes (sampler-ring deltas "
                "give the flush rate)")

# device analytics tier (PR 18), bumped by search/agg_device.py; the
# same counts back the tpu_agg section of GET /_nodes/stats
declare_counter("agg_queries",
                "agg collects served by the device aggregation engine")
declare_counter("agg_device_dispatches",
                "fused agg segment-reduce device dispatches")
declare_counter("agg_host_fallbacks",
                "agg collects that fell back to the host aggregators "
                "(unsupported shape, over budget, or device fault)")
declare_counter("agg_bytes",
                "precomputed agg-column bytes uploaded to HBM (cumulative)")

# quantized kNN tier (PR 19), bumped by parallel/knn.py; the same counts
# back the tpu_knn section of GET /_nodes/stats
declare_counter("knn_queries",
                "kNN queries served by the quantized KnnEngine")
declare_counter("knn_int8_dispatches",
                "int8 first-pass device dispatches (Pallas kernel launches)")
declare_counter("knn_rescore_docs",
                "candidate rows exact-rescored in f32 (cumulative)")
declare_counter("knn_host_fallbacks",
                "(query, partition) results served by the exact host "
                "fallback after a contained device fault")
declare_counter("knn_bytes",
                "quantized kNN shard bytes uploaded to HBM (cumulative)")
declare_counter("knn_uncertified",
                "queries whose int8 superset certificate failed and were "
                "re-served through the exact f32 first pass")

# cross-cluster plane (PR 20): CCS counters bumped by cluster/remote.py
# (the `tpu_ccs` section of GET /_nodes/stats), CCR counters by
# index/ccr.py (the `tpu_ccr` section)
declare_counter("ccs_remote_searches",
                "cross-cluster search fan-out legs dispatched to remotes")
declare_counter("ccs_skipped_clusters",
                "remote clusters degraded to _clusters.skipped "
                "(unreachable with skip_unavailable=true)")
declare_counter("ccs_remote_failures",
                "remote-cluster RPC attempts that failed (transport "
                "error or timeout; retries count separately)")
declare_counter("ccs_remote_retries",
                "remote-cluster RPC retries granted by the retry budget")
declare_counter("ccr_ops_shipped",
                "translog ops applied onto follower indices (cumulative)")
declare_counter("ccr_fetches",
                "CCR fetch_ops batches pulled from leader clusters")
declare_counter("ccr_fetch_retries",
                "CCR fetches re-issued after a failed or corrupt batch")
declare_counter("ccr_checksum_mismatches",
                "CCR op batches whose sha256 failed verification on the "
                "follower (re-fetched, bounded by ES_TPU_REMOTE_RETRIES)")
declare_counter("ccr_polls",
                "follower pull-loop poll rounds executed")


# --- Prometheus text exposition ----------------------------------------------

_PROM_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return "es_tpu_" + _PROM_SANITIZE.sub("_", name)


def _prom_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def scrape_payload() -> dict:
    """One node's full metric state in mergeable form — what the
    /_tpu/metrics fan-out RPC returns per node."""
    return {"counters": counter_values(), "gauges": gauge_values(),
            "histograms": {name: raw_dump(name) for name in DECLARED}}


def render_prometheus(per_node: Dict[str, dict],
                      failures: Sequence[dict] = ()) -> str:
    """Prometheus text exposition over per-node ``scrape_payload`` dumps.

    Every declared counter, gauge, and histogram renders for every live
    node (one ``node`` label per sample; histograms in cumulative-``le``
    bucket form against the kind's fixed bounds). Dead peers degrade to
    ``es_tpu_node_up 0`` rows instead of failing the scrape — the PR 6/11
    partial-answer contract in exposition-format clothing."""
    out: List[str] = []
    nodes = sorted(per_node)
    out.append("# HELP es_tpu_node_up 1 when the node answered the metrics "
               "fan-out, 0 when it degraded to a node_failures entry")
    out.append("# TYPE es_tpu_node_up gauge")
    for n in nodes:
        out.append(f'es_tpu_node_up{{node="{n}"}} 1')
    for f in failures:
        out.append(f'es_tpu_node_up{{node="{f.get("node_id")}"}} 0')
    for name in sorted(DECLARED_COUNTERS):
        m = _prom_name(name) + "_total"
        out.append(f"# HELP {m} {DECLARED_COUNTERS[name]}")
        out.append(f"# TYPE {m} counter")
        for n in nodes:
            v = per_node[n].get("counters", {}).get(name, 0.0)
            out.append(f'{m}{{node="{n}"}} {_prom_num(v)}')
    for name in sorted(DECLARED_GAUGES):
        m = _prom_name(name)
        out.append(f"# HELP {m} {DECLARED_GAUGES[name]}")
        out.append(f"# TYPE {m} gauge")
        for n in nodes:
            v = per_node[n].get("gauges", {}).get(name, 0.0)
            out.append(f'{m}{{node="{n}"}} {_prom_num(v)}')
    for name in sorted(DECLARED):
        kind, doc = DECLARED[name]
        m = _prom_name(name)
        bounds = _BOUNDS_BY_KIND[kind]
        out.append(f"# HELP {m} {doc}")
        out.append(f"# TYPE {m} histogram")
        for n in nodes:
            raw = per_node[n].get("histograms", {}).get(name)
            counts = raw["counts"] if raw else [0] * (len(bounds) + 1)
            acc = 0
            for b, c in zip(bounds, counts):
                acc += c
                out.append(f'{m}_bucket{{node="{n}",le="{b:g}"}} {acc}')
            total_n = raw["count"] if raw else 0
            out.append(f'{m}_bucket{{node="{n}",le="+Inf"}} {total_n}')
            out.append(f'{m}_sum{{node="{n}"}} '
                       f'{_prom_num(raw["total"] if raw else 0.0)}')
            out.append(f'{m}_count{{node="{n}"}} {total_n}')
    return "\n".join(out) + "\n"


# --- periodic sampler ring (ES_TPU_METRICS_SAMPLE_S) -------------------------
# Rates need two points in time. Rather than requiring an external scraper,
# an optional background thread snapshots every declared counter/gauge (plus
# any registered provider sections, e.g. the scheduler's per-lane inflight
# occupancy) into a bounded ring served at GET /_tpu/metrics/history.

_SAMPLE_LOCK = threading.Lock()
_SAMPLES: List[dict] = []                                # guarded by: _SAMPLE_LOCK
_SAMPLE_PROVIDERS: Dict[str, Callable[[], dict]] = {}    # guarded by: _SAMPLE_LOCK
_SAMPLER_THREAD: Optional[threading.Thread] = None       # guarded by: _SAMPLE_LOCK
_SAMPLER_STOP = threading.Event()


def register_sample_provider(name: str, fn: Callable[[], dict]) -> None:
    """Attach a named section to every sample (idempotent per name)."""
    with _SAMPLE_LOCK:
        _SAMPLE_PROVIDERS[name] = fn


def sample_now() -> dict:
    """Take one snapshot and append it to the ring (also the sampler
    thread's tick body — callable directly so tests and bench dryruns
    don't need a live thread)."""
    with _SAMPLE_LOCK:
        providers = dict(_SAMPLE_PROVIDERS)
    s: dict = {"ts": time.time(), "counters": counter_values(),
               "gauges": gauge_values()}
    for name, fn in sorted(providers.items()):
        try:
            s[name] = fn()
        except Exception:   # noqa: BLE001 — a broken provider must not
            s[name] = None  # kill the sampler
    cap = max(1, int(knob("ES_TPU_METRICS_HISTORY")))
    with _SAMPLE_LOCK:
        _SAMPLES.append(s)
        del _SAMPLES[: max(0, len(_SAMPLES) - cap)]
    return s


def metrics_history() -> List[dict]:
    with _SAMPLE_LOCK:
        return list(_SAMPLES)


def _sampler_loop() -> None:
    global _SAMPLER_THREAD
    while True:
        period = float(knob("ES_TPU_METRICS_SAMPLE_S"))
        if period <= 0 or _SAMPLER_STOP.wait(period):
            break
        sample_now()
    with _SAMPLE_LOCK:
        _SAMPLER_THREAD = None


def maybe_start_sampler() -> bool:
    """Start the background sampler when ES_TPU_METRICS_SAMPLE_S > 0.
    Idempotent; returns whether a sampler is (now) running. The knob is
    re-read every tick, so setting it to 0 retires the thread."""
    global _SAMPLER_THREAD
    if float(knob("ES_TPU_METRICS_SAMPLE_S")) <= 0:
        return False
    with _SAMPLE_LOCK:
        if _SAMPLER_THREAD is not None:
            return True
        _SAMPLER_STOP.clear()
        _SAMPLER_THREAD = threading.Thread(
            target=_sampler_loop, daemon=True, name="es-tpu-metrics-sampler")
        _SAMPLER_THREAD.start()
    return True


# --- phase histograms (the flight recorder's standing distributions) --------
# queue_wait.* names are composed dynamically in threadpool/pool.py via
# observe_if_declared(f"queue_wait.{pool}"), one per named pool.
declare_histogram("queue_wait.search", "ms", "queued->started wait, search pool")
declare_histogram("queue_wait.write", "ms", "queued->started wait, write pool")
declare_histogram("queue_wait.get", "ms", "queued->started wait, get pool")
declare_histogram("queue_wait.management", "ms", "queued->started wait, management pool")
declare_histogram("queue_wait.snapshot", "ms", "queued->started wait, snapshot pool")
declare_histogram("coalesce_wait", "ms", "wait inside DispatchCoalescer (leader fill window + follower completion wait)")
declare_histogram("device", "ms", "one device dispatch (coalesced batch or direct search_bool/search_many)")
declare_histogram("demux", "ms", "per-request hit extraction from a batched device result")
declare_histogram("fetch", "ms", "fetch phase (doc _source materialization)")
declare_histogram("query", "ms", "shard query phase end-to-end (data node side)")
declare_histogram("merge", "ms", "coordinator reduce of shard results")
declare_histogram("rest_total", "ms", "whole _search request at the REST layer")
declare_histogram("coalesce_batch_size", "count", "queries per coalesced device batch")
declare_histogram("coalesce_pad_ratio", "ratio", "fraction of a padded device batch that is qc-quantization waste")
# continuous-batching scheduler (PR 10); sched_tier_wait.* names are
# composed dynamically in threadpool/scheduler.py via
# observe_if_declared(f"sched_tier_wait.{tier}"), one per SLA tier.
declare_histogram("sched_bucket_size", "count", "bucket (padded batch shape) chosen per adaptive-scheduler flush")
declare_histogram("sched_queue_depth", "count", "lane queue depth at each adaptive-scheduler flush")
# device bitset intersection for bool queries (PR 16)
declare_histogram("bitset_blocks_skipped", "count", "2048-doc chunks skipped (all-zero intersected match set) per bool query dispatch")
declare_histogram("bitset_block_occupancy", "ratio", "fraction of 2048-doc chunks with surviving docs after clause intersection, per bool query")
# eager sparse impact slices for cold terms (PR 17)
declare_histogram("sparse_slice_width", "count", "padded width (postings) of the ladder rung chosen per eager sparse cold-term slice build")
# device analytics tier (PR 18)
declare_histogram("agg_batch_size", "count", "agg collects fused into one device segment-reduce dispatch (pre-padding)")

declare_histogram("knn_candidates_per_query", "count", "first-pass candidates kept per (query, partition) before the exact kNN rescore")
declare_histogram("knn_nprobe_ratio", "ratio", "fraction of IVF centroids probed per kNN first pass (1.0 = exact/no pruning)")
declare_histogram("sched_tier_wait.interactive", "ms", "scheduler wait, interactive tier (enqueue -> batch results ready)")
declare_histogram("sched_tier_wait.bulk", "ms", "scheduler wait, bulk tier (enqueue -> batch results ready)")
# cluster task plane (PR 11); task_duration.* names are composed
# dynamically in tasks/task_manager.py via
# observe_if_declared(f"task_duration.{action_family(...)}"), one per
# action family.
declare_histogram("task_duration.search", "ms", "task lifetime, search-family actions (register -> unregister)")
declare_histogram("task_duration.scroll", "ms", "task lifetime, scroll-family actions")
declare_histogram("task_duration.msearch", "ms", "task lifetime, msearch coordinator actions")
declare_histogram("task_duration.bulk", "ms", "task lifetime, bulk-family actions")
declare_histogram("task_duration.async_search", "ms", "task lifetime, async-search actions")
declare_histogram("task_duration.reindex", "ms", "task lifetime, reindex actions")

"""Node-wide shard-relocation counters (PR 14).

Same module-level pattern as ``common/durability.py``: one locked dict
feeding the ``tpu_relocation`` section of GET /_nodes/stats, so a rolling
maintenance window is auditable with a single GET — how many moves
committed, how many cancelled, and what the warm HBM handoff actually
primed (ref: the reference spreads the analogous signals across
_cat/recovery and allocation explain; here the TPU twist — compile-cache
priming ahead of shard-started — gets first-class counters).
"""

from __future__ import annotations

import threading
from typing import Dict

_RELOC_LOCK = threading.Lock()
_RELOC_COUNTERS: Dict[str, int] = {  # guarded by: _RELOC_LOCK
    "moves": 0,           # relocations committed (target started, source gone)
    "cancels": 0,         # relocations cancelled (target failed/died; source
                          # reverted to STARTED, still serving)
    "warm_handoffs": 0,    # targets that completed the warm HBM handoff
    "warm_ms": 0,          # wall ms spent warming (engine build + upload +
                           # qc-ladder priming) before shard-started
    "shapes_primed": 0,    # dispatch shapes primed via extend_qc_sizes
    "fields_warmed": 0,    # per-field engines built+uploaded ahead of serving
    "warm_failures": 0,    # warm handoffs that errored (relocation proceeds
                           # cold — warming is best-effort)
    "sparse_prewarms": 0,  # cold-term sparse slices rebuilt on the target
                           # from the source's hot term list
}


def count(key: str, n: int = 1) -> None:
    with _RELOC_LOCK:
        _RELOC_COUNTERS[key] += n


def relocation_stats() -> dict:
    """The ``tpu_relocation`` section of GET /_nodes/stats."""
    with _RELOC_LOCK:
        return dict(_RELOC_COUNTERS)


def reset_for_tests() -> Dict[str, int]:
    """Zero every counter and return the previous values (test isolation)."""
    with _RELOC_LOCK:
        prev = dict(_RELOC_COUNTERS)
        for k in _RELOC_COUNTERS:
            _RELOC_COUNTERS[k] = 0
    return prev

"""Typed, validated, dynamically-updatable settings.

Re-designs the reference's Setting/Settings/ClusterSettings trio
(ref: common/settings/Setting.java, ClusterSettings.java,
IndexScopedSettings.java) as plain Python: a `Setting` is a typed key with a
default, parser, validator, scope and a `dynamic` flag; `Settings` is an
immutable key->raw-value map with typed reads; `ClusterSettings` is the
registry that validates updates and notifies subscribers on dynamic changes.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Any, Callable, Generic, Iterable, Mapping, TypeVar

from elasticsearch_tpu.common.errors import IllegalArgumentError

T = TypeVar("T")

_TIME_RE = re.compile(r"^(-?\d+(?:\.\d+)?)(nanos|micros|ms|s|m|h|d)$")
_BYTES_RE = re.compile(r"^(-?\d+(?:\.\d+)?)(b|kb|mb|gb|tb|pb)?$", re.IGNORECASE)

_TIME_FACTORS = {"nanos": 1e-9, "micros": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
_BYTE_FACTORS = {None: 1, "b": 1, "kb": 1 << 10, "mb": 1 << 20, "gb": 1 << 30, "tb": 1 << 40, "pb": 1 << 50}


def parse_time_value(value: Any) -> float:
    """'30s' / '500ms' / number -> seconds."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _TIME_RE.match(str(value).strip())
    if not m:
        raise IllegalArgumentError(f"failed to parse time value [{value}]")
    return float(m.group(1)) * _TIME_FACTORS[m.group(2)]


def parse_bytes_value(value: Any) -> int:
    """'512mb' / '1gb' / number -> bytes."""
    if isinstance(value, (int, float)):
        return int(value)
    m = _BYTES_RE.match(str(value).strip())
    if not m:
        raise IllegalArgumentError(f"failed to parse byte size value [{value}]")
    unit = m.group(2).lower() if m.group(2) else None
    return int(float(m.group(1)) * _BYTE_FACTORS[unit])


def _parse_bool(value: Any) -> bool:
    if isinstance(value, bool):
        return value
    s = str(value).strip().lower()
    if s == "true":
        return True
    if s == "false":
        return False
    raise IllegalArgumentError(f"failed to parse boolean value [{value}], expected [true] or [false]")


class Setting(Generic[T]):
    """A typed setting key. Scope is 'node', 'cluster' or 'index'."""

    def __init__(
        self,
        key: str,
        default: T | Callable[["Settings"], T],
        parser: Callable[[Any], T],
        *,
        scope: str = "cluster",
        dynamic: bool = False,
        validator: Callable[[T], None] | None = None,
    ):
        self.key = key
        self._default = default
        self.parser = parser
        self.scope = scope
        self.dynamic = dynamic
        self.validator = validator

    def default(self, settings: "Settings") -> T:
        if callable(self._default):
            return self._default(settings)
        return self._default

    def get(self, settings: "Settings") -> T:
        raw = settings.raw(self.key)
        if raw is None:
            return self.default(settings)
        value = self.parser(raw)
        if self.validator is not None:
            self.validator(value)
        return value

    # -- constructors mirroring the reference's factory methods --

    @staticmethod
    def bool_setting(key: str, default: bool, **kw) -> "Setting[bool]":
        return Setting(key, default, _parse_bool, **kw)

    @staticmethod
    def int_setting(key: str, default: int, min_value: int | None = None, **kw) -> "Setting[int]":
        def parse(v):
            i = int(v)
            if min_value is not None and i < min_value:
                raise IllegalArgumentError(f"failed to parse value [{v}] for setting [{key}] must be >= {min_value}")
            return i

        return Setting(key, default, parse, **kw)

    @staticmethod
    def float_setting(key: str, default: float, **kw) -> "Setting[float]":
        return Setting(key, default, float, **kw)

    @staticmethod
    def str_setting(key: str, default: str, **kw) -> "Setting[str]":
        return Setting(key, default, str, **kw)

    @staticmethod
    def time_setting(key: str, default: float | str, **kw) -> "Setting[float]":
        dflt = parse_time_value(default) if isinstance(default, str) else default
        return Setting(key, dflt, parse_time_value, **kw)

    @staticmethod
    def bytes_setting(key: str, default: int | str, **kw) -> "Setting[int]":
        dflt = parse_bytes_value(default) if isinstance(default, str) else default
        return Setting(key, dflt, parse_bytes_value, **kw)


class Settings(Mapping[str, Any]):
    """Immutable flat key->value map. Nested dicts are flattened with dots."""

    def __init__(self, values: Mapping[str, Any] | None = None):
        self._values: dict[str, Any] = {}
        if values:
            self._flatten("", values)

    def _flatten(self, prefix: str, values: Mapping[str, Any]) -> None:
        for k, v in values.items():
            key = f"{prefix}{k}"
            if isinstance(v, Mapping):
                self._flatten(f"{key}.", v)
            else:
                self._values[key] = v

    EMPTY: "Settings"

    def raw(self, key: str, default: Any = None) -> Any:
        return self._values.get(key, default)

    def get(self, setting: "Setting[T] | str", default: Any = None) -> Any:
        if isinstance(setting, Setting):
            return setting.get(self)
        return self._values.get(setting, default)

    def with_updates(self, updates: Mapping[str, Any]) -> "Settings":
        merged = dict(self._values)
        flat = Settings(updates)
        for k, v in flat._values.items():
            if v is None:
                merged.pop(k, None)  # null value resets to default, as in the reference API
            else:
                merged[k] = v
        out = Settings()
        out._values = merged
        return out

    def filtered_by_prefix(self, prefix: str) -> "Settings":
        out = Settings()
        out._values = {k: v for k, v in self._values.items() if k.startswith(prefix)}
        return out

    def as_dict(self) -> dict[str, Any]:
        return dict(self._values)

    def as_nested_dict(self) -> dict[str, Any]:
        nested: dict[str, Any] = {}
        for key, value in sorted(self._values.items()):
            parts = key.split(".")
            node = nested
            for p in parts[:-1]:
                node = node.setdefault(p, {})
                if not isinstance(node, dict):
                    break
            else:
                node[parts[-1]] = value
        return nested

    def __getitem__(self, key: str) -> Any:
        return self._values[key]

    def __iter__(self):
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def __repr__(self) -> str:
        return f"Settings({self._values!r})"


Settings.EMPTY = Settings()


# ---------------------------------------------------------------------------
# ES_TPU_* environment knob registry (PR 7)
#
# Every process-level tuning knob the TPU serving stack reads from the
# environment is DECLARED here once — name, type, default, one-line doc —
# and read through `knob()`. tpulint rule TPU003 rejects direct
# `os.environ` reads of ES_TPU_* anywhere else in the package and flags
# `knob()` calls whose literal name is not declared below (misspellings
# die at lint time, not as silently-inert knobs in production).
# `effective_knobs()` renders the live values as the `tpu_settings`
# section of GET /_nodes/stats so a running node can be audited, and
# `python -m tools.tpulint --knob-table` generates the README table.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class EnvKnob:
    """One declared ES_TPU_* environment knob."""

    name: str
    type: str          # 'int' | 'float' | 'str' | 'flag' ('1' == on)
    default: Any       # None means "computed by the consumer"
    doc: str


ENV_KNOBS: dict[str, EnvKnob] = {}

_KNOB_PARSERS: dict[str, Callable[[str], Any]] = {
    "int": int,
    "float": float,
    "str": str,
    # the pre-registry readers treated exactly "1" as on; keep that contract
    "flag": lambda raw: raw == "1",
}

_UNSET = object()


class UndeclaredKnobError(KeyError):
    """An ES_TPU_* knob was read without being declared in the registry."""


def declare_knob(name: str, type: str, default: Any, doc: str) -> EnvKnob:
    if type not in _KNOB_PARSERS:
        raise IllegalArgumentError(f"unknown knob type [{type}] for [{name}]")
    k = EnvKnob(name, type, default, doc)
    ENV_KNOBS[name] = k
    return k


def knob(name: str, default: Any = _UNSET) -> Any:
    """Current value of a declared knob: the parsed environment value when
    set, else `default` (usually the declared one; pass `default=` for
    consumer-computed defaults like the pool sizes). Reads the environment
    per call — tests toggle knobs mid-process — and falls back to the
    default on an unparseable value, matching the lenient pre-registry
    readers (a typo'd knob must not take a node down)."""
    decl = ENV_KNOBS.get(name)
    if decl is None:
        raise UndeclaredKnobError(
            f"ES_TPU knob [{name}] is not declared in "
            f"common/settings.py — declare_knob() it")
    fallback = decl.default if default is _UNSET else default
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return fallback
    try:
        return _KNOB_PARSERS[decl.type](raw)
    except (TypeError, ValueError):
        return fallback


def effective_knobs() -> dict[str, dict]:
    """{name: {value, default, source}} for the `tpu_settings` section of
    GET /_nodes/stats — `source` says whether the environment or the
    declared default is in effect right now."""
    out: dict[str, dict] = {}
    for name in sorted(ENV_KNOBS):
        decl = ENV_KNOBS[name]
        raw = os.environ.get(name)
        out[name] = {
            "value": knob(name),
            "default": decl.default,
            "type": decl.type,
            "source": "env" if raw not in (None, "") else "default",
        }
    return out


declare_knob("ES_TPU_PLUGINS", "str", "",
             "Comma-separated plugin modules exposing install(node), "
             "loaded at node startup")
declare_knob("ES_TPU_FAULTS", "str", "",
             "Fault-injection spec `site[#part]:mode[@nth][xcount][=arg]"
             "[~prob];…` installed at import (common/faults.py)")
declare_knob("ES_TPU_FAULTS_SEED", "int", 0,
             "Seed for probabilistic (~prob) fault clauses")
declare_knob("ES_TPU_HEALTH_TRIP_N", "int", 3,
             "Consecutive device faults that open an engine's circuit")
declare_knob("ES_TPU_HEALTH_BACKOFF_MS", "int", 1000,
             "Base backoff before a half-open probe (doubles per reopen, "
             "capped at 32x)")
declare_knob("ES_TPU_COALESCE_US", "float", 2000.0,
             "Dispatch-coalescer flush window in microseconds "
             "(0 disables coalescing)")
declare_knob("ES_TPU_TURBO_HBM", "int", 6 << 30,
             "HBM budget in bytes for TurboBM25's int8 column cache")
declare_knob("ES_TPU_TURBO_COLD_DF", "int", None,
             "Doc-frequency threshold below which terms stay cold "
             "(host-rescored); default: parallel/turbo.py COLD_DF")
declare_knob("ES_TPU_TURBO_MESH", "int", None,
             "Max devices for the fused multi-partition Turbo mesh "
             "(default all visible; 0 disables fusion)")
declare_knob("ES_TPU_FORCE_TURBO", "flag", False,
             "'1' forces Turbo eligibility off-TPU (interpret-mode "
             "differential tests)")
declare_knob("ES_TPU_BITSET", "flag", True,
             "Packed-uint32 bitset intersection for bool queries: clause "
             "match sets AND/AND-NOT blockwise on device and the sweep "
             "skips all-zero blocks (0 = dense coverage-matmul sweep)")
declare_knob("ES_TPU_BITSET_HOST_DF", "int", 512,
             "Bool queries whose rarest required clause has df below this "
             "route to the galloping host intersection instead of the "
             "device bitset sweep (0 disables the fallback)")
declare_knob("ES_TPU_SPARSE", "flag", True,
             "Eager sparse impact slices: cold (df < COLD_DF) terms score "
             "on device via the sparse_gather kernel instead of the host "
             "cold path (0 restores the host fork for A/B)")
declare_knob("ES_TPU_SPARSE_WIDTHS", "str", "1024,4096,16384",
             "Comma-separated slice-width ladder for eager sparse cold-"
             "term slices (each rung rounds up to a 1024-posting granule; "
             "a term uses the smallest rung >= its df)")
declare_knob("ES_TPU_DISABLE_SHARD_SERVING", "flag", False,
             "'1' disables the shard-level serving fast path on data nodes")
declare_knob("ES_TPU_SEARCH_SHARD_RETRIES", "int", 3,
             "Max replica-failover retries per shard before it counts "
             "failed")
declare_knob("ES_TPU_RPC_TIMEOUT_MS", "int", 0,
             "Floor for the per-RPC deadline in ms (0 = request budget "
             "only)")
declare_knob("ES_TPU_TCP_TIMEOUT_S", "float", 30.0,
             "Socket timeout for TcpNodeChannels remote RPCs, seconds")
# thread-pool shape overrides (threadpool/pool.py computes the defaults
# from the cpu count) — declared literally, one per pool, so tpulint's
# static declared-name check sees every legal ES_TPU_POOL_* spelling
declare_knob("ES_TPU_POOL_SEARCH_SIZE", "int", None,
             "Worker count for the search pool (default 3*cpus/2+1)")
declare_knob("ES_TPU_POOL_SEARCH_QUEUE", "int", None,
             "Queue capacity for the search pool (default 1000)")
declare_knob("ES_TPU_POOL_WRITE_SIZE", "int", None,
             "Worker count for the write pool (default cpus)")
declare_knob("ES_TPU_POOL_WRITE_QUEUE", "int", None,
             "Queue capacity for the write pool (default 10000)")
declare_knob("ES_TPU_POOL_GET_SIZE", "int", None,
             "Worker count for the get pool (default cpus)")
declare_knob("ES_TPU_POOL_GET_QUEUE", "int", None,
             "Queue capacity for the get pool (default 1000)")
declare_knob("ES_TPU_POOL_MANAGEMENT_SIZE", "int", None,
             "Worker count for the management pool (default 2)")
declare_knob("ES_TPU_POOL_MANAGEMENT_QUEUE", "int", None,
             "Queue capacity for the management pool (default 512)")
declare_knob("ES_TPU_POOL_SNAPSHOT_SIZE", "int", None,
             "Worker count for the snapshot pool (default 1)")
declare_knob("ES_TPU_POOL_SNAPSHOT_QUEUE", "int", None,
             "Queue capacity for the snapshot pool (default 256)")
# write-path durability / resilience (PR 8)
declare_knob("ES_TPU_TRANSLOG_SYNC_OPS", "int", 128,
             "Async-durability exposure bound: fsync the translog every N "
             "appended ops (request durability syncs every op)")
declare_knob("ES_TPU_BULK_RETRIES", "int", 20,
             "Coordinator bulk retry attempts per shard before the items "
             "fail with unavailable_shards_exception")
declare_knob("ES_TPU_BULK_RETRY_MS", "int", 100,
             "Delay between coordinator bulk retries, ms")
declare_knob("ES_TPU_BULK_TIMEOUT_MS", "int", 0,
             "Overall coordinator bulk deadline in ms (0 = retries bound "
             "the wait on their own)")
declare_knob("ES_TPU_RECOVERY_RETRIES", "int", 3,
             "Peer-recovery attempts per replica before it is reported "
             "shard-failed to the master")
declare_knob("ES_TPU_RECOVERY_BACKOFF_MS", "int", 50,
             "Base backoff between peer-recovery retries, ms (doubles per "
             "attempt)")
# rolling maintenance plane (PR 14)
declare_knob("ES_TPU_RELOC_WARM", "flag", True,
             "Warm HBM handoff on shard relocation: the target builds its "
             "per-field engines, uploads columns, and primes the compile "
             "cache with the source's hot shapes BEFORE reporting "
             "shard-started (0 = relocate cold)")
declare_knob("ES_TPU_DELAYED_ALLOC_MS", "int", 0,
             "Delayed allocation window after node-left, ms: replica "
             "replacements stay UNASSIGNED this long so a bounced node "
             "can rejoin and recover its own copies (0 = reallocate "
             "immediately; index.unassigned.node_left.delayed_timeout "
             "analog)")
# search flight recorder (PR 9)
declare_knob("ES_TPU_TRACE_SAMPLE", "int", 0,
             "Trace every Nth search even without profile=true or slowlog "
             "thresholds (0 = off; sampled traces land in the trace ring)")
declare_knob("ES_TPU_TRACE_RING", "int", 64,
             "Capacity of the in-memory flight-recorder ring of completed "
             "traces")
declare_knob("ES_TPU_SLOWLOG_RING", "int", 128,
             "Capacity of the in-memory search slowlog ring served at "
             "GET /_tpu/slowlog")
# continuous-batching dispatch scheduler (PR 10)
declare_knob("ES_TPU_SCHED_MODE", "str", "adaptive",
             "Serving dispatch path: 'adaptive' (continuous-batching "
             "scheduler) or 'legacy' (fixed-window coalescer)")
declare_knob("ES_TPU_SCHED_BUCKETS", "str", "1,4,16,64,256",
             "Padded batch-size ladder for the adaptive scheduler "
             "(comma-separated, each bucket is one compiled shape); when "
             "the env var is unset the ladder autotunes from the observed "
             "sched_queue_depth / coalesce_pad_ratio histograms")
declare_knob("ES_TPU_SCHED_INTERACTIVE_US", "float", 1000.0,
             "Max scheduler queue wait for interactive-tier queries, "
             "microseconds")
declare_knob("ES_TPU_SCHED_BULK_US", "float", 8000.0,
             "Max scheduler queue wait for bulk-tier queries, "
             "microseconds")
declare_knob("ES_TPU_SCHED_INFLIGHT", "int", 2,
             "In-flight device batches per scheduler lane (2 = "
             "double-buffered: demux of batch N overlaps the sweep of "
             "N+1)")
# cluster task plane (PR 11)
declare_knob("ES_TPU_TASK_BAN_TTL_S", "float", 300.0,
             "Lifetime of a cancellation ban entry: racing child "
             "registrations for a banned parent are cancelled on arrival "
             "until the ban expires")
declare_knob("ES_TPU_TASK_FANOUT_TIMEOUT_MS", "int", 2000,
             "Per-peer budget for _tasks / hot_threads / ban fan-out RPCs "
             "(a dead peer degrades to node_failures instead of hanging "
             "the coordinator)")
declare_knob("ES_TPU_HOT_THREADS_INTERVAL_MS", "int", 15,
             "Sleep between the two stack samples of a hot_threads "
             "capture (threads idle across both samples are filtered)")
# device telemetry plane (PR 12)
declare_knob("ES_TPU_METRICS_SAMPLE_S", "float", 0.0,
             "Period of the background metrics sampler in seconds: every "
             "tick snapshots counters/gauges into the history ring served "
             "at GET /_tpu/metrics/history (0 = sampler off)")
declare_knob("ES_TPU_METRICS_HISTORY", "int", 120,
             "Capacity of the in-memory metrics-sample ring (oldest "
             "samples drop first)")
# overload control plane (PR 13)
declare_knob("ES_TPU_OVERLOAD_YELLOW", "float", 0.7,
             "Folded pressure score at which the node enters YELLOW "
             "(bulk-tier requests shed with 429 + Retry-After)")
declare_knob("ES_TPU_OVERLOAD_RED", "float", 0.9,
             "Folded pressure score at which the node enters RED "
             "(interactive requests shed too)")
declare_knob("ES_TPU_OVERLOAD_HYSTERESIS_MS", "int", 2000,
             "Pressure-level downgrade dwell: the raw level must stay "
             "below the current one this long before the node steps down "
             "(upgrades apply immediately)")
declare_knob("ES_TPU_RETRY_BUDGET_RATIO", "float", 0.2,
             "Retry tokens refilled per successful request into the "
             "node-wide retry budget (0 disables the budget: retries are "
             "unbounded as before)")
declare_knob("ES_TPU_RETRY_BUDGET_CAP", "int", 32,
             "Retry-budget bucket capacity (and initial fill): each "
             "failover / replication / bulk / recovery / poison-solo "
             "retry spends one token")
# data integrity plane (PR 15)
declare_knob("ES_TPU_CHECK_ON_STARTUP", "flag", False,
             "Re-verify every committed segment checksum before a shard "
             "copy reports started (ref: index.shard.check_on_startup) — "
             "corruption found here fails the copy instead of serving it")
declare_knob("ES_TPU_INTEGRITY_SCRUB_S", "float", 0.0,
             "HBM scrub period in seconds (0 = off): re-download one "
             "device-resident region per tick on the management pool, "
             "re-hash against the host-side fingerprint, re-upload on "
             "mismatch; skipped while the overload level is not GREEN")
# device analytics tier (PR 18)
declare_knob("ES_TPU_AGG", "flag", True,
             "Route terms/histogram/date_histogram collects (and their "
             "metric sub-aggs) through the device aggregation engine on "
             "leaves above the size floor; off = the exact host "
             "aggregators serve everything (A/B reference path)")
declare_knob("ES_TPU_AGG_HBM_FRAC", "float", 0.25,
             "Cap on precomputed agg-column HBM as a fraction of "
             "ES_TPU_TURBO_HBM: layouts that would exceed it are refused "
             "and their collects stay on host")
# quantized kNN tier (PR 19)
declare_knob("ES_TPU_KNN_INT8", "flag", True,
             "Serve KnnEngine first passes from the int8-quantized shards "
             "(exact f32 rescore restores bit-identity); off = the f32 "
             "brute-force path verbatim (A/B reference)")
declare_knob("ES_TPU_KNN_NPROBE", "int", 0,
             "IVF coarse-pruning probe count for KnnEngine first passes: "
             "score only docs assigned to the nprobe nearest k-means "
             "centroids (0 = exact, no pruning)")
declare_knob("ES_TPU_KNN_RESCORE_MULT", "int", 4,
             "Candidate over-fetch factor for the kNN exact rescore: the "
             "first pass keeps k*mult candidates per (query, partition) "
             "before the f32 rescore picks the final k")
declare_knob("ES_TPU_FORCE_KNN", "flag", False,
             "'1' forces KnnEngine serving eligibility off-TPU "
             "(interpret-mode differential tests)")
# cross-cluster plane (PR 20)
declare_knob("ES_TPU_REMOTE_RETRIES", "int", 1,
             "Extra attempts per remote-cluster RPC after the first "
             "(rotating across the remote's seed nodes), each spending a "
             "token from the PR-13 retry budget")
declare_knob("ES_TPU_REMOTE_BACKOFF_MS", "int", 25,
             "Delay between remote-cluster RPC attempts, ms")
declare_knob("ES_TPU_CCR_POLL_MS", "int", 100,
             "Follower-index pull-loop poll interval, ms (0 = no "
             "background thread; tests and bench pump poll_once() "
             "deterministically)")
declare_knob("ES_TPU_CCR_BATCH_OPS", "int", 512,
             "Max translog ops per CCR fetch batch (one sha256-verified "
             "wire payload)")


class ClusterSettings:
    """Registry of known settings + dynamic-update subscription.

    Ref: common/settings/AbstractScopedSettings.java — validates that updates
    only touch registered dynamic settings and notifies consumers.
    """

    def __init__(self, initial: Settings, registered: Iterable[Setting] | None = None):
        self._settings = initial
        self._registered: dict[str, Setting] = {}
        self._consumers: list[tuple[Setting, Callable[[Any], None]]] = []
        for s in registered or ():
            self.register(s)

    def register(self, setting: Setting) -> None:
        self._registered[setting.key] = setting

    @property
    def settings(self) -> Settings:
        return self._settings

    def get(self, setting: Setting[T]) -> T:
        return setting.get(self._settings)

    def add_settings_update_consumer(self, setting: Setting[T], consumer: Callable[[T], None]) -> None:
        self._consumers.append((setting, consumer))

    def apply(self, updates: Mapping[str, Any]) -> Settings:
        """Validate + apply updates; notify consumers whose value changed."""
        flat = Settings(updates)
        for key in flat:
            reg = self._registered.get(key)
            if reg is None:
                raise IllegalArgumentError(f"transient setting [{key}], not recognized")
            if not reg.dynamic:
                raise IllegalArgumentError(f"final {reg.scope} setting [{key}], not updateable")
            if flat.raw(key) is not None:
                reg.parser(flat.raw(key))  # validate before committing
        old = self._settings
        self._settings = old.with_updates(updates)
        for setting, consumer in self._consumers:
            new_val = setting.get(self._settings)
            if setting.get(old) != new_val:
                consumer(new_val)
        return self._settings

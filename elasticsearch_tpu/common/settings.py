"""Typed, validated, dynamically-updatable settings.

Re-designs the reference's Setting/Settings/ClusterSettings trio
(ref: common/settings/Setting.java, ClusterSettings.java,
IndexScopedSettings.java) as plain Python: a `Setting` is a typed key with a
default, parser, validator, scope and a `dynamic` flag; `Settings` is an
immutable key->raw-value map with typed reads; `ClusterSettings` is the
registry that validates updates and notifies subscribers on dynamic changes.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Generic, Iterable, Mapping, TypeVar

from elasticsearch_tpu.common.errors import IllegalArgumentError

T = TypeVar("T")

_TIME_RE = re.compile(r"^(-?\d+(?:\.\d+)?)(nanos|micros|ms|s|m|h|d)$")
_BYTES_RE = re.compile(r"^(-?\d+(?:\.\d+)?)(b|kb|mb|gb|tb|pb)?$", re.IGNORECASE)

_TIME_FACTORS = {"nanos": 1e-9, "micros": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
_BYTE_FACTORS = {None: 1, "b": 1, "kb": 1 << 10, "mb": 1 << 20, "gb": 1 << 30, "tb": 1 << 40, "pb": 1 << 50}


def parse_time_value(value: Any) -> float:
    """'30s' / '500ms' / number -> seconds."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _TIME_RE.match(str(value).strip())
    if not m:
        raise IllegalArgumentError(f"failed to parse time value [{value}]")
    return float(m.group(1)) * _TIME_FACTORS[m.group(2)]


def parse_bytes_value(value: Any) -> int:
    """'512mb' / '1gb' / number -> bytes."""
    if isinstance(value, (int, float)):
        return int(value)
    m = _BYTES_RE.match(str(value).strip())
    if not m:
        raise IllegalArgumentError(f"failed to parse byte size value [{value}]")
    unit = m.group(2).lower() if m.group(2) else None
    return int(float(m.group(1)) * _BYTE_FACTORS[unit])


def _parse_bool(value: Any) -> bool:
    if isinstance(value, bool):
        return value
    s = str(value).strip().lower()
    if s == "true":
        return True
    if s == "false":
        return False
    raise IllegalArgumentError(f"failed to parse boolean value [{value}], expected [true] or [false]")


class Setting(Generic[T]):
    """A typed setting key. Scope is 'node', 'cluster' or 'index'."""

    def __init__(
        self,
        key: str,
        default: T | Callable[["Settings"], T],
        parser: Callable[[Any], T],
        *,
        scope: str = "cluster",
        dynamic: bool = False,
        validator: Callable[[T], None] | None = None,
    ):
        self.key = key
        self._default = default
        self.parser = parser
        self.scope = scope
        self.dynamic = dynamic
        self.validator = validator

    def default(self, settings: "Settings") -> T:
        if callable(self._default):
            return self._default(settings)
        return self._default

    def get(self, settings: "Settings") -> T:
        raw = settings.raw(self.key)
        if raw is None:
            return self.default(settings)
        value = self.parser(raw)
        if self.validator is not None:
            self.validator(value)
        return value

    # -- constructors mirroring the reference's factory methods --

    @staticmethod
    def bool_setting(key: str, default: bool, **kw) -> "Setting[bool]":
        return Setting(key, default, _parse_bool, **kw)

    @staticmethod
    def int_setting(key: str, default: int, min_value: int | None = None, **kw) -> "Setting[int]":
        def parse(v):
            i = int(v)
            if min_value is not None and i < min_value:
                raise IllegalArgumentError(f"failed to parse value [{v}] for setting [{key}] must be >= {min_value}")
            return i

        return Setting(key, default, parse, **kw)

    @staticmethod
    def float_setting(key: str, default: float, **kw) -> "Setting[float]":
        return Setting(key, default, float, **kw)

    @staticmethod
    def str_setting(key: str, default: str, **kw) -> "Setting[str]":
        return Setting(key, default, str, **kw)

    @staticmethod
    def time_setting(key: str, default: float | str, **kw) -> "Setting[float]":
        dflt = parse_time_value(default) if isinstance(default, str) else default
        return Setting(key, dflt, parse_time_value, **kw)

    @staticmethod
    def bytes_setting(key: str, default: int | str, **kw) -> "Setting[int]":
        dflt = parse_bytes_value(default) if isinstance(default, str) else default
        return Setting(key, dflt, parse_bytes_value, **kw)


class Settings(Mapping[str, Any]):
    """Immutable flat key->value map. Nested dicts are flattened with dots."""

    def __init__(self, values: Mapping[str, Any] | None = None):
        self._values: dict[str, Any] = {}
        if values:
            self._flatten("", values)

    def _flatten(self, prefix: str, values: Mapping[str, Any]) -> None:
        for k, v in values.items():
            key = f"{prefix}{k}"
            if isinstance(v, Mapping):
                self._flatten(f"{key}.", v)
            else:
                self._values[key] = v

    EMPTY: "Settings"

    def raw(self, key: str, default: Any = None) -> Any:
        return self._values.get(key, default)

    def get(self, setting: "Setting[T] | str", default: Any = None) -> Any:
        if isinstance(setting, Setting):
            return setting.get(self)
        return self._values.get(setting, default)

    def with_updates(self, updates: Mapping[str, Any]) -> "Settings":
        merged = dict(self._values)
        flat = Settings(updates)
        for k, v in flat._values.items():
            if v is None:
                merged.pop(k, None)  # null value resets to default, as in the reference API
            else:
                merged[k] = v
        out = Settings()
        out._values = merged
        return out

    def filtered_by_prefix(self, prefix: str) -> "Settings":
        out = Settings()
        out._values = {k: v for k, v in self._values.items() if k.startswith(prefix)}
        return out

    def as_dict(self) -> dict[str, Any]:
        return dict(self._values)

    def as_nested_dict(self) -> dict[str, Any]:
        nested: dict[str, Any] = {}
        for key, value in sorted(self._values.items()):
            parts = key.split(".")
            node = nested
            for p in parts[:-1]:
                node = node.setdefault(p, {})
                if not isinstance(node, dict):
                    break
            else:
                node[parts[-1]] = value
        return nested

    def __getitem__(self, key: str) -> Any:
        return self._values[key]

    def __iter__(self):
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def __repr__(self) -> str:
        return f"Settings({self._values!r})"


Settings.EMPTY = Settings()


class ClusterSettings:
    """Registry of known settings + dynamic-update subscription.

    Ref: common/settings/AbstractScopedSettings.java — validates that updates
    only touch registered dynamic settings and notifies consumers.
    """

    def __init__(self, initial: Settings, registered: Iterable[Setting] | None = None):
        self._settings = initial
        self._registered: dict[str, Setting] = {}
        self._consumers: list[tuple[Setting, Callable[[Any], None]]] = []
        for s in registered or ():
            self.register(s)

    def register(self, setting: Setting) -> None:
        self._registered[setting.key] = setting

    @property
    def settings(self) -> Settings:
        return self._settings

    def get(self, setting: Setting[T]) -> T:
        return setting.get(self._settings)

    def add_settings_update_consumer(self, setting: Setting[T], consumer: Callable[[T], None]) -> None:
        self._consumers.append((setting, consumer))

    def apply(self, updates: Mapping[str, Any]) -> Settings:
        """Validate + apply updates; notify consumers whose value changed."""
        flat = Settings(updates)
        for key in flat:
            reg = self._registered.get(key)
            if reg is None:
                raise IllegalArgumentError(f"transient setting [{key}], not recognized")
            if not reg.dynamic:
                raise IllegalArgumentError(f"final {reg.scope} setting [{key}], not updateable")
            if flat.raw(key) is not None:
                reg.parser(flat.raw(key))  # validate before committing
        old = self._settings
        self._settings = old.with_updates(updates)
        for setting, consumer in self._consumers:
            new_val = setting.get(self._settings)
            if setting.get(old) != new_val:
                consumer(new_val)
        return self._settings

"""Per-engine device-health tracking with a dispatch circuit breaker.

`EngineHealth` is a small three-state machine (closed / open / half_open):

- closed: device dispatches flow normally. `trip_n` CONSECUTIVE device
  faults open the circuit.
- open: `allow_device()` is False — queries route to the host-exact /
  BlockMax fallback tier — until `backoff_ms` elapses, at which point ONE
  half-open probe is admitted.
- half_open: the probe's outcome decides: success closes the circuit and
  resets the backoff; another fault re-opens it with exponential backoff
  (doubling, capped at 32× the base).

Knobs: ``ES_TPU_HEALTH_TRIP_N`` (default 3 consecutive faults) and
``ES_TPU_HEALTH_BACKOFF_MS`` (default 1000 ms base backoff).

Every engine registers itself here so `GET /_nodes/stats` can render a
node-wide ``tpu_health`` section (`node_health_stats`), including engines
that have since been garbage-collected (cumulative totals survive).
"""

from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Dict, Optional

from elasticsearch_tpu.common.settings import knob

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

_COUNTERS = ("device_faults", "circuit_opens", "circuit_reopens", "probes",
             "probe_successes", "fallback_queries")

_REGISTRY: "weakref.WeakSet[EngineHealth]" = weakref.WeakSet()
_NODE_LOCK = threading.Lock()
_NODE_TOTALS: Dict[str, int] = {k: 0 for k in _COUNTERS}


class EngineHealth:
    """Thread-safe dispatch circuit breaker for one engine.

    Subclasses repoint `_REG`/`_TOTALS` to keep a separate population (the
    coordinator's per-node transport circuits must not pollute the
    device-health `tpu_health` section)."""

    _REG = _REGISTRY
    _TOTALS = _NODE_TOTALS  # guarded by: _NODE_LOCK

    def __init__(self, name: str, trip_n: Optional[int] = None,
                 backoff_ms: Optional[int] = None):
        self.name = name
        self.trip_n = (trip_n if trip_n is not None
                       else knob("ES_TPU_HEALTH_TRIP_N"))
        self.base_backoff_ms = (backoff_ms if backoff_ms is not None
                                else knob("ES_TPU_HEALTH_BACKOFF_MS"))
        self._lock = threading.Lock()
        self.state = CLOSED
        self.consecutive_faults = 0
        self.backoff_ms = self.base_backoff_ms
        self._retry_at = 0.0
        self._probing = False
        self.counters: Dict[str, int] = {k: 0 for k in _COUNTERS}  # guarded by: _lock
        self._transitions: collections.deque = collections.deque(maxlen=16)  # guarded by: _lock
        self.last_fault: Optional[str] = None
        self._REG.add(self)

    # ---- state machine ----

    def _move(self, state: str) -> None:  # tpulint: holds=_lock
        self._transitions.append(f"{self.state}->{state}")
        self.state = state

    def allow_device(self) -> bool:
        """True when this call may take the device path. Admits exactly one
        probe at a time while half-open."""
        with self._lock:
            if self.state == CLOSED:
                return True
            now = time.monotonic()
            if self.state == OPEN:
                if now < self._retry_at:
                    return False
                self._move(HALF_OPEN)
                self._probing = True
                self._bump("probes")
                return True
            # half_open: one probe in flight at a time
            if self._probing:
                return False
            self._probing = True
            self._bump("probes")
            return True

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_faults = 0
            if self.state == HALF_OPEN:
                self._move(CLOSED)
                self.backoff_ms = self.base_backoff_ms
                self._probing = False
                self._bump("probe_successes")

    def record_fault(self, err: Optional[BaseException] = None) -> None:
        with self._lock:
            self._bump("device_faults")
            self.consecutive_faults += 1
            if err is not None:
                self.last_fault = f"{type(err).__name__}: {err}"
            if self.state == HALF_OPEN:
                self._probing = False
                self.backoff_ms = min(self.backoff_ms * 2,
                                      self.base_backoff_ms * 32)
                self._open(reopen=True)
            elif (self.state == CLOSED
                  and self.consecutive_faults >= self.trip_n):
                self._open(reopen=False)

    def _open(self, reopen: bool) -> None:  # tpulint: holds=_lock
        self._move(OPEN)
        self._retry_at = time.monotonic() + self.backoff_ms / 1000.0
        self._bump("circuit_reopens" if reopen else "circuit_opens")

    def record_fallback(self, n: int = 1) -> None:
        with self._lock:
            self._bump("fallback_queries", n)

    def _bump(self, key: str, n: int = 1) -> None:  # tpulint: holds=_lock
        self.counters[key] += n
        with _NODE_LOCK:
            # node totals surface through node_health_stats(), not the
            # per-engine stats() payload
            self._TOTALS[key] += n  # tpulint: disable=TPU005

    # ---- reporting ----

    def stats(self) -> dict:
        with self._lock:
            out = {"state": self.state,
                   "consecutive_faults": self.consecutive_faults,
                   "backoff_ms": self.backoff_ms,
                   "trip_n": self.trip_n,
                   "transitions": list(self._transitions)}
            if self.last_fault:
                out["last_fault"] = self.last_fault
            out.update(self.counters)
        return out

    def flat_stats(self) -> Dict[str, int]:
        """Numeric-only keys for TurboEngine.stats (bench delta-friendly)."""
        with self._lock:
            out = {f"health_{k}": v for k, v in self.counters.items()}
            out["health_circuit_open"] = int(self.state != CLOSED)
        return out


def node_health_stats() -> dict:
    """Node-wide ``tpu_health`` section for GET /_nodes/stats."""
    engines = sorted(_REGISTRY, key=lambda h: h.name)
    with _NODE_LOCK:
        totals = dict(_NODE_TOTALS)
    return {
        "engines": [dict(e.stats(), name=e.name) for e in engines],
        "open_circuits": sum(1 for e in engines if e.state != CLOSED),
        **totals,
    }


# ---- coordinator-side transport circuits (PR 6) ----
#
# The SAME three-state machine guards the distributed rung of the fault
# ladder: consecutive transport failures to a target node open a circuit
# that replica routing skips (quarantine), then a half-open probe decides
# whether the node ages back in — instead of ARS slowly decaying a dead
# node's EWMA until it gets retried.

_TRANSPORT_REGISTRY: "weakref.WeakSet[EngineHealth]" = weakref.WeakSet()
_TRANSPORT_TOTALS: Dict[str, int] = {k: 0 for k in _COUNTERS}


class NodeTransportHealth(EngineHealth):
    """Circuit for one coordinator->node transport edge. `device_faults`
    counts TRANSPORT failures here (the machine is shared; the registry is
    not, so `tpu_health` never mixes the two populations)."""

    _REG = _TRANSPORT_REGISTRY
    _TOTALS = _TRANSPORT_TOTALS

    # transport-flavored aliases over the shared state machine
    allow_request = EngineHealth.allow_device


def node_transport_health_stats() -> dict:
    """Coordinator transport-circuit summary for the ``tpu_coordinator``
    section of GET /_nodes/stats."""
    circuits = sorted(_TRANSPORT_REGISTRY, key=lambda h: h.name)
    with _NODE_LOCK:
        totals = dict(_TRANSPORT_TOTALS)
    return {
        "nodes": [dict(c.stats(), name=c.name) for c in circuits],
        "open_circuits": sum(1 for c in circuits if c.state != CLOSED),
        "transport_failures": totals["device_faults"],
        "circuit_opens": totals["circuit_opens"],
        "circuit_reopens": totals["circuit_reopens"],
        "probes": totals["probes"],
        "probe_successes": totals["probe_successes"],
    }

"""Exception hierarchy mirroring the reference's ElasticsearchException family.

Each error carries an HTTP status so the REST layer can map exceptions to
responses the way the reference does (ref: ElasticsearchException.status()).
"""

from __future__ import annotations


class ElasticsearchTpuError(Exception):
    """Base error; subclasses set `status` for REST mapping."""

    status = 500
    error_type = "exception"

    def __init__(self, message: str, **metadata):
        super().__init__(message)
        self.message = message
        self.metadata = metadata

    def to_dict(self) -> dict:
        out = {"type": self.error_type, "reason": self.message}
        out.update(self.metadata)
        return out


class IndexNotFoundError(ElasticsearchTpuError):
    status = 404
    error_type = "index_not_found_exception"

    def __init__(self, index: str):
        super().__init__(f"no such index [{index}]", index=index)
        self.index = index


class ResourceAlreadyExistsError(ElasticsearchTpuError):
    status = 400
    error_type = "resource_already_exists_exception"


class DocumentMissingError(ElasticsearchTpuError):
    status = 404
    error_type = "document_missing_exception"


class VersionConflictError(ElasticsearchTpuError):
    """Optimistic-concurrency failure (ref: VersionConflictEngineException)."""

    status = 409
    error_type = "version_conflict_engine_exception"


class CircuitBreakingError(ElasticsearchTpuError):
    """Memory limit trip (ref: common/breaker/CircuitBreakingException.java)."""

    status = 429
    error_type = "circuit_breaking_exception"


class DeviceFaultError(ElasticsearchTpuError):
    """A device dispatch failed (injected or organic XLA runtime error).

    Carries the dispatch `site` and optional `part` (partition id) so the
    containment layer can attribute the failure to a shard."""

    status = 503
    error_type = "tpu_device_fault_exception"

    def __init__(self, message: str, site: str = None, part: int = None,
                 **metadata):
        super().__init__(message, **metadata)
        self.site = site
        self.part = part

    def to_dict(self) -> dict:
        out = super().to_dict()
        if self.site is not None:
            out["site"] = self.site
        if self.part is not None:
            out["partition"] = self.part
        return out


class HbmOomError(DeviceFaultError):
    """Device memory exhausted mid-dispatch (RESOURCE_EXHAUSTED)."""

    error_type = "tpu_hbm_oom_exception"


class IndexClosedError(ElasticsearchTpuError):
    status = 400
    error_type = "index_closed_exception"


class IllegalArgumentError(ElasticsearchTpuError):
    status = 400
    error_type = "illegal_argument_exception"


class ParsingError(ElasticsearchTpuError):
    status = 400
    error_type = "parsing_exception"


class MapperParsingError(ElasticsearchTpuError):
    status = 400
    error_type = "mapper_parsing_exception"


class SearchPhaseExecutionError(ElasticsearchTpuError):
    status = 500
    error_type = "search_phase_execution_exception"


class ShardNotFoundError(ElasticsearchTpuError):
    status = 404
    error_type = "shard_not_found_exception"


class JsonParseError(ElasticsearchTpuError):
    status = 400
    error_type = "json_parse_exception"

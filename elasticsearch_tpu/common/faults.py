"""Deterministic, seedable device-fault injection for the TPU serving path.

Any named dispatch site can be made to raise a device fault, return an
HBM-OOM, or hang past a deadline on the Nth call — driven either by the
``ES_TPU_FAULTS`` environment spec (parsed once at import) or by the
programmatic API (`install` / `clear` / the `inject` context manager, which
tears down cleanly enough to run inside the interpret-mode differential
suites).

Spec grammar (';'-separated clauses)::

    site[#part]:mode[@nth][xcount][=arg][~prob]

      site   one of KNOWN_SITES: device dispatch sites (turbo_sweep,
             fused_dispatch, merge_kernel, column_upload, blockmax_pass),
             transport RPC sites — query path (rpc_query, rpc_fetch,
             rpc_can_match), write path (rpc_bulk, rpc_replica_bulk,
             rpc_recovery, rpc_resync) and maintenance (rpc_relocation,
             the warm-handoff RPC) — durability sites
             (translog_fsync, translog_corrupt, segment_commit), corruption
             sites (segment_read, segment_transfer, hbm_region — callers
             flip bits instead of raising; the integrity plane detects),
             or the pressure site overload_pressure (modes pin a level
             instead of raising: hang -> YELLOW, raise/oom -> RED)
      #part  restrict to one partition id — or, for transport sites, to one
             TARGET NODE by name (``rpc_query#d1``); default: any
      mode   raise | oom | hang
      @nth   1-based call number at which the fault first fires (default 1)
      xcount how many consecutive calls fire ('inf' = forever; default 1)
      =arg   hang sleep seconds (default 0.05); ignored for raise/oom
      ~prob  fire with probability prob per eligible call, seeded from
             ES_TPU_FAULTS_SEED ^ hash(site) so runs are reproducible

Example: ``ES_TPU_FAULTS='fused_dispatch:raise@2;column_upload#1:oom@1x2'``

`device_errors` is the companion: it wraps REAL runtime errors coming out of
a device dispatch (XlaRuntimeError and friends) into `DeviceFaultError` so
the containment layer upstream sees one exception type for injected and
organic faults alike.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import DeviceFaultError, HbmOomError
from elasticsearch_tpu.common.settings import knob

TRANSPORT_SITES = frozenset({
    "rpc_query",         # coordinator -> data node shard query RPC
    "rpc_fetch",         # coordinator -> data node fetch-by-id RPC
    "rpc_can_match",     # coordinator -> data node can_match pre-filter RPC
    "rpc_bulk",          # coordinator -> primary node shard bulk RPC
    "rpc_replica_bulk",  # primary -> replica replication fan-out RPC
    "rpc_recovery",      # target -> source peer-recovery RPCs (all phases)
    "rpc_resync",        # new primary -> replica resync RPCs
    "rpc_relocation",    # relocation target -> source warm-handoff RPC
    # cross-cluster sites (PR 20): `#part` selects the remote CLUSTER
    # alias, not a node — the remote service fires them once per attempt
    # before dispatching into the remote cluster's channels
    "rpc_remote_search",  # CCS coordinator -> remote cluster search RPC
    "rpc_ccr_fetch",      # CCR follower -> leader cluster RPCs (info+ops)
})

# Durable-storage sites (translog / segment commit): failures here must
# surface as I/O errors on the WRITE path, not as unreachable nodes.
DURABILITY_SITES = frozenset({
    "translog_fsync",    # fsync of an appended translog record
    "translog_corrupt",  # bit-rot the record being appended (bad CRC)
    "segment_commit",    # segment + commit-point persistence in flush()
})

# Pressure-injection site (common/overload.py): deterministic brownout for
# tests. Modes map to levels, not errors: hang -> YELLOW, raise/oom -> RED.
OVERLOAD_SITES = frozenset({
    "overload_pressure",  # OverloadController.evaluate() injection hook
})

# Bit-flip sites (common/integrity.py): clauses here never raise at the
# site — `corruption_fires()` tells the caller to silently damage the
# payload, and the integrity plane must DETECT it downstream.
CORRUPTION_SITES = frozenset({
    "segment_read",      # segment blob read back from the shard store
    "segment_transfer",  # recovery/relocation segment payload on the wire
    "hbm_region",        # device-resident region at scrub verify time
})

KNOWN_SITES = frozenset({
    "turbo_sweep",       # TurboBM25 device sweep (disjunctive + bool)
    "fused_dispatch",    # ShardedTurbo fused S>1 shard_map dispatch
    "merge_kernel",      # device-side partition top-k merge
    "column_upload",     # int8 column build/refresh onto the device
    "bitset_intersect",  # packed-uint32 bool match-set pack/intersect
    "sparse_gather",     # eager sparse slice build/upload + gather dispatch
    "blockmax_pass",     # BlockMax engine device pass
    "agg_reduce",        # device aggregation segment-reduce dispatch
    "knn_score",         # KnnEngine first-pass candidate dispatch
    "knn_rescore",       # KnnEngine exact-rescore dispatch
}) | TRANSPORT_SITES | DURABILITY_SITES | OVERLOAD_SITES | CORRUPTION_SITES

_MODES = frozenset({"raise", "oom", "hang"})

# Real device-runtime error type names (matched by name so we never import
# jaxlib internals) plus status strings seen in stringified XLA errors.
_DEVICE_ERROR_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "RuntimeError",
    "InternalError", "ResourceExhaustedError",
})
_DEVICE_ERROR_MARKERS = ("RESOURCE_EXHAUSTED", "INTERNAL", "out of memory",
                         "DEADLINE_EXCEEDED")


class FaultSpecError(ValueError):
    """Malformed ES_TPU_FAULTS clause."""


@dataclass
class _Clause:
    site: str
    part: Optional[Any]       # partition id (int) or target node name (str)
    mode: str
    nth: int = 1
    count: float = 1          # float so 'inf' works
    arg: float = 0.05
    prob: Optional[float] = None
    calls: int = 0            # eligible calls seen so far
    fired: int = 0
    rng: Optional[random.Random] = None

    def matches(self, site: str, part: Optional[Any]) -> bool:
        if site != self.site:
            return False
        if self.part is not None and part != self.part \
                and str(part) != str(self.part):
            return False
        return True

    def should_fire(self) -> bool:
        self.calls += 1
        if self.prob is not None:
            if self.rng.random() >= self.prob:
                return False
        elif self.calls < self.nth:
            return False
        if self.fired >= self.count:
            return False
        self.fired += 1
        return True


@dataclass
class FaultRecord:
    """One contained device fault, as reported in `_shards` failures."""
    site: str
    partition: Optional[int]
    error: BaseException
    recovered: bool = True

    @classmethod
    def from_error(cls, e: BaseException, partition: Optional[int] = None,
                   recovered: bool = True) -> "FaultRecord":
        return cls(site=getattr(e, "site", None) or "device",
                   partition=(partition if partition is not None
                              else getattr(e, "part", None)),
                   error=e, recovered=recovered)


def parse_spec(spec: str) -> List[_Clause]:
    seed = knob("ES_TPU_FAULTS_SEED")
    clauses: List[_Clause] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        if ":" not in raw:
            raise FaultSpecError(f"fault clause missing ':': {raw!r}")
        head, tail = raw.split(":", 1)
        part_str: Optional[str] = None
        if "#" in head:
            head, part_str = head.split("#", 1)
            if not part_str:
                raise FaultSpecError(f"bad partition in clause {raw!r}")
        site = head.strip()
        if site not in KNOWN_SITES:
            raise FaultSpecError(
                f"unknown fault site {site!r}; known: {sorted(KNOWN_SITES)}")
        part: Optional[Any] = None
        if part_str is not None:
            try:
                part = int(part_str)
            except ValueError:
                # transport sites select by target node NAME, corruption
                # sites by node / region name; device sites still require
                # an integer partition id
                if site in TRANSPORT_SITES or site in CORRUPTION_SITES:
                    part = part_str
                else:
                    raise FaultSpecError(
                        f"bad partition in clause {raw!r}")
        c = _Clause(site=site, part=part, mode="")
        # peel ~prob, =arg, xcount, @nth off the tail (order-independent
        # parse: split on each marker from the right)
        for marker, conv, attr in (("~", float, "prob"), ("=", float, "arg"),
                                   ("x", None, "count"), ("@", int, "nth")):
            if marker in tail:
                tail, v = tail.rsplit(marker, 1)
                try:
                    if attr == "count":
                        c.count = float("inf") if v == "inf" else int(v)
                    else:
                        setattr(c, attr, conv(v))
                except ValueError:
                    raise FaultSpecError(f"bad {attr!r} in clause {raw!r}")
        c.mode = tail.strip()
        if c.mode not in _MODES:
            raise FaultSpecError(
                f"unknown fault mode {c.mode!r}; known: {sorted(_MODES)}")
        if c.prob is not None:
            c.rng = random.Random(seed ^ (hash(site) & 0xFFFFFFFF))
        clauses.append(c)
    return clauses


_LOCK = threading.Lock()
_ACTIVE: Optional[List[_Clause]] = None  # guarded by: _LOCK


def install(spec: str) -> None:
    """Install a fault spec process-wide (replaces any previous spec)."""
    global _ACTIVE
    clauses = parse_spec(spec)
    with _LOCK:
        _ACTIVE = clauses or None


def clear() -> None:
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None


@contextlib.contextmanager
def inject(spec: str):
    """Scoped installation: install `spec`, restore the prior state on exit
    (exception-safe, so differential suites can nest it freely)."""
    global _ACTIVE
    clauses = parse_spec(spec)
    with _LOCK:
        prev, _ACTIVE = _ACTIVE, clauses
    try:
        yield
    finally:
        with _LOCK:
            _ACTIVE = prev


def _fire_mode(site: str, part: Optional[Any]) -> Optional[tuple]:
    """(mode, arg) when an active clause fires for this call, else None.

    The module-level `_ACTIVE is None` check keeps the no-faults fast path
    to a single attribute load."""
    active = _ACTIVE
    if active is None:
        return None
    with _LOCK:
        if _ACTIVE is not active:     # swapped under us; re-read
            active = _ACTIVE
            if active is None:
                return None
        for c in active:
            if not c.matches(site, part):
                continue
            if not c.should_fire():
                continue
            return c.mode, c.arg
    return None


def injected_overload_level() -> Optional[str]:
    """Deterministic pressure injection for the overload controller.

    Fires the ``overload_pressure`` site like any other clause (consuming
    one call against @nth/xcount), but maps the mode to a pressure level
    instead of raising: ``hang`` -> ``"yellow"``, ``raise``/``oom`` ->
    ``"red"``. Returns None when no clause fires."""
    hit = _fire_mode("overload_pressure", None)
    if hit is None:
        return None
    mode, _arg = hit
    return "yellow" if mode == "hang" else "red"


def fault_point(site: str, part: Optional[int] = None) -> None:
    """Named dispatch site: raises/oom/hangs when an active clause fires."""
    hit = _fire_mode(site, part)
    if hit is None:
        return
    mode, arg = hit
    if mode == "hang":
        # Sleep past the deadline, then return normally: the dispatch
        # "completes" late and the Deadline check upstream times it out.
        time.sleep(arg)
        return
    if mode == "oom":
        raise HbmOomError(
            f"injected HBM OOM at {site}"
            + (f"#{part}" if part is not None else ""),
            site=site, part=part)
    raise DeviceFaultError(
        f"injected device fault at {site}"
        + (f"#{part}" if part is not None else ""),
        site=site, part=part)


def transport_fault_point(site: str, node: str) -> None:
    """Named transport RPC site (coordinator -> `node`): raises
    `NodeUnavailableError` — the SAME exception an organic dead/partitioned
    node produces, so injected and organic transport faults take identical
    recovery paths through the coordinator — or hangs past the RPC deadline
    (the reply "arrives" after the coordinator stopped waiting)."""
    hit = _fire_mode(site, node)
    if hit is None:
        return
    mode, arg = hit
    if mode == "hang":
        time.sleep(arg)
        return
    # raise and oom both model an unreachable node on a transport site
    from elasticsearch_tpu.transport.channels import NodeUnavailableError

    raise NodeUnavailableError(
        f"injected transport fault at {site}#{node}")


class DurabilityFaultError(OSError):
    """Injected durable-storage failure (fsync / commit) at a named site.

    Deliberately an OSError: the write path must treat an injected fsync
    failure exactly like the organic ENOSPC/EIO it models."""

    def __init__(self, message: str, site: Optional[str] = None,
                 part: Optional[Any] = None):
        super().__init__(message)
        self.site = site
        self.part = part


def durability_fault_point(site: str, part: Optional[Any] = None) -> None:
    """Named durable-storage site (translog fsync, segment commit): raises
    `DurabilityFaultError` — indistinguishable from an organic I/O error —
    or hangs (a stalling disk; the op completes late)."""
    hit = _fire_mode(site, part)
    if hit is None:
        return
    mode, arg = hit
    if mode == "hang":
        time.sleep(arg)
        return
    # raise and oom both model a failed durable write at a storage site
    raise DurabilityFaultError(
        f"injected durability fault at {site}"
        + (f"#{part}" if part is not None else ""), site=site, part=part)


def corruption_fires(part: Optional[Any] = None,
                     site: str = "translog_corrupt") -> bool:
    """True when a corruption clause fires for this call: the caller
    silently damages the payload (bit rot) instead of raising — the damage
    surfaces DOWNSTREAM, at whatever checksum verify guards that leg, like
    real corruption does. Defaults to the PR 8 `translog_corrupt` site;
    the integrity plane passes `segment_read` / `segment_transfer` /
    `hbm_region`."""
    return _fire_mode(site, part) is not None


def is_device_error(e: BaseException) -> bool:
    if isinstance(e, DeviceFaultError):
        return True
    name = type(e).__name__
    if name in _DEVICE_ERROR_NAMES:
        if name == "RuntimeError":
            s = str(e)
            return any(m in s for m in _DEVICE_ERROR_MARKERS)
        return True
    return False


@contextlib.contextmanager
def device_errors(site: str, part: Optional[int] = None):
    """Translate organic device-runtime errors at this site into
    `DeviceFaultError` (HBM OOMs into `HbmOomError`) so the containment
    layer sees one exception type; everything else passes through."""
    try:
        yield
    except DeviceFaultError:
        raise
    except Exception as e:
        if not is_device_error(e):
            raise
        msg = f"device fault at {site}" + (
            f"#{part}" if part is not None else "") + f": {e}"
        if "RESOURCE_EXHAUSTED" in str(e) or "out of memory" in str(e):
            raise HbmOomError(msg, site=site, part=part) from e
        raise DeviceFaultError(msg, site=site, part=part) from e


@contextlib.contextmanager
def device_dispatch(site: str, part: Optional[int] = None):
    """fault_point + device_errors: the standard wrapper for a dispatch."""
    fault_point(site, part)
    with device_errors(site, part):
        yield


# Environment-driven installation (parse errors fail LOUD at import — a
# typo'd fault spec silently doing nothing would invalidate a chaos run).
_env_spec = knob("ES_TPU_FAULTS")
if _env_spec:
    install(_env_spec)

"""Node-wide write-path durability counters (PR 8).

One module-level counter dict — the same pattern as the coordinator's
resilience counters in action/search_action.py — feeds the
``tpu_durability`` section of GET /_nodes/stats so the write-path fault
ladder is observable: translog fsync failures, replication retries,
recoveries started/failed/retried, translog replays, ghost-tracking
cleanups (ref: the reference exposes the analogous signals across
index/translog stats, RecoveryStats and indices/recovery responses; here
one flat section keeps a chaos run auditable with a single GET).

Open translogs also register here (weakly) so the async-durability
exposure window — ops appended since the last fsync — is visible live,
not only after a crash proves it mattered.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict

_DURABILITY_LOCK = threading.Lock()
_DURABILITY_COUNTERS: Dict[str, int] = {  # guarded by: _DURABILITY_LOCK
    # translog / commit durability
    "fsync_failures": 0,            # translog fsyncs that raised
    "translog_syncs": 0,            # successful explicit/periodic fsyncs
    "translog_corruptions": 0,      # records appended with a broken CRC
    "segment_commit_failures": 0,   # flush() commits that raised
    "translog_replays": 0,          # crash recoveries that replayed the log
    "translog_replayed_ops": 0,     # ops re-applied by those replays
    # replication
    "replication_retries": 0,       # transient replica-RPC retries
    "replication_failures": 0,      # replica copies failed to the master
    "fsync_shard_failures": 0,      # primary copies failed on broken WAL
    # peer recovery
    "recoveries_started": 0,
    "recoveries_failed": 0,
    "recoveries_retried": 0,
    "ghost_cleanups": 0,            # stale recovery tracking removed
    "store_corruptions_discarded": 0,  # corrupt replica stores quarantined
}

# open translogs, for the live ops-since-sync gauge
_TRANSLOGS: "weakref.WeakSet" = weakref.WeakSet()


def count(key: str, n: int = 1) -> None:
    with _DURABILITY_LOCK:
        _DURABILITY_COUNTERS[key] += n


def register_translog(translog) -> None:
    _TRANSLOGS.add(translog)


def durability_stats() -> dict:
    """The ``tpu_durability`` section of GET /_nodes/stats."""
    with _DURABILITY_LOCK:
        out = dict(_DURABILITY_COUNTERS)
    windows = [t.ops_since_sync for t in _TRANSLOGS]
    out["open_translogs"] = len(windows)
    out["max_ops_since_sync"] = max(windows, default=0)
    return out


def reset_for_tests() -> Dict[str, int]:
    """Zero every counter and return the previous values (test isolation)."""
    with _DURABILITY_LOCK:
        prev = dict(_DURABILITY_COUNTERS)
        for k in _DURABILITY_COUNTERS:
            _DURABILITY_COUNTERS[k] = 0
    return prev

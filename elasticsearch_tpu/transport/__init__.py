from elasticsearch_tpu.transport.service import TransportService, TransportRequest

__all__ = ["TransportService", "TransportRequest"]

"""Node-to-node request channels for the distributed data/control plane.

The reference routes every inter-node call through TransportService
connections looked up from the cluster state's DiscoveryNodes (ref:
transport/TransportService.java sendRequest(DiscoveryNode, ...)). Here the
same seam is a small synchronous interface so the SAME spine code (shard
replication, peer recovery, search fan-out, master actions) runs:

  * in one process for the deterministic multi-node tests
    (LocalNodeChannels — direct dispatch, with kill support to simulate
    node death, and an optional fault hook for injected failures);
  * over real framed TCP between live nodes (TcpNodeChannels — address
    book fed from the cluster state / discovery).

Requests address nodes by node NAME (the stable operator-facing identity;
coordination uses the same convention, see cluster/cluster_service.py).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set, Tuple

from elasticsearch_tpu.common.errors import ElasticsearchTpuError
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.transport.service import TransportService


class NodeUnavailableError(ElasticsearchTpuError):
    status = 503
    error_type = "node_not_connected_exception"


class RpcTimeoutError(ElasticsearchTpuError):
    """An RPC did not answer within its deadline (ref:
    ReceiveTimeoutTransportException): the coordinator stops waiting; the
    late reply — if any — is dropped."""

    status = 504
    error_type = "receive_timeout_transport_exception"


# Transport RPC actions that are named fault-injection sites (the
# `rpc_*` half of the ES_TPU_FAULTS grammar, common/faults.py).
_RPC_FAULT_SITES = {
    "indices:data/read/search[phase/query]": "rpc_query",
    "indices:data/read/search[phase/fetch/id]": "rpc_fetch",
    "indices:data/read/search[can_match]": "rpc_can_match",
    "indices:data/write/bulk[s]": "rpc_bulk",
    "indices:data/write/bulk[s][r]": "rpc_replica_bulk",
    # every peer-recovery phase shares one site: @nth counts ACROSS the
    # prepare/segments/ops/finalize/cancel sequence of a recovery
    "internal:index/shard/recovery/prepare": "rpc_recovery",
    "internal:index/shard/recovery/segments": "rpc_recovery",
    "internal:index/shard/recovery/ops": "rpc_recovery",
    "internal:index/shard/recovery/finalize": "rpc_recovery",
    "internal:index/shard/recovery/cancel": "rpc_recovery",
    "internal:index/shard/resync/prepare": "rpc_resync",
    "internal:index/shard/resync/apply": "rpc_resync",
    # relocation warm handoff (the recovery RPCs a relocating target runs
    # keep their rpc_recovery site — reuse #node selectors for those)
    "internal:index/shard/relocation/warm_info": "rpc_relocation",
}


class NodeChannels:
    """request() raises NodeUnavailableError when the target is down."""

    def request(self, node: str, action: str, payload: dict,
                source: Optional[str] = None) -> dict:
        raise NotImplementedError


class LocalNodeChannels(NodeChannels):
    """In-process dispatch between TransportServices, by node name.

    Disruption rules mirror testing/disruptable_transport.py's taxonomy —
    kill (node death), isolate (one-sided cut from everyone), partition
    (two-sided blackhole between groups), heal — and all of them surface as
    the SAME `NodeUnavailableError` the fault-injection sites raise, so
    injected and organic transport faults take identical recovery paths."""

    def __init__(self):
        self._services: Dict[str, TransportService] = {}  # guarded by: _lock
        self._killed: set = set()                         # guarded by: _lock
        self._isolated: set = set()                       # guarded by: _lock
        self._blackholed: Set[Tuple[str, str]] = set()    # guarded by: _lock
        self._lock = threading.Lock()
        # test seam: fault(to_node, action) -> raise to inject
        self.fault_hook: Optional[Callable[[str, str], None]] = None

    def register(self, name: str, service: TransportService) -> None:
        with self._lock:
            self._services[name] = service
            self._killed.discard(name)

    def kill(self, name: str) -> None:
        with self._lock:
            self._killed.add(name)

    def revive(self, name: str) -> None:
        with self._lock:
            self._killed.discard(name)

    # ---- partition rules (ref: DisruptableMockTransport) ----

    def isolate(self, name: str) -> None:
        """Cut `name` off from every other node (both directions)."""
        with self._lock:
            self._isolated.add(name)

    def partition(self, side_a: Set[str], side_b: Set[str]) -> None:
        """Two-sided blackhole between the groups."""
        with self._lock:
            for a in side_a:
                for b in side_b:
                    self._blackholed.add((a, b))
                    self._blackholed.add((b, a))

    def heal(self) -> None:
        with self._lock:
            self._isolated.clear()
            self._blackholed.clear()

    def request(self, node: str, action: str, payload: dict,
                source: Optional[str] = None) -> dict:
        with self._lock:
            if node in self._killed or node not in self._services:
                raise NodeUnavailableError(f"node [{node}] is not connected")
            if node in self._isolated or source in self._isolated:
                raise NodeUnavailableError(
                    f"node [{node}] is partitioned away")
            if source is not None and (source, node) in self._blackholed:
                raise NodeUnavailableError(
                    f"no route from [{source}] to [{node}] (partition)")
            service = self._services[node]
        site = _RPC_FAULT_SITES.get(action)
        if site is not None:
            from elasticsearch_tpu.common.faults import transport_fault_point

            transport_fault_point(site, node)
        if self.fault_hook is not None:
            self.fault_hook(node, action)
        return service.handle(action, payload, source_node=source or "local")


class TcpNodeChannels(NodeChannels):
    """Framed-TCP dispatch using an address book (host, port) by name."""

    def __init__(self, self_name: str, self_service: TransportService,
                 timeout: Optional[float] = None):
        self.self_name = self_name
        self.self_service = self_service
        self.timeout = timeout if timeout is not None \
            else knob("ES_TPU_TCP_TIMEOUT_S")
        self._addresses: Dict[str, Tuple[str, int]] = {}  # guarded by: _lock
        self._lock = threading.Lock()

    def set_address(self, name: str, host: str, port: int) -> None:
        with self._lock:
            self._addresses[name] = (host, port)

    def update_from_state(self, state) -> None:
        """Learn peer addresses from the applied cluster state."""
        for n in state.nodes.values():
            if ":" in (n.address or ""):
                host, port = n.address.rsplit(":", 1)
                self.set_address(n.name, host, int(port))

    def request(self, node: str, action: str, payload: dict,
                source: Optional[str] = None) -> dict:
        if node == self.self_name:
            # local short-circuit, as the reference does for local sends
            return self.self_service.handle(action, payload, source_node=node)
        with self._lock:
            addr = self._addresses.get(node)
        if addr is None:
            raise NodeUnavailableError(f"no known address for node [{node}]")
        try:
            return TransportService.send_remote(
                addr[0], addr[1], action, payload,
                source_node=self.self_name, timeout=self.timeout)
        except (ConnectionError, OSError, TimeoutError) as e:
            raise NodeUnavailableError(
                f"node [{node}] unreachable: {e}") from e

"""Action-registry RPC: the control-plane transport.

Re-designs the reference transport (ref: transport/TransportService.java —
registerRequestHandler / sendRequest with action-name routing) as a registry
of named handlers. In-process dispatch is the local fast path (the reference
short-circuits local sends the same way); remote dispatch serializes the
request dict as JSON over a length-prefixed TCP frame, mirroring the
reference's framed protocol (ref: transport/TcpTransport.java,
InboundDecoder/OutboundHandler) without its bespoke binary format.

Action names follow the reference convention, e.g.
"indices:data/read/search", "indices:data/write/bulk",
"cluster:monitor/health" (ref: action/ActionModule.java registrations).
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from elasticsearch_tpu.common.errors import ElasticsearchTpuError

_FRAME = struct.Struct("<I")


@dataclass
class TransportRequest:
    action: str
    payload: dict
    source_node: str = "local"


Handler = Callable[[TransportRequest], dict]


class TransportService:
    def __init__(self, node_id: str = "local"):
        self.node_id = node_id
        self._handlers: Dict[str, Handler] = {}
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self.bound_port: Optional[int] = None

    def register_request_handler(self, action: str, handler: Handler) -> None:
        self._handlers[action] = handler

    def handle(self, action: str, payload: dict, source_node: str = "local") -> dict:
        handler = self._handlers.get(action)
        if handler is None:
            raise ElasticsearchTpuError(f"No handler for action [{action}]")
        return handler(TransportRequest(action, payload, source_node))

    # ---- TCP binding (inter-node control plane over DCN) ----

    def bind(self, host: str = "127.0.0.1", port: int = 0) -> int:
        service = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        header = _recv_exact(self.request, _FRAME.size)
                        if header is None:
                            return
                        (length,) = _FRAME.unpack(header)
                        body = _recv_exact(self.request, length)
                        if body is None:
                            return
                        msg = json.loads(body)
                        try:
                            resp = service.handle(msg["action"], msg.get("payload", {}),
                                                  msg.get("source_node", "remote"))
                            out = {"ok": True, "response": resp}
                        except ElasticsearchTpuError as e:
                            out = {"ok": False, "error": e.to_dict(), "status": e.status}
                        data = json.dumps(out).encode()
                        self.request.sendall(_FRAME.pack(len(data)) + data)
                except (ConnectionError, OSError):
                    pass

        self._server = socketserver.ThreadingTCPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.bound_port = self._server.server_address[1]
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return self.bound_port

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()

    @staticmethod
    def send_remote(host: str, port: int, action: str, payload: dict,
                    source_node: str = "client", timeout: float = 30.0) -> dict:
        msg = json.dumps({"action": action, "payload": payload,
                          "source_node": source_node}).encode()
        with socket.create_connection((host, port), timeout=timeout) as sock:
            sock.sendall(_FRAME.pack(len(msg)) + msg)
            header = _recv_exact(sock, _FRAME.size)
            (length,) = _FRAME.unpack(header)
            body = _recv_exact(sock, length)
        out = json.loads(body)
        if not out.get("ok"):
            err = ElasticsearchTpuError(out.get("error", {}).get("reason", "remote error"))
            err.status = out.get("status", 500)
            raise err
        return out["response"]


def _recv_exact(sock, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf

"""Device scoring ops: blocked BM25 scatter-scoring and masked top-k.

This is the TPU-native replacement for the reference's per-segment hot loop —
Lucene postings decode + BM25 + heap collection driven from
ContextIndexSearcher (ref: search/internal/ContextIndexSearcher.java:213-216,
Lucene BM25Similarity). Instead of a branchy doc-at-a-time WAND iterator, we
score whole 128-lane postings blocks data-parallel:

    gather selected blocks from HBM  ->  vectorized BM25 over [B, 128] lanes
    ->  scatter-add into a dense per-doc score vector  ->  lax.top_k

Conventions that keep everything branch-free under jit:
  * Every segment reserves block row 0 as an all-zero block (doc 0, tf 0);
    padding a query's block-id list with 0 adds exactly 0.0 to doc 0.
  * Block-id lists are padded to power-of-two buckets so XLA compiles one
    program per bucket size, not per query.
  * tf == 0 lanes contribute 0 score by construction of the BM25 formula.

All functions are jit-compiled and cached by shape.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

BLOCK = 128


def bm25_idf(doc_count: int, doc_freq: int) -> float:
    """Lucene BM25 idf: ln(1 + (N - df + 0.5) / (df + 0.5))."""
    return math.log(1.0 + (doc_count - doc_freq + 0.5) / (doc_freq + 0.5))


def next_bucket(n: int, minimum: int = 8) -> int:
    """Round up to the next power of two to bound jit recompiles."""
    if n <= minimum:
        return minimum
    return 1 << (n - 1).bit_length()


def pad_block_ids(block_ids: np.ndarray, bucket: int | None = None) -> np.ndarray:
    """Pad a host block-id list with the reserved zero block (row 0)."""
    n = len(block_ids)
    b = bucket or next_bucket(n)
    out = np.zeros(b, dtype=np.int32)
    out[:n] = block_ids
    return out


@partial(jax.jit, static_argnames=("n_docs", "k1", "b"))
def bm25_scatter_scores(
    block_docs: jax.Array,   # [T, 128] i32 — all postings blocks of the field
    block_tfs: jax.Array,    # [T, 128] f32
    doc_len: jax.Array,      # [n_docs] f32 — field length norms
    block_ids: jax.Array,    # [B] i32 — selected block rows (padded with 0)
    idf: jax.Array,          # [B] f32 — per-block idf weight of the owning term
    avgdl: jax.Array,        # scalar f32
    *,
    n_docs: int,
    k1: float = 1.2,
    b: float = 0.75,
) -> jax.Array:
    """Score selected postings blocks, scatter-add into a dense [n_docs] f32.

    BM25: idf * tf * (k1 + 1) / (tf + k1 * (1 - b + b * dl / avgdl))
    (ref: Lucene 8 BM25Similarity with norms; boost folded into idf upstream)
    """
    docs = jnp.take(block_docs, block_ids, axis=0)           # [B, 128]
    tfs = jnp.take(block_tfs, block_ids, axis=0)             # [B, 128]
    dl = jnp.take(doc_len, docs, axis=0)                     # [B, 128] (doc 0 pad ok)
    denom = tfs + k1 * (1.0 - b + b * dl / avgdl)
    # guard tf==0 pad lanes: denom>0 always (k1*(1-b)>0), score becomes 0 via tf
    scores = idf[:, None] * tfs * (k1 + 1.0) / denom
    return jnp.zeros((n_docs,), jnp.float32).at[docs.ravel()].add(scores.ravel())


@partial(jax.jit, static_argnames=("n_docs",))
def constant_scatter_mask(
    block_docs: jax.Array,   # [T, 128] i32
    block_tfs: jax.Array,    # [T, 128] f32 (tf>0 marks real postings)
    block_ids: jax.Array,    # [B] i32 (padded with 0)
    *,
    n_docs: int,
) -> jax.Array:
    """Boolean [n_docs] mask of docs present in the selected blocks.

    Used for filter-context term/terms matching (constant score): the lane is
    real iff its tf > 0, which also neutralizes both zero-block padding and
    in-block tail padding.
    """
    docs = jnp.take(block_docs, block_ids, axis=0)
    tfs = jnp.take(block_tfs, block_ids, axis=0)
    hits = jnp.zeros((n_docs,), jnp.float32).at[docs.ravel()].add((tfs > 0).astype(jnp.float32).ravel())
    return hits > 0


@partial(jax.jit, static_argnames=("k",))
def masked_top_k(scores: jax.Array, mask: jax.Array, *, k: int):
    """Top-k by score over docs where mask is true.

    Ties break by ascending doc ordinal, matching Lucene's collector
    (ref: Lucene TopScoreDocCollector doc-id tie-break): implemented by
    sorting on (score, -ord) packed comparisons via a tiny ordinal epsilon on
    equal float scores is unsafe; instead we rely on lax.top_k which returns
    the smallest index among equals, giving the same order.
    """
    masked = jnp.where(mask, scores, -jnp.inf)
    top_scores, top_ords = jax.lax.top_k(masked, k)
    valid = top_scores > -jnp.inf
    return top_scores, top_ords, valid


@jax.jit
def total_hits(mask: jax.Array) -> jax.Array:
    return jnp.sum(mask.astype(jnp.int32))

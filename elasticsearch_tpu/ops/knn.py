"""Brute-force dense-vector kNN as batched matmul on the MXU.

Replaces the reference's script_score brute-force over binary doc values
(ref: x-pack vectors query/ScoreScriptUtils.java:113-166 — cosineSimilarity /
dotProduct / l2norm painless functions). TPU-native re-design: the segment's
vectors are one [n_docs, dims] matrix in HBM; a batch of queries [Q, dims]
scores in a single [Q, dims] x [dims, n_docs] matmul (bf16 on the MXU with
f32 accumulation), then masked top-k per query.

Score conventions follow the reference's _score definitions so results are
drop-in comparable:
  cosine:       (1 + cos(q, d)) / 2
  dot_product:  (1 + dot(q, d)) / 2        (vectors assumed unit-normalized)
  l2_norm:      1 / (1 + l2(q, d))

Cosine columns are pre-normalized at upload time (Segment.device('vec:'),
spmd.build_stacked_knn, KnnEngine all divide rows by their norm once on
host), so the per-query hot loop divides by the [Q, 1] query norm only —
the old [Q, n_docs] f32 divide is gone. `norms` still carries the RAW row
norms: the l2 path needs them (dd = norms^2), and cosine ignores them.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("similarity",))
def knn_scores(
    queries: jax.Array,       # [Q, dims] f32
    vectors: jax.Array,       # [n_docs, dims] bf16/f32 (unit rows for cosine)
    norms: jax.Array,         # [n_docs] f32 — RAW row L2 norms (l2 path)
    exists: jax.Array,        # [n_docs] bool — docs that have the vector field
    *,
    similarity: str = "cosine",
) -> jax.Array:
    """Dense [Q, n_docs] similarity scores; missing docs score -inf."""
    v = vectors.astype(jnp.bfloat16)
    q = queries.astype(jnp.bfloat16)
    dots = jax.lax.dot_general(
        q, v,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [Q, n_docs]
    if similarity == "cosine":
        # rows are unit vectors (upload-time normalization): divide by the
        # query norm only
        qn = jnp.linalg.norm(queries, axis=-1, keepdims=True)  # [Q, 1]
        cos = dots / jnp.maximum(qn, 1e-20)
        scores = (1.0 + cos) / 2.0
    elif similarity == "dot_product":
        scores = (1.0 + dots) / 2.0
    elif similarity == "l2_norm":
        qq = jnp.sum(queries * queries, axis=-1, keepdims=True)
        dd = (norms * norms)[None, :]
        d2 = jnp.maximum(qq + dd - 2.0 * dots, 0.0)
        scores = 1.0 / (1.0 + jnp.sqrt(d2))
    else:
        raise ValueError(f"unknown similarity [{similarity}]")
    return jnp.where(exists[None, :], scores, -jnp.inf)


@partial(jax.jit, static_argnames=("similarity", "k"))
def knn_top_k(
    queries: jax.Array,
    vectors: jax.Array,
    norms: jax.Array,
    exists: jax.Array,
    mask: jax.Array,          # [n_docs] bool — live docs / filter
    *,
    similarity: str = "cosine",
    k: int = 10,
):
    scores = knn_scores(queries, vectors, norms, exists, similarity=similarity)
    scores = jnp.where(mask[None, :], scores, -jnp.inf)
    top_scores, top_ords = jax.lax.top_k(scores, k)     # [Q, k]
    return top_scores, top_ords, top_scores > -jnp.inf

from elasticsearch_tpu.ops.scoring import (
    BLOCK,
    bm25_idf,
    bm25_scatter_scores,
    constant_scatter_mask,
    masked_top_k,
    next_bucket,
    pad_block_ids,
)
from elasticsearch_tpu.ops.knn import knn_scores, knn_top_k

__all__ = [
    "BLOCK",
    "bm25_idf",
    "bm25_scatter_scores",
    "constant_scatter_mask",
    "masked_top_k",
    "next_bucket",
    "pad_block_ids",
    "knn_scores",
    "knn_top_k",
]

"""elasticsearch_tpu — a TPU-native distributed search engine.

A ground-up JAX/XLA/Pallas implementation of the capabilities of Elasticsearch
(reference: lastlearner/elasticsearch, ES 8.0.0-SNAPSHOT on Lucene 8.8.0). The
host side keeps Elasticsearch's proven distributed shapes — immutable segments,
translog + seqno checkpoints, scatter-gather query-then-fetch, a typed settings
registry, and the REST surface — while the per-shard query executor (the hot
loop at reference search/internal/ContextIndexSearcher.java:213) is re-designed
as batched device programs over HBM-resident block-compressed segment arrays.

Package layout (reference layer map, SURVEY.md §1):
  common/     Settings, circuit breakers, errors   (ref: server common/, layer 2)
  analysis/   analyzers & token filters            (ref: index/analysis, analysis-common)
  mapper/     field types, document parsing        (ref: index/mapper)
  index/      segments, translog, engine, shard    (ref: index/engine, index/translog)
  ops/        JAX/Pallas device kernels            (ref: Lucene postings/BM25/top-k read path)
  search/     query DSL, query & fetch phases      (ref: index/query, search/)
  parallel/   device mesh sharding & collectives   (ref: scatter-gather fan-out, §2.10)
  cluster/    cluster state, coordination          (ref: cluster/)
  transport/  action registry RPC                  (ref: transport/, action/)
  rest/       HTTP REST frontend                   (ref: rest/, http/)
  models/     flagship scoring models (BM25/kNN/hybrid programs)
  utils/      small shared helpers
"""

__version__ = "0.1.0"

from elasticsearch_tpu.tasks.task_manager import (
    Task, TaskCancelledError, TaskManager,
)

__all__ = ["Task", "TaskCancelledError", "TaskManager"]

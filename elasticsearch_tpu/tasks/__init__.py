from elasticsearch_tpu.tasks.task_manager import (
    Task, TaskCancelledError, TaskManager, action_family, activate,
    current_task,
)

__all__ = ["Task", "TaskCancelledError", "TaskManager", "action_family",
           "activate", "current_task"]

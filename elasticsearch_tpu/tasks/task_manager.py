"""Task registry with cooperative cancellation and ban propagation.

Re-designs the reference's task management (ref: tasks/TaskManager.java:71
register/unregister, tasks/CancellableTask.java, and the cancellation
checks ContextIndexSearcher.java:66 threads through collectors): every
long-running request registers a Task; cancellation flips a flag that the
compute paths CHECK at their loop boundaries — between device dispatches,
between leaves, inside host selection/expansion loops — so a runaway query
returns promptly instead of running to completion.

Cross-node semantics follow the reference's TaskCancellationService:
cancelling a parent records a **ban** on its `{node}:{id}` so child
registrations that arrive AFTER the cancel (a shard RPC racing the ban)
are cancelled on arrival instead of leaking. Bans are TTL'd
(`ES_TPU_TASK_BAN_TTL_S`) and node-left events reap orphaned children by
banning the dead node's id prefix.

The TPU twist: a dispatched XLA program itself cannot be interrupted, but
every program here is bounded (fixed shapes, one batch chunk), so the
check granularity is one dispatch — milliseconds, not the whole query.
The scheduler/coalescer only honor cancellation at their flush
boundaries, preserving the bit-identity contract when no cancel fires.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common import metrics
from elasticsearch_tpu.common.errors import ElasticsearchTpuError
from elasticsearch_tpu.common.settings import knob


class TaskCancelledError(ElasticsearchTpuError):
    status = 400
    error_type = "task_cancelled_exception"


def action_family(action: str) -> str:
    """`indices:data/read/search[phase/query]` -> `search` — the histogram
    / gauge family key for one transport action."""
    return action.split("[", 1)[0].rsplit("/", 1)[-1]


@dataclass
class Task:
    id: int
    node: str
    action: str
    description: str
    start_time_ms: int
    cancellable: bool = True
    parent_task_id: Optional[str] = None
    _cancelled: threading.Event = field(default_factory=threading.Event,
                                        repr=False)
    cancel_reason: Optional[str] = None
    # monotonic start: running_time_in_nanos must never go negative under
    # wall-clock adjustment (start_time_ms stays wall-clock for display)
    start_monotonic: float = field(default_factory=time.monotonic)
    trace_id: Optional[str] = None
    sla: Optional[str] = None
    phase: str = ""
    dispatches: int = 0

    @property
    def is_cancelled(self) -> bool:
        return self._cancelled.is_set()

    @property
    def task_id(self) -> str:
        return f"{self.node}:{self.id}"

    def cancel(self, reason: str = "by user request") -> None:
        self.cancel_reason = reason
        self._cancelled.set()

    def check(self) -> None:
        """Raise if cancelled — called from compute loop boundaries."""
        if self._cancelled.is_set():
            raise TaskCancelledError(
                f"task [{self.node}:{self.id}] cancelled: {self.cancel_reason}")

    def note_dispatch(self, phase: str = "") -> None:
        """One engine dispatch crossed a flush boundary on behalf of this
        task (single-writer per boundary; no lock needed)."""
        self.dispatches += 1
        if phase:
            self.phase = phase

    def to_dict(self, detailed: bool = False) -> dict:
        out = {
            "node": self.node,
            "id": self.id,
            "type": "transport",
            "action": self.action,
            "description": self.description,
            "start_time_in_millis": self.start_time_ms,
            "running_time_in_nanos": int(
                (time.monotonic() - self.start_monotonic) * 1e9),
            "cancellable": self.cancellable,
            "cancelled": self.is_cancelled,
            **({"parent_task_id": self.parent_task_id}
               if self.parent_task_id else {}),
        }
        if self.trace_id:
            out["headers"] = {"trace_id": self.trace_id}
        if detailed:
            out["status"] = {
                "phase": self.phase,
                "dispatches": self.dispatches,
                "sla": self.sla,
            }
        return out


_tls = threading.local()


def current_task() -> Optional[Task]:
    """The task the current thread is executing on behalf of (mirrors
    tracing.current(): one thread-local read when the plane is idle)."""
    return getattr(_tls, "task", None)


@contextmanager
def activate(task: Optional[Task]):
    """Install ``task`` as the thread's current task. activate(None) is a
    no-op pass-through so call sites need no branching."""
    if task is None:
        yield None
        return
    prev = getattr(_tls, "task", None)
    _tls.task = task
    try:
        yield task
    finally:
        _tls.task = prev


class TaskManager:
    """Node-level task registry (ref: tasks/TaskManager.java:71) with the
    TaskCancellationService ban list grafted on."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        self._tasks: Dict[int, Task] = {}         # guarded by: _lock
        self._ids = itertools.count(1)
        # parent-task-id -> (monotonic expiry, reason); exact ids from
        # cancellations, node-id prefixes from node-left reaping
        self._bans: Dict[str, Tuple[float, str]] = {}       # guarded by: _lock
        self._node_bans: Dict[str, Tuple[float, str]] = {}  # guarded by: _lock
        # lifetime counters (surfaced via stats() -> `tpu_tasks`)
        self.registered = 0        # guarded by: _lock
        self.completed = 0         # guarded by: _lock
        self.cancelled = 0         # guarded by: _lock
        self.bans_propagated = 0   # guarded by: _lock
        self.bans_received = 0     # guarded by: _lock
        self.orphans_reaped = 0    # guarded by: _lock

    # ---- registration ----

    def register(self, action: str, description: str = "",
                 cancellable: bool = True,
                 parent_task_id: Optional[str] = None,
                 trace_id: Optional[str] = None,
                 sla: Optional[str] = None) -> Task:
        if trace_id is None:
            from elasticsearch_tpu.common import tracing

            tc = tracing.current()
            trace_id = tc.trace_id if tc is not None else None
        if sla is None:
            # runtime-only import: threadpool imports tasks at module load
            from elasticsearch_tpu.threadpool import scheduler as _sched

            sla = _sched.current_tier()
        task = Task(id=next(self._ids), node=self.node_id, action=action,
                    description=description,
                    start_time_ms=int(time.time() * 1000),
                    cancellable=cancellable, parent_task_id=parent_task_id,
                    trace_id=trace_id, sla=sla)
        ban: Optional[Tuple[float, str]] = None
        with self._lock:
            if parent_task_id:
                ban = self._ban_for_locked(parent_task_id)
            self._tasks[task.id] = task
            self.registered += 1
        if ban is not None and cancellable:
            # banned parent: the child is cancelled ON ARRIVAL, so the
            # handler's first check() raises before any engine dispatch
            task.cancel(ban[1])
        return task

    def unregister(self, task: Task) -> None:
        with self._lock:
            was_live = self._tasks.pop(task.id, None) is not None
            if was_live:
                self.completed += 1
                if task.is_cancelled:
                    self.cancelled += 1
            self._drained.notify_all()
        if was_live:
            metrics.observe_if_declared(
                f"task_duration.{action_family(task.action)}",
                (time.monotonic() - task.start_monotonic) * 1e3)

    def task(self, action: str, description: str = "", **kw):
        """Context manager: register on enter (activating the task as the
        thread's current task), unregister on exit."""
        manager = self

        class _Ctx:
            def __enter__(self):
                self.t = manager.register(action, description, **kw)
                self._act = activate(self.t)
                self._act.__enter__()
                return self.t

            def __exit__(self, *exc):
                self._act.__exit__(*exc)
                manager.unregister(self.t)
                return False

        return _Ctx()

    # ---- lookup ----

    def get(self, task_id: int) -> Optional[Task]:
        with self._lock:
            return self._tasks.get(task_id)

    def list(self, actions: Optional[str] = None) -> List[Task]:
        import fnmatch

        with self._lock:
            tasks = list(self._tasks.values())
        if actions:
            pats = actions.split(",")
            tasks = [t for t in tasks
                     if any(fnmatch.fnmatchcase(t.action, p) for p in pats)]
        return tasks

    # ---- cancellation & bans ----

    def cancel(self, task_id: int, reason: str = "by user request") -> Optional[Task]:
        """Returns the task after cancelling, None if unknown; raises on a
        non-cancellable task (ES: 400 for cancel of a non-cancellable)."""
        t = self.get(task_id)
        if t is None:
            return None
        if not t.cancellable:
            e = ElasticsearchTpuError(
                f"task [{t.node}:{t.id}] is not cancellable")
            e.status = 400
            raise e
        t.cancel(reason)
        return t

    def cancel_matching(self, actions: str, reason: str = "by user request") -> List[Task]:
        out = []
        for t in self.list(actions):
            if t.cancellable:
                t.cancel(reason)
                out.append(t)
        return out

    def _ban_for_locked(self, parent_task_id: str) -> Optional[Tuple[float, str]]:
        # tpulint: holds=_lock
        self._prune_bans_locked()
        ban = self._bans.get(parent_task_id)
        if ban is None:
            node = parent_task_id.rsplit(":", 1)[0]
            ban = self._node_bans.get(node)
        return ban

    def _prune_bans_locked(self) -> None:
        # tpulint: holds=_lock
        now = time.monotonic()
        for d in (self._bans, self._node_bans):
            for k in [k for k, (exp, _) in d.items() if exp <= now]:
                d.pop(k, None)

    def ban(self, parent_task_id: str, reason: str = "parent task cancelled") -> List[Task]:
        """Record a TTL'd ban for ``parent_task_id`` and cancel every live
        child already registered under it (ref: TaskCancellationService's
        setBan + cancel-children). Returns the children cancelled now;
        children registering later die on arrival via the ban list."""
        expiry = time.monotonic() + float(knob("ES_TPU_TASK_BAN_TTL_S"))
        with self._lock:
            self._prune_bans_locked()
            self._bans[parent_task_id] = (expiry, reason)
            self.bans_received += 1
            children = [t for t in self._tasks.values()
                        if t.parent_task_id == parent_task_id and t.cancellable]
        for t in children:
            t.cancel(reason)
        return children

    def note_bans_propagated(self, n: int = 1) -> None:
        """The local node fanned a ban out to ``n`` peers (owner side)."""
        with self._lock:
            self.bans_propagated += n

    def reap_orphans(self, dead_node: str,
                     reason: Optional[str] = None) -> List[Task]:
        """Node-left: ban the dead node's id prefix and cancel every live
        child whose parent lived there — an orphan's coordinator can never
        unblock it, so it must die at the next dispatch boundary."""
        reason = reason or f"parent node [{dead_node}] left the cluster"
        expiry = time.monotonic() + float(knob("ES_TPU_TASK_BAN_TTL_S"))
        with self._lock:
            self._prune_bans_locked()
            self._node_bans[dead_node] = (expiry, reason)
            orphans = [t for t in self._tasks.values()
                       if t.parent_task_id
                       and t.parent_task_id.rsplit(":", 1)[0] == dead_node
                       and t.cancellable]
            self.orphans_reaped += len(orphans)
        for t in orphans:
            t.cancel(reason)
        return orphans

    def wait_for_drain(self, parent_task_id: str, timeout_s: float) -> bool:
        """Block until no live task IS ``parent_task_id`` or has it as its
        parent (wait_for_completion=true). True when drained in time."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._drained:
            while True:
                live = [t for t in self._tasks.values()
                        if t.task_id == parent_task_id
                        or t.parent_task_id == parent_task_id]
                if not live:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._drained.wait(remaining)

    # ---- stats ----

    def stats(self) -> dict:
        with self._lock:
            current: Dict[str, int] = {}
            for t in self._tasks.values():
                fam = action_family(t.action)
                current[fam] = current.get(fam, 0) + 1
            return {
                "registered": self.registered,
                "completed": self.completed,
                "cancelled": self.cancelled,
                "bans_propagated": self.bans_propagated,
                "bans_received": self.bans_received,
                "orphans_reaped": self.orphans_reaped,
                "bans_active": len(self._bans) + len(self._node_bans),
                "current": dict(sorted(current.items())),
            }


def parse_timeout_ms(value) -> Optional[float]:
    """'100ms' / '2s' / '1m' / int(ms) -> milliseconds. -1 (ES's "no
    timeout" sentinel) parses to None."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value) if value >= 0 else None
    s = str(value).strip().lower()
    if s == "-1":
        return None
    for suffix, mult in (("ms", 1.0), ("s", 1000.0), ("m", 60000.0),
                         ("h", 3600000.0), ("d", 86400000.0)):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * mult
    return float(s)


class DispatchDeadlineError(Exception):
    """Raised from a dispatch-side deadline check (the `check` callable
    threaded into engine dispatches) when the request `Deadline` expires
    mid-dispatch; the serving layer converts it to timed_out partials."""


class Deadline:
    """Per-request soft deadline for timeout/terminate_after semantics
    (ref: search/internal/ContextIndexSearcher timeout runnable +
    QueryPhase.executeInternal terminateAfter): compute paths poll
    `expired` at leaf boundaries and return PARTIAL results with
    timed_out=true, unlike cancellation which raises."""

    def __init__(self, timeout_ms: Optional[float]):
        self._deadline = (time.monotonic() + timeout_ms / 1000.0
                          if timeout_ms is not None and timeout_ms >= 0
                          else None)
        self.timed_out = False

    @property
    def expired(self) -> bool:
        if self._deadline is not None and time.monotonic() > self._deadline:
            self.timed_out = True
            return True
        return False

    def remaining_ms(self) -> Optional[float]:
        """Milliseconds until expiry (negative when past), None when
        unbounded — lets a coordinator size per-RPC timeouts from the
        request budget."""
        if self._deadline is None:
            return None
        return (self._deadline - time.monotonic()) * 1000.0

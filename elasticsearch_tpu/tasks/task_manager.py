"""Task registry with cooperative cancellation.

Re-designs the reference's task management (ref: tasks/TaskManager.java:71
register/unregister, tasks/CancellableTask.java, and the cancellation
checks ContextIndexSearcher.java:66 threads through collectors): every
long-running request registers a Task; cancellation flips a flag that the
compute paths CHECK at their loop boundaries — between device dispatches,
between leaves, inside host selection/expansion loops — so a runaway query
returns promptly instead of running to completion.

The TPU twist: a dispatched XLA program itself cannot be interrupted, but
every program here is bounded (fixed shapes, one batch chunk), so the
check granularity is one dispatch — milliseconds, not the whole query.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import ElasticsearchTpuError


class TaskCancelledError(ElasticsearchTpuError):
    status = 400
    error_type = "task_cancelled_exception"


@dataclass
class Task:
    id: int
    node: str
    action: str
    description: str
    start_time_ms: int
    cancellable: bool = True
    parent_task_id: Optional[str] = None
    _cancelled: threading.Event = field(default_factory=threading.Event,
                                        repr=False)
    cancel_reason: Optional[str] = None

    @property
    def is_cancelled(self) -> bool:
        return self._cancelled.is_set()

    def cancel(self, reason: str = "by user request") -> None:
        self.cancel_reason = reason
        self._cancelled.set()

    def check(self) -> None:
        """Raise if cancelled — called from compute loop boundaries."""
        if self._cancelled.is_set():
            raise TaskCancelledError(
                f"task [{self.node}:{self.id}] cancelled: {self.cancel_reason}")

    def to_dict(self) -> dict:
        return {
            "node": self.node,
            "id": self.id,
            "type": "transport",
            "action": self.action,
            "description": self.description,
            "start_time_in_millis": self.start_time_ms,
            "running_time_in_nanos": int(
                (time.time() * 1000 - self.start_time_ms) * 1e6),
            "cancellable": self.cancellable,
            "cancelled": self.is_cancelled,
            **({"parent_task_id": self.parent_task_id}
               if self.parent_task_id else {}),
        }


class TaskManager:
    """Node-level task registry (ref: tasks/TaskManager.java:71)."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        self._lock = threading.Lock()
        self._tasks: Dict[int, Task] = {}
        self._ids = itertools.count(1)

    def register(self, action: str, description: str = "",
                 cancellable: bool = True,
                 parent_task_id: Optional[str] = None) -> Task:
        task = Task(id=next(self._ids), node=self.node_id, action=action,
                    description=description,
                    start_time_ms=int(time.time() * 1000),
                    cancellable=cancellable, parent_task_id=parent_task_id)
        with self._lock:
            self._tasks[task.id] = task
        return task

    def unregister(self, task: Task) -> None:
        with self._lock:
            self._tasks.pop(task.id, None)

    def task(self, action: str, description: str = "", **kw):
        """Context manager: register on enter, unregister on exit."""
        manager = self

        class _Ctx:
            def __enter__(self):
                self.t = manager.register(action, description, **kw)
                return self.t

            def __exit__(self, *exc):
                manager.unregister(self.t)
                return False

        return _Ctx()

    def get(self, task_id: int) -> Optional[Task]:
        with self._lock:
            return self._tasks.get(task_id)

    def list(self, actions: Optional[str] = None) -> List[Task]:
        import fnmatch

        with self._lock:
            tasks = list(self._tasks.values())
        if actions:
            pats = actions.split(",")
            tasks = [t for t in tasks
                     if any(fnmatch.fnmatchcase(t.action, p) for p in pats)]
        return tasks

    def cancel(self, task_id: int, reason: str = "by user request") -> Optional[Task]:
        """Returns the task after cancelling, None if unknown; raises on a
        non-cancellable task (ES: 400 for cancel of a non-cancellable)."""
        t = self.get(task_id)
        if t is None:
            return None
        if not t.cancellable:
            e = ElasticsearchTpuError(
                f"task [{t.node}:{t.id}] is not cancellable")
            e.status = 400
            raise e
        t.cancel(reason)
        return t

    def cancel_matching(self, actions: str, reason: str = "by user request") -> List[Task]:
        out = []
        for t in self.list(actions):
            if t.cancellable:
                t.cancel(reason)
                out.append(t)
        return out


def parse_timeout_ms(value) -> Optional[float]:
    """'100ms' / '2s' / '1m' / int(ms) -> milliseconds. -1 (ES's "no
    timeout" sentinel) parses to None."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value) if value >= 0 else None
    s = str(value).strip().lower()
    if s == "-1":
        return None
    for suffix, mult in (("ms", 1.0), ("s", 1000.0), ("m", 60000.0),
                         ("h", 3600000.0), ("d", 86400000.0)):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * mult
    return float(s)


class DispatchDeadlineError(Exception):
    """Raised from a dispatch-side deadline check (the `check` callable
    threaded into engine dispatches) when the request `Deadline` expires
    mid-dispatch; the serving layer converts it to timed_out partials."""


class Deadline:
    """Per-request soft deadline for timeout/terminate_after semantics
    (ref: search/internal/ContextIndexSearcher timeout runnable +
    QueryPhase.executeInternal terminateAfter): compute paths poll
    `expired` at leaf boundaries and return PARTIAL results with
    timed_out=true, unlike cancellation which raises."""

    def __init__(self, timeout_ms: Optional[float]):
        self._deadline = (time.monotonic() + timeout_ms / 1000.0
                          if timeout_ms is not None and timeout_ms >= 0
                          else None)
        self.timed_out = False

    @property
    def expired(self) -> bool:
        if self._deadline is not None and time.monotonic() > self._deadline:
            self.timed_out = True
            return True
        return False

    def remaining_ms(self) -> Optional[float]:
        """Milliseconds until expiry (negative when past), None when
        unbounded — lets a coordinator size per-RPC timeouts from the
        request budget."""
        if self._deadline is None:
            return None
        return (self._deadline - time.monotonic()) * 1000.0

"""Cluster-wide task plane: fan-out listing, node-routed get/cancel with
ban propagation, orphan reaping, and hot-threads fan-out.

The node-local registry (task_manager.py) knows only its own tasks; this
layer makes `GET /_tasks` a CLUSTER view (ref: TransportListTasksAction's
nodes fan-out), routes `{node}:{id}` operations to the owning node instead
of aliasing every id onto the receiving node, and carries the
TaskCancellationService ban protocol across the wire: cancelling a
coordinator fans `internal:cluster/tasks/ban` to every peer so shard
children — including ones whose registration RPC is still in flight —
die at their next dispatch boundary.

Degradation contract matches PR 6's transport tier: a dead/partitioned
peer never fails the whole listing; it becomes a `node_failures` entry
and the answer stays partial-but-useful.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuError, IllegalArgumentError,
)
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.tasks.task_manager import TaskManager
from elasticsearch_tpu.transport.channels import (
    NodeUnavailableError, RpcTimeoutError,
)

# transport actions (cluster:monitor/admin namespaces per the reference's
# action registry; internal: for the node-to-node ban/reap protocol)
ACTION_TASKS_LIST = "cluster:monitor/tasks/list"
ACTION_TASKS_GET = "cluster:monitor/tasks/get"
ACTION_TASKS_CANCEL = "cluster:admin/tasks/cancel"
ACTION_TASKS_DRAIN = "cluster:monitor/tasks/drain"
ACTION_TASKS_BAN = "internal:cluster/tasks/ban"
ACTION_TASKS_REAP = "internal:cluster/tasks/reap"
ACTION_HOT_THREADS = "cluster:monitor/nodes/hot_threads"

_FANOUT_ERRORS = (NodeUnavailableError, RpcTimeoutError)


def _not_running(tid: str) -> ElasticsearchTpuError:
    e = ElasticsearchTpuError(f"task [{tid}] isn't running")
    e.status = 404
    e.error_type = "resource_not_found_exception"
    return e


def _parse_task_id(tid: str) -> int:
    """Numeric parse FIRST: `zzz:notanum` must 400 before any node
    routing gets a chance to 404."""
    try:
        return int(tid.split(":")[-1])
    except ValueError:
        raise IllegalArgumentError(f"malformed task id [{tid}]")


class TaskPlane:
    """One node's view of cluster task management.

    ``channels``/``state_fn`` are None on a standalone Node — every
    operation then degrades to the local registry, same response shapes.
    """

    def __init__(self, tasks: TaskManager, node_name: str,
                 channels=None,
                 state_fn: Optional[Callable[[], object]] = None,
                 transport=None,
                 hot_label: Optional[str] = None):
        self.tasks = tasks
        self.node_name = node_name
        self.channels = channels
        self.state_fn = state_fn
        # "{name}{id}" header chunk for hot_threads sections
        self.hot_label = hot_label or f"{{{node_name}}}{{{tasks.node_id}}}"
        if transport is not None:
            transport.register_request_handler(ACTION_TASKS_LIST, self._on_list)
            transport.register_request_handler(ACTION_TASKS_GET, self._on_get)
            transport.register_request_handler(ACTION_TASKS_CANCEL,
                                               self._on_cancel)
            transport.register_request_handler(ACTION_TASKS_DRAIN,
                                               self._on_drain)
            transport.register_request_handler(ACTION_TASKS_BAN, self._on_ban)
            transport.register_request_handler(ACTION_TASKS_REAP, self._on_reap)
            transport.register_request_handler(ACTION_HOT_THREADS,
                                               self._on_hot_threads)

    # ---------------- topology ----------------

    def _peers(self) -> List[str]:
        if self.channels is None or self.state_fn is None:
            return []
        state = self.state_fn()
        out = []
        for nid, n in getattr(state, "nodes", {}).items():
            name = getattr(n, "name", None) or nid
            if name != self.node_name:
                out.append(name)
        return out

    def _known_node(self, name: str) -> bool:
        return name == self.node_name or name == self.tasks.node_id \
            or name in self._peers()

    # ---------------- list ----------------

    def _local_task_dicts(self, actions: Optional[str],
                          parent_task_id: Optional[str],
                          detailed: bool) -> Dict[str, dict]:
        out = {}
        for t in self.tasks.list(actions):
            if parent_task_id and t.parent_task_id != parent_task_id:
                continue
            out[t.task_id] = t.to_dict(detailed)
        return out

    def list(self, actions: Optional[str] = None,
             nodes: Optional[str] = None,
             parent_task_id: Optional[str] = None,
             detailed: bool = False,
             group_by: str = "nodes") -> dict:
        node_filter = set(nodes.split(",")) if nodes else None
        per_node: Dict[str, dict] = {}
        failures: List[dict] = []
        if node_filter is None or {self.node_name, self.tasks.node_id} & node_filter:
            per_node[self.tasks.node_id] = {"tasks": self._local_task_dicts(
                actions, parent_task_id, detailed)}
        payload = {"actions": actions, "parent_task_id": parent_task_id,
                   "detailed": detailed}
        for peer in self._peers():
            if node_filter is not None and peer not in node_filter:
                continue
            try:
                r = self.channels.request(peer, ACTION_TASKS_LIST, payload,
                                          source=self.node_name)
                per_node[peer] = {"tasks": r["tasks"]}
            except _FANOUT_ERRORS as e:
                failures.append({
                    "type": "failed_node_exception",
                    "reason": f"Failed node [{peer}]",
                    "node_id": peer,
                    "caused_by": {"type": e.error_type, "reason": str(e)},
                })
        out: dict = {}
        if group_by == "parents":
            out["tasks"] = self._group_by_parents(per_node)
        elif group_by == "none":
            out["tasks"] = [d for sec in per_node.values()
                            for d in sec["tasks"].values()]
        else:
            out["nodes"] = per_node
        if failures:
            out["node_failures"] = failures
        return out

    @staticmethod
    def _group_by_parents(per_node: Dict[str, dict]) -> Dict[str, dict]:
        """Flatten the node sections into a parent->children forest (ref:
        ListTasksResponse.getTaskGroups): a task whose parent is present
        in the result set nests under it; everything else is a root."""
        flat: Dict[str, dict] = {}
        for sec in per_node.values():
            flat.update(sec["tasks"])
        roots: Dict[str, dict] = {}
        by_id: Dict[str, dict] = {tid: dict(d) for tid, d in flat.items()}
        for tid, d in by_id.items():
            pid = d.get("parent_task_id")
            if pid and pid in by_id:
                by_id[pid].setdefault("children", []).append(d)
            else:
                roots[tid] = d
        return roots

    def _on_list(self, req) -> dict:
        p = req.payload
        return {"tasks": self._local_task_dicts(
            p.get("actions"), p.get("parent_task_id"),
            bool(p.get("detailed")))}

    # ---------------- get ----------------

    def _owner_of(self, tid: str) -> str:
        return tid.rsplit(":", 1)[0] if ":" in tid else ""

    def _is_local(self, owner: str) -> bool:
        return owner in ("", self.node_name, self.tasks.node_id)

    def get(self, tid: str) -> dict:
        num = _parse_task_id(tid)
        owner = self._owner_of(tid)
        if self._is_local(owner):
            t = self.tasks.get(num)
            if t is None:
                raise _not_running(tid)
            return {"completed": False, "task": t.to_dict(detailed=True)}
        if self.channels is None or not self._known_node(owner):
            raise _not_running(tid)
        try:
            return self.channels.request(owner, ACTION_TASKS_GET,
                                         {"id": num, "tid": tid},
                                         source=self.node_name)
        except _FANOUT_ERRORS:
            raise _not_running(tid)

    def _on_get(self, req) -> dict:
        t = self.tasks.get(req.payload["id"])
        if t is None:
            raise _not_running(req.payload.get("tid", str(req.payload["id"])))
        return {"completed": False, "task": t.to_dict(detailed=True)}

    # ---------------- cancel + ban propagation ----------------

    def cancel(self, tid: str, reason: str = "by user request",
               wait_for_completion: bool = False,
               timeout_ms: Optional[float] = None) -> dict:
        num = _parse_task_id(tid)
        owner = self._owner_of(tid)
        if not self._is_local(owner):
            if self.channels is None or not self._known_node(owner):
                raise _not_running(tid)
            try:
                return self.channels.request(
                    owner, ACTION_TASKS_CANCEL,
                    {"id": num, "tid": tid, "reason": reason,
                     "wait_for_completion": wait_for_completion,
                     "timeout_ms": timeout_ms},
                    source=self.node_name)
            except _FANOUT_ERRORS:
                raise _not_running(tid)
        t = self.tasks.cancel(num, reason)  # 400s on non-cancellable
        if t is None:
            raise _not_running(tid)
        self._propagate_ban(t.task_id, reason)
        if wait_for_completion:
            self.await_drain(t.task_id, timeout_ms)
        return {"nodes": {self.tasks.node_id: {
            "tasks": {t.task_id: t.to_dict(detailed=True)}}}}

    def _propagate_ban(self, parent_task_id: str, reason: str) -> None:
        """Ban locally (cancels registered children + arms
        cancel-on-arrival), then fan the ban to every peer. A peer we
        cannot reach holds no live children we could save anyway — its
        next contact with the cluster re-reaps via node-left."""
        self.tasks.ban(parent_task_id, reason)
        sent = 0
        for peer in self._peers():
            try:
                self.channels.request(
                    peer, ACTION_TASKS_BAN,
                    {"parent_task_id": parent_task_id, "reason": reason},
                    source=self.node_name)
                sent += 1
            except _FANOUT_ERRORS:
                pass
        if sent:
            self.tasks.note_bans_propagated(sent)

    def _on_cancel(self, req) -> dict:
        p = req.payload
        return self.cancel(p.get("tid", str(p["id"])),
                           reason=p.get("reason", "by user request"),
                           wait_for_completion=bool(
                               p.get("wait_for_completion")),
                           timeout_ms=p.get("timeout_ms"))

    def _on_ban(self, req) -> dict:
        p = req.payload
        cancelled = self.tasks.ban(p["parent_task_id"],
                                   p.get("reason", "parent task cancelled"))
        return {"cancelled": len(cancelled)}

    # ---------------- drain (wait_for_completion) ----------------

    def await_drain(self, parent_task_id: str,
                    timeout_ms: Optional[float] = None) -> bool:
        """Block until the task and its descendants are gone cluster-wide
        (bounded by the fan-out timeout knob when no explicit timeout)."""
        if timeout_ms is None:
            timeout_ms = float(knob("ES_TPU_TASK_FANOUT_TIMEOUT_MS"))
        deadline = time.monotonic() + timeout_ms / 1000.0
        ok = self.tasks.wait_for_drain(parent_task_id,
                                       timeout_ms / 1000.0)
        for peer in self._peers():
            remaining_ms = (deadline - time.monotonic()) * 1000.0
            if remaining_ms <= 0:
                return False
            try:
                r = self.channels.request(
                    peer, ACTION_TASKS_DRAIN,
                    {"parent_task_id": parent_task_id,
                     "timeout_ms": remaining_ms},
                    source=self.node_name)
                ok = ok and bool(r.get("drained", True))
            except _FANOUT_ERRORS:
                pass  # a dead peer's tasks died with it
        return ok

    def _on_drain(self, req) -> dict:
        p = req.payload
        return {"drained": self.tasks.wait_for_drain(
            p["parent_task_id"],
            float(p.get("timeout_ms") or 0.0) / 1000.0)}

    # ---------------- orphan reaping (node-left) ----------------

    def broadcast_reap(self, dead_node: str) -> None:
        """Master-side node-left hook: every surviving node bans the dead
        node's id prefix and cancels the children it orphaned."""
        self.tasks.reap_orphans(dead_node)
        for peer in self._peers():
            if peer == dead_node:
                continue
            try:
                self.channels.request(peer, ACTION_TASKS_REAP,
                                      {"node": dead_node},
                                      source=self.node_name)
            except _FANOUT_ERRORS:
                pass

    def _on_reap(self, req) -> dict:
        reaped = self.tasks.reap_orphans(req.payload["node"])
        return {"reaped": len(reaped)}

    # ---------------- hot threads ----------------

    def hot_threads(self) -> str:
        from elasticsearch_tpu.threadpool.pool import hot_threads_report

        sections = [hot_threads_report(self.hot_label)]
        for peer in self._peers():
            try:
                r = self.channels.request(peer, ACTION_HOT_THREADS, {},
                                          source=self.node_name)
                sections.append(r["report"])
            except _FANOUT_ERRORS as e:
                sections.append(f"::: {{{peer}}}\n"
                                f"   failed to fetch hot_threads: {e}\n")
        return "\n".join(sections)

    def _on_hot_threads(self, req) -> dict:
        from elasticsearch_tpu.threadpool.pool import hot_threads_report

        return {"report": hot_threads_report(self.hot_label)}

    # ---------------- /_cat/tasks ----------------

    def cat_rows(self, detailed: bool = False) -> List[str]:
        """Whitespace-table rows for `GET /_cat/tasks` (ref:
        RestTasksAction columns: action, task_id, parent, type,
        start_time, timestamp, running_time, node)."""
        listing = self.list(detailed=detailed, group_by="nodes")
        rows = []
        for nid, sec in sorted(listing.get("nodes", {}).items()):
            for tid, d in sorted(sec["tasks"].items()):
                start_s = d["start_time_in_millis"] / 1000.0
                hhmmss = time.strftime("%H:%M:%S", time.gmtime(start_s))
                running_ms = d["running_time_in_nanos"] / 1e6
                rows.append(" ".join([
                    d["action"], tid,
                    d.get("parent_task_id", "-") or "-",
                    d["type"], str(d["start_time_in_millis"]), hhmmss,
                    f"{running_ms:.1f}ms", d["node"],
                ]))
        return rows

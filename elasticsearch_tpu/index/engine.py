"""InternalEngine: the per-shard write path and searcher view.

Re-designs the reference engine (ref: index/engine/InternalEngine.java:842
`index()`, :913 translog add, :1057 indexIntoLucene; LiveVersionMap for
versioned upserts; CombinedDeletionPolicy for commits) around immutable TPU
segments:

  * Writes parse into LuceneDocs, get a seqno from the LocalCheckpointTracker,
    go to the translog, and land in an in-memory indexing buffer.
  * refresh() freezes the buffer into a new immutable Segment (the analog of
    Lucene's flush to a new reader) and tombstones superseded copies in older
    segments via per-segment live masks — deletes never mutate a segment.
  * Versioning: internal versioning with optimistic concurrency via
    if_seq_no/if_primary_term (ref: VersionConflictEngineException paths).
  * flush() persists segments + a commit point; recovery replays the translog
    above the committed local checkpoint.
  * merge() compacts segments by rebuilding from live docs' _source (host
    recompaction; ref: ElasticsearchConcurrentMergeScheduler conceptually).

The searcher view is an immutable snapshot: (segments, live-mask copies)
pinned at refresh, like Lucene's point-in-time readers.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from elasticsearch_tpu.common import integrity
from elasticsearch_tpu.common.durability import count as _count_durability
from elasticsearch_tpu.common.errors import DocumentMissingError, VersionConflictError
from elasticsearch_tpu.common.faults import corruption_fires, durability_fault_point
from elasticsearch_tpu.common.integrity import SegmentCorruptedError
from elasticsearch_tpu.index.segment import Segment, SegmentBuilder
from elasticsearch_tpu.index.segment_io import (
    segment_from_blob, segment_to_blob, verify_blob,
)
from elasticsearch_tpu.index.seqno import LocalCheckpointTracker, NO_OPS_PERFORMED
from elasticsearch_tpu.index.translog import Translog, TranslogFsyncError
from elasticsearch_tpu.mapper.mapper_service import MapperService


@dataclass
class EngineResult:
    doc_id: str
    version: int
    seq_no: int
    primary_term: int
    result: str  # created | updated | deleted | not_found


@dataclass
class SegmentView:
    """One segment plus its live mask frozen at snapshot time."""

    segment: Segment
    live: np.ndarray  # [n_docs] bool
    live_epoch: int   # increments when the mask changes; keys device cache


class EngineSearcher:
    """Point-in-time view over the engine's published segments."""

    def __init__(self, views: List[SegmentView]):
        self.views = views

    @property
    def n_docs(self) -> int:
        return sum(int(v.live.sum()) for v in self.views)

    @property
    def max_docs(self) -> int:
        return sum(v.segment.n_docs for v in self.views)


@dataclass
class _VersionEntry:
    seq_no: int
    version: int
    deleted: bool
    # where the latest live copy lives: buffer or (segment_index, ordinal)
    in_buffer: bool = False
    seg_idx: int = -1
    ord: int = -1


class InternalEngine:
    def __init__(
        self,
        mapper_service: MapperService,
        data_path: Optional[str] = None,
        primary_term: int = 1,
        translog_durability: str = "request",
    ):
        self.mapper = mapper_service
        self.primary_term = primary_term
        self.data_path = data_path
        self._lock = threading.RLock()
        self._seqno = LocalCheckpointTracker()
        self._versions: Dict[str, _VersionEntry] = {}  # LiveVersionMap analog
        self._buffer: Dict[str, tuple] = {}            # id -> (LuceneDoc, seq_no, version)
        self._buffer_order: List[str] = []
        self._segments: List[Segment] = []
        self._live: List[np.ndarray] = []
        self._live_epochs: List[int] = []
        self._next_seg_id = 0
        self._last_committed_checkpoint = NO_OPS_PERFORMED
        self._refresh_listeners: List = []
        # tragic-event latch (ref: Engine.failEngine): once the WAL failed
        # under this engine, no further write may be accepted — the copy is
        # failed via the master and replaced by a fresh instance
        self._failed_reason: Optional[str] = None
        if data_path is not None:
            os.makedirs(data_path, exist_ok=True)
            self.translog = Translog(os.path.join(data_path, "translog"), translog_durability)
            self.recover_from_disk()
        else:
            self.translog = None

    # ---------------- write path ----------------

    def index(
        self,
        doc_id: str,
        source: dict,
        *,
        seq_no: Optional[int] = None,
        if_seq_no: Optional[int] = None,
        if_primary_term: Optional[int] = None,
        op_type: str = "index",
        from_translog: bool = False,
        op_primary_term: Optional[int] = None,
    ) -> EngineResult:
        """Index or update one document (ref: InternalEngine.index:842)."""
        with self._lock:
            self._check_not_failed()
            self._check_op_term(op_primary_term)
            entry = self._versions.get(doc_id)
            exists = entry is not None and not entry.deleted
            if seq_no is not None and entry is not None and entry.seq_no >= seq_no:
                # replica/replay path: op is older than what we already hold
                # (ref: InternalEngine OpVsLuceneDocStatus.OP_STALE_OR_EQUAL)
                self._seqno.mark_processed(seq_no)
                return EngineResult(doc_id, entry.version, seq_no,
                                    self.primary_term, "noop")
            if if_seq_no is not None or if_primary_term is not None:
                cur_seq = entry.seq_no if entry else NO_OPS_PERFORMED
                if not exists or cur_seq != if_seq_no or self.primary_term != if_primary_term:
                    raise VersionConflictError(
                        f"[{doc_id}]: version conflict, required seqNo [{if_seq_no}], "
                        f"primary term [{if_primary_term}], current document has seqNo [{cur_seq}]"
                    )
            if op_type == "create" and exists:
                raise VersionConflictError(
                    f"[{doc_id}]: version conflict, document already exists "
                    f"(current version [{entry.version}])"
                )
            doc = self.mapper.parse(doc_id, source)
            seq = seq_no if seq_no is not None else self._seqno.generate_seq_no()
            version = (entry.version + 1) if entry is not None else 1
            # tombstone a previous published copy
            if entry is not None and not entry.in_buffer and entry.seg_idx >= 0:
                self._tombstone(entry.seg_idx, entry.ord)
            self._buffer[doc_id] = (doc, seq, version)
            if not (entry is not None and entry.in_buffer):
                self._buffer_order.append(doc_id)
            self._versions[doc_id] = _VersionEntry(seq_no=seq, version=version, deleted=False, in_buffer=True)
            if self.translog is not None and not from_translog:
                self._translog_add(
                    {"op": "index", "id": doc_id, "seq_no": seq,
                     "primary_term": self.primary_term, "version": version, "source": source}
                )
            self._seqno.mark_processed(seq)
            return EngineResult(doc_id, version, seq, self.primary_term,
                                "updated" if exists else "created")

    def delete(
        self,
        doc_id: str,
        *,
        seq_no: Optional[int] = None,
        if_seq_no: Optional[int] = None,
        if_primary_term: Optional[int] = None,
        from_translog: bool = False,
        op_primary_term: Optional[int] = None,
    ) -> EngineResult:
        with self._lock:
            self._check_not_failed()
            self._check_op_term(op_primary_term)
            entry = self._versions.get(doc_id)
            exists = entry is not None and not entry.deleted
            if seq_no is not None and entry is not None and entry.seq_no >= seq_no:
                self._seqno.mark_processed(seq_no)
                return EngineResult(doc_id, entry.version, seq_no,
                                    self.primary_term, "noop")
            if if_seq_no is not None or if_primary_term is not None:
                cur_seq = entry.seq_no if entry else NO_OPS_PERFORMED
                if not exists or cur_seq != if_seq_no or self.primary_term != if_primary_term:
                    raise VersionConflictError(
                        f"[{doc_id}]: version conflict on delete, required seqNo [{if_seq_no}]"
                    )
            seq = seq_no if seq_no is not None else self._seqno.generate_seq_no()
            if not exists:
                if seq_no is not None:
                    # replica path: record the tombstone so a stale index op
                    # arriving later cannot resurrect the doc
                    self._versions[doc_id] = _VersionEntry(
                        seq_no=seq, version=(entry.version + 1) if entry else 1,
                        deleted=True)
                self._seqno.mark_processed(seq)
                return EngineResult(doc_id, entry.version if entry else 1, seq,
                                    self.primary_term, "not_found")
            version = entry.version + 1
            if entry.in_buffer:
                self._buffer.pop(doc_id, None)
                if doc_id in self._buffer_order:
                    self._buffer_order.remove(doc_id)
            elif entry.seg_idx >= 0:
                self._tombstone(entry.seg_idx, entry.ord)
            self._versions[doc_id] = _VersionEntry(seq_no=seq, version=version, deleted=True)
            if self.translog is not None and not from_translog:
                self._translog_add({"op": "delete", "id": doc_id, "seq_no": seq,
                                    "primary_term": self.primary_term, "version": version})
            self._seqno.mark_processed(seq)
            return EngineResult(doc_id, version, seq, self.primary_term, "deleted")

    def _check_not_failed(self) -> None:  # tpulint: holds=_lock
        if self._failed_reason is not None:
            raise TranslogFsyncError(
                f"engine failed [{self._failed_reason}]; the shard copy "
                f"must be reallocated, not written to")

    def _translog_add(self, op: dict) -> None:  # tpulint: holds=_lock
        """Append one op to the WAL; a failed fsync is a tragic event: the
        engine latches failed so no later write can be acked into a WAL
        that already lost a record (ref: InternalEngine failOnTragicEvent).
        The in-memory effect of THIS op stays — it was never acked, and a
        write surviving unacked is the safe direction."""
        try:
            self.translog.add(op)
        except TranslogFsyncError as e:
            self._failed_reason = str(e)
            raise

    @property
    def failed_reason(self) -> Optional[str]:
        return self._failed_reason

    def _check_op_term(self, op_primary_term: Optional[int]) -> None:
        """Primary-term fencing on the replica path (ref: IndexShard
        acquireReplicaOperationPermit — ops from a deposed primary are
        rejected; a newer term is adopted)."""
        if op_primary_term is None:
            return
        if op_primary_term < self.primary_term:
            raise VersionConflictError(
                f"operation primary term [{op_primary_term}] is too old "
                f"(current [{self.primary_term}])")
        self.primary_term = op_primary_term

    def advance_primary_term(self, term: int) -> None:
        """Adopt a newer primary term (replica-side fencing bump on failover;
        ref: IndexShard.acquireReplicaOperationPermit term adoption). Happens
        explicitly during resync so fully-caught-up survivors — which replay
        zero ops — still reject the deposed primary's writes."""
        with self._lock:
            if term > self.primary_term:
                self.primary_term = term

    def docs_above(self, seq_no: int) -> List[str]:
        """Doc ids whose latest op is above seq_no (divergence candidates)."""
        with self._lock:
            return [d for d, e in self._versions.items() if e.seq_no > seq_no]

    def doc_resync_state(self, doc_id: str) -> Optional[dict]:
        """Authoritative latest state of one doc for primary-replica resync."""
        with self._lock:
            entry = self._versions.get(doc_id)
            if entry is None:
                return None
            if entry.deleted:
                return {"deleted": True, "seq_no": entry.seq_no, "version": entry.version}
            if entry.in_buffer:
                source = self._buffer[doc_id][0].source
            else:
                source = self._segments[entry.seg_idx].sources[entry.ord]
            return {"deleted": False, "seq_no": entry.seq_no,
                    "version": entry.version, "source": source}

    def force_resync_doc(self, doc_id: str, state: Optional[dict]) -> None:
        """Replace this copy's state for one doc with the new primary's
        authoritative state, discarding divergent local history — the per-doc
        form of the reference's engine rollback to the global checkpoint
        during primary-replica resync (ref: index/shard/IndexShard.java
        resetEngineToGlobalCheckpoint)."""
        with self._lock:
            entry = self._versions.get(doc_id)
            if entry is not None and state is not None \
                    and entry.seq_no == state["seq_no"] \
                    and entry.version == state["version"] \
                    and entry.deleted == state["deleted"]:
                return  # already identical — don't churn segments/caches
            if entry is not None and not entry.deleted:
                if entry.in_buffer:
                    self._buffer.pop(doc_id, None)
                    if doc_id in self._buffer_order:
                        self._buffer_order.remove(doc_id)
                elif entry.seg_idx >= 0:
                    self._tombstone(entry.seg_idx, entry.ord)
            if state is None:
                self._versions.pop(doc_id, None)
            elif state["deleted"]:
                self._versions[doc_id] = _VersionEntry(
                    seq_no=state["seq_no"], version=state["version"], deleted=True)
            else:
                doc = self.mapper.parse(doc_id, state["source"])
                self._buffer[doc_id] = (doc, state["seq_no"], state["version"])
                self._buffer_order.append(doc_id)
                self._versions[doc_id] = _VersionEntry(
                    seq_no=state["seq_no"], version=state["version"],
                    deleted=False, in_buffer=True)

    def reset_local_checkpoint(self, seq_no: int) -> None:
        """Rebuild the seqno tracker at a rollback point, discarding marks
        from a divergent history (resync resets to the global checkpoint).
        The translog is trimmed at the same point so crash recovery cannot
        resurrect the divergent tail."""
        with self._lock:
            self._seqno = LocalCheckpointTracker(max_seq_no=seq_no, local_checkpoint=seq_no)
            if self.translog is not None:
                self.translog.trim_above(seq_no)

    def fill_seqno_gaps(self, up_to: int) -> None:
        """Advance the local checkpoint over seqnos collapsed away by
        latest-op-per-doc replay (ops-based recovery / promotion no-op fill)."""
        with self._lock:
            self._seqno.fast_forward(up_to)

    def relog_above(self, seq_no: int) -> None:
        """Re-append the current op of every doc above seq_no to the translog.

        After a resync trim, replayed ops can no-op against already-identical
        in-memory entries (the stale-seqno check fires before translog.add),
        leaving acked writes with no durable record. Re-logging the surviving
        state above the trim point restores crash-recovery coverage."""
        with self._lock:
            if self.translog is None:
                return
            entries = sorted((e.seq_no, d) for d, e in self._versions.items()
                             if e.seq_no > seq_no)
            for _, doc_id in entries:
                entry = self._versions[doc_id]
                if entry.deleted:
                    self.translog.add({"op": "delete", "id": doc_id,
                                       "seq_no": entry.seq_no,
                                       "primary_term": self.primary_term,
                                       "version": entry.version})
                else:
                    if entry.in_buffer:
                        source = self._buffer[doc_id][0].source
                    else:
                        source = self._segments[entry.seg_idx].sources[entry.ord]
                    self.translog.add({"op": "index", "id": doc_id,
                                       "seq_no": entry.seq_no,
                                       "primary_term": self.primary_term,
                                       "version": entry.version, "source": source})

    def _tombstone(self, seg_idx: int, ord_: int) -> None:
        self._live[seg_idx][ord_] = False
        self._live_epochs[seg_idx] += 1

    # ---------------- reads ----------------

    def get(self, doc_id: str) -> Optional[dict]:
        """Realtime get (ref: InternalEngine.get — reads from the version map /
        translog before refresh makes the doc searchable)."""
        with self._lock:
            entry = self._versions.get(doc_id)
            if entry is None or entry.deleted:
                return None
            if entry.in_buffer:
                doc, seq, version = self._buffer[doc_id]
                return {"_id": doc_id, "_version": version, "_seq_no": seq,
                        "_primary_term": self.primary_term, "_source": doc.source}
            seg = self._segments[entry.seg_idx]
            return {"_id": doc_id, "_version": entry.version, "_seq_no": entry.seq_no,
                    "_primary_term": self.primary_term, "_source": seg.sources[entry.ord]}

    def changes_since(self, min_seq_no: int) -> List[dict]:
        """Operation history above a seqno, latest op per doc, seqno-ordered
        (ref: index/engine/LuceneChangesSnapshot.java — ops-based peer
        recovery and CCR read from the index's retained history; here the
        version map + segments retain the latest op for every doc including
        tombstones)."""
        with self._lock:
            ops = []
            for doc_id, entry in self._versions.items():
                if entry.seq_no <= min_seq_no:
                    continue
                if entry.deleted:
                    ops.append({"op": "delete", "id": doc_id, "seq_no": entry.seq_no,
                                "version": entry.version})
                else:
                    if entry.in_buffer:
                        source = self._buffer[doc_id][0].source
                    else:
                        source = self._segments[entry.seg_idx].sources[entry.ord]
                    ops.append({"op": "index", "id": doc_id, "seq_no": entry.seq_no,
                                "version": entry.version, "source": source})
            ops.sort(key=lambda o: o["seq_no"])
            return ops

    def acquire_searcher(self) -> EngineSearcher:
        with self._lock:
            views = [
                SegmentView(segment=s, live=self._live[i].copy(), live_epoch=self._live_epochs[i])
                for i, s in enumerate(self._segments)
            ]
            return EngineSearcher(views)

    def searcher_version(self) -> tuple:
        """Cheap identity of what acquire_searcher would return — no live-mask
        copies. Serving-snapshot caches key on this (ref: Lucene reader
        version as used by the shard request cache)."""
        with self._lock:
            # seg_id is engine-unique and never recycled (unlike id()):
            # cache keys built from it cannot alias a GC'd segment
            return tuple((s.seg_id, self._live_epochs[i])
                         for i, s in enumerate(self._segments))

    # ---------------- refresh / flush / merge ----------------

    def refresh(self) -> bool:
        """Freeze the indexing buffer into a new searchable segment."""
        with self._lock:
            if not self._buffer_order:
                return False
            builder = SegmentBuilder(seg_id=self._next_seg_id)
            ords: Dict[str, int] = {}
            for doc_id in self._buffer_order:
                if doc_id not in self._buffer:
                    continue
                doc, seq, version = self._buffer[doc_id]
                ords[doc_id] = builder.add(doc, seq_no=seq, version=version)
            segment = builder.build()
            seg_idx = len(self._segments)
            self._segments.append(segment)
            self._live.append(np.ones(segment.n_docs, bool))
            self._live_epochs.append(0)
            self._next_seg_id += 1
            for doc_id, ord_ in ords.items():
                entry = self._versions[doc_id]
                entry.in_buffer = False
                entry.seg_idx = seg_idx
                entry.ord = ord_
            self._buffer.clear()
            self._buffer_order.clear()
            return True

    def flush(self) -> None:
        """Commit: persist segments + metadata, roll translog generation.

        Ref: InternalEngine.flush — Lucene commit + translog rollover. Segment
        payloads are data-only array blobs (the segment IS the checkpoint;
        SURVEY.md §5.4; segment_io replaces pickle so on-disk state is never
        executable on load — ADVICE r3)."""
        if self.data_path is None:
            return
        with self._lock:
            try:
                durability_fault_point("segment_commit")
            except OSError:
                # a failed commit loses nothing durable: the previous commit
                # point + translog tail still recover every op
                _count_durability("segment_commit_failures")
                raise
            self.refresh()
            seg_dir = os.path.join(self.data_path, "segments")
            os.makedirs(seg_dir, exist_ok=True)
            names = []
            for i, seg in enumerate(self._segments):
                name = f"seg-{seg.seg_id}.seg"
                path = os.path.join(seg_dir, name)
                if not os.path.exists(path):
                    with open(path + ".tmp", "wb") as f:
                        f.write(segment_to_blob(seg))
                    os.replace(path + ".tmp", path)
                names.append({"file": name, "live": self._live[i].tolist()})
            gen = self.translog.rollover()
            commit = {
                "segments": names,
                "local_checkpoint": self._seqno.checkpoint,
                "max_seq_no": self._seqno.max_seq_no,
                "translog_generation": gen,
                "primary_term": self.primary_term,
            }
            tmp = os.path.join(self.data_path, "commit.json.tmp")
            with open(tmp, "w") as f:
                json.dump(commit, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.data_path, "commit.json"))
            self._last_committed_checkpoint = self._seqno.checkpoint
            self.translog.trim_below(gen)

    def recover_from_disk(self) -> None:
        """Crash recovery: load committed segments, replay translog tail
        (ref: index/shard/StoreRecovery.java + translog replay). Called
        from __init__ and by the crash-restart harness's reopened nodes."""
        commit_path = os.path.join(self.data_path, "commit.json")
        committed_cp = NO_OPS_PERFORMED
        if os.path.exists(commit_path):
            with open(commit_path) as f:
                commit = json.load(f)
            committed_cp = commit["local_checkpoint"]
            self.primary_term = max(self.primary_term, commit.get("primary_term", 1))
            self._seqno = LocalCheckpointTracker(
                max_seq_no=commit["max_seq_no"], local_checkpoint=committed_cp
            )
            seg_dir = os.path.join(self.data_path, "segments")
            for meta in commit["segments"]:
                seg: Segment = self._load_committed_segment(seg_dir, meta)
                seg_idx = len(self._segments)
                live = np.asarray(meta["live"], bool)
                self._segments.append(seg)
                self._live.append(live)
                self._live_epochs.append(0)
                self._next_seg_id = max(self._next_seg_id, seg.seg_id + 1)
                for ord_, doc_id in enumerate(seg.doc_ids):
                    if live[ord_]:
                        self._versions[doc_id] = _VersionEntry(
                            seq_no=int(seg.seq_nos[ord_]), version=int(seg.versions[ord_]),
                            deleted=False, in_buffer=False, seg_idx=seg_idx, ord=ord_,
                        )
                        self._seqno.mark_processed(int(seg.seq_nos[ord_]))
        # replay translog tail
        replayed = 0
        for op in self.translog.read_ops(min_seq_no=committed_cp):
            if op["op"] == "index":
                self.index(op["id"], op["source"], seq_no=op["seq_no"], from_translog=True)
            else:
                self.delete(op["id"], seq_no=op["seq_no"], from_translog=True)
            replayed += 1
        if replayed:
            _count_durability("translog_replays")
            _count_durability("translog_replayed_ops", replayed)

    # ---------------- integrity: at-rest verification ----------------

    def _load_committed_segment(self, seg_dir: str, meta: dict) -> Segment:
        """Read + verify one committed blob. The `segment_read` corruption
        site flips a bit in the bytes as read (bit rot between commit and
        reload); the footer verify inside `segment_from_blob` must catch
        it — a failure drops a ``corrupted-*`` marker so the copy cannot
        be reused before a fresh recovery overwrites the store."""
        with open(os.path.join(seg_dir, meta["file"]), "rb") as f:
            blob = f.read()
        if corruption_fires(meta["file"], site="segment_read"):
            blob = integrity.bitflip(blob)
        try:
            return segment_from_blob(blob)
        except SegmentCorruptedError as e:
            integrity.write_corruption_marker(
                self.data_path, str(e), segment=meta["file"])
            raise

    def verify_store(self) -> int:
        """Full-store checksum scan (the ES_TPU_CHECK_ON_STARTUP leg, ref:
        index.shard.check_on_startup): re-read every committed blob and
        verify its footer WITHOUT rebuilding segments. Returns the number
        of blobs checked; the first failure writes a ``corrupted-*``
        marker and raises `SegmentCorruptedError`."""
        if self.data_path is None:
            return 0
        commit_path = os.path.join(self.data_path, "commit.json")
        if not os.path.exists(commit_path):
            return 0
        with open(commit_path) as f:
            commit = json.load(f)
        seg_dir = os.path.join(self.data_path, "segments")
        checked = 0
        for meta in commit["segments"]:
            with open(os.path.join(seg_dir, meta["file"]), "rb") as f:
                blob = f.read()
            if corruption_fires(meta["file"], site="segment_read"):
                blob = integrity.bitflip(blob)
            try:
                verify_blob(blob)
            except SegmentCorruptedError as e:
                integrity.write_corruption_marker(
                    self.data_path, str(e), segment=meta["file"])
                raise
            checked += 1
        return checked

    # ---------------- peer-recovery snapshot transfer ----------------

    def segment_payloads(self) -> tuple:
        """File-phase recovery source: freeze the buffer, then hand out each
        published segment with its live mask (ref:
        indices/recovery/RecoverySourceHandler.java:267 phase1 — segment
        files are the recovery snapshot; here the segment IS the file).
        Returns ([(segment blob bytes, live mask)], max_seq_no)."""
        with self._lock:
            self.refresh()
            # segments are immutable once published: snapshot the references
            # and mask copies under the lock, serialize OUTSIDE it so a
            # large phase1 transfer does not stall indexing on the source
            snapshot = [(seg, self._live[i].copy())
                        for i, seg in enumerate(self._segments)]
            max_seq_no = self._seqno.max_seq_no
        payloads = [(segment_to_blob(seg), live) for seg, live in snapshot]
        return payloads, max_seq_no

    def install_segment(self, blob: bytes, live_mask) -> None:
        """File-phase recovery target: install one transferred segment
        (ref: indices/recovery/MultiFileWriter.java writes phase1 files).
        Ops-phase replay above the snapshot's seqnos follows separately."""
        with self._lock:
            seg: Segment = segment_from_blob(blob)
            seg_idx = len(self._segments)
            live = np.asarray(live_mask, bool)
            # remap to a locally-assigned seg id: the source's id can collide
            # with a locally-refreshed segment's id, and flush()'s
            # dedup-by-filename would then commit one payload under both
            seg.seg_id = self._next_seg_id
            self._segments.append(seg)
            self._live.append(live.copy())
            self._live_epochs.append(0)
            self._next_seg_id += 1
            for ord_, doc_id in enumerate(seg.doc_ids):
                if not live[ord_]:
                    continue
                seq = int(seg.seq_nos[ord_])
                prev = self._versions.get(doc_id)
                if prev is not None and prev.seq_no >= seq:
                    # a live write that raced ahead of the transfer wins;
                    # hide the stale installed copy
                    self._live[seg_idx][ord_] = False
                    self._live_epochs[seg_idx] += 1
                    continue
                if prev is not None and not prev.deleted:
                    if prev.in_buffer:
                        self._buffer.pop(doc_id, None)
                        if doc_id in self._buffer_order:
                            self._buffer_order.remove(doc_id)
                    elif prev.seg_idx >= 0:
                        self._tombstone(prev.seg_idx, prev.ord)
                self._versions[doc_id] = _VersionEntry(
                    seq_no=seq, version=int(seg.versions[ord_]),
                    deleted=False, in_buffer=False, seg_idx=seg_idx, ord=ord_)
                self._seqno.mark_processed(seq)

    def force_merge(self, max_num_segments: int = 1) -> None:
        """Compact segments by RECOMBINING columnar data (ref: Lucene
        SegmentMerger — postings/doc values concatenate with ord remaps;
        no _source re-parse, no re-analysis, so merging is O(postings)
        array work instead of O(corpus re-analysis))."""
        from elasticsearch_tpu.index.segment import merge_segments

        with self._lock:
            self.refresh()
            if len(self._segments) <= max_num_segments:
                return
            merged = merge_segments(self._segments, self._live,
                                    seg_id=self._next_seg_id)
            self._segments = [merged]
            self._live = [np.ones(merged.n_docs, bool)]
            self._live_epochs = [0]
            self._next_seg_id += 1
            for ord_, doc_id in enumerate(merged.doc_ids):
                entry = self._versions.get(doc_id)
                if entry is not None and not entry.in_buffer:
                    entry.seg_idx = 0
                    entry.ord = ord_

    # ---------------- stats ----------------

    @property
    def local_checkpoint(self) -> int:
        return self._seqno.checkpoint

    @property
    def max_seq_no(self) -> int:
        return self._seqno.max_seq_no

    @property
    def seqno_tracker(self) -> LocalCheckpointTracker:
        return self._seqno

    def doc_count(self) -> int:
        with self._lock:
            n = sum(int(l.sum()) for l in self._live)
            n += len([d for d in self._buffer_order if d in self._buffer])
            return n

    def segment_count(self) -> int:
        return len(self._segments)

    def close(self) -> None:
        if self.translog is not None:
            self.translog.close()

"""The TPU segment: an immutable index partition as fixed-shape arrays.

Re-designs Lucene's per-segment read structures (block postings with skip
data, norms, doc values, stored fields; ref: Lucene 8.8 Lucene87Codec as
wrapped by index/codec/CodecService.java:27) for device execution:

  * Inverted fields -> block-compressed postings: all of a field's postings
    concatenated as [n_blocks, 128] (doc-id, tf) arrays in HBM, plus per-term
    (block_start, block_count) host metadata. Block row 0 is reserved as
    all-zero padding target (see ops/scoring.py).
  * Norms -> a dense f32 doc_len column per text field.
  * Positions (phrase queries) -> host-side CSR arrays per field
    (term -> postings -> positions); phrase verification runs on candidates.
  * Numeric doc values -> host f64 columns (+ device f32 copies for aggs);
    f64 stays host-side because TPUs have no fast f64 and range/sort need
    exact date-millis semantics.
  * Keyword doc values -> ordinals into a sorted per-segment term dictionary
    (ref: Lucene SortedSetDocValues), single-valued fast path column.
  * dense_vector -> one [n_docs, dims] matrix (bf16 on device) + norms.
  * Stored fields (_source) -> host list of dicts.

Deletes never mutate a segment: the owning shard keeps per-segment live-doc
masks (tombstones), exactly like Lucene's liveDocs bitsets.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List

import numpy as np

from elasticsearch_tpu.mapper.mapper_service import LuceneDoc

BLOCK = 128


@dataclass
class FieldPostings:
    """Block postings + positions for one inverted (text/keyword) field."""

    field: str
    term_to_ord: Dict[str, int]
    terms: List[str]                    # ord -> term (sorted)
    doc_freq: np.ndarray                # [n_terms] i32
    total_term_freq: np.ndarray         # [n_terms] i64
    block_start: np.ndarray             # [n_terms] i32 (row into block arrays)
    block_count: np.ndarray             # [n_terms] i32
    block_docs: np.ndarray              # [n_blocks, BLOCK] i32 (row 0 = zeros)
    block_tfs: np.ndarray               # [n_blocks, BLOCK] f32
    block_max_tf: np.ndarray            # [n_blocks] f32 (block-max metadata)
    # positions CSR (host): term -> slice of postings -> slice of positions
    post_start: np.ndarray              # [n_terms + 1] i64
    post_doc: np.ndarray                # [total_postings] i32
    pos_start: np.ndarray               # [total_postings + 1] i64
    pos_data: np.ndarray                # [total_positions] i32
    # norms
    doc_len: np.ndarray                 # [n_docs] f32 (token count; 0 if absent)
    sum_doc_len: float

    def ord(self, term: str) -> int:
        return self.term_to_ord.get(term, -1)

    def term_block_ids(self, term: str) -> np.ndarray:
        o = self.term_to_ord.get(term)
        if o is None:
            return np.empty(0, np.int32)
        s, c = int(self.block_start[o]), int(self.block_count[o])
        return np.arange(s, s + c, dtype=np.int32)

    def positions(self, term: str, doc_ord: int) -> np.ndarray:
        """Positions of `term` in `doc_ord` (host lookup for phrase verify)."""
        o = self.term_to_ord.get(term)
        if o is None:
            return np.empty(0, np.int32)
        lo, hi = int(self.post_start[o]), int(self.post_start[o + 1])
        idx = np.searchsorted(self.post_doc[lo:hi], doc_ord)
        if idx >= hi - lo or self.post_doc[lo + idx] != doc_ord:
            return np.empty(0, np.int32)
        p = lo + idx
        return self.pos_data[int(self.pos_start[p]): int(self.pos_start[p + 1])]


def tf_at(fp: "FieldPostings", term: str,
          docs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(tf f32[n], present bool[n]) of `term` for sorted candidate docs.

    SHARED by the serving conjunctive reference (search/serving.py) and
    the TurboBM25 bool rescore (parallel/turbo.py): both sides computing
    tf through this one function is what keeps their scores bit-identical.
    """
    o = fp.term_to_ord.get(term)
    if o is None:
        return np.zeros(len(docs), np.float32), np.zeros(len(docs), bool)
    lo, hi = int(fp.post_start[o]), int(fp.post_start[o + 1])
    seg = fp.post_doc[lo:hi]
    j = np.searchsorted(seg, docs)
    present = (j < hi - lo)
    present[present] = seg[j[present]] == docs[present]
    within = np.where(present, j, 0).astype(np.int64)
    row = int(fp.block_start[o]) + within // 128
    lane = within % 128
    tf = fp.block_tfs[row, lane].astype(np.float32)
    return np.where(present, tf, 0.0), present


@dataclass
class NumericColumn:
    values: np.ndarray                  # [n_docs] f64 (min value; asc sort mode)
    max_values: np.ndarray              # [n_docs] f64 (max value; desc sort mode)
    exists: np.ndarray                  # [n_docs] bool
    # full multi-value CSR for range semantics ("any value in range")
    value_start: np.ndarray             # [n_docs + 1] i64
    all_values: np.ndarray              # [total_values] f64 (per-doc sorted)

    def min_values(self) -> np.ndarray:
        return self.values

    def range_mask(self, lo: float, hi: float, include_lo: bool, include_hi: bool) -> np.ndarray:
        left = self.all_values >= lo if include_lo else self.all_values > lo
        right = self.all_values <= hi if include_hi else self.all_values < hi
        hit = (left & right).astype(np.int64)
        cum = np.concatenate([[0], np.cumsum(hit)])
        counts = cum[self.value_start[1:]] - cum[self.value_start[:-1]]
        return (counts > 0) & self.exists


@dataclass
class KeywordColumn:
    terms: List[str]                    # sorted dictionary
    term_to_ord: Dict[str, int]
    ords: np.ndarray                    # [n_docs] i32, -1 = missing (min value;
    #                                     the reference's asc sort mode "min")
    max_ords: np.ndarray                # [n_docs] i32 (max value; desc sort mode)
    exists: np.ndarray                  # [n_docs] bool
    ord_start: np.ndarray               # [n_docs + 1] i64 — multivalue CSR
    all_ords: np.ndarray                # [total_values] i32 (per-doc sorted)

    def doc_terms(self, ord_: int) -> List[str]:
        lo, hi = int(self.ord_start[ord_]), int(self.ord_start[ord_ + 1])
        return [self.terms[o] for o in self.all_ords[lo:hi]]


@dataclass
class GeoColumn:
    """Paired lat/lon multivalues (CSR, UNSORTED so index i of lat pairs
    with index i of lon — per-axis sorting would scramble the points)."""

    lat: np.ndarray                     # [total_points] f64
    lon: np.ndarray                     # [total_points] f64
    value_start: np.ndarray             # [n_docs + 1] i64
    exists: np.ndarray                  # [n_docs] bool


@dataclass
class NestedTable:
    """Child-table sidecar for one nested field: a full child Segment
    (postings/columns over child rows) plus the child->parent map. The
    TPU-first block-join: parent doc ids/seqnos/live masks are untouched;
    nested queries score the child table and CSR-reduce to parents."""

    child: "Segment"                    # child rows as their own segment
    parent_of: np.ndarray               # [n_children] i32 parent ord (sorted)
    child_start: np.ndarray             # [n_parents + 1] i64 CSR


@dataclass
class VectorColumn:
    vectors: np.ndarray                 # [n_docs, dims] f32
    norms: np.ndarray                   # [n_docs] f32
    exists: np.ndarray                  # [n_docs] bool
    dims: int
    similarity: str


class Segment:
    """Immutable per-shard index partition. Host arrays always present;
    device arrays materialized lazily per field via `device()`."""

    def __init__(
        self,
        seg_id: int,
        doc_ids: List[str],
        sources: List[dict],
        postings: Dict[str, FieldPostings],
        numeric: Dict[str, NumericColumn],
        keyword: Dict[str, KeywordColumn],
        vectors: Dict[str, VectorColumn],
        seq_nos: np.ndarray,
        versions: np.ndarray | None = None,
        geo: Dict[str, "GeoColumn"] | None = None,
        nested: Dict[str, "NestedTable"] | None = None,
    ):
        self.seg_id = seg_id
        self.n_docs = len(doc_ids)
        self.doc_ids = doc_ids
        self.id_to_ord = {d: i for i, d in enumerate(doc_ids)}
        self.sources = sources
        self.postings = postings
        self.numeric = numeric
        self.keyword = keyword
        self.vectors = vectors
        self.geo = geo or {}
        self.nested = nested or {}
        self.seq_nos = seq_nos          # [n_docs] i64 — seqno of each op
        self.versions = versions if versions is not None else np.ones(self.n_docs, np.int64)
        self._device: dict = {}
        self._device_lock = threading.Lock()

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_device"] = {}          # device arrays are never persisted
        state.pop("_device_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.__dict__.setdefault("geo", {})   # pre-geo pickled segments
        self.__dict__.setdefault("nested", {})
        self._device = {}
        self._device_lock = threading.Lock()

    # ---- stats (combined at shard level for idf/avgdl) ----

    def field_stats(self, field: str) -> tuple[int, float]:
        """(docs with field, sum of field lengths) for BM25 norms."""
        fp = self.postings.get(field)
        if fp is None:
            return 0, 0.0
        return int(np.count_nonzero(fp.doc_len)), float(fp.sum_doc_len)

    def term_stats(self, field: str, term: str) -> tuple[int, int]:
        """(doc_freq, total_term_freq) of term in this segment."""
        fp = self.postings.get(field)
        if fp is None:
            return 0, 0
        o = fp.ord(term)
        if o < 0:
            return 0, 0
        return int(fp.doc_freq[o]), int(fp.total_term_freq[o])

    # ---- device residency ----

    def device(self, key: str):
        """Lazily device_put one array group. Keys:
        'post:<field>' -> (block_docs, block_tfs, doc_len)
        'vec:<field>'  -> (vectors[bf16], norms, exists)
        'num:<field>'  -> (values f32, exists)
        'kw:<field>'   -> (ords i32, exists)
        """
        import jax
        import jax.numpy as jnp

        with self._device_lock:
            if key in self._device:
                return self._device[key]
            kind, _, fname = key.partition(":")
            if kind == "post":
                fp = self.postings[fname]
                out = (
                    jax.device_put(fp.block_docs),
                    jax.device_put(fp.block_tfs),
                    jax.device_put(fp.doc_len),
                )
            elif kind == "vec":
                vc = self.vectors[fname]
                host = vc.vectors.astype(np.float32)
                if vc.similarity == "cosine":
                    # pre-normalize rows at upload: the scoring hot loop
                    # then divides by the query norm only (ops/knn.py)
                    host = host / np.maximum(vc.norms, 1e-20)[:, None]
                out = (
                    jax.device_put(host).astype(jnp.bfloat16),
                    jax.device_put(vc.norms),
                    jax.device_put(vc.exists),
                )
            elif kind == "num":
                nc = self.numeric[fname]
                out = (jax.device_put(nc.values.astype(np.float32)), jax.device_put(nc.exists))
            elif kind == "kw":
                kc = self.keyword[fname]
                out = (jax.device_put(kc.ords), jax.device_put(kc.exists))
            else:
                raise KeyError(key)
            self._device[key] = out
            return out

    def ram_bytes(self) -> int:
        total = 0
        for fp in self.postings.values():
            total += fp.block_docs.nbytes + fp.block_tfs.nbytes + fp.doc_len.nbytes
            total += fp.pos_data.nbytes + fp.post_doc.nbytes
        for vc in self.vectors.values():
            total += vc.vectors.nbytes
        for nc in self.numeric.values():
            total += nc.values.nbytes + nc.all_values.nbytes
        for kc in self.keyword.values():
            total += kc.ords.nbytes
        return total


def build_field_postings(
    field: str,
    doc_lens: np.ndarray,      # [n_docs] token count per doc
    token_docs: np.ndarray,    # [n_tokens] doc ord of each token
    token_terms: np.ndarray,   # [n_tokens] term ord of each token
    term_names: List[str],     # term ord -> term string (sorted)
    token_pos: np.ndarray | None = None,  # [n_tokens] position within its doc
) -> FieldPostings:
    """Columnar bulk postings build: token arrays -> block postings, fully
    vectorized (the analog of Lucene's flush from sorted (term, doc) pairs,
    ref: Lucene87 postings writer) — indexes millions of docs in seconds
    where the per-doc builder path takes minutes. When `token_pos` is given,
    the positions CSR is recorded too (phrase/highlight support); the sort
    groups (term, doc) runs with ascending positions, matching the per-doc
    SegmentBuilder layout."""
    n_docs = len(doc_lens)
    n_terms = len(term_names)
    # tf per (term, doc): unique over a combined key, sorted by term then doc
    key = token_terms.astype(np.int64) * n_docs + token_docs.astype(np.int64)
    if token_pos is not None:
        # group-order tokens by (term, doc) with positions ascending inside a
        # group: np.unique's ascending uniq matches this lexsort's group order
        order = np.lexsort((token_pos, token_docs, token_terms))
        pos_sorted = np.ascontiguousarray(token_pos[order]).astype(np.int32)
    uniq, tf = np.unique(key, return_counts=True)
    term_ord = (uniq // n_docs).astype(np.int64)
    doc_ord = (uniq % n_docs).astype(np.int64)
    # block layout + CSR assembly shared with the segment merger
    return _assemble_postings(
        field, n_docs, list(term_names), term_ord, doc_ord,
        tf.astype(np.float32),
        tf.astype(np.int64) if token_pos is not None else np.empty(0, np.int64),
        pos_sorted if token_pos is not None else np.empty(0, np.int32),
        doc_lens.astype(np.float32),
        has_positions=token_pos is not None)


class SegmentBuilder:
    """Accumulates parsed docs and freezes them into a Segment.

    The analog of Lucene's DocumentsWriter + flush: called under the engine's
    refresh (ref: index/engine/InternalEngine.java refresh -> new reader).
    """

    def __init__(self, seg_id: int = 0):
        self.seg_id = seg_id
        self._docs: List[LuceneDoc] = []
        self._seq_nos: List[int] = []
        self._versions: List[int] = []

    def add(self, doc: LuceneDoc, seq_no: int = -1, version: int = 1) -> int:
        self._docs.append(doc)
        self._seq_nos.append(seq_no)
        self._versions.append(version)
        return len(self._docs) - 1

    def __len__(self) -> int:
        return len(self._docs)

    def build(self) -> Segment:
        docs = self._docs
        n_docs = len(docs)

        # -- collect field name sets --
        inverted_fields: dict[str, None] = {}
        numeric_fields: dict[str, None] = {}
        keyword_fields: dict[str, None] = {}
        vector_fields: dict[str, None] = {}
        geo_fields: dict[str, None] = {}
        nested_fields: dict[str, None] = {}
        for d in docs:
            for f in d.geo:
                geo_fields[f] = None
            for f in d.nested:
                nested_fields[f] = None
            for f in d.inverted:
                inverted_fields[f] = None
            for f in d.numeric:
                numeric_fields[f] = None
            for f in d.keyword:
                keyword_fields[f] = None
            for f in d.vectors:
                vector_fields[f] = None

        postings = {}
        for fname in inverted_fields:
            postings[fname] = self._build_postings(fname, docs, is_keyword=False)
        # keyword fields are ALSO inverted (term filters run on device blocks)
        for fname in keyword_fields:
            postings.setdefault(fname, self._build_postings(fname, docs, is_keyword=True))

        numeric = {f: self._build_numeric(f, docs) for f in numeric_fields}
        keyword = {f: self._build_keyword(f, docs) for f in keyword_fields}
        vectors = {f: self._build_vectors(f, docs) for f in vector_fields}
        geo = {f: self._build_geo(f, docs) for f in geo_fields}
        nested = {f: self._build_nested(f, docs) for f in nested_fields}

        return Segment(
            seg_id=self.seg_id,
            doc_ids=[d.doc_id for d in docs],
            sources=[d.source for d in docs],
            postings=postings,
            numeric=numeric,
            keyword=keyword,
            vectors=vectors,
            seq_nos=np.asarray(self._seq_nos, np.int64),
            versions=np.asarray(self._versions, np.int64),
            geo=geo,
            nested=nested,
        )

    # ---- builders ----

    def _build_postings(self, fname: str, docs: List[LuceneDoc], *, is_keyword: bool) -> FieldPostings:
        # term -> list[(doc_ord, tf, positions)]
        term_postings: Dict[str, list] = {}
        doc_len = np.zeros(len(docs), np.float32)
        for ord_, d in enumerate(docs):
            if is_keyword:
                entries = [(t, [0]) for t in d.keyword.get(fname, ())]
            else:
                entries = d.inverted.get(fname, ())
                doc_len[ord_] = d.field_lengths.get(fname, 0)
            if not entries:
                continue
            # merge duplicate term entries within one doc (multi-valued text)
            merged: Dict[str, list] = {}
            for term, positions in entries:
                merged.setdefault(term, []).extend(positions)
            for term, positions in merged.items():
                term_postings.setdefault(term, []).append((ord_, len(positions), sorted(positions)))

        terms = sorted(term_postings)
        n_terms = len(terms)
        term_to_ord = {t: i for i, t in enumerate(terms)}

        doc_freq = np.zeros(n_terms, np.int32)
        total_tf = np.zeros(n_terms, np.int64)
        block_start = np.zeros(n_terms, np.int32)
        block_count = np.zeros(n_terms, np.int32)

        # count blocks; row 0 reserved for zero padding
        total_blocks = 1
        for i, t in enumerate(terms):
            plist = term_postings[t]
            doc_freq[i] = len(plist)
            total_tf[i] = sum(tf for _, tf, _ in plist)
            nb = (len(plist) + BLOCK - 1) // BLOCK
            block_start[i] = total_blocks
            block_count[i] = nb
            total_blocks += nb

        block_docs = np.zeros((total_blocks, BLOCK), np.int32)
        block_tfs = np.zeros((total_blocks, BLOCK), np.float32)
        block_max_tf = np.zeros(total_blocks, np.float32)

        post_start = np.zeros(n_terms + 1, np.int64)
        post_doc_parts: List[np.ndarray] = []
        pos_counts: List[int] = []
        pos_parts: List[np.ndarray] = []

        for i, t in enumerate(terms):
            plist = term_postings[t]  # already doc-ord sorted (insertion order)
            d_arr = np.fromiter((p[0] for p in plist), np.int32, len(plist))
            tf_arr = np.fromiter((p[1] for p in plist), np.float32, len(plist))
            row = int(block_start[i])
            for off in range(0, len(plist), BLOCK):
                chunk_d = d_arr[off: off + BLOCK]
                chunk_tf = tf_arr[off: off + BLOCK]
                block_docs[row, : len(chunk_d)] = chunk_d
                block_tfs[row, : len(chunk_tf)] = chunk_tf
                block_max_tf[row] = float(chunk_tf.max()) if len(chunk_tf) else 0.0
                row += 1
            post_start[i + 1] = post_start[i] + len(plist)
            post_doc_parts.append(d_arr)
            for p in plist:
                pos_counts.append(len(p[2]))
                pos_parts.append(np.asarray(p[2], np.int32))

        post_doc = np.concatenate(post_doc_parts) if post_doc_parts else np.empty(0, np.int32)
        pos_start = np.zeros(len(post_doc) + 1, np.int64)
        if pos_counts:
            np.cumsum(pos_counts, out=pos_start[1:])
        pos_data = np.concatenate(pos_parts) if pos_parts else np.empty(0, np.int32)

        return FieldPostings(
            field=fname,
            term_to_ord=term_to_ord,
            terms=terms,
            doc_freq=doc_freq,
            total_term_freq=total_tf,
            block_start=block_start,
            block_count=block_count,
            block_docs=block_docs,
            block_tfs=block_tfs,
            block_max_tf=block_max_tf,
            post_start=post_start,
            post_doc=post_doc,
            pos_start=pos_start,
            pos_data=pos_data,
            doc_len=doc_len,
            sum_doc_len=float(doc_len.sum()),
        )

    def _build_nested(self, fname: str, docs: List[LuceneDoc]) -> "NestedTable":
        child_builder = SegmentBuilder(seg_id=0)
        parent_of: List[int] = []
        child_start = np.zeros(len(docs) + 1, np.int64)
        for i, d in enumerate(docs):
            child_start[i] = len(parent_of)
            for child in d.nested.get(fname, ()):
                child_builder.add(child, seq_no=-1)
                parent_of.append(i)
        child_start[len(docs)] = len(parent_of)
        return NestedTable(child=child_builder.build(),
                           parent_of=np.asarray(parent_of, np.int32),
                           child_start=child_start)

    def _build_geo(self, fname: str, docs: List[LuceneDoc]) -> "GeoColumn":
        n = len(docs)
        exists = np.zeros(n, bool)
        starts = np.zeros(n + 1, np.int64)
        lat_parts: List[float] = []
        lon_parts: List[float] = []
        for i, d in enumerate(docs):
            pts = d.geo.get(fname)
            starts[i] = len(lat_parts)
            if pts:
                exists[i] = True
                for la, lo in pts:
                    lat_parts.append(la)
                    lon_parts.append(lo)
        starts[n] = len(lat_parts)
        return GeoColumn(lat=np.asarray(lat_parts, np.float64),
                         lon=np.asarray(lon_parts, np.float64),
                         value_start=starts, exists=exists)

    def _build_numeric(self, fname: str, docs: List[LuceneDoc]) -> NumericColumn:
        n = len(docs)
        values = np.zeros(n, np.float64)
        max_values = np.zeros(n, np.float64)
        exists = np.zeros(n, bool)
        starts = np.zeros(n + 1, np.int64)
        all_parts: List[np.ndarray] = []
        total = 0
        for i, d in enumerate(docs):
            vs = d.numeric.get(fname)
            starts[i] = total
            if vs:
                arr = np.sort(np.asarray(vs, np.float64))
                values[i] = arr[0]
                max_values[i] = arr[-1]
                exists[i] = True
                all_parts.append(arr)
                total += len(arr)
        starts[n] = total
        all_values = np.concatenate(all_parts) if all_parts else np.empty(0, np.float64)
        return NumericColumn(values=values, max_values=max_values, exists=exists,
                             value_start=starts, all_values=all_values)

    def _build_keyword(self, fname: str, docs: List[LuceneDoc]) -> KeywordColumn:
        n = len(docs)
        vocab: dict[str, None] = {}
        for d in docs:
            for v in d.keyword.get(fname, ()):
                vocab[v] = None
        terms = sorted(vocab)
        term_to_ord = {t: i for i, t in enumerate(terms)}
        ords = np.full(n, -1, np.int32)
        max_ords = np.full(n, -1, np.int32)
        exists = np.zeros(n, bool)
        ord_start = np.zeros(n + 1, np.int64)
        all_parts: List[np.ndarray] = []
        total = 0
        for i, d in enumerate(docs):
            vs = d.keyword.get(fname)
            ord_start[i] = total
            if vs:
                os_ = sorted({term_to_ord[v] for v in vs})
                ords[i] = os_[0]
                max_ords[i] = os_[-1]
                exists[i] = True
                all_parts.append(np.asarray(os_, np.int32))
                total += len(os_)
        ord_start[n] = total
        all_ords = np.concatenate(all_parts) if all_parts else np.empty(0, np.int32)
        return KeywordColumn(terms=terms, term_to_ord=term_to_ord, ords=ords,
                             max_ords=max_ords, exists=exists,
                             ord_start=ord_start, all_ords=all_ords)

    def _build_vectors(self, fname: str, docs: List[LuceneDoc]) -> VectorColumn:
        n = len(docs)
        dims = 0
        sim = "cosine"
        for d in docs:
            v = d.vectors.get(fname)
            if v is not None:
                dims = len(v)
                break
        vectors = np.zeros((n, max(dims, 1)), np.float32)
        exists = np.zeros(n, bool)
        for i, d in enumerate(docs):
            v = d.vectors.get(fname)
            if v is not None:
                vectors[i] = v
                exists[i] = True
        norms = np.linalg.norm(vectors, axis=1).astype(np.float32)
        return VectorColumn(vectors=vectors, norms=norms, exists=exists, dims=dims, similarity=sim)


# --------------------------------------------------------------------------
# Columnar segment merge
# --------------------------------------------------------------------------


def merge_segments(segments: List[Segment], live_masks: List[np.ndarray],
                   seg_id: int) -> Segment:
    """Compact segments into one by RECOMBINING columnar data directly —
    no _source re-parse, no re-analysis (ref: Lucene SegmentMerger, which
    likewise concatenates postings/doc values with ord remaps; VERDICT r2
    weak #9 called the re-parse merge unusable at 1M+ docs).

    Dead docs are dropped; surviving docs keep their relative order
    (segment-major), so per-term postings stay doc-ascending after the
    remap and block arrays rebuild vectorized."""
    keeps = [np.asarray(m, bool) for m in live_masks]
    bases: List[int] = []
    ord_maps: List[np.ndarray] = []
    total = 0
    for seg, keep in zip(segments, keeps):
        bases.append(total)
        m = np.cumsum(keep) - 1 + total
        ord_maps.append(m.astype(np.int64))
        total += int(keep.sum())

    doc_ids: List[str] = []
    sources: List[dict] = []
    seq_parts, ver_parts = [], []
    for seg, keep in zip(segments, keeps):
        idx = np.nonzero(keep)[0]
        doc_ids.extend(seg.doc_ids[i] for i in idx)
        sources.extend(seg.sources[i] for i in idx)
        seq_parts.append(seg.seq_nos[idx])
        ver_parts.append(seg.versions[idx])

    fields = {}
    for seg in segments:
        for name in seg.postings:
            fields[name] = None
    postings = {f: _merge_postings(f, segments, keeps, ord_maps, total)
                for f in fields}
    num_fields = {n: None for seg in segments for n in seg.numeric}
    numeric = {f: _merge_numeric(f, segments, keeps, total) for f in num_fields}
    kw_fields = {n: None for seg in segments for n in seg.keyword}
    keyword = {f: _merge_keyword(f, segments, keeps, total) for f in kw_fields}
    vec_fields = {n: None for seg in segments for n in seg.vectors}
    vectors = {f: _merge_vectors(f, segments, keeps, total) for f in vec_fields}
    geo_fields = {n: None for seg in segments for n in seg.geo}
    geo = {f: _merge_geo(f, segments, keeps, total) for f in geo_fields}
    nested_fields = {n: None for seg in segments for n in seg.nested}
    nested = {f: _merge_nested(f, segments, keeps, total)
              for f in nested_fields}

    return Segment(
        seg_id=seg_id, doc_ids=doc_ids, sources=sources, postings=postings,
        numeric=numeric, keyword=keyword, vectors=vectors,
        seq_nos=np.concatenate(seq_parts) if seq_parts else np.empty(0, np.int64),
        versions=np.concatenate(ver_parts) if ver_parts else np.empty(0, np.int64),
        geo=geo, nested=nested,
    )


def _merge_csr(keep: np.ndarray, value_start: np.ndarray, base: int):
    """Shared CSR recombination: (per-kept-doc new start offsets, flat take
    mask over the values, number of surviving values)."""
    counts = (value_start[1:] - value_start[:-1])[keep]
    n = len(counts)
    starts = base + (np.concatenate([[0], np.cumsum(counts)[:-1]])
                     if n else np.empty(0, np.int64))
    take = np.repeat(keep, value_start[1:] - value_start[:-1])
    return starts.astype(np.int64), take, int(counts.sum())


def _posting_tf(fp: FieldPostings) -> np.ndarray:
    """Per-posting tf aligned with post_doc, gathered from block lanes."""
    n = len(fp.post_doc)
    if n == 0:
        return np.empty(0, np.float32)
    df = fp.doc_freq.astype(np.int64)
    within = np.arange(n, dtype=np.int64) - np.repeat(
        fp.post_start[:-1], df)
    lane_ids = np.repeat(fp.block_start.astype(np.int64) * BLOCK, df) + within
    return fp.block_tfs.ravel()[lane_ids]


def _merge_postings(field: str, segments, keeps, ord_maps, total: int
                    ) -> FieldPostings:
    # union over terms with at least one SURVIVING posting — dead-only
    # terms must not accumulate across merge generations
    term_arrays = []
    for seg, keep in zip(segments, keeps):
        fp = seg.postings.get(field)
        if fp is not None and fp.terms and len(fp.post_doc):
            local = np.repeat(np.arange(len(fp.terms), dtype=np.int64),
                              fp.doc_freq.astype(np.int64))
            live_locals = np.unique(local[keep[fp.post_doc]])
            if len(live_locals):
                term_arrays.append(
                    np.asarray(fp.terms, object)[live_locals])
    union = np.unique(np.concatenate(term_arrays)) if term_arrays \
        else np.empty(0, object)
    term_names = [str(t) for t in union]

    tp, dp_, fp_parts, pc_parts, pd_parts, dl_parts = [], [], [], [], [], []
    has_positions = True
    for seg, keep, omap in zip(segments, keeps, ord_maps):
        fp = seg.postings.get(field)
        if fp is None:
            dl_parts.append(np.zeros(int(keep.sum()), np.float32))
            continue
        dl_parts.append(fp.doc_len[keep])
        if len(fp.post_doc) == 0:
            continue
        g_ord = np.searchsorted(union, np.asarray(fp.terms, object))
        per_post_term = np.repeat(g_ord.astype(np.int64),
                                  fp.doc_freq.astype(np.int64))
        live_post = keep[fp.post_doc]
        pos_counts = (fp.pos_start[1:] - fp.pos_start[:-1]).astype(np.int64)
        if len(fp.pos_data) == 0 and int(fp.total_term_freq.sum()) > 0:
            has_positions = False
        tp.append(per_post_term[live_post])
        dp_.append(omap[fp.post_doc[live_post]])
        fp_parts.append(_posting_tf(fp)[live_post])
        pc_parts.append(pos_counts[live_post])
        pd_parts.append(fp.pos_data[np.repeat(live_post, pos_counts)])

    if tp:
        term_all = np.concatenate(tp)
        doc_all = np.concatenate(dp_)
        tf_all = np.concatenate(fp_parts)
        pc_all = np.concatenate(pc_parts)
        pd_all = np.concatenate(pd_parts)
        # postings must sort by (term, doc); docs ascend within a segment
        # and segments concatenate in base order, so a stable sort on term
        # alone would suffice — lexsort keeps it explicit
        order = np.lexsort((doc_all, term_all))
        term_all, doc_all, tf_all = term_all[order], doc_all[order], tf_all[order]
        # reorder the ragged positions with the postings
        pc_sorted = pc_all[order]
        pos_of = np.zeros(len(pc_all) + 1, np.int64)
        np.cumsum(pc_all, out=pos_of[1:])
        take_val, _ = _ragged_gather(pos_of[order], pos_of[order] + pc_sorted,
                                     pd_all)
        pd_all, pc_all = take_val, pc_sorted
    else:
        term_all = np.empty(0, np.int64)
        doc_all = np.empty(0, np.int64)
        tf_all = np.empty(0, np.float32)
        pc_all = np.empty(0, np.int64)
        pd_all = np.empty(0, np.int32)

    return _assemble_postings(field, total, term_names, term_all, doc_all,
                              tf_all, pc_all, pd_all,
                              np.concatenate(dl_parts) if dl_parts
                              else np.zeros(total, np.float32),
                              has_positions)


def _ragged_gather(starts, ends, data):
    lens = (ends - starts).astype(np.int64)
    n = int(lens.sum())
    if n == 0:
        return np.empty(0, data.dtype), np.empty(0, np.int64)
    row = np.repeat(np.arange(len(lens), dtype=np.int64), lens)
    first = np.concatenate([[0], np.cumsum(lens)[:-1]])
    flat = starts[row] + (np.arange(n, dtype=np.int64) - first[row])
    return data[flat], row


def _assemble_postings(field: str, n_docs: int, term_names: List[str],
                       term_ord, doc_ord, tf, pos_counts, pos_data,
                       doc_len, has_positions: bool) -> FieldPostings:
    """Block-array assembly from sorted (term, doc, tf) postings — the
    shared back half of build_field_postings, taking explicit tf/positions
    instead of raw tokens."""
    n_terms = len(term_names)
    term_ord = term_ord.astype(np.int64)
    doc_ord = doc_ord.astype(np.int64)
    tf = tf.astype(np.float32)

    doc_freq = np.bincount(term_ord, minlength=n_terms).astype(np.int32)
    n_blocks_per_term = (doc_freq + BLOCK - 1) // BLOCK
    block_start = np.zeros(n_terms, np.int32)
    if n_terms:
        block_start[0] = 1
        np.cumsum(n_blocks_per_term[:-1], out=block_start[1:])
        block_start[1:] += 1
    total_blocks = 1 + int(n_blocks_per_term.sum())

    term_offsets = np.zeros(n_terms + 1, np.int64)
    np.cumsum(doc_freq, out=term_offsets[1:])
    within = np.arange(len(term_ord), dtype=np.int64) - term_offsets[term_ord]
    row = block_start[term_ord] + (within // BLOCK).astype(np.int32)
    lane = (within % BLOCK).astype(np.int32)

    block_docs = np.zeros((total_blocks, BLOCK), np.int32)
    block_tfs = np.zeros((total_blocks, BLOCK), np.float32)
    block_docs[row, lane] = doc_ord
    block_tfs[row, lane] = tf
    block_max_tf = np.zeros(total_blocks, np.float32)
    if len(term_ord):
        starts = np.nonzero(lane == 0)[0]
        block_max_tf[row[starts]] = np.maximum.reduceat(tf, starts)

    post_start = np.zeros(n_terms + 1, np.int64)
    post_start[1:] = term_offsets[1:]
    total_tf = np.zeros(n_terms, np.int64)
    nz = doc_freq > 0
    if nz.any():
        total_tf[nz] = np.add.reduceat(tf.astype(np.int64),
                                       term_offsets[:-1][nz])

    pos_start = np.zeros(len(term_ord) + 1, np.int64)
    if has_positions and len(pos_counts):
        np.cumsum(pos_counts, out=pos_start[1:])
    else:
        pos_data = np.empty(0, np.int32)

    return FieldPostings(
        field=field,
        term_to_ord={t: i for i, t in enumerate(term_names)},
        terms=list(term_names),
        doc_freq=doc_freq,
        total_term_freq=total_tf,
        block_start=block_start,
        block_count=n_blocks_per_term.astype(np.int32),
        block_docs=block_docs,
        block_tfs=block_tfs,
        block_max_tf=block_max_tf,
        post_start=post_start,
        post_doc=doc_ord.astype(np.int32),
        pos_start=pos_start,
        pos_data=pos_data.astype(np.int32),
        doc_len=doc_len.astype(np.float32),
        sum_doc_len=float(doc_len.sum()),
    )


def _merge_numeric(field: str, segments, keeps, total: int) -> NumericColumn:
    values = np.zeros(total, np.float64)
    max_values = np.zeros(total, np.float64)
    exists = np.zeros(total, bool)
    starts = np.zeros(total + 1, np.int64)
    val_parts = []
    off = 0
    vtotal = 0
    for seg, keep in zip(segments, keeps):
        n = int(keep.sum())
        col = seg.numeric.get(field)
        if col is not None:
            values[off: off + n] = col.values[keep]
            max_values[off: off + n] = col.max_values[keep]
            exists[off: off + n] = col.exists[keep]
            s, take, nv = _merge_csr(keep, col.value_start, vtotal)
            starts[off: off + n] = s
            val_parts.append(col.all_values[take])
            vtotal += nv
        else:
            starts[off: off + n] = vtotal
        off += n
    starts[total] = vtotal
    return NumericColumn(values=values, max_values=max_values, exists=exists,
                         value_start=starts,
                         all_values=np.concatenate(val_parts) if val_parts
                         else np.empty(0, np.float64))


def _merge_keyword(field: str, segments, keeps, total: int) -> KeywordColumn:
    # union over terms that SURVIVE on at least one live doc (dead-only
    # terms would otherwise accumulate across merge generations)
    live_term_arrays = []
    for seg, keep in zip(segments, keeps):
        kc = seg.keyword.get(field)
        if kc is not None and kc.terms:
            _, take, _ = _merge_csr(keep, kc.ord_start, 0)
            live = np.unique(kc.all_ords[take])
            if len(live):
                live_term_arrays.append(
                    np.asarray(kc.terms, object)[live])
    union = np.unique(np.concatenate(live_term_arrays)) \
        if live_term_arrays else np.empty(0, object)
    terms = [str(t) for t in union]
    ords = np.full(total, -1, np.int32)
    max_ords = np.full(total, -1, np.int32)
    exists = np.zeros(total, bool)
    ord_start = np.zeros(total + 1, np.int64)
    parts = []
    off = 0
    vtotal = 0
    for seg, keep in zip(segments, keeps):
        n = int(keep.sum())
        kc = seg.keyword.get(field)
        if kc is not None and kc.terms:
            remap = np.searchsorted(union, np.asarray(kc.terms, object)
                                    ).astype(np.int32)
            old = kc.ords[keep]
            ords[off: off + n] = np.where(old >= 0, remap[np.maximum(old, 0)], -1)
            oldm = kc.max_ords[keep]
            max_ords[off: off + n] = np.where(oldm >= 0,
                                              remap[np.maximum(oldm, 0)], -1)
            exists[off: off + n] = kc.exists[keep]
            s, take, nv = _merge_csr(keep, kc.ord_start, vtotal)
            ord_start[off: off + n] = s
            parts.append(remap[kc.all_ords[take]])
            vtotal += nv
        else:
            ord_start[off: off + n] = vtotal
        off += n
    ord_start[total] = vtotal
    return KeywordColumn(terms=terms,
                         term_to_ord={t: i for i, t in enumerate(terms)},
                         ords=ords, max_ords=max_ords, exists=exists,
                         ord_start=ord_start,
                         all_ords=np.concatenate(parts) if parts
                         else np.empty(0, np.int32))


def _merge_vectors(field: str, segments, keeps, total: int) -> VectorColumn:
    dims = 1
    sim = "cosine"
    for seg in segments:
        vc = seg.vectors.get(field)
        if vc is not None and vc.dims:
            dims, sim = vc.dims, vc.similarity
            break
    vectors = np.zeros((total, max(dims, 1)), np.float32)
    norms = np.zeros(total, np.float32)
    exists = np.zeros(total, bool)
    off = 0
    for seg, keep in zip(segments, keeps):
        n = int(keep.sum())
        vc = seg.vectors.get(field)
        if vc is not None and vc.dims == dims:
            vectors[off: off + n] = vc.vectors[keep]
            norms[off: off + n] = vc.norms[keep]
            exists[off: off + n] = vc.exists[keep]
        off += n
    return VectorColumn(vectors=vectors, norms=norms, exists=exists,
                        dims=dims, similarity=sim)


def _merge_geo(field: str, segments, keeps, total: int) -> GeoColumn:
    lat_parts, lon_parts = [], []
    exists = np.zeros(total, bool)
    starts = np.zeros(total + 1, np.int64)
    off = 0
    vtotal = 0
    for seg, keep in zip(segments, keeps):
        n = int(keep.sum())
        gc = seg.geo.get(field)
        if gc is not None:
            exists[off: off + n] = gc.exists[keep]
            s, take, nv = _merge_csr(keep, gc.value_start, vtotal)
            starts[off: off + n] = s
            lat_parts.append(gc.lat[take])
            lon_parts.append(gc.lon[take])
            vtotal += nv
        else:
            starts[off: off + n] = vtotal
        off += n
    starts[total] = vtotal
    return GeoColumn(
        lat=np.concatenate(lat_parts) if lat_parts else np.empty(0, np.float64),
        lon=np.concatenate(lon_parts) if lon_parts else np.empty(0, np.float64),
        value_start=starts, exists=exists)


def _merge_nested(field: str, segments, keeps, total: int) -> NestedTable:
    child_segs, child_keeps = [], []
    parent_parts = []
    child_start = np.zeros(total + 1, np.int64)
    off = 0
    ctotal = 0
    for seg, keep in zip(segments, keeps):
        n = int(keep.sum())
        nt = seg.nested.get(field)
        if nt is not None:
            s, ckeep, nc = _merge_csr(keep, nt.child_start, ctotal)
            child_start[off: off + n] = s
            child_segs.append(nt.child)
            child_keeps.append(ckeep)
            omap = np.cumsum(keep) - 1 + off
            parent_parts.append(omap[nt.parent_of[ckeep]])
            ctotal += nc
        else:
            child_start[off: off + n] = ctotal
        off += n
    child_start[total] = ctotal
    merged_child = merge_segments(child_segs, child_keeps, seg_id=0) \
        if child_segs else SegmentBuilder().build()
    return NestedTable(child=merged_child,
                       parent_of=np.concatenate(parent_parts).astype(np.int32)
                       if parent_parts else np.empty(0, np.int32),
                       child_start=child_start)

from elasticsearch_tpu.index.segment import FieldPostings, Segment, SegmentBuilder, BLOCK

__all__ = ["FieldPostings", "Segment", "SegmentBuilder", "BLOCK"]

"""IndexService / IndicesService: per-index shard management.

Re-designs the reference pair (ref: index/IndexModule.java:390
newIndexService, indices/IndicesService.java:538 createIndex,
index/shard/IndexShard.java): an IndexService owns N shard engines plus the
shared mapper and analysis registry; IndicesService is the node-level
registry creating/removing them from cluster-state metadata.

Search across shards is scatter-gather (ref P3): per-shard query phases merge
at the coordinator. Default stats scope is shard-local like the reference's
query_then_fetch; search_type=dfs_query_then_fetch combines term stats
across shards first (ref P5: SearchDfsQueryThenFetchAsyncAction).
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Dict, List, Optional

from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.common.errors import (
    DocumentMissingError,
    IndexNotFoundError,
    ResourceAlreadyExistsError,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.cluster.state import IndexMetadata, ShardRouting
from elasticsearch_tpu.index.engine import EngineResult, InternalEngine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.parallel.routing import shard_for_id
from elasticsearch_tpu.search.executor import QueryExecutor, ShardStats
from elasticsearch_tpu.search.fetch_phase import execute_fetch_phase
from elasticsearch_tpu.search.query_phase import execute_query_phase


class IndexService:
    def __init__(self, meta: IndexMetadata, data_path: Optional[str] = None):
        self.meta = meta
        self.name = meta.index
        analyzer_settings = meta.settings.raw("analysis")  # rarely set flat; see below
        nested = meta.settings.filtered_by_prefix("index.analysis.analyzer.")
        self.analysis = AnalysisRegistry(_analyzer_config(meta))
        self.mapper = MapperService(meta.mappings, self.analysis)
        self.shards: List[InternalEngine] = []
        durability = meta.settings.raw("index.translog.durability", "request")
        for shard_id in range(meta.number_of_shards):
            path = os.path.join(data_path, self.name, str(shard_id)) if data_path else None
            self.shards.append(
                InternalEngine(self.mapper, data_path=path, translog_durability=durability)
            )
        from elasticsearch_tpu.search.serving import ServingContext

        self.serving = ServingContext(self)

    # ---- document ops ----

    def shard_for(self, doc_id: str, routing: str | None = None) -> InternalEngine:
        return self.shards[shard_for_id(doc_id, len(self.shards), routing)]

    def index_doc(self, doc_id: str, source: dict, **kw) -> EngineResult:
        return self.shard_for(doc_id, kw.pop("routing", None)).index(doc_id, source, **kw)

    def delete_doc(self, doc_id: str, **kw) -> EngineResult:
        return self.shard_for(doc_id, kw.pop("routing", None)).delete(doc_id, **kw)

    def get_doc(self, doc_id: str, routing: str | None = None) -> Optional[dict]:
        return self.shard_for(doc_id, routing).get(doc_id)

    def refresh(self) -> None:
        for s in self.shards:
            s.refresh()

    def flush(self) -> None:
        for s in self.shards:
            s.flush()

    def force_merge(self, max_num_segments: int = 1) -> None:
        for s in self.shards:
            s.force_merge(max_num_segments)

    def doc_count(self) -> int:
        return sum(s.doc_count() for s in self.shards)

    def close(self) -> None:
        for s in self.shards:
            s.close()

    # ---- search (scatter-gather across shards) ----

    def search(self, request: dict, search_type: str = "query_then_fetch") -> dict:
        fast = self.serving.try_search(request, search_type)
        if fast is not None:
            return fast
        return self._search_dense(request, search_type)

    def msearch(self, requests: List[dict],
                search_type: str = "query_then_fetch") -> List[dict]:
        """Batched search: eligible flat queries ride ONE device dispatch
        through the blockmax serving path (ref P8/SURVEY §2.10: batch many
        queries per step); the rest run the dense path individually.

        Per-body error isolation (ref: _msearch contract — one bad body must
        not fail its neighbors): failures come back as the exception object
        in that body's slot for the caller to render."""
        from elasticsearch_tpu.common.errors import ElasticsearchTpuError

        out = self.serving.try_msearch(requests, search_type)
        results: List = []
        for i, r in enumerate(out):
            if r is not None:
                results.append(r)
                continue
            try:
                results.append(self._search_dense(requests[i], search_type))
            except ElasticsearchTpuError as e:
                results.append(e)
        return results

    def _search_dense(self, request: dict, search_type: str = "query_then_fetch") -> dict:
        import time as _time

        from elasticsearch_tpu.search.query_phase import QuerySearchResult, _sort_key, parse_sort

        start = _time.monotonic()
        searchers = [s.acquire_searcher() for s in self.shards]

        global_stats = None
        if search_type == "dfs_query_then_fetch":
            all_views = [v for se in searchers for v in se.views]
            global_stats = ShardStats(all_views)

        size = int(request.get("size", 10))
        from_ = int(request.get("from", 0))
        sort = parse_sort(request.get("sort"))

        shard_results: List[QuerySearchResult] = []
        per_shard_hits = []
        for shard_id, searcher in enumerate(searchers):
            ex = None
            if global_stats is not None:
                ex = QueryExecutor(self.mapper, global_stats)
            qr = execute_query_phase(searcher, self.mapper, request, executor=ex)
            shard_results.append(qr)
            for h in qr.hits:
                per_shard_hits.append((shard_id, h))

        total = sum(r.total for r in shard_results)
        relation = "gte" if any(r.relation == "gte" for r in shard_results) else "eq"
        if sort:
            per_shard_hits.sort(key=lambda t: _sort_key(t[1], sort))
        else:
            per_shard_hits.sort(key=lambda t: (-t[1].score, t[0], t[1].global_ord))
        window = per_shard_hits[from_: from_ + size]

        max_score = None
        if not sort:
            ms = [r.max_score for r in shard_results if r.max_score is not None]
            if ms:
                max_score = max(ms)

        hits = []
        for shard_id, h in window:
            fetched = execute_fetch_phase(searchers[shard_id], [h], request, self.name)
            hit = fetched[0]
            if hit.get("_score") is None and h.sort_values is None:
                hit["_score"] = h.score
            hits.append(hit)

        aggs = _merge_shard_aggs(request, shard_results)
        took = int((_time.monotonic() - start) * 1000)
        resp = {
            "took": took,
            "timed_out": False,
            "_shards": {"total": len(self.shards), "successful": len(self.shards),
                        "skipped": 0, "failed": 0},
            "hits": {
                "total": {"value": total, "relation": relation},
                "max_score": max_score,
                "hits": hits,
            },
        }
        if request.get("track_total_hits") is False:
            resp["hits"].pop("total")   # ref: ES omits total when untracked
        if aggs is not None:
            resp["aggregations"] = aggs
        return resp

    def stats(self) -> dict:
        total_segments = sum(s.segment_count() for s in self.shards)
        return {
            "docs": {"count": self.doc_count(), "deleted": 0},
            "segments": {"count": total_segments},
            "store": {"size_in_bytes": sum(
                sum(seg.ram_bytes() for seg in s._segments) for s in self.shards)},
        }


def _merge_shard_aggs(request, shard_results) -> Optional[dict]:
    """Commutative partial reduce of per-shard aggregation partials, then
    finalize once at the coordinator (ref P6: QueryPhaseResultConsumer
    batched reduce + SearchPhaseController final reduce)."""
    parts = [r.aggregations for r in shard_results if r.aggregations is not None]
    if not parts:
        return None
    from elasticsearch_tpu.search.aggregations import finalize_shard_aggs

    return finalize_shard_aggs(request, parts)


def _analyzer_config(meta: IndexMetadata) -> dict:
    """Extract index.analysis.analyzer.<name>.* settings into registry config."""
    nested = meta.settings.as_nested_dict()
    try:
        return nested["index"]["analysis"]["analyzer"]
    except (KeyError, TypeError):
        return {}


class IndicesService:
    """Node-level index registry (ref: indices/IndicesService.java:168)."""

    def __init__(self, data_path: Optional[str] = None):
        self.data_path = data_path
        self._indices: Dict[str, IndexService] = {}
        self._lock = threading.Lock()

    def create_index(self, name: str, settings: Settings, mappings: dict,
                     aliases: Dict[str, dict] | None = None) -> IndexMetadata:
        with self._lock:
            if name in self._indices:
                raise ResourceAlreadyExistsError(f"index [{name}] already exists", index=name)
            meta = IndexMetadata(
                index=name,
                uuid=uuid.uuid4().hex[:20],
                settings=settings,
                mappings=mappings or {},
                aliases=aliases or {},
            )
            self._indices[name] = IndexService(meta, self.data_path)
            return meta

    def delete_index(self, name: str) -> None:
        with self._lock:
            svc = self._indices.pop(name, None)
            if svc is None:
                raise IndexNotFoundError(name)
            svc.close()
            if self.data_path:
                import shutil

                shutil.rmtree(os.path.join(self.data_path, name), ignore_errors=True)

    def get(self, name: str) -> IndexService:
        svc = self._indices.get(name)
        if svc is None:
            raise IndexNotFoundError(name)
        return svc

    def has(self, name: str) -> bool:
        return name in self._indices

    def names(self) -> List[str]:
        return sorted(self._indices)

    def close(self) -> None:
        for svc in self._indices.values():
            svc.close()

"""IndexService / IndicesService: per-index shard management.

Re-designs the reference pair (ref: index/IndexModule.java:390
newIndexService, indices/IndicesService.java:538 createIndex,
index/shard/IndexShard.java): an IndexService owns N shard engines plus the
shared mapper and analysis registry; IndicesService is the node-level
registry creating/removing them from cluster-state metadata.

Search across shards is scatter-gather (ref P3): per-shard query phases merge
at the coordinator. Default stats scope is shard-local like the reference's
query_then_fetch; search_type=dfs_query_then_fetch combines term stats
across shards first (ref P5: SearchDfsQueryThenFetchAsyncAction).
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Dict, List, Optional

from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.common.errors import (
    DocumentMissingError,
    IndexNotFoundError,
    ResourceAlreadyExistsError,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.cluster.state import IndexMetadata, ShardRouting
from elasticsearch_tpu.index.engine import EngineResult, InternalEngine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.parallel.routing import shard_for_id
from elasticsearch_tpu.search.executor import QueryExecutor, ShardStats
from elasticsearch_tpu.search.fetch_phase import execute_fetch_phase
from elasticsearch_tpu.search.query_phase import execute_query_phase


class IndexService:
    def __init__(self, meta: IndexMetadata, data_path: Optional[str] = None,
                 breakers=None):
        self.meta = meta
        self.breakers = breakers
        self.name = meta.index
        analyzer_settings = meta.settings.raw("analysis")  # rarely set flat; see below
        nested = meta.settings.filtered_by_prefix("index.analysis.analyzer.")
        self.analysis = AnalysisRegistry(_analyzer_config(meta))
        self.mapper = MapperService(meta.mappings, self.analysis)
        self.shards: List[InternalEngine] = []
        durability = meta.settings.raw("index.translog.durability", "request")
        for shard_id in range(meta.number_of_shards):
            path = os.path.join(data_path, self.name, str(shard_id)) if data_path else None
            self.shards.append(
                InternalEngine(self.mapper, data_path=path, translog_durability=durability)
            )
        from elasticsearch_tpu.search.serving import ServingContext

        self.serving = ServingContext(self)
        # shard request cache (ref: indices/IndicesRequestCache.java:57 —
        # caches size=0/aggs-only responses keyed on reader version + request)
        self._req_cache: Dict[tuple, dict] = {}  # guarded by: _req_cache_lock
        self._req_cache_lock = threading.Lock()
        self.request_cache_stats = {"hits": 0, "misses": 0}  # guarded by: _req_cache_lock

    # ---- document ops ----

    def check_open(self) -> None:
        """Closed indices reject data ops with index_closed_exception
        (ref: cluster/block/ClusterBlocks INDEX_CLOSED_BLOCK)."""
        if getattr(self, "closed", False):
            from elasticsearch_tpu.common.errors import IndexClosedError

            raise IndexClosedError(f"closed index [{self.name}]")

    def check_write_allowed(self) -> None:
        """index.blocks.write / read_only reject writes with 403 (ref:
        ClusterBlocks WRITE + IndexMetadata INDEX_WRITE_BLOCK)."""
        self.check_open()
        for key in ("index.blocks.write", "index.blocks.read_only"):
            self._check_block(key, 8)

    def _check_block(self, key: str, block_id: int) -> None:
        if str(self.meta.settings.raw(key, "false")).lower() == "true":
            from elasticsearch_tpu.common.errors import ElasticsearchTpuError

            err = ElasticsearchTpuError(
                f"index [{self.name}] blocked by: [FORBIDDEN/{block_id}/"
                f"{key} (api)]")
            err.status = 403
            err.error_type = "cluster_block_exception"
            raise err

    def check_read_allowed(self) -> None:
        """index.blocks.read rejects get/search/count with 403 (ref:
        IndexMetadata INDEX_READ_BLOCK, id 7). read_only does NOT block
        data reads — only writes and metadata writes."""
        self.check_open()
        self._check_block("index.blocks.read", 7)

    def check_metadata_allowed(self) -> None:
        """index.blocks.metadata / read_only reject metadata reads and
        writes with 403 (ref: IndexMetadata INDEX_METADATA_BLOCK, id 9)."""
        self._check_block("index.blocks.metadata", 9)

    def shard_for(self, doc_id: str, routing: str | None = None) -> InternalEngine:
        return self.shards[shard_for_id(doc_id, len(self.shards), routing)]

    def index_doc(self, doc_id: str, source: dict, **kw) -> EngineResult:
        self.check_write_allowed()
        return self.shard_for(doc_id, kw.pop("routing", None)).index(doc_id, source, **kw)

    def delete_doc(self, doc_id: str, **kw) -> EngineResult:
        self.check_write_allowed()
        return self.shard_for(doc_id, kw.pop("routing", None)).delete(doc_id, **kw)

    def get_doc(self, doc_id: str, routing: str | None = None) -> Optional[dict]:
        self.check_read_allowed()
        return self.shard_for(doc_id, routing).get(doc_id)

    def store_size_bytes(self) -> int:
        """Rough resident size of published segments (rollover max_size)."""
        total = 0
        for engine in self.shards:
            for v in engine.acquire_searcher().views:
                seg = v.segment
                for fp in seg.postings.values():
                    total += (fp.block_docs.nbytes + fp.block_tfs.nbytes
                              + fp.post_doc.nbytes + fp.pos_data.nbytes)
                for col in seg.numeric.values():
                    total += col.values.nbytes
                for vc in seg.vectors.values():
                    total += vc.vectors.nbytes
        return total

    def refresh(self) -> None:
        for s in self.shards:
            s.refresh()

    def flush(self) -> None:
        for s in self.shards:
            s.flush()

    def force_merge(self, max_num_segments: int = 1) -> None:
        for s in self.shards:
            s.force_merge(max_num_segments)

    def doc_count(self) -> int:
        return sum(s.doc_count() for s in self.shards)

    def close(self) -> None:
        for s in self.shards:
            s.close()

    # ---- search (scatter-gather across shards) ----

    _REQ_CACHE_MAX = 64

    def _request_cache_key(self, request: dict, search_type: str):
        """None when the request is not cacheable. Cacheable = size 0 (the
        aggregations/count shape the reference caches by default) with no
        cursor/pit mechanics; the searcher version in the key invalidates
        on every refresh/delete."""
        import json as _json

        if int(request.get("size", 10)) != 0 or request.get("search_after")                 is not None or "_after_full" in request                 or request.get("_want_cursor") or request.get("timeout") or request.get("profile"):
            return None
        try:
            body = _json.dumps(request, sort_keys=True)
        except (TypeError, ValueError):
            return None
        version = tuple(sv for s in self.shards for sv in s.searcher_version())
        return (version, search_type, body)

    def search(self, request: dict, search_type: str = "query_then_fetch",
               searchers=None, task=None) -> dict:
        import copy as _copy

        self.check_read_allowed()

        key = self._request_cache_key(request, search_type)             if searchers is None else None
        if key is not None:
            with self._req_cache_lock:
                hit = self._req_cache.get(key)
                if hit is not None:
                    self.request_cache_stats["hits"] += 1
                else:
                    self.request_cache_stats["misses"] += 1
            if hit is not None:
                return _copy.deepcopy(hit)
        if searchers is None:
            resp = self.serving.try_search(request, search_type, task=task)
        else:
            resp = None
        if resp is not None and not isinstance(resp, dict):
            # request-level failure from the fast path (e.g.
            # allow_partial_search_results=false with a faulted shard):
            # the error, not a dense retry, is the answer
            raise resp
        if resp is None:
            resp = self._search_dense(request, search_type,
                                      searchers=searchers, task=task)
        if key is not None and not resp.get("timed_out"):
            with self._req_cache_lock:
                if len(self._req_cache) >= self._REQ_CACHE_MAX:
                    self._req_cache.pop(next(iter(self._req_cache)))
                self._req_cache[key] = _copy.deepcopy(resp)
        self._maybe_slow_log(request, resp)
        return resp

    def effective_slowlog_thresholds(self) -> dict:
        """Effective per-phase slowlog thresholds (ms) parsed from this
        index's settings — {'query': {'warn': ms|None, ...}, 'fetch': ...}.
        The seam every slowlog consumer reads (REST trace enablement, the
        shard handlers, and this service's own check), so the parse
        semantics ('-1' disables, bare numbers are ms) exist exactly once."""
        from elasticsearch_tpu.common import tracing

        return tracing.slowlog_thresholds(self.meta.settings)

    def _maybe_slow_log(self, request: dict, resp: dict) -> None:
        """Search slow log (ref: index/SearchSlowLog.java): queries over
        index.search.slowlog.threshold.query.{warn,info} append a
        structured record (trace id + phase breakdown when the flight
        recorder is on) to the bounded ring behind GET /_tpu/slowlog AND
        log with the request source — the first stop when a query pattern
        goes bad."""
        import json as _json
        import logging

        from elasticsearch_tpu.common import tracing

        took = float(resp.get("took", 0))
        th = self.effective_slowlog_thresholds().get("query") or {}
        level = tracing.slowlog_check("query", took, th)
        if level is None:
            return
        tracing.slowlog_record(
            "query", level, self.name, took,
            source=request.get("query"), tc=tracing.current())
        logging.getLogger("index.search.slowlog").log(
            logging.WARNING if level == "warn" else logging.INFO,
            "[%s] took[%dms], source[%s]", self.name, int(took),
            _json.dumps({k: v for k, v in request.items()
                         if not k.startswith("_")})[:1000])

    def msearch(self, requests: List[dict],
                search_type: str = "query_then_fetch") -> List[dict]:
        """Batched search: eligible flat queries ride ONE device dispatch
        through the blockmax serving path (ref P8/SURVEY §2.10: batch many
        queries per step); the rest run the dense path individually.

        Per-body error isolation (ref: _msearch contract — one bad body must
        not fail its neighbors): failures come back as the exception object
        in that body's slot for the caller to render."""
        from elasticsearch_tpu.common.errors import ElasticsearchTpuError

        self.check_open()
        out = self.serving.try_msearch(requests, search_type)
        results: List = []
        for i, r in enumerate(out):
            if r is not None:
                results.append(r)
                continue
            try:
                # public entry: request cache + slow log apply to msearch too
                results.append(self.search(requests[i], search_type))
            except ElasticsearchTpuError as e:
                results.append(e)
        return results

    def _search_dense(self, request: dict, search_type: str = "query_then_fetch",
                      searchers=None, task=None) -> dict:
        import time as _time

        from elasticsearch_tpu.search.query_phase import QuerySearchResult, _sort_key, parse_sort

        start = _time.monotonic()
        if searchers is None:
            searchers = [s.acquire_searcher() for s in self.shards]

        global_stats = None
        if search_type == "dfs_query_then_fetch":
            all_views = [v for se in searchers for v in se.views]
            global_stats = ShardStats(all_views)

        size = int(request.get("size", 10))
        from_ = int(request.get("from", 0))
        collapse_field = (request.get("collapse") or {}).get("field")
        score_sort_injected = False
        if (request.get("search_after") is not None or collapse_field
                or request.get("_want_cursor") or "_after_full" in request) \
                and not request.get("sort"):
            # cursor/collapse mechanics need an explicit order; default to
            # score with the canonical (shard, ord) tiebreak
            request = {**request, "sort": [{"_score": "desc"}]}
            score_sort_injected = True
        sort = parse_sort(request.get("sort"))

        shard_results: List[QuerySearchResult] = []
        per_shard_hits = []
        for shard_id, searcher in enumerate(searchers):
            ex = None
            if global_stats is not None:
                ex = QueryExecutor(self.mapper, global_stats)
            shard_req = request if "_after_full" not in request else \
                {**request, "_shard_id": shard_id}
            breaker = self.breakers.get_breaker("request") \
                if self.breakers is not None else None
            qr = execute_query_phase(searcher, self.mapper, shard_req,
                                     executor=ex, task=task, breaker=breaker)
            shard_results.append(qr)
            for h in qr.hits:
                per_shard_hits.append((shard_id, h))

        total = sum(r.total for r in shard_results)
        relation = "gte" if any(r.relation == "gte" for r in shard_results) else "eq"
        if sort:
            per_shard_hits.sort(
                key=lambda t: (_sort_key(t[1], sort), t[0], t[1].global_ord))
        else:
            per_shard_hits.sort(key=lambda t: (-t[1].score, t[0], t[1].global_ord))
        if collapse_field:
            from elasticsearch_tpu.search.query_phase import _collapse_ranked, collapse_value

            ranked = [((sid, h),
                       collapse_value(searchers[sid].views[h.leaf_idx].segment,
                                      h.ord, collapse_field))
                      for sid, h in per_shard_hits]
            per_shard_hits = _collapse_ranked(ranked, from_ + size)
        window = per_shard_hits[from_: from_ + size]

        max_score = None
        if not sort:
            ms = [r.max_score for r in shard_results if r.max_score is not None]
            if ms:
                max_score = max(ms)

        hits = []
        cursor = None
        for shard_id, h in window:
            fetched = execute_fetch_phase(searchers[shard_id], [h], request,
                                          self.name, mapper=self.mapper)
            hit = fetched[0]
            if hit.get("_score") is None and h.sort_values is None:
                hit["_score"] = h.score
            if score_sort_injected:
                # the sort was internal plumbing: restore plain score hits
                hit["_score"] = h.score
                hit.pop("sort", None)
            if collapse_field:
                hit.setdefault("fields", {})[collapse_field] = [
                    collapse_value(searchers[shard_id].views[h.leaf_idx].segment,
                                   h.ord, collapse_field)]
            hits.append(hit)
        if window and request.get("_want_cursor"):
            sid, last = window[-1]
            cursor = {"values": [s.s if hasattr(s, "s") else s
                                 for s in (last.sort_values or [])],
                      "shard_id": sid, "ord": last.global_ord}

        aggs = _merge_shard_aggs(request, shard_results)
        took = int((_time.monotonic() - start) * 1000)
        resp = {
            "took": took,
            "timed_out": any(r.timed_out for r in shard_results),
            "_shards": {"total": len(self.shards), "successful": len(self.shards),
                        "skipped": 0, "failed": 0},
            "hits": {
                "total": {"value": total, "relation": relation},
                "max_score": max_score,
                "hits": hits,
            },
        }
        from elasticsearch_tpu.search.response import finalize_hits_envelope

        finalize_hits_envelope(resp, request)
        if aggs is not None:
            resp["aggregations"] = aggs
        if request.get("suggest") is not None:
            from elasticsearch_tpu.search.suggest import execute_suggest

            resp["suggest"] = execute_suggest(
                [v for se in searchers for v in se.views], self.mapper,
                request["suggest"])
        if any(r.terminated_early for r in shard_results):
            resp["terminated_early"] = True
        if request.get("profile"):
            resp["profile"] = {"shards": [
                {"id": f"[{self.name}][{sid}]",
                 "searches": [{"query": r.profile or [],
                               "rewrite_time": 0, "collector": []}]}
                for sid, r in enumerate(shard_results)]}
        if cursor is not None:
            resp["_cursor"] = cursor
        return resp

    # ---- scroll (ref: RestSearchScrollAction + SearchService scroll
    #      continuation over a pinned reader context) ----

    def scroll_start(self, request: dict, keep_alive_s: float, registry,
                     task=None) -> dict:
        self.check_read_allowed()
        searchers = [s.acquire_searcher() for s in self.shards]
        ctx = registry.create(searchers=searchers, mapper=self.mapper,
                              index=self.name, keep_alive_s=keep_alive_s)
        body = {k: v for k, v in request.items() if k != "scroll"}
        resp = self._search_dense({**body, "_want_cursor": True},
                                  searchers=searchers, task=task)
        cursor = resp.pop("_cursor", None)
        ctx.scroll_state = {"request": body, "cursor": cursor}
        resp["_scroll_id"] = ctx.context_id
        return resp

    def scroll_continue(self, ctx, task=None) -> dict:
        state = ctx.scroll_state or {}
        body = dict(state.get("request") or {})
        cursor = state.get("cursor")
        if cursor is None or not cursor.get("values"):
            resp = self._search_dense({**body, "size": 0},
                                      searchers=ctx.extra["searchers"])
            resp["_scroll_id"] = ctx.context_id
            resp["hits"]["hits"] = []
            return resp
        body["_after_full"] = cursor
        body["_want_cursor"] = True
        body.pop("from", None)
        resp = self._search_dense(body, searchers=ctx.extra["searchers"],
                                  task=task)
        new_cursor = resp.pop("_cursor", None)
        ctx.scroll_state = {"request": state.get("request"),
                            "cursor": new_cursor or {"values": []}}
        resp["_scroll_id"] = ctx.context_id
        return resp

    def stats(self) -> dict:
        total_segments = sum(s.segment_count() for s in self.shards)
        with self._req_cache_lock:
            request_cache = dict(self.request_cache_stats)
        return {
            "docs": {"count": self.doc_count(), "deleted": 0},
            "segments": {"count": total_segments},
            "store": {"size_in_bytes": sum(
                sum(seg.ram_bytes() for seg in s._segments) for s in self.shards)},
            "request_cache": request_cache,
        }


def _merge_shard_aggs(request, shard_results) -> Optional[dict]:
    """Commutative partial reduce of per-shard aggregation partials, then
    finalize once at the coordinator (ref P6: QueryPhaseResultConsumer
    batched reduce + SearchPhaseController final reduce)."""
    parts = [r.aggregations for r in shard_results if r.aggregations is not None]
    if not parts:
        return None
    from elasticsearch_tpu.search.aggregations import finalize_shard_aggs

    return finalize_shard_aggs(request, parts)


def _analyzer_config(meta: IndexMetadata) -> dict:
    """Extract index.analysis.analyzer.<name>.* settings into registry config."""
    nested = meta.settings.as_nested_dict()
    try:
        return nested["index"]["analysis"]["analyzer"]
    except (KeyError, TypeError):
        return {}


def parse_keep_alive(value, default_s: float = 300.0) -> float:
    """'30s' / '1m' / '2h' -> seconds (one duration parser for the repo:
    tasks/task_manager.parse_timeout_ms; bare numbers are SECONDS here,
    matching this API's pre-existing contract)."""
    from elasticsearch_tpu.tasks.task_manager import parse_timeout_ms

    if value is None:
        return default_s
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    try:
        return float(s)          # unitless string -> seconds
    except ValueError:
        pass
    ms = parse_timeout_ms(s)
    return (ms / 1000.0) if ms is not None else default_s


class IndicesService:
    """Node-level index registry (ref: indices/IndicesService.java:168)."""

    def __init__(self, data_path: Optional[str] = None, breakers=None):
        from elasticsearch_tpu.search.reader_context import ReaderContextRegistry

        self.data_path = data_path
        self.breakers = breakers
        self._indices: Dict[str, IndexService] = {}
        self._lock = threading.Lock()
        # PIT/scroll contexts + keepalive reaper (ref: SearchService.Reaper)
        self.contexts = ReaderContextRegistry()
        self.templates: Dict[str, dict] = {}
        self._reaper_stop = threading.Event()
        self._reaper: Optional[threading.Thread] = None

    def _ensure_reaper(self) -> None:
        with self._lock:
            if self._reaper is None or not self._reaper.is_alive():
                def loop():
                    while not self._reaper_stop.wait(5.0):
                        self.contexts.reap()

                self._reaper = threading.Thread(
                    target=loop, name="context-reaper", daemon=True)
                self._reaper.start()

    # ---- point-in-time (ref: RestOpenPointInTimeAction,
    #      SearchService.openReaderContext) ----

    def open_pit(self, index: str, keep_alive_s: float) -> str:
        svc = self.get(index)
        searchers = [s.acquire_searcher() for s in svc.shards]
        ctx = self.contexts.create(searchers=searchers, mapper=svc.mapper,
                                   index=index, keep_alive_s=keep_alive_s)
        self._ensure_reaper()
        return ctx.context_id

    def close_pit(self, pit_id: str) -> bool:
        return self.contexts.release(pit_id)

    def scroll_start(self, index: str, request: dict, keep_alive_s: float,
                     task=None) -> dict:
        self._ensure_reaper()
        return self.get(index).scroll_start(request, keep_alive_s,
                                            self.contexts, task=task)

    def scroll_continue(self, scroll_id: str, keep_alive_s: Optional[float] = None,
                        task=None) -> dict:
        ctx = self.contexts.get(scroll_id)
        if keep_alive_s:
            ctx.keep_alive_s = keep_alive_s
        return self.get(ctx.index).scroll_continue(ctx, task=task)

    # ---- index templates (ref: cluster/metadata/
    #      MetadataIndexTemplateService.java — composable v2 templates).
    #      NOTE: node-local registry; the multi-node control plane
    #      (cluster_node.create_index) does not replicate templates yet —
    #      replicating them through cluster-state metadata is the follow-up ----

    def put_template(self, name: str, body: dict) -> None:
        patterns = body.get("index_patterns")
        if not patterns:
            from elasticsearch_tpu.common.errors import IllegalArgumentError

            raise IllegalArgumentError("index template must specify "
                                       "index_patterns")
        if isinstance(patterns, str):
            patterns = [patterns]
        try:
            priority = int(body.get("priority", 0))
        except (TypeError, ValueError):
            from elasticsearch_tpu.common.errors import IllegalArgumentError

            raise IllegalArgumentError(
                f"[priority] must be an integer, got "
                f"[{body.get('priority')}]")
        with self._lock:
            self.templates[name] = {
                "index_patterns": patterns,
                "priority": priority,
                "template": body.get("template", {}),
            }

    def delete_template(self, name: str) -> None:
        with self._lock:
            if self.templates.pop(name, None) is None:
                from elasticsearch_tpu.common.errors import (
                    ElasticsearchTpuError,
                )

                e = ElasticsearchTpuError(
                    f"index template [{name}] missing")
                e.status = 404
                raise e

    def _apply_templates(self, name: str, settings: Settings,
                         mappings: dict, aliases: Dict[str, dict]):
        """Highest-priority matching template underlays request values
        (request wins on conflicts, ref: composable template resolution)."""
        import fnmatch

        with self._lock:   # puts/deletes mutate under the same lock
            candidates = list(self.templates.values())
        matches = sorted(
            (t for t in candidates
             if any(fnmatch.fnmatchcase(name, p)
                    for p in t["index_patterns"])),
            key=lambda t: t["priority"], reverse=True)
        if not matches:
            return settings, mappings, aliases
        tpl = matches[0]["template"]
        tpl_settings = Settings(tpl.get("settings", {}))
        merged_settings = {k: tpl_settings.raw(k) for k in tpl_settings}
        # bare topology keys normalize to their index.-prefixed forms (the
        # same normalization Node.create_index applies to request bodies)
        for bare in ("number_of_shards", "number_of_replicas",
                     "default_pipeline"):
            if bare in merged_settings and \
                    f"index.{bare}" not in merged_settings:
                merged_settings[f"index.{bare}"] = merged_settings.pop(bare)
        for k in settings:
            merged_settings[k] = settings.raw(k)
        tpl_maps = dict(tpl.get("mappings", {}).get("properties", {}))
        tpl_maps.update((mappings or {}).get("properties", {}))
        merged_mappings = {"properties": tpl_maps} if tpl_maps else (mappings or {})
        merged_aliases = dict(tpl.get("aliases", {}))
        merged_aliases.update(aliases or {})
        return Settings(merged_settings), merged_mappings, merged_aliases

    def create_index(self, name: str, settings: Settings, mappings: dict,
                     aliases: Dict[str, dict] | None = None) -> IndexMetadata:
        settings, mappings, aliases = self._apply_templates(
            name, settings, mappings, aliases or {})
        with self._lock:
            if name in self._indices:
                raise ResourceAlreadyExistsError(f"index [{name}] already exists", index=name)
            meta = IndexMetadata(
                index=name,
                uuid=uuid.uuid4().hex[:20],
                settings=settings,
                mappings=mappings or {},
                aliases=aliases or {},
            )
            self._indices[name] = IndexService(meta, self.data_path,
                                               breakers=self.breakers)
            return meta

    def delete_index(self, name: str) -> None:
        with self._lock:
            svc = self._indices.pop(name, None)
            if svc is None:
                raise IndexNotFoundError(name)
            svc.close()
            if self.data_path:
                import shutil

                shutil.rmtree(os.path.join(self.data_path, name), ignore_errors=True)

    def get(self, name: str) -> IndexService:
        svc = self._indices.get(name)
        if svc is None:
            raise IndexNotFoundError(name)
        return svc

    def has(self, name: str) -> bool:
        return name in self._indices

    def names(self) -> List[str]:
        return sorted(self._indices)

    def close(self) -> None:
        self._reaper_stop.set()
        for svc in self._indices.values():
            svc.close()

"""Data-only segment serialization: JSON header + raw numpy arrays.

Replaces pickle for every path where segment bytes cross a trust boundary —
snapshot repositories (an arbitrary, shareable directory; ref:
repositories/blobstore/BlobStoreRepository.java stores data-only formats),
peer-recovery file transfers, and on-disk commits. Deserialization never
executes code: arrays load with ``allow_pickle=False`` and everything else
is JSON.

Blob layout (v3, written since the integrity plane)::

    b"ESTPUSEG3" | u64 header_len | header JSON (utf-8) | npz payload
                 | sha256(header_len .. payload) footer (32 bytes)

The header carries structure (which fields exist, term dictionaries,
doc ids, sources); the npz payload carries every numpy array keyed by a
flat path (nested child segments recurse with a ``nested.<name>/`` key
prefix). The trailing footer is the at-rest integrity leg (ref: Lucene's
per-file CodecUtil.writeFooter checksum): `segment_from_blob` re-hashes
on EVERY read and raises `SegmentCorruptedError` on mismatch. v2 blobs
(no footer) remain readable — verification is skipped and the read is
counted under `legacy_blobs_read`.
"""

from __future__ import annotations

import hashlib
import io
import json
from typing import Dict

import numpy as np

MAGIC = b"ESTPUSEG3"
MAGIC_V2 = b"ESTPUSEG2"    # pre-integrity blobs: readable, unverifiable
_FOOTER_LEN = 32           # sha256 digest size


def _put_field_postings(fp, prefix: str, arrays: Dict[str, np.ndarray],
                        meta: dict) -> None:
    meta["terms"] = fp.terms
    meta["sum_doc_len"] = float(fp.sum_doc_len)
    for name in ("doc_freq", "total_term_freq", "block_start", "block_count",
                 "block_docs", "block_tfs", "block_max_tf", "post_start",
                 "post_doc", "pos_start", "pos_data", "doc_len"):
        arrays[prefix + name] = getattr(fp, name)


def _get_field_postings(field: str, prefix: str, arrays, meta: dict):
    from elasticsearch_tpu.index.segment import FieldPostings

    terms = list(meta["terms"])
    kw = {name: np.asarray(arrays[prefix + name])
          for name in ("doc_freq", "total_term_freq", "block_start",
                       "block_count", "block_docs", "block_tfs",
                       "block_max_tf", "post_start", "post_doc", "pos_start",
                       "pos_data", "doc_len")}
    return FieldPostings(field=field, term_to_ord={t: i for i, t in enumerate(terms)},
                         terms=terms, sum_doc_len=float(meta["sum_doc_len"]), **kw)


def _flatten_segment(seg, prefix: str, arrays: Dict[str, np.ndarray]) -> dict:
    meta: dict = {
        "seg_id": int(seg.seg_id),
        "doc_ids": list(seg.doc_ids),
        "sources": list(seg.sources),
        "postings": {},
        "numeric": sorted(seg.numeric),
        "keyword": {},
        "vectors": {},
        "geo": sorted(seg.geo),
        "nested": {},
    }
    arrays[prefix + "seq_nos"] = seg.seq_nos
    arrays[prefix + "versions"] = seg.versions
    for field, fp in seg.postings.items():
        fmeta: dict = {}
        _put_field_postings(fp, f"{prefix}post.{field}/", arrays, fmeta)
        meta["postings"][field] = fmeta
    for field, nc in seg.numeric.items():
        p = f"{prefix}num.{field}/"
        arrays[p + "values"] = nc.values
        arrays[p + "max_values"] = nc.max_values
        arrays[p + "exists"] = nc.exists
        arrays[p + "value_start"] = nc.value_start
        arrays[p + "all_values"] = nc.all_values
    for field, kc in seg.keyword.items():
        p = f"{prefix}kw.{field}/"
        meta["keyword"][field] = {"terms": kc.terms}
        arrays[p + "ords"] = kc.ords
        arrays[p + "max_ords"] = kc.max_ords
        arrays[p + "exists"] = kc.exists
        arrays[p + "ord_start"] = kc.ord_start
        arrays[p + "all_ords"] = kc.all_ords
    for field, vc in seg.vectors.items():
        p = f"{prefix}vec.{field}/"
        meta["vectors"][field] = {"dims": int(vc.dims),
                                  "similarity": vc.similarity}
        arrays[p + "vectors"] = vc.vectors
        arrays[p + "norms"] = vc.norms
        arrays[p + "exists"] = vc.exists
    for field, gc in seg.geo.items():
        p = f"{prefix}geo.{field}/"
        arrays[p + "lat"] = gc.lat
        arrays[p + "lon"] = gc.lon
        arrays[p + "value_start"] = gc.value_start
        arrays[p + "exists"] = gc.exists
    for field, nt in seg.nested.items():
        p = f"{prefix}nested.{field}/"
        child_meta = _flatten_segment(nt.child, p + "child/", arrays)
        arrays[p + "parent_of"] = nt.parent_of
        arrays[p + "child_start"] = nt.child_start
        meta["nested"][field] = child_meta
    return meta


def _rebuild_segment(meta: dict, prefix: str, arrays):
    from elasticsearch_tpu.index.segment import (
        GeoColumn, KeywordColumn, NestedTable, NumericColumn, Segment,
        VectorColumn,
    )

    postings = {f: _get_field_postings(f, f"{prefix}post.{f}/", arrays, m)
                for f, m in meta["postings"].items()}
    numeric = {}
    for f in meta["numeric"]:
        p = f"{prefix}num.{f}/"
        numeric[f] = NumericColumn(
            values=np.asarray(arrays[p + "values"]),
            max_values=np.asarray(arrays[p + "max_values"]),
            exists=np.asarray(arrays[p + "exists"]),
            value_start=np.asarray(arrays[p + "value_start"]),
            all_values=np.asarray(arrays[p + "all_values"]))
    keyword = {}
    for f, km in meta["keyword"].items():
        p = f"{prefix}kw.{f}/"
        terms = list(km["terms"])
        keyword[f] = KeywordColumn(
            terms=terms, term_to_ord={t: i for i, t in enumerate(terms)},
            ords=np.asarray(arrays[p + "ords"]),
            max_ords=np.asarray(arrays[p + "max_ords"]),
            exists=np.asarray(arrays[p + "exists"]),
            ord_start=np.asarray(arrays[p + "ord_start"]),
            all_ords=np.asarray(arrays[p + "all_ords"]))
    vectors = {}
    for f, vm in meta["vectors"].items():
        p = f"{prefix}vec.{f}/"
        vectors[f] = VectorColumn(
            vectors=np.asarray(arrays[p + "vectors"]),
            norms=np.asarray(arrays[p + "norms"]),
            exists=np.asarray(arrays[p + "exists"]),
            dims=int(vm["dims"]), similarity=vm["similarity"])
    geo = {}
    for f in meta["geo"]:
        p = f"{prefix}geo.{f}/"
        geo[f] = GeoColumn(
            lat=np.asarray(arrays[p + "lat"]),
            lon=np.asarray(arrays[p + "lon"]),
            value_start=np.asarray(arrays[p + "value_start"]),
            exists=np.asarray(arrays[p + "exists"]))
    nested = {}
    for f, child_meta in meta["nested"].items():
        p = f"{prefix}nested.{f}/"
        nested[f] = NestedTable(
            child=_rebuild_segment(child_meta, p + "child/", arrays),
            parent_of=np.asarray(arrays[p + "parent_of"]),
            child_start=np.asarray(arrays[p + "child_start"]))
    return Segment(
        seg_id=int(meta["seg_id"]), doc_ids=list(meta["doc_ids"]),
        sources=list(meta["sources"]), postings=postings, numeric=numeric,
        keyword=keyword, vectors=vectors,
        seq_nos=np.asarray(arrays[prefix + "seq_nos"]),
        versions=np.asarray(arrays[prefix + "versions"]),
        geo=geo, nested=nested)


def segment_to_blob(seg) -> bytes:
    """Serialize a Segment to a self-contained data-only blob."""
    arrays: Dict[str, np.ndarray] = {}
    meta = _flatten_segment(seg, "", arrays)
    # field names may contain any character; npz keys are positional
    # (`a<i>`) and the header maps real key -> position, so no escaping
    # scheme can collide
    names = sorted(arrays)
    meta["__array_names__"] = names
    header = json.dumps(meta).encode()
    buf = io.BytesIO()
    np.savez(buf, **{f"a{i}": arrays[name] for i, name in enumerate(names)})
    payload = buf.getvalue()
    body = len(header).to_bytes(8, "big") + header + payload
    return MAGIC + body + hashlib.sha256(body).digest()


def blob_hash(blob: bytes) -> str:
    """Hex sha256 of the whole wire blob — what recovery sources advertise
    next to each segment payload so the target can verify before install."""
    return hashlib.sha256(blob).hexdigest()


def verify_blob(blob: bytes) -> None:
    """Re-hash a v3 blob against its footer; raise on mismatch.

    v2 blobs pass (nothing to verify against); anything else — truncation,
    bad magic, footer mismatch — raises `SegmentCorruptedError`."""
    from elasticsearch_tpu.common.integrity import SegmentCorruptedError

    from elasticsearch_tpu.common import integrity

    if blob.startswith(MAGIC_V2):
        return
    if not blob.startswith(MAGIC) or len(blob) < len(MAGIC) + 8 + _FOOTER_LEN:
        integrity.count("segments_corrupted")
        raise SegmentCorruptedError(
            "not a segment blob (bad magic or truncated)")
    body, footer = blob[len(MAGIC):-_FOOTER_LEN], blob[-_FOOTER_LEN:]
    digest = hashlib.sha256(body).digest()
    if digest != footer:
        integrity.count("segments_corrupted")
        raise SegmentCorruptedError(
            f"segment blob failed checksum verification: footer "
            f"{footer.hex()[:16]}.. != computed {digest.hex()[:16]}..")
    integrity.count("segments_verified")
    integrity.count("bytes_verified", len(blob))


def segment_from_blob(blob: bytes):
    """Rebuild a Segment from a blob, verifying the checksum footer on
    every read. Never unpickles."""
    from elasticsearch_tpu.common import integrity

    if blob.startswith(MAGIC_V2):
        # pre-footer blob: parseable but unverifiable (counted, so fleets
        # can watch the legacy population drain as segments rewrite)
        integrity.count("legacy_blobs_read")
        magic, end = MAGIC_V2, len(blob)
    elif blob.startswith(MAGIC):
        verify_blob(blob)
        magic, end = MAGIC, len(blob) - _FOOTER_LEN
    else:
        raise ValueError(
            "not a segment blob (bad magic); refusing to parse — legacy "
            "pickled segments are unsupported (reindex from source)")
    hlen = int.from_bytes(blob[len(magic): len(magic) + 8], "big")
    off = len(magic) + 8
    meta = json.loads(blob[off: off + hlen].decode())
    npz = np.load(io.BytesIO(blob[off + hlen: end]), allow_pickle=False)
    names = meta.pop("__array_names__")
    arrays = {name: npz[f"a{i}"] for i, name in enumerate(names)}
    return _rebuild_segment(meta, "", arrays)

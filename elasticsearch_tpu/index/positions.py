"""Vectorized positional phrase matching over columnar postings.

The TPU-framework replacement for Lucene's PhraseScorer doc-at-a-time
position intersection (ref: Lucene ExactPhraseMatcher/SloppyPhraseMatcher as
driven by search/query/... PhraseQuery weights): instead of walking one
candidate doc at a time with per-doc position iterators, the whole
candidate set is verified in a handful of columnar array ops.

Key idea: a (doc, position) pair becomes one integer key

    key = doc * stride + position          (stride > max_position + phrase_len)

Because postings are doc-ascending and positions ascend within a doc, each
term's key array is globally sorted, so "does term i occur at position
p + i in doc d" is one `searchsorted` probe — vectorized over EVERY
candidate occurrence of the phrase's first term at once. An exact phrase of
T terms costs T-1 searchsorted passes over arrays sized by the rarest
term's candidate occurrences; a sloppy phrase enumerates the (small) set of
displacement tuples and ORs their matches.

This module is pure NumPy on purpose: candidate sets after conjunction are
tiny relative to the corpus, and position verify is memory-latency bound --
a device round trip would dominate. The device side of phrase execution is
the conjunction itself (block postings intersection on the mesh); see
parallel/blockmax.py.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from elasticsearch_tpu.index.segment import FieldPostings


def _csr_rows(fp: FieldPostings, ord_: int, docs: np.ndarray) -> np.ndarray:
    """Row indices into fp.post_doc/pos_start for `docs` under term `ord_`.

    `docs` must all be present in the term's postings (candidates come from
    an intersection, so they are)."""
    lo, hi = int(fp.post_start[ord_]), int(fp.post_start[ord_ + 1])
    return lo + np.searchsorted(fp.post_doc[lo:hi], docs)


def _ragged_take(starts: np.ndarray, ends: np.ndarray,
                 data: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Gather data[starts[i]:ends[i]] for all i, concatenated.

    Returns (values, row_of_value). Fully vectorized (repeat + cumsum)."""
    lens = (ends - starts).astype(np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, data.dtype), np.empty(0, np.int64)
    row = np.repeat(np.arange(len(lens), dtype=np.int64), lens)
    # flat[j] = starts[row[j]] + (j - first_j_of_row)
    first = np.concatenate([[0], np.cumsum(lens)[:-1]])
    flat = starts[row] + (np.arange(total, dtype=np.int64) - first[row])
    return data[flat], row


def candidate_docs(fp: FieldPostings, ords: List[int]) -> np.ndarray:
    """Docs containing ALL terms: sorted-list intersection, rarest first."""
    ords = sorted(ords, key=lambda o: int(fp.doc_freq[o]))
    cand: np.ndarray | None = None
    for o in ords:
        docs = fp.post_doc[int(fp.post_start[o]): int(fp.post_start[o + 1])]
        cand = docs if cand is None else cand[np.isin(cand, docs, assume_unique=True)]
        if len(cand) == 0:
            return np.empty(0, np.int32)
    return np.asarray(cand, np.int32)


def _offset_tuples(n_terms: int, slop: int):
    """Per-term displacement tuples with total |displacement| <= slop
    (term 0 anchored). Matches the simplified sloppy semantics the dense
    executor has always used (see search/executor.py history)."""
    def rec(i, remaining):
        if i == n_terms:
            yield ()
            return
        for d in range(-remaining, remaining + 1):
            for rest in rec(i + 1, remaining - abs(d)):
                yield (d,) + rest
    for offs in rec(1, slop):
        yield (0,) + offs


def phrase_freqs(fp: FieldPostings, terms: List[str], slop: int = 0,
                 docs_filter: np.ndarray | None = None,
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Phrase frequency per matching doc, fully vectorized.

    Returns (docs i32[n], freqs f32[n]) for docs with freq > 0, ascending.
    Requires the field to have been indexed with positions (pos_data
    non-empty whenever postings exist); segments built without positions
    raise ValueError rather than silently matching nothing.
    """
    ords = []
    for t in terms:
        o = fp.ord(t)
        if o < 0:
            return np.empty(0, np.int32), np.empty(0, np.float32)
        ords.append(o)
    if len(fp.pos_data) == 0 and int(fp.total_term_freq.sum()) > 0:
        raise ValueError(
            f"field [{fp.field}] was indexed without positions; "
            "phrase queries need the positional builder")
    if len(ords) == 1:
        lo, hi = int(fp.post_start[ords[0]]), int(fp.post_start[ords[0] + 1])
        docs = fp.post_doc[lo:hi].astype(np.int32)
        tf = (fp.pos_start[lo + 1: hi + 1] - fp.pos_start[lo:hi]).astype(np.float32)
        return docs, tf

    cand = candidate_docs(fp, ords)
    if docs_filter is not None and len(cand):
        cand = cand[np.isin(cand, docs_filter, assume_unique=True)]
    if len(cand) == 0:
        return np.empty(0, np.int32), np.empty(0, np.float32)

    max_pos = getattr(fp, "_max_pos_cache", None)
    if max_pos is None:
        max_pos = int(fp.pos_data.max()) if len(fp.pos_data) else 0
        fp._max_pos_cache = max_pos   # immutable postings: compute once
    stride = max_pos + len(terms) + slop + 2

    # occurrences of term 0 restricted to candidate docs
    rows0 = _csr_rows(fp, ords[0], cand)
    base_pos, occ_row = _ragged_take(
        fp.pos_start[rows0], fp.pos_start[rows0 + 1], fp.pos_data)
    base_key = cand[occ_row].astype(np.int64) * stride + base_pos.astype(np.int64)

    # sorted key arrays for the other terms (restricted to candidates keeps
    # the searchsorted arrays small)
    keys = []
    for i in range(1, len(ords)):
        rows = _csr_rows(fp, ords[i], cand)
        pos_i, row_i = _ragged_take(
            fp.pos_start[rows], fp.pos_start[rows + 1], fp.pos_data)
        keys.append(cand[row_i].astype(np.int64) * stride + pos_i.astype(np.int64))

    def probe(offsets) -> np.ndarray:
        ok = np.ones(len(base_key), bool)
        for i, k in enumerate(keys, start=1):
            want = base_key + i + offsets[i]
            j = np.searchsorted(k, want)
            hit = (j < len(k))
            hit[hit] = k[j[hit]] == want[hit]
            ok &= hit
            if not ok.any():
                break
        return ok

    if slop == 0:
        matched = probe((0,) * len(ords))
    else:
        matched = np.zeros(len(base_key), bool)
        for offs in _offset_tuples(len(ords), slop):
            matched |= probe(offs)

    freq = np.bincount(occ_row[matched], minlength=len(cand)).astype(np.float32)
    nz = freq > 0
    return cand[nz], freq[nz]

"""Shard replication: primary/replica groups with seqno-acked writes.

Re-designs the reference's replication template (ref:
action/support/replication/ReplicationOperation.java:99 — primary executes,
fans to every in-sync replica, collects acks, fails stale copies via the
master, advances the global checkpoint; index/seqno/ReplicationTracker.java
for the checkpoint algebra; indices/recovery/RecoverySourceHandler.java:139
for peer recovery) around the TPU engine:

  * writes execute on the primary engine, then replicate the seqno-stamped
    op to every in-sync copy through a pluggable channel (direct call in
    one process, transport action across nodes);
  * a failed replica is reported to the failure listener (the master's
    shard-failed path) and dropped from the in-sync set;
  * peer recovery = phase1 segment snapshot copy + phase2 ops replay above
    the snapshot's max seqno, then mark in-sync — writes concurrent with
    recovery flow to the new copy as soon as it is tracked, and the engine's
    per-doc seqno comparison makes replayed ops idempotent;
  * failover promotes a replica: bumps the primary term and resyncs copies
    above the global checkpoint (ref: index/shard/PrimaryReplicaSyncer.java).
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import ElasticsearchTpuError
from elasticsearch_tpu.index.engine import EngineResult, InternalEngine
from elasticsearch_tpu.index.seqno import NO_OPS_PERFORMED, ReplicationTracker


class ReplicationFailedError(ElasticsearchTpuError):
    status = 503
    error_type = "replication_failed_exception"


@dataclass
class ShardCopy:
    """One physical copy of the shard."""

    allocation_id: str
    node_id: str
    engine: InternalEngine


class ReplicationGroup:
    """Primary-side controller for one shard's copies."""

    def __init__(self, primary: ShardCopy,
                 on_replica_failure: Optional[Callable[[str, Exception], None]] = None):
        self._lock = threading.RLock()
        self.primary = primary
        self.tracker = ReplicationTracker(primary.allocation_id)
        self.tracker.mark_in_sync(primary.allocation_id)
        self.replicas: Dict[str, ShardCopy] = {}
        self.on_replica_failure = on_replica_failure or (lambda aid, e: None)

    # ---- write path (ref: ReplicationOperation.execute) ----

    def index(self, doc_id: str, source: dict, **kw) -> EngineResult:
        with self._lock:
            result = self.primary.engine.index(doc_id, source, **kw)
            self._replicate({"op": "index", "id": doc_id, "source": source,
                             "seq_no": result.seq_no,
                             "primary_term": result.primary_term})
            self._after_write()
            return result

    def delete(self, doc_id: str, **kw) -> EngineResult:
        with self._lock:
            result = self.primary.engine.delete(doc_id, **kw)
            self._replicate({"op": "delete", "id": doc_id,
                             "seq_no": result.seq_no,
                             "primary_term": result.primary_term})
            self._after_write()
            return result

    def _replicate(self, op: dict) -> None:
        in_sync = self.tracker.in_sync_ids
        tracked = {aid: c for aid, c in self.replicas.items()}
        for aid, copy in tracked.items():
            required = aid in in_sync
            try:
                self._apply_to_copy(copy, op)
                self.tracker.update_local_checkpoint(
                    aid, copy.engine.local_checkpoint)
            except Exception as e:  # noqa: BLE001 — any failure fails the copy
                self._fail_replica(aid, e)
                if required:
                    # in the reference the master confirms the failure before
                    # the write acks; here the listener is invoked inline
                    pass

    @staticmethod
    def _apply_to_copy(copy: ShardCopy, op: dict) -> None:
        term = op.get("primary_term")
        if op["op"] == "index":
            copy.engine.index(op["id"], op["source"], seq_no=op["seq_no"],
                              op_primary_term=term)
        else:
            copy.engine.delete(op["id"], seq_no=op["seq_no"],
                               op_primary_term=term)

    def _after_write(self) -> None:
        self.tracker.update_local_checkpoint(
            self.primary.allocation_id, self.primary.engine.local_checkpoint)

    def _fail_replica(self, allocation_id: str, error: Exception) -> None:
        self.replicas.pop(allocation_id, None)
        self.tracker.remove_tracking(allocation_id)
        self.on_replica_failure(allocation_id, error)

    # ---- peer recovery (ref: RecoverySourceHandler.recoverToTarget) ----

    def add_replica(self, copy: ShardCopy) -> None:
        """Recover a new copy and bring it in-sync.

        phase0: track the copy so concurrent writes reach it immediately;
        phase1: snapshot the primary's published segments and install them;
        phase2: replay ops above the snapshot's max seqno (the engine's
        stale-op checks make overlap with live writes idempotent);
        finalize: mark in-sync.
        """
        with self._lock:
            self.replicas[copy.allocation_id] = copy
            self.tracker.add_tracking(copy.allocation_id)

        # phase1: segment-file copy, modeled as a deep snapshot transfer
        term = self.primary.engine.primary_term
        snapshot_ops = self.primary.engine.changes_since(NO_OPS_PERFORMED)
        for op in snapshot_ops:
            self._apply_to_copy(copy, {"op": op["op"], "id": op["id"],
                                       "source": op.get("source"),
                                       "seq_no": op["seq_no"],
                                       "primary_term": term})
        # phase2: replay anything that arrived while phase1 streamed
        with self._lock:
            if copy.allocation_id not in self.replicas:
                # a concurrent write failed this copy during phase1 — do not
                # resurrect it into the in-sync set (its checkpoint would pin
                # the global checkpoint at -1 with no copy behind it)
                return
            gap_ops = self.primary.engine.changes_since(copy.engine.local_checkpoint)
            for op in gap_ops:
                self._apply_to_copy(copy, {"op": op["op"], "id": op["id"],
                                           "source": op.get("source"),
                                           "seq_no": op["seq_no"],
                                           "primary_term": term})
            copy.engine.refresh()
            # latest-op-per-doc replay collapses superseded seqnos; fill the
            # gaps so the copy's checkpoint reaches the replayed history's end
            copy.engine.fill_seqno_gaps(self.primary.engine.max_seq_no)
            self.tracker.update_local_checkpoint(
                copy.allocation_id, copy.engine.local_checkpoint)
            self.tracker.mark_in_sync(copy.allocation_id)

    # ---- failover (ref: IndexShard primary promotion + PrimaryReplicaSyncer) ----

    def promote(self, allocation_id: str) -> "ReplicationGroup":
        """Promote a replica to primary after primary loss. Returns the new
        group; remaining replicas resync from the new primary.

        Resync semantics (ref: index/shard/PrimaryReplicaSyncer.java + the
        replica engine reset to the global checkpoint): each survivor first
        adopts the new primary term — explicitly, so a fully-caught-up copy
        that replays zero ops is still fenced against the deposed primary —
        then rolls back any history above the old global checkpoint to the
        new primary's authoritative per-doc state, then replays the new
        primary's ops above that checkpoint."""
        with self._lock:
            gcp = self.tracker.global_checkpoint
            new_primary = self.replicas.pop(allocation_id)
            new_term = self.primary.engine.primary_term + 1
            new_primary.engine.advance_primary_term(new_term)
            # promotion fills seqno gaps so the new primary's checkpoint
            # reaches its max seqno (reference fills with no-ops)
            new_primary.engine.fill_seqno_gaps(new_primary.engine.max_seq_no)
            group = ReplicationGroup(new_primary, self.on_replica_failure)
            survivors = dict(self.replicas)
        for aid, copy in survivors.items():
            try:
                # fence FIRST: a late write from the deposed primary accepted
                # after docs_above would otherwise escape the rollback set
                copy.engine.advance_primary_term(new_term)
                divergent = copy.engine.docs_above(gcp)
                doc_states = {d: new_primary.engine.doc_resync_state(d)
                              for d in divergent}
                # a copy still catching up (tracked, not yet in-sync) may be
                # behind the global checkpoint — replay from wherever it is
                replay_from = min(gcp, copy.engine.local_checkpoint)
                resync_target_apply(
                    copy.engine, new_term, doc_states, replay_from,
                    new_primary.engine.changes_since(replay_from),
                    new_primary.engine.max_seq_no)
            except Exception as e:  # noqa: BLE001
                group.on_replica_failure(aid, e)
                continue
            group.replicas[aid] = copy
            group.tracker.add_tracking(aid)
            group.tracker.update_local_checkpoint(aid, copy.engine.local_checkpoint)
            group.tracker.mark_in_sync(aid)
        group._after_write()
        return group

    # ---- introspection ----

    @property
    def global_checkpoint(self) -> int:
        return self.tracker.global_checkpoint

    def copies(self) -> List[ShardCopy]:
        with self._lock:
            return [self.primary, *self.replicas.values()]


def resync_target_apply(engine: InternalEngine, new_term: int,
                        doc_states: Dict[str, Optional[dict]],
                        replay_from: int, ops: List[dict],
                        max_seq_no: int) -> None:
    """Target-side primary-replica resync: adopt the new term, roll back
    divergent docs to the new primary's authoritative per-doc state, replay
    its history above the rollback point, and make the result durable.

    Shared by the in-process ReplicationGroup.promote and the transport
    resync action (ref: index/shard/PrimaryReplicaSyncer.java + replica
    engine reset to the global checkpoint).

      * advance term first so even a zero-op resync fences the deposed
        primary;
      * rollback before replay so force_resync_doc's per-doc tombstones
        cannot clobber replayed newer ops;
      * relog + flush so a crash after resync recovers the resynced state,
        not the divergent one (divergent ops already flushed into committed
        segments sit below the committed checkpoint, out of translog-replay
        range — only a re-commit removes them durably).
    """
    engine.advance_primary_term(new_term)
    for doc_id, state in doc_states.items():
        engine.force_resync_doc(doc_id, state)
    engine.reset_local_checkpoint(replay_from)
    for op in ops:
        if op["op"] == "index":
            engine.index(op["id"], op.get("source"), seq_no=op["seq_no"],
                         op_primary_term=new_term)
        else:
            engine.delete(op["id"], seq_no=op["seq_no"],
                          op_primary_term=new_term)
    engine.fill_seqno_gaps(max_seq_no)
    engine.relog_above(replay_from)
    engine.flush()


def new_allocation_id() -> str:
    return uuid.uuid4().hex[:20]

"""Cross-cluster replication: follower indices pulling a leader's translog
ops by global-checkpoint range (PR 20).

The reference's CCR (ref: x-pack ccr — ShardFollowNodeTask's
read/write loop over ShardChangesAction, bootstrapped by
PutFollowAction) is a PULL design: the follower polls the leader for
operation batches and applies them under its own primary term. The same
loop here, built from seams that already exist:

  * the leader serves ops from `InternalEngine.changes_since` — latest
    op per doc, seqno-ordered (the resync/ops-recovery history source) —
    but only up to its GLOBAL checkpoint: an op above the gcp is acked
    on the primary but not yet durable on every in-sync copy, so a
    leader crash may legally lose it; shipping only ``(from, gcp]``
    means the follower never holds history the leader can roll back.
  * every batch carries a sha256 computed on the leader BEFORE the wire
    (the PR-15 segment-transfer discipline); a follower-side mismatch
    re-fetches, bounded by ``ES_TPU_REMOTE_RETRIES``.
  * apply is seq-no idempotent via the engine's replica path
    (`index(seq_no=..., op_primary_term=...)` no-ops on stale seqnos) at
    the FOLLOWER's own primary term — leader and follower term spaces
    never entangle — then `fill_seqno_gaps` fast-forwards over seqnos
    collapsed by latest-op-per-doc history, exactly as ops-based
    recovery does.
  * leader unavailability auto-retries on the PR-13 retry budget (inside
    `RemoteClusterService.request`) and again at the next poll tick —
    the loop is re-entrant and makes progress whenever the leader is
    reachable.

`CcrService` runs on BOTH node flavors through a host adapter: the
multi-node `ClusterNode` (ops route to the follower shard's primary via
`internal:index/ccr/apply_ops` and fan to replicas through the existing
`_replicate` path) and the standalone REST `Node` (engines applied
directly). All leader-bound RPCs share the `rpc_ccr_fetch` fault site
(``#part`` = the remote cluster alias), the way every recovery phase
shares `rpc_recovery`."""

from __future__ import annotations

import hashlib
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from elasticsearch_tpu.common import faults, metrics
from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuError, IllegalArgumentError, IndexNotFoundError,
)
from elasticsearch_tpu.common.integrity import SegmentCorruptedError
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.index.seqno import NO_OPS_PERFORMED
from elasticsearch_tpu.transport.channels import (
    NodeUnavailableError, RpcTimeoutError,
)

# Follower -> leader (cross-cluster, via RemoteClusterService):
ACTION_CCR_INFO = "internal:index/ccr/leader_info"
ACTION_CCR_FETCH = "internal:index/ccr/fetch_ops"
# Follower-internal (route an op batch to the follower shard's primary):
ACTION_CCR_APPLY = "internal:index/ccr/apply_ops"

# every follower->leader RPC shares one fault site (#part = cluster alias)
CCR_FAULT_SITE = "rpc_ccr_fetch"


def batch_checksum(ops: List[dict]) -> str:
    """sha256 of the canonical JSON of an op batch, computed on the leader
    BEFORE the wire (PR-15 `blob_hash` discipline for segment payloads)."""
    return hashlib.sha256(
        json.dumps(ops, sort_keys=True).encode()).hexdigest()


@dataclass
class _Follower:
    """Pull-loop state for one follower index."""

    index: str
    remote_cluster: str
    leader_index: str
    n_shards: int
    paused: bool = False
    # per shard: highest seqno applied AND gap-filled (next fetch is
    # exclusive of this value — the leader's changes_since contract)
    from_seq: Dict[int, int] = field(default_factory=dict)
    # per shard: the leader global checkpoint last seen (lag accounting)
    leader_gcp: Dict[int, int] = field(default_factory=dict)
    last_error: Optional[str] = None


class CcrHost:
    """What CcrService needs from its node. Two implementations below —
    the duck type is the contract, this class is documentation."""

    node_name: str

    def index_info(self, index: str) -> dict: ...
    def ensure_follower_index(self, index: str, n_shards: int,
                              mappings: dict, settings: dict) -> None: ...
    def primary_owner(self, index: str, shard_id: int) -> Optional[str]: ...
    def forward(self, node: str, action: str, payload: dict) -> dict: ...
    def primary_engine(self, index: str, shard_id: int): ...
    def apply_local(self, index: str, shard_id: int, ops: List[dict],
                    fill_to: int) -> dict: ...


class ClusterNodeHost:
    """Adapter over a multi-node ClusterNode: cluster-state lookups,
    channel forwards to the owning primary, replica fan-out through the
    shard service's existing `_replicate` path."""

    def __init__(self, node):
        self.node = node
        self.node_name = node.node_name

    def index_info(self, index: str) -> dict:
        meta = self.node.state.indices.get(index)
        if meta is None:
            raise IndexNotFoundError(index)
        return {"number_of_shards": meta.number_of_shards,
                "mappings": dict(meta.mappings)}

    def ensure_follower_index(self, index: str, n_shards: int,
                              mappings: dict, settings: dict) -> None:
        if index in self.node.state.indices:
            return
        body_settings = {"index.number_of_shards": n_shards,
                         "index.number_of_replicas": 0}
        body_settings.update(settings or {})
        self.node.create_index(index, {"settings": body_settings,
                                       "mappings": mappings})

    def primary_owner(self, index: str, shard_id: int) -> Optional[str]:
        r = self.node.state.primary_of(index, shard_id)
        if r is None or r.node_id is None or not r.serving:
            raise ElasticsearchTpuError(
                f"no started primary for [{index}][{shard_id}]")
        return r.node_id

    def forward(self, node: str, action: str, payload: dict) -> dict:
        return self.node.channels.request(node, action, payload,
                                          source=self.node_name)

    def primary_engine(self, index: str, shard_id: int):
        inst = self.node.shard_service.get_shard(index, shard_id)
        if not inst.primary:
            from elasticsearch_tpu.indices.shard_service import (
                ShardNotFoundError,
            )

            raise ShardNotFoundError(
                f"[{index}][{shard_id}] copy here is not the primary")
        gcp = inst.tracker.global_checkpoint if inst.tracker is not None \
            else inst.engine.local_checkpoint
        return inst.engine, gcp

    def apply_local(self, index: str, shard_id: int, ops: List[dict],
                    fill_to: int) -> dict:
        from elasticsearch_tpu.indices.shard_service import (
            DistributedShardService,
        )

        svc = self.node.shard_service
        inst = svc.get_shard(index, shard_id)
        with inst.lock:
            # the follower's OWN term: leader terms never cross the
            # boundary, so a leader-side primary failover cannot fence
            # the follower's writes (ref: ShardFollowNodeTask applies
            # under the follower primary's term)
            DistributedShardService._apply_recovery_ops(
                inst, ops, inst.primary_term)
            inst.engine.fill_seqno_gaps(fill_to)
            if inst.tracker is not None:
                inst.tracker.update_local_checkpoint(
                    inst.allocation_id, inst.engine.local_checkpoint)
            svc._replicate(inst, ops)
        inst.engine.refresh()
        return {"local_checkpoint": inst.engine.local_checkpoint}


class StandaloneNodeHost:
    """Adapter over the standalone REST Node: one process owns every
    shard, so ownership is always local and apply hits engines directly."""

    def __init__(self, node):
        self.node = node
        self.node_name = node.node_name

    def index_info(self, index: str) -> dict:
        meta = self.node.cluster_state.indices.get(index)
        if meta is None:
            raise IndexNotFoundError(index)
        return {"number_of_shards": meta.number_of_shards,
                "mappings": dict(meta.mappings)}

    def ensure_follower_index(self, index: str, n_shards: int,
                              mappings: dict, settings: dict) -> None:
        if self.node.indices.has(index):
            return
        body_settings = {"index.number_of_shards": n_shards}
        body_settings.update(settings or {})
        self.node.create_index(index, {"settings": body_settings,
                                       "mappings": mappings})

    def primary_owner(self, index: str, shard_id: int) -> Optional[str]:
        return None   # always local

    def forward(self, node: str, action: str, payload: dict) -> dict:
        raise AssertionError("standalone node never forwards")

    def primary_engine(self, index: str, shard_id: int):
        svc = self.node.indices.get(index)
        engine = svc.shards[shard_id]
        return engine, engine.local_checkpoint

    def apply_local(self, index: str, shard_id: int, ops: List[dict],
                    fill_to: int) -> dict:
        engine = self.node.indices.get(index).shards[shard_id]
        for op in ops:
            if op["op"] == "index":
                engine.index(op["id"], op.get("source"),
                             seq_no=op["seq_no"],
                             op_primary_term=engine.primary_term)
            else:
                engine.delete(op["id"], seq_no=op["seq_no"],
                              op_primary_term=engine.primary_term)
        engine.fill_seqno_gaps(fill_to)
        engine.refresh()
        return {"local_checkpoint": engine.local_checkpoint}


class CcrService:
    """Follower-index registry + the leader-side op-shipping handlers.

    One instance per node: the LEADER handlers (`leader_info`,
    `fetch_ops`) answer any remote follower; the FOLLOWER side holds the
    pull-loop state for indices this node was told to `follow`."""

    def __init__(self, host, remotes, transport):
        self.host = host
        self.remotes = remotes
        self._followers: Dict[str, _Follower] = {}   # guarded by: _lock
        self._lock = threading.Lock()
        self._poll_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        transport.register_request_handler(ACTION_CCR_INFO,
                                           self._on_leader_info)
        transport.register_request_handler(ACTION_CCR_FETCH,
                                           self._on_fetch_ops)
        transport.register_request_handler(ACTION_CCR_APPLY,
                                           self._on_apply_ops)

    # ---------------- leader-side handlers ----------------

    def _on_leader_info(self, req) -> dict:
        """Index shape for PutFollow: shard count + mappings, so the
        follower can create a congruent index."""
        return self.host.index_info(req.payload["index"])

    def _on_fetch_ops(self, req) -> dict:
        """One op batch in ``(from_seq_no, global_checkpoint]``, capped at
        `max_ops` (``ES_TPU_CCR_BATCH_OPS``), checksummed pre-wire.

        Ops above the gcp are NOT shipped: they are acked on the primary
        but a leader-cluster crash may lawfully roll them back (resync
        resets to the gcp), and a follower must never hold history its
        leader can lose. A node that doesn't own the primary forwards one
        hop to the owner."""
        p = req.payload
        index, sid = p["index"], p["shard_id"]
        owner = self.host.primary_owner(index, sid)
        if owner is not None and owner != self.host.node_name:
            return self.host.forward(owner, ACTION_CCR_FETCH, p)
        engine, gcp = self.host.primary_engine(index, sid)
        from_seq = int(p.get("from_seq_no", NO_OPS_PERFORMED))
        max_ops = int(p.get("max_ops") or knob("ES_TPU_CCR_BATCH_OPS"))
        ops = [op for op in engine.changes_since(from_seq)
               if op["seq_no"] <= gcp]
        truncated = len(ops) > max_ops
        ops = ops[:max_ops]
        # a complete batch lets the follower fast-forward its checkpoint
        # all the way to the gcp (seqnos in between collapsed away by
        # latest-op-per-doc history); a truncated one only to its last op
        fill_to = ops[-1]["seq_no"] if truncated else max(
            gcp, ops[-1]["seq_no"] if ops else NO_OPS_PERFORMED)
        return {"ops": ops, "fill_to": fill_to, "global_checkpoint": gcp,
                "max_seq_no": engine.max_seq_no,
                "checksum": batch_checksum(ops)}

    def _on_apply_ops(self, req) -> dict:
        """Follower-cluster internal: apply a verified batch on the
        follower shard's primary (forwarding one hop if needed), fan to
        replicas through the existing replication path."""
        p = req.payload
        index, sid = p["index"], p["shard_id"]
        owner = self.host.primary_owner(index, sid)
        if owner is not None and owner != self.host.node_name:
            return self.host.forward(owner, ACTION_CCR_APPLY, p)
        return self.host.apply_local(index, sid, p.get("ops") or [],
                                     int(p["fill_to"]))

    # ---------------- follower lifecycle ----------------

    def follow(self, follower_index: str, remote_cluster: str,
               leader_index: str, settings: Optional[dict] = None) -> dict:
        """POST /{index}/_ccr/follow: create the congruent follower index
        and start pulling (ref: PutFollowAction -> ResumeFollowAction)."""
        self.remotes.get(remote_cluster)   # unknown alias -> 400 here
        with self._lock:
            if follower_index in self._followers \
                    and not self._followers[follower_index].paused:
                raise IllegalArgumentError(
                    f"index [{follower_index}] is already a follower")
        info = self.remotes.request(
            remote_cluster, ACTION_CCR_INFO, {"index": leader_index},
            site=CCR_FAULT_SITE)
        n_shards = int(info["number_of_shards"])
        self.host.ensure_follower_index(
            follower_index, n_shards, info.get("mappings") or {},
            settings or {})
        f = _Follower(index=follower_index, remote_cluster=remote_cluster,
                      leader_index=leader_index, n_shards=n_shards)
        for sid in range(n_shards):
            # resume from whatever the follower copy already holds (an
            # empty ops apply is a checkpoint read)
            cp = self._follower_checkpoint(follower_index, sid)
            f.from_seq[sid] = cp
            f.leader_gcp[sid] = NO_OPS_PERFORMED
        with self._lock:
            self._followers[follower_index] = f
        self._maybe_start_poll_thread()
        return {"follow_index_created": True,
                "follow_index_shards_acked": True,
                "index_following_started": True}

    def pause_follow(self, follower_index: str) -> dict:
        f = self._follower(follower_index)
        f.paused = True
        return {"acknowledged": True}

    def resume_follow(self, follower_index: str) -> dict:
        f = self._follower(follower_index)
        f.paused = False
        self._maybe_start_poll_thread()
        return {"acknowledged": True}

    def _follower(self, index: str) -> _Follower:
        with self._lock:
            f = self._followers.get(index)
        if f is None:
            raise IndexNotFoundError(
                f"[{index}] is not a follower index")
        return f

    def _follower_checkpoint(self, index: str, sid: int) -> int:
        owner = self.host.primary_owner(index, sid)
        if owner is not None and owner != self.host.node_name:
            r = self.host.forward(owner, ACTION_CCR_APPLY,
                                  {"index": index, "shard_id": sid,
                                   "ops": [],
                                   "fill_to": NO_OPS_PERFORMED})
        else:
            r = self.host.apply_local(index, sid, [], NO_OPS_PERFORMED)
        return int(r["local_checkpoint"])

    # ---------------- the pull loop ----------------

    def poll_once(self, index: Optional[str] = None) -> int:
        """One pull round over every (or one) unpaused follower. Returns
        the number of ops applied — tests and the chaos harness pump this
        until 0 instead of racing the background thread
        (``ES_TPU_CCR_POLL_MS=0`` disables the thread entirely)."""
        with self._lock:
            followers = [f for f in self._followers.values()
                         if (index is None or f.index == index)
                         and not f.paused]
        applied = 0
        for f in followers:
            metrics.counter_add("ccr_polls")
            for sid in range(f.n_shards):
                try:
                    applied += self._pull_shard(f, sid)
                    f.last_error = None
                except (NodeUnavailableError, RpcTimeoutError,
                        SegmentCorruptedError,
                        ElasticsearchTpuError) as e:
                    # leader unreachable / mid-failover: the budgeted
                    # retries inside remotes.request already ran — note
                    # it and make progress at the next tick
                    f.last_error = f"{type(e).__name__}: {e}"
        return applied

    def _pull_shard(self, f: _Follower, sid: int) -> int:
        """Fetch-verify-apply until this shard is caught up to the
        leader's global checkpoint (bounded per round by batch size so a
        huge backlog still yields between shards)."""
        applied = 0
        max_ops = max(1, int(knob("ES_TPU_CCR_BATCH_OPS")))
        while True:
            resp = self._fetch_verified(f, sid, max_ops)
            ops = resp["ops"]
            fill_to = int(resp["fill_to"])
            f.leader_gcp[sid] = int(resp["global_checkpoint"])
            if not ops and fill_to <= f.from_seq[sid]:
                return applied
            owner = self.host.primary_owner(f.index, sid)
            payload = {"index": f.index, "shard_id": sid, "ops": ops,
                       "fill_to": fill_to}
            if owner is not None and owner != self.host.node_name:
                self.host.forward(owner, ACTION_CCR_APPLY, payload)
            else:
                self.host.apply_local(f.index, sid, ops, fill_to)
            f.from_seq[sid] = fill_to
            applied += len(ops)
            if len(ops):
                metrics.counter_add("ccr_ops_shipped", len(ops))
            if fill_to >= f.leader_gcp[sid]:
                return applied

    def _fetch_verified(self, f: _Follower, sid: int,
                        max_ops: int) -> dict:
        """One verified fetch: sha256 the received batch against the
        leader's pre-wire checksum; a mismatch (wire bit-rot — the
        `segment_transfer#<cluster>` corruption site models it on the
        receive side) re-fetches, bounded by ``ES_TPU_REMOTE_RETRIES``."""
        retries = max(0, int(knob("ES_TPU_REMOTE_RETRIES")))
        attempt = 0
        while True:
            resp = self.remotes.request(
                f.remote_cluster, ACTION_CCR_FETCH,
                {"index": f.leader_index, "shard_id": sid,
                 "from_seq_no": f.from_seq[sid], "max_ops": max_ops},
                site=CCR_FAULT_SITE)
            metrics.counter_add("ccr_fetches")
            ops = resp["ops"]
            if ops and faults.corruption_fires(f.remote_cluster,
                                               "segment_transfer"):
                # damage a COPY: in-process channels share objects with
                # the leader, and wire rot must never touch its engine
                ops = [dict(ops[0], id=f"{ops[0]['id']}\x00")] + ops[1:]
            if batch_checksum(ops) == resp["checksum"]:
                return dict(resp, ops=ops)
            metrics.counter_add("ccr_checksum_mismatches")
            if attempt >= retries:
                raise SegmentCorruptedError(
                    f"CCR op batch from [{f.remote_cluster}:"
                    f"{f.leader_index}][{sid}] failed sha256 verification "
                    f"{attempt + 1}x (transfer corruption)")
            attempt += 1
            metrics.counter_add("ccr_fetch_retries")

    # ---------------- background poll thread ----------------

    def _maybe_start_poll_thread(self) -> None:
        poll_ms = int(knob("ES_TPU_CCR_POLL_MS"))
        if poll_ms <= 0:
            return
        with self._lock:
            if self._poll_thread is not None and self._poll_thread.is_alive():
                return
            self._stop.clear()
            t = threading.Thread(target=self._poll_loop, daemon=True,
                                 name=f"ccr-poll[{self.host.node_name}]")
            self._poll_thread = t
        t.start()

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            poll_ms = int(knob("ES_TPU_CCR_POLL_MS"))
            if poll_ms <= 0:
                return
            self._stop.wait(poll_ms / 1000.0)
            if self._stop.is_set():
                return
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the loop must survive any
                pass           # transient; per-shard errors are recorded

    def stop(self) -> None:
        self._stop.set()
        t = self._poll_thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    # ---------------- stats ----------------

    def follower_stats(self, index: Optional[str] = None) -> dict:
        """GET /{index}/_ccr/stats shape: per-shard checkpoint, the
        leader gcp last seen, and the lag between them."""
        with self._lock:
            followers = [f for f in self._followers.values()
                         if index is None or f.index == index]
        if index is not None and not followers:
            raise IndexNotFoundError(f"[{index}] is not a follower index")
        out = []
        for f in followers:
            shards = []
            for sid in range(f.n_shards):
                cp = f.from_seq.get(sid, NO_OPS_PERFORMED)
                gcp = f.leader_gcp.get(sid, NO_OPS_PERFORMED)
                shards.append({"shard_id": sid,
                               "follower_checkpoint": cp,
                               "leader_global_checkpoint": gcp,
                               "lag_ops": max(0, gcp - cp)})
            entry = {"index": f.index,
                     "remote_cluster": f.remote_cluster,
                     "leader_index": f.leader_index,
                     "paused": f.paused, "shards": shards}
            if f.last_error:
                entry["last_error"] = f.last_error
            out.append(entry)
        return {"indices": out}

    def stats(self) -> dict:
        """`tpu_ccr` section of GET /_nodes/stats: shipping counters from
        the central registry + this node's follower states."""
        vals = metrics.counter_values()
        return {
            "ops_shipped": vals["ccr_ops_shipped"],
            "fetches": vals["ccr_fetches"],
            "fetch_retries": vals["ccr_fetch_retries"],
            "checksum_mismatches": vals["ccr_checksum_mismatches"],
            "polls": vals["ccr_polls"],
            "followers": self.follower_stats()["indices"],
        }

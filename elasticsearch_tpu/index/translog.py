"""Write-ahead log: checksummed op framing, generations, replay, trim.

Re-designs the reference translog (ref: index/translog/Translog.java,
TranslogWriter.java, Checkpoint.java): every index/delete op is appended as a
length-prefixed, CRC32-checksummed JSON record before it is acknowledged.
Generations roll over on flush; recovery replays ops above the last commit's
checkpoint. Fsync policy mirrors index.translog.durability request/async.

Record framing: [u32 length][u32 crc32 of payload][payload utf-8 json]

Fault ladder (PR 8): every fsync runs through the ``translog_fsync`` fault
site and surfaces failure as `TranslogFsyncError` — the caller must NOT ack
the op (the shard copy gets failed via the master instead of writing into a
broken WAL). The ``translog_corrupt`` site bit-rots the record being
appended (bad CRC), so the damage surfaces at replay, like the real thing.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Any, Dict, Iterator, List

from elasticsearch_tpu.common.durability import count as _count
from elasticsearch_tpu.common.durability import register_translog
from elasticsearch_tpu.common.errors import ElasticsearchTpuError
from elasticsearch_tpu.common.faults import corruption_fires, durability_fault_point
from elasticsearch_tpu.common.settings import knob

_HEADER = struct.Struct("<II")


class TranslogCorruptedError(Exception):
    pass


class TranslogFsyncError(ElasticsearchTpuError):
    """A translog fsync failed: the op is NOT durable and must not be acked
    (ref: the reference fails the engine on a tragic translog event —
    Engine.failEngine via TranslogException)."""

    status = 503
    error_type = "translog_fsync_exception"


class Translog:
    def __init__(self, directory: str, durability: str = "request"):
        self.dir = directory
        self.durability = durability
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._generation = self._latest_generation()
        self._file = open(self._gen_path(self._generation), "ab")
        self._ops_since_sync = 0  # guarded by: _lock
        register_translog(self)

    # ---- paths/generations ----

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.dir, f"translog-{gen}.tlog")

    def _latest_generation(self) -> int:
        gens = self.generations()
        return gens[-1] if gens else 1

    def generations(self) -> List[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("translog-") and name.endswith(".tlog"):
                out.append(int(name[len("translog-"):-len(".tlog")]))
        return sorted(out)

    @property
    def generation(self) -> int:
        return self._generation

    # ---- writes ----

    def add(self, op: Dict[str, Any]) -> None:
        payload = json.dumps(op, separators=(",", ":")).encode()
        crc = zlib.crc32(payload)
        if corruption_fires():
            # bit-rot the checksum, not the raise path: real corruption is
            # silent at write time and detected at replay
            crc ^= 0x5A5A5A5A
            _count("translog_corruptions")
        rec = _HEADER.pack(len(payload), crc) + payload
        with self._lock:
            self._file.write(rec)
            if self.durability == "request":
                self._sync_locked()
            else:
                self._ops_since_sync += 1
                # bound the async exposure window: at most N acked-but-
                # unsynced ops can be lost to a crash (ref: the reference's
                # async durability still syncs on the flush interval; an
                # unread counter bounds nothing)
                if self._ops_since_sync >= knob("ES_TPU_TRANSLOG_SYNC_OPS"):
                    self._sync_locked()

    def _sync_locked(self) -> None:  # tpulint: holds=_lock
        """Flush + fsync the active generation; resets the async window.

        On failure (injected via the ``translog_fsync`` site or organic
        EIO/ENOSPC) the record MAY still be in the file — the write preceded
        the failed sync — but the caller must treat the op as NOT durable:
        a write surviving unacked is safe, an acked write lost is not."""
        try:
            durability_fault_point("translog_fsync")
            self._file.flush()
            os.fsync(self._file.fileno())
        except OSError as e:
            _count("fsync_failures")
            raise TranslogFsyncError(f"translog fsync failed: {e}") from e
        self._ops_since_sync = 0
        _count("translog_syncs")

    def sync(self) -> None:
        with self._lock:
            self._sync_locked()

    @property
    def ops_since_sync(self) -> int:
        """Current async-durability exposure: ops appended since the last
        successful fsync (0 under request durability)."""
        return self._ops_since_sync

    def rollover(self) -> int:
        """Start a new generation (called at flush/commit time)."""
        with self._lock:
            self._sync_locked()
            self._file.close()
            self._generation += 1
            self._file = open(self._gen_path(self._generation), "ab")
        return self._generation

    def trim_below(self, generation: int) -> None:
        """Delete generations < `generation` (retention policy after commit)."""
        for gen in self.generations():
            if gen < generation:
                os.remove(self._gen_path(gen))

    def trim_above(self, seq_no: int) -> None:
        """Logically discard ops with seq_no > seq_no from replay — a trim
        marker record, honored in order during reads, so a resynced replica's
        divergent tail cannot be resurrected by crash recovery (ref:
        index/translog/Translog.java trimOperations, called when a replica
        rolls back to the global checkpoint on primary failover)."""
        self.add({"op": "trim", "above": seq_no})

    # ---- reads ----

    def read_ops(self, min_seq_no: int = -1) -> Iterator[Dict[str, Any]]:
        """Replay all ops with seq_no > min_seq_no across generations.

        Trim markers drop earlier-appended ops above their threshold, in log
        order. Replay streams (constant memory): a cheap first pass collects
        the trim markers' positions, the second pass yields ops, suppressing
        any op a later trim covers. A torn final record (crash mid-write) is
        tolerated and ends replay of that generation; a corrupt interior
        record raises.
        """
        with self._lock:
            self._file.flush()
        gens = self.generations()
        trims: List[tuple] = []  # (record_position, trim_above)
        pos = 0
        for gen in gens:
            for op in self._read_gen(gen, -2):
                if op.get("op") == "trim":
                    trims.append((pos, op["above"]))
                pos += 1
        pos = 0
        for gen in gens:
            for op in self._read_gen(gen, -2):
                i = pos
                pos += 1
                if op.get("op") == "trim":
                    continue
                seq = op.get("seq_no", -1)
                if seq <= min_seq_no:
                    continue
                if any(t_pos > i and seq > above for t_pos, above in trims):
                    continue
                yield op

    def _read_gen(self, gen: int, min_seq_no: int) -> Iterator[Dict[str, Any]]:
        path = self._gen_path(gen)
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            while True:
                header = f.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                length, crc = _HEADER.unpack(header)
                payload = f.read(length)
                if len(payload) < length:
                    break  # torn tail record
                if zlib.crc32(payload) != crc:
                    if f.tell() >= size:
                        break  # torn tail
                    raise TranslogCorruptedError(
                        f"translog corruption in generation {gen} at offset {f.tell()}"
                    )
                op = json.loads(payload)
                # trim markers always flow through: they affect replay even
                # when their own record carries no seq_no
                if op.get("op") == "trim" or op.get("seq_no", -1) > min_seq_no:
                    yield op

    def total_ops(self) -> int:
        return sum(1 for _ in self.read_ops())

    def close(self) -> None:
        with self._lock:
            self._file.flush()
            self._file.close()

"""Sequence-number machinery: local and global checkpoints.

Ports the reference's replication bookkeeping concepts
(ref: index/seqno/LocalCheckpointTracker.java — max contiguous processed
seqno; index/seqno/ReplicationTracker.java — global checkpoint = min local
checkpoint over the in-sync copy set, plus in-sync membership management).
The algebra is identical; only the implementation is Pythonic (sorted set of
pending seqnos above the checkpoint instead of bitset pages).
"""

from __future__ import annotations

import threading

NO_OPS_PERFORMED = -1
UNASSIGNED_SEQ_NO = -2


class LocalCheckpointTracker:
    def __init__(self, max_seq_no: int = NO_OPS_PERFORMED, local_checkpoint: int = NO_OPS_PERFORMED):
        self._lock = threading.Lock()
        self._next_seq_no = max_seq_no + 1
        self._checkpoint = local_checkpoint
        self._pending: set[int] = set()

    def generate_seq_no(self) -> int:
        with self._lock:
            seq = self._next_seq_no
            self._next_seq_no += 1
            return seq

    def mark_processed(self, seq_no: int) -> None:
        with self._lock:
            if seq_no <= self._checkpoint:
                return
            self._pending.add(seq_no)
            while self._checkpoint + 1 in self._pending:
                self._checkpoint += 1
                self._pending.remove(self._checkpoint)
            if seq_no >= self._next_seq_no:
                self._next_seq_no = seq_no + 1

    @property
    def checkpoint(self) -> int:
        return self._checkpoint

    @property
    def max_seq_no(self) -> int:
        return self._next_seq_no - 1

    def contains(self, seq_no: int) -> bool:
        with self._lock:
            return seq_no <= self._checkpoint or seq_no in self._pending

    def fast_forward(self, seq_no: int) -> None:
        """Mark every seqno <= seq_no processed in one step (the no-op gap
        fill the reference performs on primary promotion and at the end of
        ops-based recovery, where replayed history collapses superseded ops;
        ref: index/shard/IndexShard.java primary-promotion no-op fill)."""
        with self._lock:
            if seq_no > self._checkpoint:
                self._checkpoint = seq_no
                self._pending = {s for s in self._pending if s > seq_no}
                while self._checkpoint + 1 in self._pending:
                    self._checkpoint += 1
                    self._pending.remove(self._checkpoint)
            if seq_no >= self._next_seq_no:
                self._next_seq_no = seq_no + 1


class ReplicationTracker:
    """Primary-side global-checkpoint computation over in-sync copies.

    Ref: index/seqno/ReplicationTracker.java: global checkpoint advances to
    the min of local checkpoints of the in-sync set; copies join the set once
    caught up; stale copies are removed (master-driven in the reference).
    """

    def __init__(self, shard_allocation_id: str):
        self._lock = threading.Lock()
        self.allocation_id = shard_allocation_id
        self._local_checkpoints: dict[str, int] = {shard_allocation_id: NO_OPS_PERFORMED}
        self._in_sync: set[str] = {shard_allocation_id}
        self._global_checkpoint = NO_OPS_PERFORMED

    def update_local_checkpoint(self, allocation_id: str, checkpoint: int) -> None:
        with self._lock:
            prev = self._local_checkpoints.get(allocation_id, NO_OPS_PERFORMED)
            self._local_checkpoints[allocation_id] = max(prev, checkpoint)
            self._recompute()

    def add_tracking(self, allocation_id: str) -> None:
        with self._lock:
            self._local_checkpoints.setdefault(allocation_id, NO_OPS_PERFORMED)

    def mark_in_sync(self, allocation_id: str) -> None:
        with self._lock:
            self._local_checkpoints.setdefault(allocation_id, NO_OPS_PERFORMED)
            self._in_sync.add(allocation_id)
            self._recompute()

    def remove_tracking(self, allocation_id: str) -> None:
        with self._lock:
            self._local_checkpoints.pop(allocation_id, None)
            self._in_sync.discard(allocation_id)
            self._recompute()

    def _recompute(self) -> None:
        if self._in_sync:
            cp = min(self._local_checkpoints.get(a, NO_OPS_PERFORMED) for a in self._in_sync)
            # the global checkpoint never goes backwards
            self._global_checkpoint = max(self._global_checkpoint, cp) if cp != NO_OPS_PERFORMED else self._global_checkpoint

    @property
    def global_checkpoint(self) -> int:
        return self._global_checkpoint

    @property
    def in_sync_ids(self) -> set[str]:
        with self._lock:
            return set(self._in_sync)

    @property
    def tracked_ids(self) -> set[str]:
        """Every tracked copy, in-sync or still recovering — the superset a
        ghost-tracking cleanup must consult."""
        with self._lock:
            return set(self._local_checkpoints)

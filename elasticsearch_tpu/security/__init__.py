from elasticsearch_tpu.security.service import (  # noqa: F401
    AuthenticationError, AuthorizationError, SecurityService,
)

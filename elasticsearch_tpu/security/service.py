"""Security v1: authentication (basic + API key) and role-based
authorization as a REST action filter (VERDICT r4 item 9).

Re-designs the reference's security plugin core (ref:
x-pack/plugin/security/src/main/java/org/elasticsearch/xpack/security/
authc/AuthenticationService.java:71 realm-chain authentication,
authz/AuthorizationService.java:100 privilege resolution,
authz/store/ReservedRolesStore.java built-in roles) at this framework's
scale: a native realm (PBKDF2-hashed users), API keys, and roles with
cluster privileges + index privilege grants matched by wildcard pattern.
Every REST call passes the filter before its handler — authc failure is
401, authz failure 403 — and anonymous access exists ONLY when the
operator grants the anonymous user roles (off by default when security is
enabled, the reference's xpack.security.authc.anonymous.* contract).

Index-privilege checks happen at the ROUTE's target expression; the
NDJSON bodies of _bulk/_msearch are scanned for their per-item target
indices so a role scoped to `logs-*` cannot smuggle writes to another
index through a global bulk (the REST-layer approximation of the
reference's per-item action-level checks).
"""

from __future__ import annotations

import base64
import fnmatch
import hashlib
import hmac
import json
import os
import secrets
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuError, IllegalArgumentError,
)


class AuthenticationError(ElasticsearchTpuError):
    status = 401
    error_type = "security_exception"


class AuthorizationError(ElasticsearchTpuError):
    status = 403
    error_type = "security_exception"


# ---- privileges ----

CLUSTER_PRIVS = {"all", "monitor", "manage", "manage_security"}
INDEX_PRIVS = {"all", "read", "write", "create_index", "delete_index",
               "manage"}
# implication lattice (ref: IndexPrivilege/ClusterPrivilege resolution)
_CLUSTER_IMPLIES = {"all": {"monitor", "manage", "manage_security"},
                    "manage": {"monitor"}}
_INDEX_IMPLIES = {"all": {"read", "write", "create_index", "delete_index",
                          "manage"},
                  "manage": {"create_index", "delete_index"}}


def _implied(granted: Sequence[str], implies: dict) -> set:
    out = set(granted)
    for g in granted:
        out |= implies.get(g, set())
    return out


@dataclass
class Role:
    name: str
    cluster: List[str] = field(default_factory=list)
    indices: List[dict] = field(default_factory=list)  # {names, privileges}

    def grants_cluster(self, priv: str) -> bool:
        return priv in _implied(self.cluster, _CLUSTER_IMPLIES)

    def grants_index(self, priv: str, index: str) -> bool:
        for grant in self.indices:
            if priv not in _implied(grant.get("privileges", ()),
                                    _INDEX_IMPLIES):
                continue
            for pat in grant.get("names", ()):
                if fnmatch.fnmatchcase(index, pat):
                    return True
        return False


SUPERUSER = Role("superuser", cluster=["all"],
                 indices=[{"names": ["*"], "privileges": ["all"]}])
_BUILTIN_ROLES = {
    "superuser": SUPERUSER,
    "monitoring_user": Role("monitoring_user", cluster=["monitor"]),
}


@dataclass
class User:
    username: str
    pw_hash: bytes
    salt: bytes
    roles: List[str] = field(default_factory=list)
    enabled: bool = True


@dataclass
class Authentication:
    username: str
    roles: List[Role]
    auth_type: str = "realm"        # realm | api_key | anonymous


def _hash_pw(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode("utf-8"), salt,
                               10_000)


class SecurityService:
    """Realms + role store + the REST action filter."""

    def __init__(self, settings=None):
        raw = (lambda k, d=None: settings.raw(k, d)) if settings is not None \
            else (lambda k, d=None: d)
        self.enabled = str(raw("xpack.security.enabled", "false")
                           ).lower() == "true"
        self._lock = threading.Lock()
        self.users: Dict[str, User] = {}
        self.roles: Dict[str, Role] = dict(_BUILTIN_ROLES)
        self.api_keys: Dict[str, dict] = {}   # id -> {hash, salt, user, ...}
        anon = raw("xpack.security.authc.anonymous.roles")
        self.anonymous_roles = ([r.strip() for r in str(anon).split(",")]
                                if anon else None)
        bootstrap = str(raw("bootstrap.password", "changeme"))
        self._put_user_locked("elastic", bootstrap, ["superuser"])

    # ---------------- user / role / key management ----------------

    def _put_user_locked(self, name: str, password: str,
                         roles: List[str]) -> None:
        salt = os.urandom(16)
        self.users[name] = User(name, _hash_pw(password, salt), salt,
                                list(roles))

    def put_user(self, name: str, password: Optional[str],
                 roles: List[str]) -> None:
        with self._lock:
            if password is None:
                cur = self.users.get(name)
                if cur is None:
                    raise IllegalArgumentError(
                        f"password is required to create user [{name}]")
                cur.roles = list(roles)
                return
            self._put_user_locked(name, password, roles)

    def delete_user(self, name: str) -> bool:
        with self._lock:
            return self.users.pop(name, None) is not None

    def put_role(self, name: str, body: dict) -> None:
        cluster = list(body.get("cluster", ()))
        bad = set(cluster) - CLUSTER_PRIVS
        if bad:
            raise IllegalArgumentError(
                f"unknown cluster privileges {sorted(bad)}")
        indices = []
        for grant in body.get("indices", ()):
            privs = list(grant.get("privileges", ()))
            bad = set(privs) - INDEX_PRIVS
            if bad:
                raise IllegalArgumentError(
                    f"unknown index privileges {sorted(bad)}")
            indices.append({"names": list(grant.get("names", ())),
                            "privileges": privs})
        with self._lock:
            self.roles[name] = Role(name, cluster=cluster, indices=indices)

    def delete_role(self, name: str) -> bool:
        with self._lock:
            if name in _BUILTIN_ROLES:
                raise IllegalArgumentError(
                    f"role [{name}] is reserved")
            return self.roles.pop(name, None) is not None

    def create_api_key(self, for_user: str, name: str,
                       roles: Optional[List[str]] = None,
                       owned_roles: Optional[List[str]] = None) -> dict:
        key_id = secrets.token_hex(10)
        secret = secrets.token_urlsafe(24)
        salt = os.urandom(16)
        with self._lock:
            owner_roles = list(self.users[for_user].roles) \
                if for_user in self.users else []
            self.api_keys[key_id] = {
                "name": name, "hash": _hash_pw(secret, salt), "salt": salt,
                "username": for_user,
                "roles": list(roles) if roles is not None else owner_roles,
                "owned_roles": list(owned_roles or ()),
                "invalidated": False,
            }
        encoded = base64.b64encode(
            f"{key_id}:{secret}".encode("ascii")).decode("ascii")
        return {"id": key_id, "name": name, "api_key": secret,
                "encoded": encoded}

    def invalidate_api_key(self, key_id: str) -> bool:
        with self._lock:
            k = self.api_keys.get(key_id)
            if k is None:
                return False
            k["invalidated"] = True
            for rname in k.get("owned_roles", ()):
                self.roles.pop(rname, None)   # key-owned ad-hoc roles die
            return True

    # ---------------- authentication ----------------

    def authenticate(self, headers: Dict[str, str]) -> Authentication:
        auth = headers.get("authorization")
        if auth:
            scheme, _, payload = auth.partition(" ")
            scheme = scheme.lower()
            if scheme == "basic":
                return self._authc_basic(payload.strip())
            if scheme == "apikey":
                return self._authc_api_key(payload.strip())
            raise AuthenticationError(
                f"unsupported authorization scheme [{scheme}]")
        if self.anonymous_roles is not None:
            return Authentication("_anonymous",
                                  self._resolve_roles(self.anonymous_roles),
                                  "anonymous")
        raise AuthenticationError(
            "missing authentication credentials for REST request")

    def _authc_basic(self, payload: str) -> Authentication:
        try:
            user, _, password = base64.b64decode(payload).decode(
                "utf-8").partition(":")
        except Exception:
            raise AuthenticationError("invalid basic authentication header")
        u = self.users.get(user)
        if (u is None or not u.enabled
                or not hmac.compare_digest(u.pw_hash,
                                           _hash_pw(password, u.salt))):
            raise AuthenticationError(
                f"unable to authenticate user [{user}]")
        return Authentication(user, self._resolve_roles(u.roles))

    def _authc_api_key(self, payload: str) -> Authentication:
        try:
            key_id, _, secret = base64.b64decode(payload).decode(
                "utf-8").partition(":")
        except Exception:
            raise AuthenticationError("invalid ApiKey header")
        k = self.api_keys.get(key_id)
        if (k is None or k["invalidated"]
                or not hmac.compare_digest(k["hash"],
                                           _hash_pw(secret, k["salt"]))):
            raise AuthenticationError("unable to authenticate api key")
        return Authentication(k["username"],
                              self._resolve_roles(k["roles"]), "api_key")

    def _resolve_roles(self, names: Sequence[str]) -> List[Role]:
        return [self.roles[n] for n in names if n in self.roles]

    # ---------------- authorization ----------------

    def authorize_cluster(self, authn: Authentication, priv: str) -> None:
        if any(r.grants_cluster(priv) for r in authn.roles):
            return
        raise AuthorizationError(
            f"action [cluster:{priv}] is unauthorized for user "
            f"[{authn.username}]")

    def authorize_index(self, authn: Authentication, priv: str,
                        indices: Sequence[str]) -> None:
        for index in indices:
            if not any(r.grants_index(priv, index) for r in authn.roles):
                raise AuthorizationError(
                    f"action [indices:{priv}] is unauthorized for user "
                    f"[{authn.username}] on indices [{index}]")

    # ---------------- the REST action filter ----------------

    def rest_filter(self, req, parts: List[str]) -> None:
        authn = self.authenticate(req.headers)
        req.params["_authn_user"] = authn.username
        kind, priv, indices = _classify(req, parts)
        if kind == "cluster":
            self.authorize_cluster(authn, priv)
        elif kind == "index":
            self.authorize_index(authn, priv, indices)
        elif kind == "multi":
            # compound actions (_reindex): every (privilege, indices)
            # check must pass
            for p, idxs in priv:
                self.authorize_index(authn, p, idxs)
        # kind == "open": _authenticate etc — authn only


_READ_ENDPOINTS = {"_search", "_msearch", "_count", "_mget", "_doc",
                   "_source", "_explain", "_termvectors", "_field_caps",
                   "_validate", "_search_shards", "_analyze", "_pit",
                   "_knn_search", "_rank_eval"}
_WRITE_ENDPOINTS = {"_bulk", "_update", "_update_by_query",
                    "_delete_by_query", "_create"}
# _reindex and _aliases are NOT here: both name data indices in their
# bodies and classify as index actions below (a cluster-manage role must
# not read arbitrary indices through reindex, nor repoint aliases on
# indices it cannot manage). _scripts stays cluster-scoped on purpose —
# stored scripts are cluster metadata (ref: cluster:admin/script/put);
# data access only happens when a script runs inside a search, which is
# authorized as that search.
_CLUSTER_PREFIXES = {"_cluster", "_nodes", "_cat", "_tasks", "_snapshot",
                     "_scripts", "_ingest", "_template", "_index_template",
                     "_component_template", "_alias", "_stats",
                     "_async_search", "_render", "_scroll",
                     "_search_scroll", "_mapping", "_resolve"}


def _ndjson_indices(raw: bytes, default: Optional[str],
                    meta_key: str) -> List[str]:
    out = set()
    if default:
        out.add(default)
    lines = [ln for ln in raw.split(b"\n") if ln.strip()]
    if meta_key == "bulk":
        for line in lines:
            try:
                obj = json.loads(line)
            except Exception:
                continue
            if isinstance(obj, dict):
                for action in ("index", "create", "update", "delete"):
                    spec = obj.get(action)
                    if isinstance(spec, dict) and spec.get("_index"):
                        out.add(str(spec["_index"]))
    else:
        # msearch: even lines are HEADERS; one without an explicit index
        # targets the path default or, absent that, every index — it must
        # demand "*" so a scoped role cannot widen through an empty header
        for i in range(0, len(lines), 2):
            try:
                obj = json.loads(lines[i])
            except Exception:
                continue
            if not isinstance(obj, dict):
                continue
            v = obj.get("index")
            if v:
                out.update(v if isinstance(v, list) else [v])
            elif default is None:
                out.add("*")
    return sorted(out)


def _classify(req, parts: List[str]):
    """(kind, privilege, indices) for a REST call — the route->privilege
    map (ref: the reference's action-name driven authorization; REST paths
    map 1:1 onto action families here)."""
    if not parts:
        return "cluster", "monitor", None
    head = parts[0]
    if head == "_security":
        if parts[1:2] == ["_authenticate"]:
            return "open", None, None
        return "cluster", "manage_security", None
    if head == "_bulk":
        return "index", "write", _ndjson_indices(req.raw_body, None, "bulk")
    if head == "_msearch":
        return "index", "read", _ndjson_indices(req.raw_body, None, "ms") \
            or ["*"]
    if head == "_mget":
        body = req.body if isinstance(req.body, dict) else {}
        targets = {str(d["_index"]) for d in (body.get("docs") or [])
                   if isinstance(d, dict) and d.get("_index")}
        return "index", "read", sorted(targets) or ["*"]
    if head == "_reindex":
        # an INDEX action on both ends — read the source, write the dest
        # (ref: TransportReindexAction resolves per-index privileges); a
        # body that names no index demands the privilege on "*" so a
        # scoped role cannot widen through a malformed request
        body = req.body if isinstance(req.body, dict) else {}
        src = (body.get("source") or {}).get("index") \
            if isinstance(body.get("source"), dict) else None
        dst = (body.get("dest") or {}).get("index") \
            if isinstance(body.get("dest"), dict) else None
        src_list = sorted({str(s) for s in
                           (src if isinstance(src, list) else [src]) if s}) \
            or ["*"]
        dst_list = [str(dst)] if dst else ["*"]
        return "multi", [("read", src_list), ("write", dst_list)], None
    if head == "_aliases":
        # alias actions name their indices in the body: index `manage` on
        # each target (ref: TransportIndicesAliasesAction)
        body = req.body if isinstance(req.body, dict) else {}
        targets = set()
        for action in (body.get("actions") or []):
            if not isinstance(action, dict):
                continue
            for spec in action.values():
                if not isinstance(spec, dict):
                    continue
                v = spec.get("index") or spec.get("indices")
                if v:
                    targets.update(str(i) for i in
                                   (v if isinstance(v, list) else [v]))
        return "index", "manage", sorted(targets) or ["*"]
    if head.startswith("_") and head != "_all":
        if head in _CLUSTER_PREFIXES or head not in _READ_ENDPOINTS:
            return ("cluster",
                    "monitor" if req.method in ("GET", "HEAD") else "manage",
                    None)
        return "index", "read", ["*"]

    # "_all" is an index expression, not a cluster endpoint: it demands
    # the privilege on "*"
    indices = ["*"] if head == "_all" else \
        [n.strip() for n in head.split(",") if n.strip()]
    sub = parts[1] if len(parts) > 1 else None
    if sub is None:
        if req.method in ("GET", "HEAD"):
            return "index", "read", indices
        if req.method == "PUT":
            return "index", "create_index", indices
        if req.method == "DELETE":
            return "index", "delete_index", indices
        return "index", "manage", indices
    if sub == "_bulk":
        return "index", "write", _ndjson_indices(req.raw_body, head, "bulk")
    if sub in ("_msearch",):
        return "index", "read", _ndjson_indices(req.raw_body, head, "ms")
    if sub == "_mget":
        # per-doc "_index" overrides join the authorized set (the handler
        # honors them)
        extra = set(indices)
        body = req.body if isinstance(req.body, dict) else {}
        for d in (body.get("docs") or []):
            if isinstance(d, dict) and d.get("_index"):
                extra.add(str(d["_index"]))
        return "index", "read", sorted(extra)
    if sub in ("_doc", "_create", "_update"):
        return ("index",
                "read" if req.method in ("GET", "HEAD") else "write",
                indices)
    if sub in _READ_ENDPOINTS:
        return "index", "read", indices
    if sub in _WRITE_ENDPOINTS or sub == "_delete_by_query":
        return "index", "write", indices
    if sub in ("_rollover", "_shrink", "_split", "_clone"):
        return "index", "manage", indices
    # _settings/_mapping/_close/_open/_refresh/_flush/_forcemerge/_cache...
    return ("index",
            "read" if req.method in ("GET", "HEAD") else "manage",
            indices)

"""MapperService: index schema registry + JSON document parsing.

Re-designs the reference's MapperService/DocumentParser pair
(ref: index/mapper/MapperService.java:54, DocumentParser.java:35): holds the
per-index mapping, parses JSON docs into the flat representation the segment
builder consumes, performs dynamic mapping for unseen fields, and merges
mapping updates (new fields only; type changes are conflicts, as in the
reference's strict merge).

Dot-notation flattening handles object fields; arrays index every element
into the same field (reference array semantics).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

import numpy as np

from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.common.errors import IllegalArgumentError, MapperParsingError
from elasticsearch_tpu.mapper.field_types import (
    DateFieldType,
    FieldType,
    build_field_type,
    parse_date_millis,
)


@dataclass
class LuceneDoc:
    """The indexable form of one document (analog of the reference's
    ParseContext.Document): what the segment builder consumes."""

    doc_id: str
    source: dict
    # field -> [(term, positions)], for inverted ("text") fields
    inverted: Dict[str, List[Tuple[str, List[int]]]] = field(default_factory=dict)
    # field -> list of float values (numeric family columns; multivalued)
    numeric: Dict[str, List[float]] = field(default_factory=dict)
    # field -> list of str values (keyword family; ordinal columns)
    keyword: Dict[str, List[str]] = field(default_factory=dict)
    # field -> np.ndarray (dense vectors)
    vectors: Dict[str, np.ndarray] = field(default_factory=dict)
    # total token count per text field (field length norm for BM25)
    field_lengths: Dict[str, int] = field(default_factory=dict)
    # field -> [(lat, lon)] pairs (geo_point columns keep pairing intact)
    geo: Dict[str, List[Tuple[float, float]]] = field(default_factory=dict)
    # nested field -> list of CHILD docs (each a LuceneDoc over the child
    # object, fields under their full dotted names)
    nested: Dict[str, List["LuceneDoc"]] = field(default_factory=dict)
    # next free position per text field (internal; positions-gap bookkeeping)
    _pos_ceiling: Dict[str, int] = field(default_factory=dict)


# type used for ParsedDocument in external signatures; kept as alias
ParsedDocument = LuceneDoc


_DEFAULT_DATE_PATTERNS = ("date_optional_time",)


class MapperService:
    SINGLE_MAPPING_NAME = "_doc"

    def __init__(self, mappings: dict | None = None, analysis_registry: AnalysisRegistry | None = None,
                 dynamic: bool = True):
        self._lock = threading.Lock()
        self._field_types: Dict[str, FieldType] = {}
        self._analyzers = analysis_registry or AnalysisRegistry()
        self.dynamic = dynamic
        if mappings:
            self.merge(mappings)

    # ---- schema ----

    def merge(self, mappings: dict) -> None:
        """Merge a mapping definition {"properties": {...}}; conflicting type
        changes raise, new fields are added (ref: MapperService.merge)."""
        props = mappings.get("properties")
        if props is None:
            # a bare field map; meta sections (_source, dynamic, ...) are
            # index options, not fields — but anything shaped like a field
            # definition (a dict with type/properties) IS a field, whatever
            # its name
            props = {k: v for k, v in mappings.items()
                     if isinstance(v, dict)
                     and ("type" in v or "properties" in v)
                     and not k.startswith("_")}
        props = props or {}
        with self._lock:
            self._merge_props("", props)

    def _merge_props(self, prefix: str, props: dict) -> None:
        for name, definition in props.items():
            full = f"{prefix}{name}"
            if not isinstance(definition, dict):
                raise MapperParsingError(f"Expected map for property [{full}]")
            if "properties" in definition and "type" not in definition:
                self._merge_props(f"{full}.", definition["properties"])
                continue
            if definition.get("type") == "object":
                self._merge_props(f"{full}.", definition.get("properties", {}))
                continue
            if definition.get("type") == "nested":
                self._field_types[full] = build_field_type(full, definition)
                # child sub-fields register under their dotted names; the
                # nested root intercepts parsing so they only index into
                # the child table, never the parent
                self._merge_props(f"{full}.", definition.get("properties", {}))
                continue
            new_type = build_field_type(full, definition)
            existing = self._field_types.get(full)
            if existing is not None:
                if existing.params.get("type") != definition.get("type"):
                    raise IllegalArgumentError(
                        f"mapper [{full}] cannot be changed from type "
                        f"[{existing.params.get('type')}] to [{definition.get('type')}]"
                    )
                continue
            for sub_name, sub_def in (definition.get("fields") or {}).items():
                sub = build_field_type(f"{full}.{sub_name}", sub_def)
                new_type.multi_fields.append(sub)
                self._field_types[f"{full}.{sub_name}"] = sub
            self._field_types[full] = new_type

    def field_type(self, name: str) -> FieldType | None:
        return self._field_types.get(name)

    def join_field(self) -> FieldType | None:
        """The index's single join field, if mapped (the reference allows
        at most one, ParentJoinFieldMapper.java)."""
        for ft in self._field_types.values():
            if ft.family == "join":
                return ft
        return None

    def field_names(self) -> List[str]:
        return sorted(self._field_types)

    def mapping(self) -> dict:
        """Render back as nested {"properties": ...} JSON."""
        root: dict = {}
        for name in sorted(self._field_types):
            parts = name.split(".")
            node = root
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node[parts[-1]] = self._field_types[name].mapping()
        return {"properties": root}

    def analyzer_for(self, ft: FieldType):
        name = ft.params.get("analyzer", "standard")
        return self._analyzers.get(name)

    # ---- document parsing ----

    def parse(self, doc_id: str, source: dict) -> LuceneDoc:
        doc = LuceneDoc(doc_id=doc_id, source=source)
        dynamic_updates: Dict[str, FieldType] = {}
        self._parse_obj("", source, doc, dynamic_updates)
        if dynamic_updates:
            with self._lock:
                for name, ft in dynamic_updates.items():
                    self._field_types.setdefault(name, ft)
        return doc

    def _parse_obj(self, prefix: str, obj: dict, doc: LuceneDoc, dyn: Dict[str, FieldType]) -> None:
        for key, value in obj.items():
            full = f"{prefix}{key}"
            known = self._field_types.get(full)
            if known is not None and known.family == "nested":
                objs = value if isinstance(value, list) else [value]
                children = doc.nested.setdefault(full, [])
                for child_obj in objs:
                    if not isinstance(child_obj, dict):
                        raise MapperParsingError(
                            f"object mapping for [{full}] tried to parse "
                            "a non-object value as nested")
                    child = LuceneDoc(doc_id=f"{doc.doc_id}#{full}#{len(children)}",
                                      source=child_obj)
                    self._parse_obj(f"{full}.", child_obj, child, dyn)
                    children.append(child)
                continue
            if known is not None and known.family == "completion":
                # {"input": [...], "weight": n} shapes are suggester data
                # read from _source (search/suggest.py), not sub-objects
                continue
            if known is not None and known.family == "join":
                name, parent = known.parse_join_value(value)
                doc.keyword.setdefault(full, []).append(name)
                if parent is not None:
                    doc.keyword.setdefault(f"{full}.__parent",
                                           []).append(parent)
                continue
            if known is not None and known.family == "percolator":
                # stored query: extract candidate-prefilter terms into the
                # hidden keyword sidecar (ref: PercolatorFieldMapper
                # processQuery -> extraction fields)
                from elasticsearch_tpu.search.percolate import (
                    query_index_tokens,
                )

                if not isinstance(value, dict):
                    raise MapperParsingError(
                        f"percolator field [{full}] must hold a query object")
                # an empty token list (match_none) means never-candidate
                toks = query_index_tokens(self, value)
                if toks:
                    doc.keyword.setdefault(f"{full}.__terms", []).extend(toks)
                continue
            if isinstance(value, dict) and not (
                    known is not None and known.family == "geo"):
                self._parse_obj(f"{full}.", value, doc, dyn)
                continue
            if known is not None and known.family == "vector":
                self._index_values(known, [value], doc)  # whole array is one value
                continue
            if known is not None and known.family == "geo":
                # [lon, lat] is ONE point; a list of dicts/strings/pairs is
                # multi-valued
                if isinstance(value, list) and value and \
                        isinstance(value[0], (dict, str, list, tuple)):
                    self._index_values(known, list(value), doc)
                else:
                    self._index_values(known, [value], doc)
                continue
            values = value if isinstance(value, list) else [value]
            # nested objects inside arrays are flattened (reference object-array semantics)
            if values and isinstance(values[0], dict):
                for v in values:
                    if isinstance(v, dict):
                        self._parse_obj(f"{full}.", v, doc, dyn)
                continue
            ft = self._field_types.get(full)
            if ft is None:
                ft = self._dynamic_field_type(full, values, dyn)
                if ft is None:
                    continue
            self._index_values(ft, values, doc)

    def _index_values(self, ft: FieldType, values: list, doc: LuceneDoc) -> None:
        for mf in ft.multi_fields:
            self._index_values(mf, values, doc)
        for v in values:
            if v is None:
                continue
            if ft.family == "inverted":
                analyzer = self.analyzer_for(ft)
                terms = ft.index_terms(v, analyzer)
                # position offset so multi-valued text keeps phrase semantics
                # separate across values (reference position_increment_gap=100)
                base = doc._pos_ceiling.get(ft.name, 0)
                if base:
                    base += 100
                shifted = [(t, [p + base for p in ps]) for t, ps in terms]
                bucket = doc.inverted.setdefault(ft.name, [])
                bucket.extend(shifted)
                n_tokens = sum(len(ps) for _, ps in terms)
                max_pos = max((p for _, ps in shifted for p in ps), default=base - 1)
                doc._pos_ceiling[ft.name] = max_pos + 1
                doc.field_lengths[ft.name] = doc.field_lengths.get(ft.name, 0) + n_tokens
            elif ft.family == "numeric":
                doc.numeric.setdefault(ft.name, []).append(ft.doc_value(v))
            elif ft.family == "keyword":
                dv = ft.doc_value(v)
                if dv is not None:
                    doc.keyword.setdefault(ft.name, []).append(dv)
            elif ft.family == "vector":
                doc.vectors[ft.name] = ft.doc_value(v)
            elif ft.family == "geo":
                doc.geo.setdefault(ft.name, []).append(ft.doc_value(v))

    def _dynamic_field_type(self, name: str, values: list, dyn: Dict[str, FieldType]) -> FieldType | None:
        """Dynamic mapping rules (ref: DocumentParser dynamic templates default):
        bool->boolean, int->long, float->double (reference maps to float),
        date-parseable string->date, other string->text with .keyword subfield."""
        if not self.dynamic:
            return None
        sample = next((v for v in values if v is not None), None)
        if sample is None:
            return None
        if isinstance(sample, bool):
            params = {"type": "boolean"}
        elif isinstance(sample, int):
            params = {"type": "long"}
        elif isinstance(sample, float):
            params = {"type": "float"}
        elif isinstance(sample, str):
            if _looks_like_date(sample):
                params = {"type": "date"}
            else:
                params = {"type": "text"}
        else:
            return None
        ft = build_field_type(name, params)
        if params["type"] == "text":
            kw = build_field_type(f"{name}.keyword", {"type": "keyword", "ignore_above": 256})
            ft.multi_fields.append(kw)
            dyn[f"{name}.keyword"] = kw
            self._field_types.setdefault(f"{name}.keyword", kw)
        dyn[name] = ft
        self._field_types.setdefault(name, ft)
        return ft


def _looks_like_date(s: str) -> bool:
    if len(s) < 8 or not s[:4].isdigit():
        return False
    try:
        parse_date_millis(s)
        return True
    except MapperParsingError:
        return False

"""Field types: how JSON values become indexable/columnar data.

Re-designs the reference's MappedFieldType + *FieldMapper pairs
(ref: index/mapper/TextFieldMapper.java, NumberFieldMapper.java,
DateFieldMapper.java, KeywordFieldMapper.java, BooleanFieldMapper.java and
x-pack vectors DenseVectorFieldMapper.java:44) into one class per family.

Each field type knows how to:
  * parse a JSON value into index terms (inverted) and/or a doc value (columnar)
  * normalize query-time values the same way (term/range queries must agree
    with index-time encoding)

Columnar encoding choices are TPU-first: every doc value becomes either an
f64/i64 cell in a dense column, an ordinal into a per-segment sorted term
dictionary (keyword), or a row of a dense [n_docs, dims] matrix (dense_vector).
"""

from __future__ import annotations

import datetime as _dt
import ipaddress
import math
from typing import Any, List, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import IllegalArgumentError, MapperParsingError


class FieldType:
    """Base field type. `family` drives segment storage layout."""

    family = "none"  # inverted | numeric | keyword | vector
    searchable = True
    has_doc_values = True

    def __init__(self, name: str, params: dict):
        self.name = name
        self.params = params
        # sub-fields indexed from the same JSON value (mapping "fields": {...})
        self.multi_fields: list["FieldType"] = []

    # inverted-index terms for one JSON value: list of (term, [positions])
    def index_terms(self, value: Any, analyzer=None) -> List[Tuple[str, List[int]]]:
        return []

    # columnar value (float for numeric family, str for keyword family)
    def doc_value(self, value: Any) -> Any:
        return None

    def mapping(self) -> dict:
        out = {"type": self.params.get("type", "object")}
        for k, v in self.params.items():
            if k not in ("type", "fields"):
                out[k] = v
        if self.multi_fields:
            out["fields"] = {
                mf.name.rsplit(".", 1)[1]: mf.mapping() for mf in self.multi_fields
            }
        return out


class TextFieldType(FieldType):
    """Full-text: analyzed into positioned terms; no doc values (ref:
    TextFieldMapper — fielddata off by default)."""

    family = "inverted"
    has_doc_values = False

    def index_terms(self, value, analyzer=None):
        tokens = analyzer.tokenize(str(value))
        by_term: dict[str, list[int]] = {}
        for t in tokens:
            by_term.setdefault(t.term, []).append(t.position)
        return list(by_term.items())


class KeywordFieldType(FieldType):
    """Exact-match string; indexed untokenized + ordinal doc values."""

    family = "keyword"

    def __init__(self, name: str, params: dict):
        super().__init__(name, params)
        self.ignore_above = params.get("ignore_above", 2147483647)

    def _normalize(self, value: Any) -> str | None:
        s = value if isinstance(value, str) else _json_str(value)
        if len(s) > self.ignore_above:
            return None
        return s

    def index_terms(self, value, analyzer=None):
        s = self._normalize(value)
        return [] if s is None else [(s, [0])]

    def doc_value(self, value):
        return self._normalize(value)


_INT_TYPES = {"long": (-(2**63), 2**63 - 1), "integer": (-(2**31), 2**31 - 1),
              "short": (-(2**15), 2**15 - 1), "byte": (-(2**7), 2**7 - 1)}
_FLOAT_TYPES = {"double", "float", "half_float"}


class NumberFieldType(FieldType):
    """Numeric family; stored as an f64 column (exact for all int53 and the
    reference's float types at query precision)."""

    family = "numeric"

    def __init__(self, name: str, params: dict):
        super().__init__(name, params)
        self.number_type = params["type"]

    def parse(self, value: Any) -> float:
        if isinstance(value, bool):
            raise MapperParsingError(f"failed to parse field [{self.name}] of type [{self.number_type}]")
        try:
            f = float(value)
        except (TypeError, ValueError):
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type [{self.number_type}]: value [{value}]"
            )
        if self.number_type in _INT_TYPES:
            if not float(f).is_integer():
                # the reference rejects fractional values for integer types unless coerce
                if self.params.get("coerce", True):
                    f = float(int(f))
                else:
                    raise MapperParsingError(f"failed to parse field [{self.name}]: [{value}] has a decimal part")
            lo, hi = _INT_TYPES[self.number_type]
            if not (lo <= f <= hi):
                raise MapperParsingError(f"Value [{value}] out of range for field [{self.name}]")
        return f

    def index_terms(self, value, analyzer=None):
        return []  # numeric search runs against the column, not the inverted index

    def doc_value(self, value):
        return self.parse(value)


class DateFieldType(FieldType):
    """Dates stored as epoch-millis i64 column (ref: DateFieldMapper)."""

    family = "numeric"

    def parse(self, value: Any) -> float:
        return float(parse_date_millis(value))

    def doc_value(self, value):
        return self.parse(value)


class BooleanFieldType(FieldType):
    family = "numeric"

    def parse(self, value: Any) -> float:
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        if value in ("true", "True"):
            return 1.0
        if value in ("false", "False", ""):
            return 0.0
        raise MapperParsingError(f"failed to parse boolean field [{self.name}], value [{value}]")

    def doc_value(self, value):
        return self.parse(value)


class IpFieldType(FieldType):
    """IPs normalized to integer form in an f64 column (v4; v6 stored as
    ordinal keyword fallback)."""

    family = "keyword"

    def _normalize(self, value: Any) -> str:
        try:
            return str(ipaddress.ip_address(str(value)))
        except ValueError:
            raise MapperParsingError(f"failed to parse IP [{value}] for field [{self.name}]")

    def index_terms(self, value, analyzer=None):
        return [(self._normalize(value), [0])]

    def doc_value(self, value):
        return self._normalize(value)


class DenseVectorFieldType(FieldType):
    """Dense float vectors as rows of a per-segment [n_docs, dims] matrix.

    Ref: x-pack vectors DenseVectorFieldMapper.java:56-64 (max 2048 dims,
    binary doc values). TPU-first re-design: the whole segment's vectors are
    one HBM-resident matrix so kNN is a single batched matmul on the MXU.
    """

    family = "vector"
    searchable = False

    def __init__(self, name: str, params: dict):
        super().__init__(name, params)
        self.dims = int(params.get("dims", 0))
        if not (0 < self.dims <= 4096):
            raise MapperParsingError(f"[dims] must be in [1, 4096] for field [{self.name}]")
        self.similarity = params.get("similarity", "cosine")

    def doc_value(self, value):
        arr = np.asarray(value, dtype=np.float32)
        if arr.shape != (self.dims,):
            raise MapperParsingError(
                f"The [dims] of field [{self.name}] is [{self.dims}], "
                f"but the provided vector has [{arr.shape}]"
            )
        if not np.all(np.isfinite(arr)):
            raise MapperParsingError(f"Vector for field [{self.name}] contains non-finite values")
        return arr


class NestedFieldType(FieldType):
    """Nested object arrays (ref: index/mapper/NestedObjectMapper and
    Lucene's block join). TPU-first re-design: instead of interleaving
    hidden child documents into the parent doc-id space (Lucene's layout),
    each nested field owns a columnar CHILD TABLE sidecar in the segment —
    its own postings/columns over child rows plus a child->parent map — so
    the nested query is a child-table scoring pass + one CSR reduce back to
    parents, with parent doc ids, seqnos and live masks untouched."""

    family = "nested"


class GeoPointFieldType(FieldType):
    """lat/lon pairs as TWO dense numeric columns ({field}.lat/{field}.lon —
    ref: GeoPointFieldMapper; the reference packs into a BKD tree, here
    distance/box predicates are vectorized column math over the pair, which
    is the columnar play for spatial filtering on dense hardware)."""

    family = "geo"

    def parse(self, value: Any) -> tuple:
        from elasticsearch_tpu.search.queries import parse_geo_point

        try:
            return parse_geo_point(value)
        except Exception:
            raise MapperParsingError(
                f"failed to parse geo_point [{value}] for [{self.name}]")

    def doc_value(self, value):
        return self.parse(value)


_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)


def parse_date_millis(value: Any) -> int:
    """epoch_millis int | ISO8601 | yyyy-MM-dd — the reference's
    strict_date_optional_time||epoch_millis default format."""
    if isinstance(value, bool):
        raise MapperParsingError(f"failed to parse date value [{value}]")
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip()
    if s.isdigit() or (s.startswith("-") and s[1:].isdigit()):
        return int(s)
    try:
        if s.endswith("Z"):
            s = s[:-1] + "+00:00"
        dt = _dt.datetime.fromisoformat(s)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=_dt.timezone.utc)
        return int(dt.timestamp() * 1000)
    except ValueError:
        raise MapperParsingError(f"failed to parse date value [{value}]")


def _json_str(value: Any) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, float) and value.is_integer() and abs(value) < 2**53:
        return str(int(value))
    return str(value)


class JoinFieldType(FieldType):
    """Parent-join relations (ref: modules/parent-join/
    ParentJoinFieldMapper.java). The field's own keyword value is the
    relation NAME (term-searchable, like the reference); a child doc's
    parent id lands in the hidden `<name>.__parent` keyword sidecar.
    Parent and child must share a shard (routing by parent id), exactly
    the reference's constraint."""

    family = "join"

    def __init__(self, name: str, params: dict):
        super().__init__(name, params)
        rels = params.get("relations", {}) or {}
        self.relations = rels
        self.parent_of: dict[str, str] = {}
        for p, cs in rels.items():
            for c in ([cs] if isinstance(cs, str) else cs):
                self.parent_of[c] = p

    def parse_join_value(self, value):
        """(relation_name, parent_id|None), validated."""
        if isinstance(value, str):
            name, parent = value, None
        elif isinstance(value, dict):
            name = value.get("name")
            parent = value.get("parent")
        else:
            raise MapperParsingError(
                f"join field [{self.name}] expects a name or object")
        known = set(self.relations) | set(self.parent_of)
        if name not in known:
            raise MapperParsingError(
                f"unknown join name [{name}] for field [{self.name}]")
        if name in self.parent_of and parent is None:
            raise MapperParsingError(
                f"[parent] is missing for join field [{self.name}]")
        return name, (None if parent is None else str(parent))

    def index_terms(self, value, analyzer=None):
        return []


class PercolatorFieldType(FieldType):
    """Stored-query field (ref: modules/percolator/
    PercolatorFieldMapper.java). The query JSON stays in _source; index
    time extracts its terms into a hidden `<name>.__terms` keyword sidecar
    for candidate prefiltering (search/percolate.py)."""

    family = "percolator"

    def index_terms(self, value, analyzer=None):
        return []


class CompletionFieldType(FieldType):
    """Completion-suggester input field (ref: CompletionFieldMapper.java).
    The suggester builds its per-segment sorted prefix arrays from stored
    _source values (search/suggest.py); no postings are indexed."""

    family = "completion"

    def index_terms(self, value, analyzer=None):
        return []


_TYPES = {
    "text": TextFieldType,
    "keyword": KeywordFieldType,
    "completion": CompletionFieldType,
    "percolator": PercolatorFieldType,
    "join": JoinFieldType,
    "date": DateFieldType,
    "date_nanos": DateFieldType,
    "boolean": BooleanFieldType,
    "ip": IpFieldType,
    "dense_vector": DenseVectorFieldType,
    "geo_point": GeoPointFieldType,
    "nested": NestedFieldType,
}


def build_field_type(name: str, params: dict) -> FieldType:
    t = params.get("type")
    if t in _TYPES:
        return _TYPES[t](name, params)
    if t in _INT_TYPES or t in _FLOAT_TYPES:
        return NumberFieldType(name, params)
    raise MapperParsingError(f"No handler for type [{t}] declared on field [{name}]")

from elasticsearch_tpu.mapper.field_types import (
    FieldType,
    TextFieldType,
    KeywordFieldType,
    NumberFieldType,
    DateFieldType,
    BooleanFieldType,
    DenseVectorFieldType,
    build_field_type,
)
from elasticsearch_tpu.mapper.mapper_service import MapperService, ParsedDocument, LuceneDoc

__all__ = [
    "FieldType",
    "TextFieldType",
    "KeywordFieldType",
    "NumberFieldType",
    "DateFieldType",
    "BooleanFieldType",
    "DenseVectorFieldType",
    "build_field_type",
    "MapperService",
    "ParsedDocument",
    "LuceneDoc",
]

"""Plugin SPI: load extension modules into a node.

Re-designs the reference's plugin architecture (ref: plugins/Plugin.java,
plugins/PluginsService.java — classpath jars implementing extension
points) as importable Python modules: `plugins: ["pkg.module", ...]` in
node settings (or ES_TPU_PLUGINS env, comma-separated) names modules
exposing `install(node)`. Extension points are the live registries the
node already exposes:

    node.ingest (PROCESSORS registry)       — ingest processors
    analysis.AnalysisRegistry._BUILTIN      — analyzers
    rest controller via install(node, rc)   — REST handlers (optional 2-arg)
    search.queries parse table              — query types (module-level)

A plugin that raises at install time fails node startup loudly (the
reference's policy: a broken plugin must not half-load).
"""

from __future__ import annotations

import importlib
from typing import List

from elasticsearch_tpu.common.errors import ElasticsearchTpuError
from elasticsearch_tpu.common.settings import knob


class PluginError(ElasticsearchTpuError):
    status = 500
    error_type = "plugin_exception"


def plugin_modules(settings) -> List[str]:
    names = []
    raw = settings.raw("plugins") if settings is not None else None
    if isinstance(raw, str):
        names.extend(p for p in raw.split(",") if p)
    elif isinstance(raw, (list, tuple)):
        names.extend(raw)
    env = knob("ES_TPU_PLUGINS")
    names.extend(p for p in env.split(",") if p)
    return names


def load_plugins(node, rest_controller=None) -> List[str]:
    """Import + install every configured plugin; returns their names."""
    loaded = []
    for name in plugin_modules(getattr(node, "settings", None)):
        try:
            module = importlib.import_module(name)
        except ImportError as e:
            raise PluginError(f"failed to load plugin [{name}]: {e}")
        install = getattr(module, "install", None)
        if install is None:
            raise PluginError(
                f"plugin [{name}] does not define install(node)")
        try:
            if rest_controller is not None and \
                    install.__code__.co_argcount >= 2:
                install(node, rest_controller)
            else:
                install(node)
        except Exception as e:  # noqa: BLE001 — fail startup loudly
            raise PluginError(f"plugin [{name}] failed to install: {e}")
        loaded.append(name)
    node.plugins = loaded
    return loaded

"""TPU dispatch coalescer: micro-batching for concurrent small searches.

BENCH_r05 measured the gap this closes: the Turbo engine sustains ~292
qps at batch 256 but a single query pays 148-161ms p50/p95, because
concurrent batch-1 searches each launch their OWN device dispatch. This
is the continuous-batching regime from inference serving (and the eager
batched-scoring regime BM25S, arxiv 2407.03618, shows for sparse BM25):
hold concurrent single/small queries targeting the same engine for a
short flush window, execute them as ONE padded `search_many` dispatch,
and de-multiplex the rows back to their waiters.

Bit-identity with solo execution is a hard requirement (the serving
differential tests enforce it), so merging is conservative:

- batches are keyed by `(engine identity, k)` — queries never share a
  dispatch across engines (a snapshot refresh mid-window swaps the
  engine object, so late arrivals key onto the NEW engine and in-flight
  waiters finish on the snapshot they captured) and never across
  different top-k depths;
- both engines score and select top-k per query-row independently
  (TurboBM25's host rescore is exact per query; BlockMax's pass-B pads
  with row copies), so a merged row equals its solo row bitwise.

The flush window comes from `ES_TPU_COALESCE_US` (microseconds, default
2000; 0 disables coalescing entirely — every call dispatches solo).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common import metrics, tracing
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.tasks import task_manager as _taskmgr

DEFAULT_WINDOW_US = 2000.0
# a query batch larger than this is already a good device shape — merging
# it would only add latency to its peers
SMALL_BATCH_MAX = 8
# flush early once a held batch reaches this many queries
MAX_BATCH = 64


# monotonic engine serials for batch keying: id(engine) could be REUSED
# by a new engine allocated after an old one is garbage-collected
# mid-window (a snapshot refresh drops the old TurboEngine/ShardedTurbo
# wrapper), silently merging waiters across snapshots; a serial pinned on
# the object can never collide
_engine_serials = itertools.count(1)


def _engine_key(engine) -> int:
    s = getattr(engine, "_coalesce_serial", None)
    if s is None:
        s = next(_engine_serials)
        try:
            engine._coalesce_serial = s
        except AttributeError:     # __slots__ engines: degrade to id()
            return id(engine)
    return s


def _env_window_us() -> float:
    # per-call registry read: tests toggle the window mid-process
    return knob("ES_TPU_COALESCE_US")


def record_device(engine, n_queries: int, dt_ms: float,
                  engine_name: Optional[str] = None) -> None:
    """Flight recorder: one device dispatch. Every dispatch path funnels
    through its single authoritative call site of this helper (coalescer
    direct + leader, scheduler, serving's search_bool sites), so latency
    AND batch-shape/pad-waste land together — including direct and fused
    ShardedTurbo dispatches that the old leader-only pad accounting
    missed."""
    metrics.observe("device", dt_ms)
    record_pad_waste(engine, n_queries)
    tc = tracing.current()
    if tc is not None:
        tc.add_span("device", dt_ms,
                    engine=engine_name or getattr(engine, "kind", "?"),
                    batch=n_queries)


def record_pad_waste(engine, n: int) -> None:
    """Batch-shape histograms: how many query rows the qc quantization pads
    on top of the real batch (the pad-waste the adaptive scheduler's
    bucket ladder exists to minimize)."""
    metrics.observe("coalesce_batch_size", n)
    sizes = getattr(engine, "qc_sizes", None)
    if not sizes or n <= 0:
        return
    cap = sizes[-1]
    full, rem = divmod(n, cap)
    padded = full * cap
    if rem:
        padded += next((s for s in sizes if s >= rem), cap)
    if padded > 0:
        metrics.observe("coalesce_pad_ratio", (padded - n) / padded)


def _accepts_fault_log(engine) -> bool:
    """Whether engine.search_many takes a fault_log kwarg (TurboEngine
    does; BlockMax and test stubs may not). Cached on the engine."""
    cached = getattr(engine, "_accepts_fault_log_", None)
    if cached is None:
        import inspect

        try:
            params = inspect.signature(engine.search_many).parameters
            cached = "fault_log" in params or any(
                p.kind == inspect.Parameter.VAR_KEYWORD
                for p in params.values())
        except (TypeError, ValueError):
            cached = False
        try:
            engine._accepts_fault_log_ = cached
        except AttributeError:
            pass
    return cached


class _PendingBatch:
    __slots__ = ("engine", "k", "queries", "closed", "fill", "done",
                 "results", "error", "fault_log", "query_errors")

    def __init__(self, engine, k: int):
        self.engine = engine
        self.k = k
        self.queries: List = []
        self.closed = False
        self.fill = threading.Event()    # wakes the leader early when full
        self.done = threading.Event()    # results ready for the waiters
        self.results = None
        self.error: Optional[BaseException] = None
        self.fault_log: List = []        # shard fault records (recovered)
        self.query_errors: Dict[int, BaseException] = {}  # slot -> error


def retry_batch_solo(batch, original: BaseException) -> None:
    """Poison-batch containment, shared by the coalescer and the adaptive
    scheduler: re-run each of a failed merged batch's queries as its own
    solo dispatch (once). Slots whose retry also fails carry their error
    to exactly their waiter; if every retry fails the original batch
    error goes to everyone. `batch` is any object with the _PendingBatch
    result-surface (engine, k, queries, fault_log, results, error,
    query_errors)."""
    import numpy as np

    rows: List = [None] * len(batch.queries)
    errors: Dict[int, BaseException] = {}
    for qi, query in enumerate(batch.queries):
        try:
            s, p, o = DispatchCoalescer._run(batch.engine, [query], batch.k,
                                             fault_log=batch.fault_log)
        except Exception as e:
            errors[qi] = e
            continue
        rows[qi] = (np.asarray(s[0]), np.asarray(p[0]),
                    np.asarray(o[0]))
    if all(r is None for r in rows):
        batch.error = original
        return
    template = next(r for r in rows if r is not None)
    for qi, r in enumerate(rows):
        if r is None:
            rows[qi] = tuple(np.zeros_like(x) for x in template)
    batch.results = tuple(np.stack([r[j] for r in rows])
                          for j in range(3))
    batch.query_errors = errors


class DispatchCoalescer:
    """Merges concurrent `search_many` calls on the same engine+k into
    one device dispatch. The FIRST arrival for a key becomes the batch
    leader: it waits out the flush window (or until the batch fills),
    closes the batch, runs the single merged dispatch, and publishes the
    rows; followers only wait on the result event."""

    def __init__(self, window_us: Optional[float] = None,
                 max_batch: int = MAX_BATCH,
                 small_batch_max: int = SMALL_BATCH_MAX):
        self._window_us = window_us     # None -> read env per dispatch
        self.max_batch = max_batch
        self.small_batch_max = small_batch_max
        self._lock = threading.Lock()
        self._pending: Dict[Tuple[int, int], _PendingBatch] = {}  # guarded by: _lock
        # stats
        self._direct_dispatches = 0      # guarded by: _lock
        self._coalesced_dispatches = 0   # guarded by: _lock
        self._coalesced_queries = 0      # guarded by: _lock
        self._largest_batch = 0          # guarded by: _lock
        self._batch_retries = 0          # guarded by: _lock

    def window_us(self) -> float:
        return self._window_us if self._window_us is not None \
            else _env_window_us()

    @staticmethod
    def _run(engine, queries: List, k: int, check=None, fault_log=None):
        kw = {}
        if check is not None:
            kw["check"] = check
        if fault_log is not None and _accepts_fault_log(engine):
            kw["fault_log"] = fault_log
        return engine.search_many([list(queries)], k=k, **kw)[0]

    def dispatch(self, engine, queries: List, k: int, check=None,
                 fault_log=None):
        """One batch of queries -> (scores [Q,k], partition [Q,k],
        ord [Q,k]) — the engine `search_many` single-batch contract.
        Small batches coalesce with concurrent peers; large ones (or a
        zero window) dispatch directly. `fault_log`, when given, collects
        the engine's recovered-shard FaultRecords for `_shards`
        accounting."""
        window_s = self.window_us() / 1e6
        if check is not None:
            # cooperative cancellation happens at the caller's boundary:
            # a merged dispatch must never fail EVERY waiter because one
            # task was cancelled
            check()
        ct = _taskmgr.current_task()
        if ct is not None:
            # registered-task cancellation (direct or ban-propagated)
            # honors the same boundary-only contract
            ct.check()
            ct.note_dispatch()
        if window_s <= 0 or len(queries) > self.small_batch_max:
            with self._lock:
                self._direct_dispatches += 1
            t_dev = time.monotonic()
            out = self._run(engine, queries, k, check=check,
                            fault_log=fault_log)
            record_device(engine, len(queries),
                          (time.monotonic() - t_dev) * 1e3)
            return out

        with self._lock:
            # key under the lock so one engine gets exactly one serial
            key = (_engine_key(engine), int(k))
            batch = self._pending.get(key)
            leader = batch is None
            if leader:
                batch = _PendingBatch(engine, int(k))
                self._pending[key] = batch
            base = len(batch.queries)
            batch.queries.extend(queries)
            if len(batch.queries) >= self.max_batch:
                batch.closed = True
                if self._pending.get(key) is batch:
                    del self._pending[key]
                batch.fill.set()

        if leader:
            t_wait = time.monotonic()
            batch.fill.wait(window_s)
            with self._lock:
                # close the window: late arrivals start a fresh batch
                batch.closed = True
                if self._pending.get(key) is batch:
                    del self._pending[key]
                n = len(batch.queries)
                self._coalesced_dispatches += 1
                self._coalesced_queries += n
                if n > self._largest_batch:
                    self._largest_batch = n
            wait_ms = (time.monotonic() - t_wait) * 1e3
            metrics.observe("coalesce_wait", wait_ms)
            tc = tracing.current()
            if tc is not None:
                tc.add_span("coalesce_wait", wait_ms, role="leader", batch=n)
            try:
                t_dev = time.monotonic()
                batch.results = self._run(engine, batch.queries, batch.k,
                                          fault_log=batch.fault_log)
                record_device(engine, n, (time.monotonic() - t_dev) * 1e3)
                from elasticsearch_tpu.common.overload import (
                    default_overload,
                )

                default_overload().note_success()
            except Exception as e:
                # poison-batch containment: a failed FUSED dispatch must
                # not fail every waiter — retry each query solo once so
                # only the query (if any) that actually trips the fault
                # sees the error
                self._retry_solo(batch, e)
            except BaseException as e:  # noqa: BLE001 — ferried to waiters
                batch.error = e
            finally:
                batch.done.set()
        else:
            t_wait = time.monotonic()
            batch.done.wait()
            wait_ms = (time.monotonic() - t_wait) * 1e3
            metrics.observe("coalesce_wait", wait_ms)
            tc = tracing.current()
            if tc is not None:
                tc.add_span("coalesce_wait", wait_ms, role="follower")
        if check is not None:
            check()
        if ct is not None:
            # a cancel that landed mid-window kills only THIS waiter;
            # co-batched peers keep their bit-identical slices
            ct.check()
        if batch.error is not None:
            raise batch.error
        if fault_log is not None and batch.fault_log:
            fault_log.extend(batch.fault_log)
        if batch.query_errors:
            for qi in range(base, base + len(queries)):
                if qi in batch.query_errors:
                    raise batch.query_errors[qi]
        scores, parts, ords = batch.results
        sl = slice(base, base + len(queries))
        return scores[sl], parts[sl], ords[sl]

    def _retry_solo(self, batch: _PendingBatch,
                    original: BaseException) -> None:
        from elasticsearch_tpu.common.overload import default_overload

        if not default_overload().retry_allowed("coalesce_solo"):
            # retry budget exhausted: every waiter gets the ORIGINAL
            # batch error instead of N solo re-dispatches
            batch.error = original
            return
        with self._lock:
            self._batch_retries += 1
        retry_batch_solo(batch, original)

    def stats(self) -> dict:
        with self._lock:
            merged = self._coalesced_queries
            dispatches = self._coalesced_dispatches
            return {
                "window_us": self.window_us(),
                "direct_dispatches": self._direct_dispatches,
                "coalesced_dispatches": dispatches,
                "coalesced_queries": merged,
                "largest_batch": self._largest_batch,
                "mean_batch": round(merged / dispatches, 3) if dispatches
                else 0.0,
                "coalesce_batch_retries": self._batch_retries,
            }


# the process-default coalescer: ServingContext instances all dispatch
# through it so concurrent searches coalesce across REST entry points
_default = DispatchCoalescer()


def default_coalescer() -> DispatchCoalescer:
    return _default

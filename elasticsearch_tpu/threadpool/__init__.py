from elasticsearch_tpu.threadpool.coalescer import (
    DispatchCoalescer, default_coalescer,
)
from elasticsearch_tpu.threadpool.pool import (
    EsRejectedExecutionError, FixedExecutor, ThreadPool, pool_for_request,
)

__all__ = ["DispatchCoalescer", "EsRejectedExecutionError", "FixedExecutor",
           "ThreadPool", "default_coalescer", "pool_for_request"]

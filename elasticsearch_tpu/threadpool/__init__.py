from elasticsearch_tpu.threadpool.coalescer import (
    DispatchCoalescer, default_coalescer,
)
from elasticsearch_tpu.threadpool.pool import (
    EsRejectedExecutionError, FixedExecutor, ThreadPool, pool_for_request,
    tier_for_request,
)
from elasticsearch_tpu.threadpool.scheduler import (
    AdaptiveDispatchScheduler, activate_tier, current_tier,
    default_scheduler, scheduler_stats, serving_dispatch,
)

__all__ = ["AdaptiveDispatchScheduler", "DispatchCoalescer",
           "EsRejectedExecutionError", "FixedExecutor", "ThreadPool",
           "activate_tier", "current_tier", "default_coalescer",
           "default_scheduler", "pool_for_request", "scheduler_stats",
           "serving_dispatch", "tier_for_request"]

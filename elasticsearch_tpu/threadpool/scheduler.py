"""Continuous-batching dispatch scheduler: bucketed shapes, double
buffering, SLA tiers.

BENCH_r05 measured the wall the fixed-window coalescer hits: batch-1 p95
is 160+ ms while batch-256 p50 is ~1 s, because ONE flush window and ONE
padded shape force the device to alternate between starvation (tiny
batches after a full 2 ms wait) and giant pads (a stray single riding a
256-wide dispatch). This module is the continuous-batching discipline of
modern inference servers applied to the search dispatch path:

- **bucketed batch shapes** — a small ladder of padded batch sizes
  (`ES_TPU_SCHED_BUCKETS`, default 1/4/16/64/256). Each bucket is one
  compiled kernel shape (the ladder is pushed into the engine's
  `qc_sizes` compile cache), and every flush picks the smallest bucket
  covering the queries that must go now, so light traffic never pays a
  heavy pad.
- **queue-depth-adaptive flush timing** — a flush fires the moment the
  largest bucket fills or the oldest waiter exceeds its SLA-tier budget;
  there is no fixed window. Under load the queue naturally deepens while
  the device is busy (both in-flight slots taken), so batches grow with
  pressure and shrink when it lifts.
- **double-buffered dispatch** — a dedicated dispatch thread per
  (engine, k) lane and `ES_TPU_SCHED_INFLIGHT` (default 2) in-flight
  slots: host demux + waiter wakeup of batch N overlap the device sweep
  of batch N+1. A slot is released by the LAST waiter to consume its
  batch, so deadline checks and fault accounting stay per-slot.
- **SLA tiers** — every request carries an `interactive` or `bulk` class
  (thread-pool classifier + optional `sla` request param, propagated
  across pool hops and shard RPCs like the trace context), with per-tier
  max-wait budgets (`ES_TPU_SCHED_INTERACTIVE_US` /
  `ES_TPU_SCHED_BULK_US`). A deep bulk backlog can never pin an
  interactive query past its budget: the interactive deadline triggers
  the flush, the bucket is sized to the queries that are DUE, and bulk
  only rides along in the pad slack that would be wasted anyway.

The coalescer's serving contracts are inherited, not re-invented: lanes
are keyed by (engine serial, k) so queries never share a dispatch across
engines or top-k depths; merged rows are bit-identical to solo rows (the
engines score per query-row); a poisoned batch is retried solo per query
(threadpool/coalescer.retry_batch_solo); cooperative `check()` runs only
at the caller boundary so one cancelled task can't fail its batch peers.

`ES_TPU_COALESCE_US=0` still disables batching entirely (every call
dispatches directly), and `ES_TPU_SCHED_MODE=legacy` routes serving
dispatches through the old fixed-window coalescer so the differential
suite can A/B the two schedulers bit-identically.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common import metrics, tracing
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.tasks import task_manager as _taskmgr
from elasticsearch_tpu.threadpool.coalescer import (
    SMALL_BATCH_MAX, DispatchCoalescer, _engine_key, default_coalescer,
    record_device, retry_batch_solo,
)

TIER_INTERACTIVE = "interactive"
TIER_BULK = "bulk"
_TIERS = (TIER_INTERACTIVE, TIER_BULK)

DEFAULT_BUCKETS = (1, 4, 16, 64, 256)

# ladder autotune (knob unset): derive rungs from the observed flush-time
# demand. The queue-depth histogram (count kind, power-of-2 bucket upper
# bounds) gives the rung positions; the pad-ratio histogram decides
# whether to densify them. Each rung is one compiled kernel shape, so the
# ladder is cached and only re-derived after AUTOTUNE_REOBS more flushes.
AUTOTUNE_MIN_OBS = 64     # flushes before trusting the histograms at all
AUTOTUNE_REOBS = 256      # new flushes between ladder re-derivations
AUTOTUNE_CAP = 512        # largest rung autotune will compile
AUTOTUNE_PAD_P90 = 0.25   # p90 pad waste that triggers densification


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _derive_ladder(depth: dict, pad: Optional[dict]) -> Tuple[int, ...]:
    """Ladder from flush-time demand: rungs at the queue-depth p50/p90/p99
    (already power-of-2 bucket bounds) plus the rounded-up max, always
    anchored at 1 (a lone interactive query must never pad). When the
    observed pad waste stays high anyway, add geometric midpoints between
    adjacent rungs — halving the worst-case pad at the cost of more
    compiled shapes."""
    pts = {1}
    for key in ("p50", "p90", "p99"):
        v = int(depth.get(key, 0))
        if v > 0:
            pts.add(min(_next_pow2(v), AUTOTUNE_CAP))
    mx = int(depth.get("max", 0))
    if mx > 0:
        pts.add(min(_next_pow2(mx), AUTOTUNE_CAP))
    rungs = sorted(pts)
    if (pad and pad.get("count", 0) >= AUTOTUNE_MIN_OBS
            and pad.get("p90", 0.0) > AUTOTUNE_PAD_P90):
        dense = set(rungs)
        for lo, hi in zip(rungs, rungs[1:]):
            if hi >= 4 * lo:
                dense.add(_next_pow2(int((lo * hi) ** 0.5)))
        rungs = sorted(dense)
    return tuple(rungs)

# how long a lane's dispatch thread idles on an empty queue before
# retiring itself (and unregistering the lane, so a snapshot refresh's
# swapped-out engine can be garbage collected)
LANE_IDLE_S = 2.0


# ---------------------------------------------------------------------------
# SLA tier context: which class the current request belongs to. Mirrors the
# tracing.current()/activate() thread-local pattern; threadpool/pool.py
# captures the submitter's tier into each _Task and re-activates it in the
# worker, and action/search_action.py ferries it across shard RPCs.
# ---------------------------------------------------------------------------

_tier_tls = threading.local()


def current_tier() -> str:
    """The active SLA tier, defaulting to interactive (the tighter budget
    — misclassified traffic must not be starved)."""
    t = getattr(_tier_tls, "tier", None)
    return t if t in _TIERS else TIER_INTERACTIVE


@contextmanager
def activate_tier(tier: Optional[str]):
    """Bind the SLA tier for the duration of a request. Unknown/None
    tiers leave the current binding untouched (RPC payloads from older
    nodes simply inherit the worker's default)."""
    prev = getattr(_tier_tls, "tier", None)
    if tier in _TIERS:
        _tier_tls.tier = tier
    try:
        yield
    finally:
        _tier_tls.tier = prev


def _parse_buckets(raw) -> Tuple[int, ...]:
    """`ES_TPU_SCHED_BUCKETS` ("1,4,16,64,256") -> ascending unique
    positive ints; malformed specs fall back to the default ladder (a
    typo'd knob must not take the dispatch path down)."""
    try:
        vals = sorted({int(str(x).strip())
                       for x in str(raw).split(",") if str(x).strip()})
    except (TypeError, ValueError):
        return DEFAULT_BUCKETS
    vals = [v for v in vals if v > 0]
    return tuple(vals) if vals else DEFAULT_BUCKETS


class _Waiter:
    """One dispatch() call parked in a lane queue."""

    __slots__ = ("queries", "tier", "enqueued", "done", "batch", "base",
                 "trace", "error")

    def __init__(self, queries: List, tier: str):
        self.queries = queries
        self.tier = tier
        self.enqueued = time.monotonic()
        self.done = threading.Event()
        self.batch: Optional[_SchedBatch] = None   # set at flush
        self.base = 0                              # row offset in the batch
        self.trace = tracing.current()
        self.error: Optional[BaseException] = None  # lane-thread crash only

    def age(self, now: float) -> float:
        return now - self.enqueued


class _SchedBatch:
    """One flushed device dispatch (result surface shared with the
    coalescer's _PendingBatch so retry_batch_solo applies to both)."""

    __slots__ = ("engine", "k", "queries", "waiters", "bucket", "results",
                 "error", "fault_log", "query_errors", "trace", "_lock",
                 "_remaining")

    def __init__(self, engine, k: int, waiters: List[_Waiter], bucket: int):
        self.engine = engine
        self.k = k
        self.queries: List = []
        self.waiters = waiters
        self.bucket = bucket
        self.results = None
        self.error: Optional[BaseException] = None
        self.fault_log: List = []
        self.query_errors: Dict[int, BaseException] = {}
        # the first waiter's trace plays the coalescer-leader role: the
        # device span lands on exactly one requester's flight record
        self.trace = waiters[0].trace if waiters else None
        self._lock = threading.Lock()
        self._remaining = len(waiters)  # guarded by: _lock
        for w in waiters:
            w.batch = self
            w.base = len(self.queries)
            self.queries.extend(w.queries)

    def consume(self) -> bool:
        """Called once per waiter after it has read its rows; True for
        the LAST waiter out — that consumption releases the batch's
        in-flight slot (this is what makes dispatch double-buffered: the
        slot stays held while any waiter is still demuxing)."""
        with self._lock:
            self._remaining -= 1
            return self._remaining == 0


class _Lane:
    """Per-(engine, k) dispatch queue plus its dedicated dispatch
    thread. The lane object is created/looked up under the scheduler's
    registry lock; its own state is guarded by `lock` below."""

    __slots__ = ("engine", "k", "key", "lock", "cond", "queue", "thread",
                 "slots", "dead")

    def __init__(self, engine, k: int, key, inflight: int):
        self.engine = engine
        self.k = k
        self.key = key
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.queue: List[_Waiter] = []   # guarded by: lock
        self.thread = None               # guarded by: lock
        # the double-buffer: device dispatches in flight for this lane
        self.slots = threading.Semaphore(max(1, inflight))
        self.dead = False                # guarded by: lock


class AdaptiveDispatchScheduler:
    """Continuous-batching scheduler for engine `search_many` dispatches.

    dispatch() parks each small query batch in a per-(engine, k) lane;
    the lane's dispatch thread flushes the queue to the smallest ladder
    bucket covering the queries that are due, runs the merged device
    dispatch (overlapping up to `inflight` batches), and wakes the
    waiters, each of which demuxes its own rows. Constructor arguments
    override the knobs for tests; None means "read the knob per call"
    so a live node follows environment changes."""

    def __init__(self, buckets: Optional[Tuple[int, ...]] = None,
                 interactive_us: Optional[float] = None,
                 bulk_us: Optional[float] = None,
                 inflight: Optional[int] = None,
                 small_batch_max: int = SMALL_BATCH_MAX,
                 idle_s: float = LANE_IDLE_S):
        self._buckets = tuple(buckets) if buckets else None
        self._interactive_us = interactive_us
        self._bulk_us = bulk_us
        self._inflight_cfg = inflight
        self.small_batch_max = small_batch_max
        self._idle_s = idle_s
        self._lock = threading.Lock()
        self._lanes: Dict[Tuple[int, int], _Lane] = {}  # guarded by: _lock
        # stats
        self._direct_dispatches = 0   # guarded by: _lock
        self._flushes = 0             # guarded by: _lock
        self._sched_queries = 0       # guarded by: _lock
        self._batch_retries = 0       # guarded by: _lock
        self._largest_batch = 0       # guarded by: _lock
        self._inflight = 0            # guarded by: _lock
        self._max_inflight = 0        # guarded by: _lock
        self._bucket_counts: Dict[int, int] = {}        # guarded by: _lock
        self._tier_counts: Dict[str, int] = {}          # guarded by: _lock
        self._tier_wait_ms: Dict[str, float] = {}       # guarded by: _lock
        # per-lane in-flight batches, the raw series behind the sampler's
        # per-lane device busy fraction (PR 12)
        self._lane_inflight: Dict[Tuple[int, int], int] = {}  # guarded by: _lock
        # autotuned ladder cache (knob unset); own lock: ladder() is read
        # under _lock by stats(), so the cache must not share it
        self._auto_lock = threading.Lock()
        self._auto_ladder: Optional[Tuple[int, ...]] = None  # guarded by: _auto_lock
        self._auto_obs = 0            # guarded by: _auto_lock

    # ---- knob-or-constructor configuration ----

    def ladder(self) -> Tuple[int, ...]:
        if self._buckets is not None:
            return self._buckets
        raw = knob("ES_TPU_SCHED_BUCKETS", default=None)
        if raw is not None:
            return _parse_buckets(raw)
        return self._autotune_ladder()

    def _autotune_ladder(self) -> Tuple[int, ...]:
        """Knob-unset ladder: DEFAULT_BUCKETS until enough flushes have
        been observed, then the demand-derived ladder, re-derived only
        every AUTOTUNE_REOBS flushes (each rung is a compiled shape — a
        jittery ladder would churn the kernel cache)."""
        depth = metrics.summary("sched_queue_depth") or {}
        n = int(depth.get("count", 0))
        with self._auto_lock:
            if (self._auto_ladder is not None
                    and n - self._auto_obs < AUTOTUNE_REOBS):
                return self._auto_ladder
        if n < AUTOTUNE_MIN_OBS:
            return DEFAULT_BUCKETS
        derived = _derive_ladder(depth,
                                 metrics.summary("coalesce_pad_ratio"))
        with self._auto_lock:
            self._auto_ladder = derived
            self._auto_obs = n
            return self._auto_ladder

    def budget_s(self, tier: str) -> float:
        if tier == TIER_BULK:
            us = self._bulk_us if self._bulk_us is not None \
                else knob("ES_TPU_SCHED_BULK_US")
        else:
            us = self._interactive_us if self._interactive_us is not None \
                else knob("ES_TPU_SCHED_INTERACTIVE_US")
        return max(0.0, float(us)) / 1e6

    def _inflight_slots(self) -> int:
        n = self._inflight_cfg if self._inflight_cfg is not None \
            else knob("ES_TPU_SCHED_INFLIGHT")
        return max(1, int(n))

    # ---- the dispatch entry ----

    def dispatch(self, engine, queries: List, k: int, check=None,
                 fault_log=None, tier: Optional[str] = None):
        """One batch of queries -> (scores [Q,k], partition [Q,k],
        ord [Q,k]) — the engine `search_many` single-batch contract,
        bit-identical to solo execution. Small batches continuous-batch
        with concurrent peers on the same (engine, k) lane; large ones
        (or a zero ES_TPU_COALESCE_US) dispatch directly."""
        if check is not None:
            # cooperative cancellation only at the caller's boundary: a
            # merged dispatch must never fail EVERY waiter because one
            # task was cancelled
            check()
        ct = _taskmgr.current_task()
        if ct is not None:
            # registered-task cancellation (direct or ban-propagated)
            # honors the same boundary-only contract
            ct.check()
            ct.note_dispatch()
        if knob("ES_TPU_COALESCE_US") <= 0 \
                or len(queries) > self.small_batch_max:
            # direct dispatches skip the lane but still belong to an SLA
            # tier — account them so stats()["tiers"] covers ALL traffic
            tier = tier if tier in _TIERS else current_tier()
            with self._lock:
                self._direct_dispatches += 1
                self._tier_counts[tier] = self._tier_counts.get(tier, 0) + 1
            t_dev = time.monotonic()
            out = DispatchCoalescer._run(engine, queries, k, check=check,
                                         fault_log=fault_log)
            record_device(engine, len(queries),
                          (time.monotonic() - t_dev) * 1e3)
            return out

        tier = tier if tier in _TIERS else current_tier()
        w = _Waiter(list(queries), tier)
        lane = self._enqueue(engine, k, w)
        t0 = time.monotonic()
        w.done.wait()
        wait_ms = (time.monotonic() - t0) * 1e3
        # composed name: exactly the declared sched_tier_wait.* pair
        metrics.observe_if_declared(f"sched_tier_wait.{tier}", wait_ms)
        with self._lock:
            self._tier_counts[tier] = self._tier_counts.get(tier, 0) + 1
            self._tier_wait_ms[tier] = \
                self._tier_wait_ms.get(tier, 0.0) + wait_ms
        batch = w.batch
        if batch is None:          # lane thread crashed before the flush
            raise w.error if w.error is not None else \
                RuntimeError("scheduler lane failed before dispatch")
        tc = tracing.current()
        if tc is not None:
            tc.add_span("sched_wait", wait_ms, tier=tier,
                        batch=len(batch.queries), bucket=batch.bucket)
        try:
            if check is not None:
                check()
            if ct is not None:
                # a ban that landed while we were parked in the batch
                # kills only THIS waiter; co-batched peers keep their
                # bit-identical slices
                ct.check()
            if batch.error is not None:
                raise batch.error
            if fault_log is not None and batch.fault_log:
                fault_log.extend(batch.fault_log)
            if batch.query_errors:
                for qi in range(w.base, w.base + len(w.queries)):
                    if qi in batch.query_errors:
                        raise batch.query_errors[qi]
            scores, parts, ords = batch.results
            sl = slice(w.base, w.base + len(w.queries))
            return scores[sl], parts[sl], ords[sl]
        finally:
            if batch.consume():
                lane.slots.release()
                with self._lock:
                    self._inflight -= 1
                    left = self._lane_inflight.get(lane.key, 0) - 1
                    if left > 0:
                        self._lane_inflight[lane.key] = left
                    else:
                        self._lane_inflight.pop(lane.key, None)
                    inflight_now = self._inflight
                metrics.gauge_set("sched_inflight", inflight_now)

    # ---- lane registry ----

    def _enqueue(self, engine, k: int, w: _Waiter) -> _Lane:
        while True:
            lane = self._lane(engine, k)
            with lane.lock:
                if lane.dead:
                    continue       # lost the race with idle expiry: retry
                lane.queue.append(w)
                if lane.thread is None:
                    lane.thread = threading.Thread(
                        target=self._lane_loop, args=(lane,), daemon=True,
                        name=f"es-tpu-sched[{lane.key[0]}/{lane.key[1]}]")
                    lane.thread.start()
                lane.cond.notify()
            return lane

    def _lane(self, engine, k: int) -> _Lane:
        key = (_engine_key(engine), int(k))
        with self._lock:
            lane = self._lanes.get(key)
            if lane is not None and not lane.dead:
                return lane
            lane = _Lane(engine, int(k), key, self._inflight_slots())
            self._lanes[key] = lane
        self._prime_engine(engine)
        return lane

    def _prime_engine(self, engine) -> None:
        """Push the bucket ladder into the engine's compiled-width cache
        (TurboBM25 / ShardedTurbo qc_sizes): each bucket becomes one
        cached kernel shape so a flush to bucket B pads to B, not to the
        engine's default widths. The primed ladder itself is the guard —
        an autotune re-derivation (or a live knob change) re-primes the
        engine before the new rungs ever reach a flush, so the widened
        shapes are traced once up front instead of retracing mid-dispatch.
        Engines without the hook (BlockMax, stubs) keep their own internal
        chunking."""
        ext = getattr(engine, "extend_qc_sizes", None)
        if ext is None:
            return
        ladder = self.ladder()
        if getattr(engine, "_sched_primed_", None) == ladder:
            return
        try:
            ext(ladder)
            engine._sched_primed_ = ladder
        except AttributeError:     # __slots__ engines: re-prime per lane
            pass

    # ---- the per-lane dispatch thread ----

    def _lane_loop(self, lane: _Lane) -> None:
        try:
            while True:
                with lane.lock:
                    if not lane.queue:
                        notified = lane.cond.wait(self._idle_s)
                        if not lane.queue:
                            if notified:
                                continue      # spurious wakeup
                            # idle: retire the thread and unregister the
                            # lane so a swapped-out engine can be GC'd
                            lane.dead = True
                            with self._lock:
                                if self._lanes.get(lane.key) is lane:
                                    del self._lanes[lane.key]
                            return
                    now = time.monotonic()
                    batch, depth = self._build_batch(lane, now)
                    if batch is None:
                        # nothing due and the top bucket not full: sleep
                        # until the oldest waiter's tier budget expires
                        due_at = min(w.enqueued + self.budget_s(w.tier)
                                     for w in lane.queue)
                        lane.cond.wait(max(due_at - now, 1e-4))
                        continue
                # device work happens OUTSIDE the lane lock: late
                # arrivals keep queueing into the next batch while this
                # one is on the device
                self._execute(lane, batch, depth)
        except BaseException as e:  # noqa: BLE001 — fail queued waiters
            with lane.lock:
                lane.dead = True
                orphans = list(lane.queue)
                lane.queue.clear()
                with self._lock:
                    if self._lanes.get(lane.key) is lane:
                        del self._lanes[lane.key]
            for w in orphans:
                w.error = e
                w.done.set()
            raise

    def _build_batch(self, lane: _Lane, now: float):  # tpulint: holds=lock
        """Flush decision + bucket selection. Returns (batch, depth) or
        (None, depth) when the lane should keep waiting. A flush fires
        when the largest bucket fills or any waiter is past its tier
        budget; the bucket is the smallest ladder entry covering the DUE
        queries (everything, on a full queue), and remaining capacity is
        back-filled FIFO with not-yet-due waiters — bulk rides the pad
        slack of an interactive flush instead of widening it."""
        depth = sum(len(w.queries) for w in lane.queue)
        if depth == 0:
            return None, 0
        ladder = self.ladder()
        due = [w for w in lane.queue
               if w.age(now) >= self.budget_s(w.tier)]
        full = depth >= ladder[-1]
        if not due and not full:
            return None, depth
        need = depth if full else sum(len(w.queries) for w in due)
        bucket = next((b for b in ladder if b >= need), ladder[-1])
        chosen: List[_Waiter] = []
        n = 0
        for w in due:
            if n + len(w.queries) > bucket:
                break              # overflow backlog: the next flush is
            chosen.append(w)       # immediate (they stay due)
            n += len(w.queries)
        taken = set(id(x) for x in chosen)
        for w in lane.queue:
            if id(w) in taken:
                continue
            if n + len(w.queries) <= bucket:
                chosen.append(w)
                taken.add(id(w))
                n += len(w.queries)
        remaining = [w for w in lane.queue if id(w) not in taken]
        lane.queue.clear()
        lane.queue.extend(remaining)
        return _SchedBatch(lane.engine, lane.k, chosen, bucket), depth

    def _execute(self, lane: _Lane, batch: _SchedBatch, depth: int) -> None:
        # ladder-change re-prime (near-free tuple compare when unchanged):
        # the batch's bucket may be a rung the lane-creation prime never
        # saw if the autotuner re-derived while the lane was alive
        self._prime_engine(lane.engine)
        # take an in-flight slot BEFORE the device call; the last waiter
        # to consume the batch gives it back (double buffering: demux of
        # this batch overlaps the device sweep of the next one)
        lane.slots.acquire()
        n = len(batch.queries)
        with self._lock:
            self._inflight += 1
            if self._inflight > self._max_inflight:
                self._max_inflight = self._inflight
            self._flushes += 1
            self._sched_queries += n
            if n > self._largest_batch:
                self._largest_batch = n
            self._bucket_counts[batch.bucket] = \
                self._bucket_counts.get(batch.bucket, 0) + 1
            self._lane_inflight[lane.key] = \
                self._lane_inflight.get(lane.key, 0) + 1
            inflight_now, lanes_now = self._inflight, len(self._lanes)
        metrics.observe("sched_bucket_size", batch.bucket)
        metrics.observe("sched_queue_depth", depth)
        metrics.gauge_set("sched_inflight", inflight_now)
        metrics.gauge_set("sched_lanes", lanes_now)
        metrics.counter_add("sched_flushes")
        try:
            with tracing.activate(batch.trace):
                t_dev = time.monotonic()
                batch.results = DispatchCoalescer._run(
                    batch.engine, batch.queries, batch.k,
                    fault_log=batch.fault_log)
                record_device(batch.engine, n,
                              (time.monotonic() - t_dev) * 1e3)
                from elasticsearch_tpu.common.overload import (
                    default_overload,
                )

                default_overload().note_success()
        except Exception as e:
            # poison-batch containment (coalescer parity): retry each
            # query solo so only the one tripping the fault sees it —
            # but only while the node-wide retry budget holds out; an
            # exhausted budget ferries the ORIGINAL error to the waiters
            from elasticsearch_tpu.common.overload import default_overload

            if not default_overload().retry_allowed("sched_solo"):
                batch.error = e
            else:
                with self._lock:
                    self._batch_retries += 1
                retry_batch_solo(batch, e)
        except BaseException as e:  # noqa: BLE001 — ferried to waiters
            batch.error = e
        finally:
            for w in batch.waiters:
                w.done.set()

    # ---- observability ----

    def stats(self) -> dict:
        with self._lock:
            flushes = self._flushes
            merged = self._sched_queries
            tiers = {
                t: {"dispatches": self._tier_counts.get(t, 0),
                    "mean_wait_ms": round(
                        self._tier_wait_ms.get(t, 0.0)
                        / max(1, self._tier_counts.get(t, 0)), 3)}
                for t in _TIERS}
            source = ("constructor" if self._buckets is not None
                      else "knob"
                      if knob("ES_TPU_SCHED_BUCKETS", default=None)
                      is not None
                      else "auto" if self._auto_ladder is not None
                      else "default")
            return {
                "buckets": list(self.ladder()),
                "bucket_source": source,
                "interactive_budget_us":
                    self.budget_s(TIER_INTERACTIVE) * 1e6,
                "bulk_budget_us": self.budget_s(TIER_BULK) * 1e6,
                "inflight_slots": self._inflight_slots(),
                "lanes": len(self._lanes),
                "direct_dispatches": self._direct_dispatches,
                "sched_dispatches": flushes,
                "sched_queries": merged,
                "largest_batch": self._largest_batch,
                "mean_batch": round(merged / flushes, 3) if flushes
                else 0.0,
                "sched_batch_retries": self._batch_retries,
                "inflight": self._inflight,
                "max_inflight": self._max_inflight,
                "bucket_counts": {str(b): c for b, c in
                                  sorted(self._bucket_counts.items())},
                "lane_inflight": {f"{e}/{k}": c for (e, k), c in
                                  sorted(self._lane_inflight.items())},
                "tiers": tiers,
            }

    def sample(self) -> dict:
        """Sampler-ring section: per-lane slot occupancy at the sample
        instant, so the history ring yields a device busy-fraction series
        without an external scraper."""
        slots = max(1, self._inflight_slots())
        with self._lock:
            return {
                "inflight": self._inflight,
                "lanes": len(self._lanes),
                "lane_busy_fraction": {
                    f"{e}/{k}": round(min(1.0, c / slots), 4)
                    for (e, k), c in sorted(self._lane_inflight.items())},
            }


# ---------------------------------------------------------------------------
# the process-default scheduler + the serving dispatch facade
# ---------------------------------------------------------------------------

_default = AdaptiveDispatchScheduler()

_MODE_LOCK = threading.Lock()
_MODE_COUNTS = {"adaptive": 0, "legacy": 0}  # guarded by: _MODE_LOCK


def default_scheduler() -> AdaptiveDispatchScheduler:
    return _default


def serving_dispatch(engine, queries: List, k: int, check=None,
                     fault_log=None, tier: Optional[str] = None):
    """THE serving dispatch entry (search/serving.py call sites):
    routes through the adaptive scheduler, or through the legacy
    fixed-window coalescer when ES_TPU_SCHED_MODE=legacy — both honor
    ES_TPU_COALESCE_US=0 as "no batching at all"."""
    if knob("ES_TPU_SCHED_MODE") == "legacy":
        with _MODE_LOCK:
            _MODE_COUNTS["legacy"] += 1
        return default_coalescer().dispatch(engine, queries, k,
                                            check=check,
                                            fault_log=fault_log)
    with _MODE_LOCK:
        _MODE_COUNTS["adaptive"] += 1
    return _default.dispatch(engine, queries, k, check=check,
                             fault_log=fault_log, tier=tier)


def scheduler_stats() -> dict:
    """The `tpu_scheduler` section of GET /_nodes/stats."""
    with _MODE_LOCK:
        modes = dict(_MODE_COUNTS)
    return {"mode": knob("ES_TPU_SCHED_MODE"),
            "mode_dispatches": modes,
            **default_scheduler().stats()}


# every metrics-history sample carries the default scheduler's per-lane
# occupancy snapshot (PR 12)
metrics.register_sample_provider(
    "tpu_scheduler", lambda: default_scheduler().sample())

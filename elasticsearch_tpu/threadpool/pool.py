"""Named bounded executors with admission control.

Re-designs the reference's node-level ThreadPool (ref:
threadpool/ThreadPool.java:59-75 builders, common/util/concurrent/
EsThreadPoolExecutor + EsRejectedExecutionException): a node owns ONE
ThreadPool holding a fixed-size executor per stage (`search`, `write`,
`get`, `management`, `snapshot`), each with a bounded queue. When a
pool's workers are all busy and its queue is full, submission fails
fast with `es_rejected_execution_exception` (HTTP 429) — load sheds at
the door instead of queueing unboundedly, and saturating one stage
never starves another (a bulk storm cannot take search down).

Workers spawn lazily (first submissions grow the pool to its size), so
constructing a ThreadPool is cheap for nodes that never serve a stage.
Per-pool sizes/queues are overridable via `ES_TPU_POOL_<NAME>_SIZE` /
`ES_TPU_POOL_<NAME>_QUEUE`.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from elasticsearch_tpu.common import metrics, tracing
from elasticsearch_tpu.common.errors import ElasticsearchTpuError
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.tasks import task_manager as _taskmgr
from elasticsearch_tpu.threadpool import scheduler as _sched


class EsRejectedExecutionError(ElasticsearchTpuError):
    """Pool saturated: workers busy and queue full (ref:
    EsRejectedExecutionException -> RestStatus.TOO_MANY_REQUESTS)."""

    status = 429
    error_type = "es_rejected_execution_exception"


# EWMA smoothing for per-task execution time (ref: the reference's
# ExponentiallyWeightedMovingAverage used for queue auto-scaling)
_EWMA_ALPHA = 0.2

_tls = threading.local()


class _Task:
    """Submission handle: a tiny future (result or raised error)."""

    __slots__ = ("fn", "args", "kwargs", "result", "error", "_done",
                 "submitted", "trace", "tier", "taskref")

    def __init__(self, fn, args, kwargs):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.result = None
        self.error: Optional[BaseException] = None
        self._done = threading.Event()
        self.submitted = time.monotonic()
        # the submitter's trace, SLA tier, and registered task ride the
        # submission across the thread hop and are re-activated in the
        # worker (flight recorder + scheduler-tier + cancellation
        # propagation)
        self.trace = tracing.current()
        self.tier = _sched.current_tier()
        self.taskref = _taskmgr.current_task()

    def run(self) -> None:
        try:
            self.result = self.fn(*self.args, **self.kwargs)
        except BaseException as e:  # noqa: BLE001 — ferried to the waiter
            self.error = e
        finally:
            self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(f"task [{self.fn}] did not complete")
        if self.error is not None:
            raise self.error
        return self.result


class FixedExecutor:
    """One named stage: `size` workers over a queue of `queue_size`."""

    def __init__(self, name: str, size: int, queue_size: int):
        self.name = name
        self.size = max(1, int(size))
        self.queue_size = max(0, int(queue_size))
        self._queue: deque = deque()  # guarded by: _lock
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._threads: list = []      # guarded by: _lock
        self._idle = 0                # guarded by: _lock
        self._shutdown = False        # guarded by: _lock
        # stats (ref: ThreadPoolStats.Stats)
        self.active = 0               # guarded by: _lock
        self.largest = 0              # guarded by: _lock
        self.completed = 0            # guarded by: _lock
        self.rejected = 0             # guarded by: _lock
        self.ewma_ms = 0.0            # guarded by: _lock
        self.queue_ewma_ms = 0.0      # guarded by: _lock

    def submit(self, fn: Callable, *args, **kwargs) -> _Task:
        task = _Task(fn, args, kwargs)
        with self._lock:
            if self._shutdown:
                self.rejected += 1
                raise EsRejectedExecutionError(
                    f"rejected execution of task on [{self.name}]: "
                    f"executor is shut down", bucket=self.name,
                    retry_after_s=self._retry_after_s())
            busy = self._idle == 0
            if busy and len(self._threads) >= self.size \
                    and len(self._queue) >= self.queue_size:
                self.rejected += 1
                raise EsRejectedExecutionError(
                    f"rejected execution of task on [{self.name}]: "
                    f"pool size [{self.size}] active and queue capacity "
                    f"[{self.queue_size}] full", bucket=self.name,
                    retry_after_s=self._retry_after_s())
            if busy and len(self._threads) < self.size:
                t = threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"es-tpu[{self.name}][{len(self._threads)}]")
                self._threads.append(t)
                t.start()
            self._queue.append(task)
            self._work.notify()
        return task

    def _retry_after_s(self) -> int:
        """Backoff hint for 429 rejections: how long the queue has been
        making tasks wait, rounded up (caller holds _lock)."""
        return min(30, 1 + int(self.queue_ewma_ms // 1000))

    def _worker(self) -> None:
        _tls.executor = self
        while True:
            with self._lock:
                while not self._queue and not self._shutdown:
                    self._idle += 1
                    self._work.wait()
                    self._idle -= 1
                if not self._queue and self._shutdown:
                    return
                task = self._queue.popleft()
                self.active += 1
                if self.active > self.largest:
                    self.largest = self.active
                t0 = time.monotonic()
                qw_ms = (t0 - task.submitted) * 1e3
                self.queue_ewma_ms = qw_ms if self.completed == 0 else \
                    (1 - _EWMA_ALPHA) * self.queue_ewma_ms \
                    + _EWMA_ALPHA * qw_ms
            # composed name: ad-hoc test pools fall outside the registry
            metrics.observe_if_declared(f"queue_wait.{self.name}", qw_ms)
            if task.trace is not None:
                task.trace.add_span(f"queue_wait.{self.name}", qw_ms)
            with tracing.activate(task.trace), \
                    _sched.activate_tier(task.tier), \
                    _taskmgr.activate(task.taskref):
                task.run()
            dt_ms = (time.monotonic() - t0) * 1e3
            with self._lock:
                self.active -= 1
                self.completed += 1
                self.ewma_ms = dt_ms if self.completed == 1 else \
                    (1 - _EWMA_ALPHA) * self.ewma_ms + _EWMA_ALPHA * dt_ms

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": self.size,
                "threads": len(self._threads),
                "queue": len(self._queue),
                "queue_size": self.queue_size,
                "active": self.active,
                "rejected": self.rejected,
                "largest": self.largest,
                "completed": self.completed,
                "ewma_ms": round(self.ewma_ms, 3),
                "queue_ewma_ms": round(self.queue_ewma_ms, 3),
            }

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._work.notify_all()


# ---- request -> pool classification (the REST layer's stage routing;
#      ref: the reference's per-action executor names in ActionModule) ----

_SEARCH_ENDPOINTS = {"_search", "_msearch", "_count", "_async_search",
                     "_pit", "_knn_search", "_search_shards", "_rank_eval",
                     "_field_caps", "_explain", "_validate", "_percolate",
                     "_terms_enum", "_scroll", "_search_scroll", "_render"}
_WRITE_ENDPOINTS = {"_bulk", "_update", "_delete_by_query",
                    "_update_by_query", "_reindex", "_create"}
_GET_ENDPOINTS = {"_source", "_mget", "_termvectors", "_mtermvectors"}


def pool_for_request(method: str, path: str) -> str:
    parts = set(p for p in path.split("?")[0].split("/") if p)
    if parts & _SEARCH_ENDPOINTS:
        return "search"
    if parts & _WRITE_ENDPOINTS:
        return "write"
    if "_doc" in parts:
        return "get" if method in ("GET", "HEAD") else "write"
    if parts & _GET_ENDPOINTS:
        return "get"
    if "_snapshot" in parts:
        return "snapshot"
    return "management"


# endpoints that are batch/scan-shaped even though they ride the search
# pool: their queries tolerate a wider scheduler pad, so they default to
# the bulk SLA tier
_BULK_SEARCH_ENDPOINTS = {"_msearch", "scroll", "_scroll", "_search_scroll",
                          "_async_search", "_rank_eval", "_terms_enum"}


def tier_for_request(method: str, path: str, params=None) -> str:
    """SLA-tier classification for the adaptive dispatch scheduler: an
    explicit `sla` request param wins; otherwise batch/scan endpoints and
    everything outside the latency-sensitive search/get pools are bulk,
    and interactive singles stay interactive."""
    sla = (params or {}).get("sla")
    if sla in (_sched.TIER_INTERACTIVE, _sched.TIER_BULK):
        return sla
    parts = set(p for p in path.split("?")[0].split("/") if p)
    if parts & _BULK_SEARCH_ENDPOINTS:
        return _sched.TIER_BULK
    if pool_for_request(method, path) in ("search", "get"):
        return _sched.TIER_INTERACTIVE
    return _sched.TIER_BULK


class ThreadPool:
    """The node-level set of named executors — ONE per node, shared by
    the HTTP frontend and the transport-action handlers (the same
    single-budget rule as the shared IndexingPressure: two pools would
    admit twice the work)."""

    POOL_NAMES = ("search", "write", "get", "management", "snapshot")

    def __init__(self, sizes: Optional[Dict[str, int]] = None,
                 queue_sizes: Optional[Dict[str, int]] = None):
        cpus = os.cpu_count() or 1
        defaults = {
            # (workers, queue) — the reference's fixed-pool shapes scaled
            # to this process (search: 3*cpus/2+1 q1000; write: cpus
            # q10000; get: cpus q1000; management/snapshot small)
            "search": (max(2, cpus * 3 // 2 + 1), 1000),
            "write": (max(1, cpus), 10000),
            "get": (max(1, cpus), 1000),
            "management": (2, 512),
            "snapshot": (1, 256),
        }
        self.executors: Dict[str, FixedExecutor] = {}
        for name, (size, queue) in defaults.items():
            size = (sizes or {}).get(name) or knob(
                f"ES_TPU_POOL_{name.upper()}_SIZE", default=size)
            queue = (queue_sizes or {}).get(name) or knob(
                f"ES_TPU_POOL_{name.upper()}_QUEUE", default=queue)
            self.executors[name] = FixedExecutor(name, size, queue)

    def executor(self, pool: str) -> FixedExecutor:
        return self.executors[pool]

    def submit(self, pool: str, fn: Callable, *args, **kwargs) -> _Task:
        return self.executors[pool].submit(fn, *args, **kwargs)

    def execute(self, pool: str, fn: Callable, *args, **kwargs):
        """Submit and wait. Re-entrant submissions from a worker of the
        SAME executor run inline — a stage calling itself must not wait
        on its own bounded pool (self-deadlock under saturation)."""
        ex = self.executors[pool]
        if getattr(_tls, "executor", None) is ex:
            return fn(*args, **kwargs)
        return ex.submit(fn, *args, **kwargs).get()

    def stats(self) -> Dict[str, dict]:
        return {name: ex.stats() for name, ex in self.executors.items()}

    def shutdown(self) -> None:
        for ex in self.executors.values():
            ex.shutdown()


# ---- hot threads (ref: monitor/jvm/HotThreads.java two-sample diff) ----

def _format_stack(frame, max_frames: int) -> list:
    import traceback

    return ["     " + ln for ln in traceback.format_stack(frame)[-max_frames:]]


def _is_parked_pool_stack(stack: list) -> bool:
    """An es-tpu pool worker blocked in its queue wait contributes
    nothing to a hot-threads reading — same filtering the reference
    applies to idle threadpool threads."""
    tail = "".join(stack[-3:])
    return "_worker" in tail and ("self._work.wait()" in tail
                                  or "waiter.acquire()" in tail)


def hot_threads_report(node_label: str,
                       interval_ms: Optional[float] = None,
                       max_frames: int = 12) -> str:
    """One node's hot_threads section: two stack samples `interval_ms`
    apart; a thread whose stack CHANGED between samples is hot, an
    es-tpu pool worker parked in its queue wait across both samples is
    dropped, and everything else prints as idle for context."""
    import sys

    if interval_ms is None:
        interval_ms = float(knob("ES_TPU_HOT_THREADS_INTERVAL_MS"))
    names = {t.ident: t.name for t in threading.enumerate()}
    first = {tid: _format_stack(f, max_frames)
             for tid, f in sys._current_frames().items()}
    time.sleep(max(0.0, float(interval_ms)) / 1000.0)
    second = {tid: _format_stack(f, max_frames)
              for tid, f in sys._current_frames().items()}
    out = [f"::: {node_label}",
           f"   interval={interval_ms:g}ms, "
           f"sampled {len(second)} threads:"]
    for tid, stack in sorted(second.items()):
        name = names.get(tid, str(tid))
        pooled = str(name).startswith("es-tpu[")
        changed = first.get(tid) != stack
        if pooled and not changed and _is_parked_pool_stack(stack):
            continue
        state = "hot" if changed else "idle"
        out.append(f"\n   {state} thread [{name}] id [{tid}]:")
        out.extend(ln.rstrip("\n") for ln in stack)
    return "\n".join(out) + "\n"

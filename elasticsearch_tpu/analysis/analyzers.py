"""Text analysis: tokenizers + token-filter chains.

Host-side (indexing is CPU work in this design; ref SURVEY.md §3.3 — JSON
parse + analysis is the host hot loop). Mirrors the reference's analyzer
registry model (ref: index/analysis/AnalysisRegistry.java and the
analysis-common module's standard/whitespace/keyword/stop analyzers) without
its class explosion: an Analyzer is a tokenizer function plus a list of
token-filter functions; custom analyzers are assembled from named parts.

Tokens carry positions (for phrase queries) and offsets (for highlighting).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Iterable, List

from elasticsearch_tpu.common.errors import IllegalArgumentError

# Reference standard tokenizer is UAX#29 word-break; this regex covers the
# alnum word segmentation that matters for scoring parity on English corpora.
_WORD_RE = re.compile(r"[0-9A-Za-z_À-ɏЀ-ӿ؀-ۿ一-鿿]+")
_WS_RE = re.compile(r"\S+")
_LETTER_RE = re.compile(r"[A-Za-zÀ-ɏЀ-ӿ]+")

ENGLISH_STOPWORDS = frozenset(
    """a an and are as at be but by for if in into is it no not of on or such
    that the their then there these they this to was will with""".split()
)


@dataclass
class Token:
    term: str
    position: int
    start_offset: int
    end_offset: int


TokenFilter = Callable[[Iterable[Token]], Iterable[Token]]


def lowercase_filter(tokens: Iterable[Token]) -> Iterable[Token]:
    for t in tokens:
        t.term = t.term.lower()
        yield t


def make_stop_filter(stopwords: frozenset[str]) -> TokenFilter:
    def stop(tokens: Iterable[Token]) -> Iterable[Token]:
        # Positions are preserved across removed stopwords (position gaps),
        # matching the reference's StopFilter posInc behaviour.
        for t in tokens:
            if t.term not in stopwords:
                yield t

    return stop


def make_length_filter(min_len: int, max_len: int) -> TokenFilter:
    def length(tokens: Iterable[Token]) -> Iterable[Token]:
        for t in tokens:
            if min_len <= len(t.term) <= max_len:
                yield t

    return length


_ASCII_FOLD = str.maketrans(
    "àáâãäåçèéêëìíîïñòóôõöùúûüýÿÀÁÂÃÄÅÇÈÉÊËÌÍÎÏÑÒÓÔÕÖÙÚÛÜÝ",
    "aaaaaaceeeeiiiinooooouuuuyyAAAAAACEEEEIIIINOOOOOUUUUY",
)


def asciifolding_filter(tokens: Iterable[Token]) -> Iterable[Token]:
    for t in tokens:
        t.term = t.term.translate(_ASCII_FOLD)
        yield t


class Analyzer:
    def __init__(self, name: str, token_re: re.Pattern | None, filters: List[TokenFilter]):
        self.name = name
        self._token_re = token_re  # None => emit whole input as one token
        self._filters = filters

    def tokenize(self, text: str) -> List[Token]:
        if self._token_re is None:
            tokens: Iterable[Token] = [Token(text, 0, 0, len(text))] if text else []
        else:
            tokens = (
                Token(m.group(0), pos, m.start(), m.end())
                for pos, m in enumerate(self._token_re.finditer(text))
            )
        for f in self._filters:
            tokens = f(tokens)
        return list(tokens)

    def terms(self, text: str) -> List[str]:
        return [t.term for t in self.tokenize(text)]


def StandardAnalyzer() -> Analyzer:
    return Analyzer("standard", _WORD_RE, [lowercase_filter])


def WhitespaceAnalyzer() -> Analyzer:
    return Analyzer("whitespace", _WS_RE, [])


def KeywordAnalyzer() -> Analyzer:
    return Analyzer("keyword", None, [])


def SimpleAnalyzer() -> Analyzer:
    return Analyzer("simple", _LETTER_RE, [lowercase_filter])


def StopAnalyzer(stopwords: frozenset[str] = ENGLISH_STOPWORDS) -> Analyzer:
    return Analyzer("stop", _LETTER_RE, [lowercase_filter, make_stop_filter(stopwords)])


class AnalysisRegistry:
    """Named analyzers per index, with custom-analyzer assembly from settings.

    Ref: index/analysis/AnalysisRegistry.java:46. Custom analyzers are defined
    in index settings as {"tokenizer": ..., "filter": [...]}.
    """

    _BUILTIN = {
        "standard": StandardAnalyzer,
        "whitespace": WhitespaceAnalyzer,
        "keyword": KeywordAnalyzer,
        "simple": SimpleAnalyzer,
        "stop": StopAnalyzer,
    }

    _TOKENIZERS = {
        "standard": _WORD_RE,
        "whitespace": _WS_RE,
        "letter": _LETTER_RE,
        "keyword": None,
    }

    def __init__(self, analyzer_settings: dict | None = None):
        self._analyzers: dict[str, Analyzer] = {}
        for name, config in (analyzer_settings or {}).items():
            self._analyzers[name] = self._build_custom(name, config)

    def _build_custom(self, name: str, config: dict) -> Analyzer:
        if config.get("type") in self._BUILTIN:
            return self._BUILTIN[config["type"]]()
        tokenizer = config.get("tokenizer", "standard")
        if tokenizer not in self._TOKENIZERS:
            raise IllegalArgumentError(f"failed to find tokenizer [{tokenizer}] for analyzer [{name}]")
        filters: List[TokenFilter] = []
        for fname in config.get("filter", []):
            if fname == "lowercase":
                filters.append(lowercase_filter)
            elif fname == "stop":
                filters.append(make_stop_filter(ENGLISH_STOPWORDS))
            elif fname == "asciifolding":
                filters.append(asciifolding_filter)
            else:
                raise IllegalArgumentError(f"failed to find filter [{fname}] for analyzer [{name}]")
        return Analyzer(name, self._TOKENIZERS[tokenizer], filters)

    def get(self, name: str) -> Analyzer:
        if name in self._analyzers:
            return self._analyzers[name]
        builder = self._BUILTIN.get(name)
        if builder is None:
            raise IllegalArgumentError(f"failed to find analyzer [{name}]")
        analyzer = builder()
        self._analyzers[name] = analyzer
        return analyzer

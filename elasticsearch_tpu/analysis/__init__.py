from elasticsearch_tpu.analysis.analyzers import (
    Analyzer,
    AnalysisRegistry,
    Token,
    StandardAnalyzer,
    WhitespaceAnalyzer,
    KeywordAnalyzer,
    SimpleAnalyzer,
    StopAnalyzer,
    ENGLISH_STOPWORDS,
)

__all__ = [
    "Analyzer",
    "AnalysisRegistry",
    "Token",
    "StandardAnalyzer",
    "WhitespaceAnalyzer",
    "KeywordAnalyzer",
    "SimpleAnalyzer",
    "StopAnalyzer",
    "ENGLISH_STOPWORDS",
]

"""Distributed shard instances: replicated writes, peer recovery, resync —
all over the transport.

This is the node-local half of the distributed spine. The reference spreads
it across IndexShard (op application, ref: index/shard/IndexShard.java:798
applyIndexOperationOnPrimary / :807 OnReplica), the replication template
(ref: action/support/replication/ReplicationOperation.java:99 — primary
executes, fans to in-sync replicas, collects acks, fails stale copies via
the master), peer recovery (ref:
indices/recovery/RecoverySourceHandler.java:139 recoverToTarget — file
phase1 + ops phase2 + finalize; PeerRecoveryTargetService.java), and the
primary-replica syncer (ref: index/shard/PrimaryReplicaSyncer.java). Here
one service owns the shard registry and registers every shard-level
transport action; the cluster-state applier (cluster_state_service.py)
drives lifecycle.

Recovery is TARGET-DRIVEN (pull): the new replica asks the primary to
track it, pulls the segment snapshot (the segment IS the recovery file),
replays the op tail, then finalizes. Pull keeps every step idempotent, so
an interrupted recovery simply restarts.
"""

from __future__ import annotations

import base64
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.common import integrity
from elasticsearch_tpu.common.durability import count as _count
from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuError, VersionConflictError,
)
from elasticsearch_tpu.common.faults import corruption_fires
from elasticsearch_tpu.common.integrity import SegmentCorruptedError
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.cluster.state import ClusterState, IndexMetadata, ShardRouting
from elasticsearch_tpu.index.engine import InternalEngine
from elasticsearch_tpu.index.segment_io import blob_hash
from elasticsearch_tpu.index.replication import resync_target_apply
from elasticsearch_tpu.index.seqno import NO_OPS_PERFORMED, ReplicationTracker
from elasticsearch_tpu.index.translog import (
    TranslogCorruptedError, TranslogFsyncError,
)
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.transport.channels import (
    NodeChannels, NodeUnavailableError, RpcTimeoutError,
)
from elasticsearch_tpu.transport.service import TransportService


def _ops_bytes(ops) -> int:
    """Byte estimate of a bulk ops payload for IndexingPressure accounting
    (source sizes dominate; metadata gets a flat allowance)."""
    import json as _json

    total = 0
    for op in ops:
        src = op.get("source")
        total += 64 + (len(_json.dumps(src)) if src is not None else 0)
    return total


class ShardNotFoundError(ElasticsearchTpuError):
    status = 404
    error_type = "shard_not_found_exception"


class PrimaryTermMismatchError(ElasticsearchTpuError):
    status = 409
    error_type = "illegal_index_shard_state_exception"


@dataclass
class ShardInstance:
    """One local shard copy (ref: index/shard/IndexShard.java state)."""

    index: str
    shard_id: int
    allocation_id: str
    primary: bool
    primary_term: int
    engine: InternalEngine
    mapper: MapperService
    tracker: Optional[ReplicationTracker] = None      # primary only
    # replica-side view of the primary's global checkpoint, refreshed on
    # every replicated write (ref: GlobalCheckpointSyncAction) — the
    # rollback point if this copy is promoted
    known_global_checkpoint: int = NO_OPS_PERFORMED
    state: str = "INITIALIZING"                       # mirrors routing state
    lock: threading.RLock = field(default_factory=threading.RLock)


def build_mapper(meta: IndexMetadata) -> MapperService:
    nested = meta.settings.as_nested_dict()
    try:
        analyzers = nested["index"]["analysis"]["analyzer"]
    except (KeyError, TypeError):
        analyzers = {}
    return MapperService(dict(meta.mappings), AnalysisRegistry(analyzers))


class DistributedShardService:
    """Registry of local shard copies + shard-level transport actions."""

    def __init__(self, node_name: str, transport: TransportService,
                 channels: NodeChannels,
                 master_client: Callable[[str, dict], dict],
                 data_path: Optional[str] = None,
                 indexing_pressure=None, thread_pool=None, tasks=None,
                 overload=None):
        self.node_name = node_name
        self.transport = transport
        self.channels = channels
        self.master_client = master_client
        self.data_path = data_path
        # node TaskManager: primary-bulk handlers register child tasks
        # under the coordinator's `_parent_task` payload field when wired
        self.tasks = tasks
        # overload controller (common/overload.py): bulk-tier admission at
        # the primary-bulk handler + the replication retry budget
        self.overload = overload
        self.shards: Dict[Tuple[str, int], ShardInstance] = {}
        self.state: ClusterState = ClusterState()
        self._registry_lock = threading.Lock()
        from elasticsearch_tpu.common.indexing_pressure import IndexingPressure
        from elasticsearch_tpu.threadpool import ThreadPool

        # per-node write backpressure (ref: index/IndexingPressure.java) —
        # injectable so all of a node's stages share ONE budget
        self.indexing_pressure = indexing_pressure or IndexingPressure()
        # injectable for the same reason: the bulk stages execute on the
        # node's WRITE pool so a bulk storm is bounded by write workers
        # and cannot occupy the search stage (ref: ThreadPool.Names.WRITE)
        self.thread_pool = thread_pool or ThreadPool()
        t = transport
        t.register_request_handler(
            "indices:data/write/bulk[s]",
            lambda req: self.thread_pool.execute(
                "write", self._on_primary_bulk, req))
        t.register_request_handler(
            "indices:data/write/bulk[s][r]",
            lambda req: self.thread_pool.execute(
                "write", self._on_replica_bulk, req))
        t.register_request_handler("internal:index/shard/recovery/prepare",
                                   self._on_recovery_prepare)
        t.register_request_handler("internal:index/shard/recovery/segments",
                                   self._on_recovery_segments)
        t.register_request_handler("internal:index/shard/recovery/ops",
                                   self._on_recovery_ops)
        t.register_request_handler("internal:index/shard/recovery/finalize",
                                   self._on_recovery_finalize)
        t.register_request_handler("internal:index/shard/recovery/cancel",
                                   self._on_recovery_cancel)
        t.register_request_handler("internal:index/shard/resync/prepare",
                                   self._on_resync_prepare)
        t.register_request_handler("internal:index/shard/resync/apply",
                                   self._on_resync_apply)
        t.register_request_handler(
            "internal:index/shard/relocation/warm_info",
            self._on_relocation_warm_info)

    # ---------------- registry ----------------

    def get_shard(self, index: str, shard_id: int) -> ShardInstance:
        inst = self.shards.get((index, shard_id))
        if inst is None:
            raise ShardNotFoundError(
                f"no shard [{index}][{shard_id}] on node [{self.node_name}]")
        return inst

    def create_shard(self, meta: IndexMetadata,
                     routing: ShardRouting) -> ShardInstance:
        import os

        mapper = build_mapper(meta)
        path = None
        if self.data_path is not None:
            path = os.path.join(self.data_path, meta.index,
                                str(routing.shard_id))
        durability = meta.settings.raw("index.translog.durability", "request")
        marker = integrity.corruption_marker(path) if path else None
        if marker is not None:
            # a previous incarnation of this copy failed checksum
            # verification and dropped a corrupted-* marker: the store must
            # never serve again as-is. A replica quarantines it and
            # re-bootstraps via peer recovery; a primary assignment is
            # refused outright (the master must pick a healthy copy).
            if routing.primary:
                raise SegmentCorruptedError(
                    f"store [{path}] is marked corrupted: "
                    f"{marker.get('reason')}")
            self._quarantine_store(path)
        try:
            engine = InternalEngine(
                mapper, data_path=path,
                primary_term=meta.primary_term(routing.shard_id),
                translog_durability=durability)
        except (TranslogCorruptedError, SegmentCorruptedError):
            # a replica's store is expendable: quarantine the damaged dir and
            # re-bootstrap empty via peer recovery (ref: the reference drops
            # a corrupt replica store and recovers from the primary). A
            # primary has nothing to recover FROM — surface the corruption.
            if routing.primary or path is None:
                raise
            self._quarantine_store(path)
            engine = InternalEngine(
                mapper, data_path=path,
                primary_term=meta.primary_term(routing.shard_id),
                translog_durability=durability)
        inst = ShardInstance(
            index=meta.index, shard_id=routing.shard_id,
            allocation_id=routing.allocation_id, primary=routing.primary,
            primary_term=meta.primary_term(routing.shard_id),
            engine=engine, mapper=mapper)
        if routing.primary:
            inst.tracker = ReplicationTracker(routing.allocation_id)
            inst.tracker.update_local_checkpoint(
                routing.allocation_id, engine.local_checkpoint)
        with self._registry_lock:
            self.shards[(meta.index, routing.shard_id)] = inst
        return inst

    @staticmethod
    def _quarantine_store(path: str) -> None:
        """Move a damaged store (and its corrupted-* marker) aside so a
        fresh peer recovery can rebuild into a clean directory."""
        import os
        import shutil

        if not os.path.isdir(path):
            return
        shutil.rmtree(path + ".corrupt", ignore_errors=True)
        os.rename(path, path + ".corrupt")
        _count("store_corruptions_discarded")
        integrity.count("copies_quarantined")

    def remove_shard(self, index: str, shard_id: int) -> None:
        with self._registry_lock:
            inst = self.shards.pop((index, shard_id), None)
        if inst is not None:
            inst.engine.close()

    # ---------------- write path (primary side) ----------------

    def _overload_ctl(self):
        if self.overload is None:
            from elasticsearch_tpu.common.overload import default_overload

            self.overload = default_overload()
        return self.overload

    def _on_primary_bulk(self, req) -> dict:
        from elasticsearch_tpu.tasks import task_manager as _taskmgr

        p = req.payload
        # bulk-tier admission BEFORE any op is applied: a YELLOW node
        # sheds the whole shard-bulk with 429 + Retry-After; nothing was
        # written, nothing acked, so the coordinator can fail the items
        # cleanly (replica/recovery paths are never shed — they finish
        # work the primary already admitted)
        ov = self.overload
        if ov is not None:
            retry_after = ov.admit("bulk")
            if retry_after is not None:
                from elasticsearch_tpu.threadpool import (
                    EsRejectedExecutionError,
                )

                raise EsRejectedExecutionError(
                    f"[{self.node_name}] overload shed "
                    f"({ov.stats()['level']}): bulk-tier shard write "
                    f"[{p['index']}][{p['shard_id']}]",
                    node=self.node_name, tier="bulk",
                    retry_after_s=retry_after)
        child = None
        if self.tasks is not None and p.get("_parent_task"):
            # child write task linked by the coordinator's `_parent_task`
            # payload field (next to the op list, never inside an op)
            child = self.tasks.register(
                "indices:data/write/bulk[s]",
                f"shard [{p['index']}][{p['shard_id']}] "
                f"ops[{len(p['ops'])}]",
                parent_task_id=p["_parent_task"])
        try:
            with _taskmgr.activate(child):
                if child is not None:
                    # ban raced this registration: reject before any op
                    # is applied (the coordinator fails these items)
                    child.check()
                    child.note_dispatch(phase="bulk")
                return self._primary_bulk_inner(req)
        finally:
            if child is not None:
                self.tasks.unregister(child)

    def _primary_bulk_inner(self, req) -> dict:
        p = req.payload
        inst = self.get_shard(p["index"], p["shard_id"])
        if not inst.primary:
            raise ShardNotFoundError(
                f"shard [{p['index']}][{p['shard_id']}] on "
                f"[{self.node_name}] is not the primary")
        req_term = p.get("primary_term")
        if req_term is not None and req_term < inst.primary_term:
            # the coordinator routed with a stale cluster state; make it retry
            raise PrimaryTermMismatchError(
                f"request term [{req_term}] below current "
                f"[{inst.primary_term}]")
        ops_bytes = p.get("ops_bytes") or _ops_bytes(p["ops"])
        try:
            with self.indexing_pressure.primary(ops_bytes), inst.lock:
                results: List[dict] = []
                rep_ops: List[dict] = []
                for op in p["ops"]:
                    try:
                        if op["op"] in ("index", "create"):
                            r = inst.engine.index(
                                op["id"], op["source"], op_type=op["op"],
                                if_seq_no=op.get("if_seq_no"),
                                if_primary_term=op.get("if_primary_term"))
                            status = 201 if r.result == "created" else 200
                        else:
                            r = inst.engine.delete(
                                op["id"],
                                if_seq_no=op.get("if_seq_no"),
                                if_primary_term=op.get("if_primary_term"))
                            status = 404 if r.result == "not_found" else 200
                        results.append({"_id": r.doc_id, "_version": r.version,
                                        "_seq_no": r.seq_no,
                                        "_primary_term": r.primary_term,
                                        "result": r.result, "status": status})
                        if r.result != "not_found":
                            rep_ops.append({
                                "op": "delete" if op["op"] == "delete" else "index",
                                "id": op["id"], "source": op.get("source"),
                                "seq_no": r.seq_no})
                    except VersionConflictError as e:
                        results.append({"_id": op["id"], "status": 409,
                                        "error": e.to_dict()})
                self._replicate(inst, rep_ops, ops_bytes)
                inst.tracker.update_local_checkpoint(
                    inst.allocation_id, inst.engine.local_checkpoint)
                return {"results": results,
                        "local_checkpoint": inst.engine.local_checkpoint,
                        "global_checkpoint": inst.tracker.global_checkpoint}
        except TranslogFsyncError as e:
            # the WAL could not persist the op: NEVER ack into a broken
            # translog. Fail this primary copy via the master (promotion /
            # reallocation follow from apply_failed_shard's reroute) and let
            # the coordinator retry against the new primary. Reported
            # outside inst.lock: the state-store applier chain runs
            # synchronously and re-enters shard locks.
            _count("fsync_shard_failures")
            self._report_shard_failed(
                inst.index, inst.shard_id, inst.allocation_id,
                f"translog fsync failed: {e}")
            raise

    def _replicate(self, inst: ShardInstance, rep_ops: List[dict],
                   ops_bytes: Optional[int] = None) -> None:
        """Fan one op batch to every assigned copy (ref:
        ReplicationOperation.java:137 performOnReplicas). A TRANSIENT
        transport blip gets exactly one immediate retry; a persistent
        failure of an in-sync copy -> remove_tracking + shard-failed to the
        master. A still-recovering copy may miss writes (recovery's finalize
        gap replay covers it)."""
        if not rep_ops:
            return
        state = self.state
        gcp = inst.tracker.global_checkpoint
        for r in state.shard_copies(inst.index, inst.shard_id):
            if r.node_id is None or r.state == "UNASSIGNED":
                continue
            # skip SELF by allocation id, not by the primary flag: during a
            # primary relocation the target carries the primary flag in
            # routing but must receive every replicated write until the swap
            if r.allocation_id == inst.allocation_id:
                continue
            in_sync = r.allocation_id in inst.tracker.in_sync_ids
            payload = {"index": inst.index, "shard_id": inst.shard_id,
                       "primary_term": inst.primary_term, "ops": rep_ops,
                       "ops_bytes": ops_bytes, "global_checkpoint": gcp}
            try:
                resp = self._replica_request(r.node_id, payload)
                inst.tracker.update_local_checkpoint(
                    r.allocation_id, resp["local_checkpoint"])
            except Exception as e:  # noqa: BLE001 — any failure fails the copy
                if in_sync:
                    inst.tracker.remove_tracking(r.allocation_id)
                    _count("replication_failures")
                    self._report_shard_failed(inst.index, inst.shard_id,
                                              r.allocation_id, str(e))

    def _replica_request(self, node_id: str, payload: dict) -> dict:
        """One replica-bulk RPC with a single transient retry: a transport
        blip (channel mid-reconnect, injected `rpc_replica_bulk` fault) must
        not cost an in-sync copy; anything that fails twice — or fails
        inside the replica (an application error) — escalates."""
        try:
            resp = self.channels.request(
                node_id, "indices:data/write/bulk[s][r]", payload)
        except (NodeUnavailableError, RpcTimeoutError):
            if not self._overload_ctl().retry_allowed("replication"):
                # retry budget exhausted: escalate the organic transport
                # error instead of doubling the replication storm
                raise
            _count("replication_retries")
            resp = self.channels.request(
                node_id, "indices:data/write/bulk[s][r]", payload)
        self._overload_ctl().note_success()
        return resp

    def _report_shard_failed(self, index: str, shard_id: int,
                             allocation_id: str, reason: str) -> None:
        try:
            self.master_client("internal:cluster/shard/failed",
                               {"index": index, "shard_id": shard_id,
                                "allocation_id": allocation_id,
                                "reason": reason})
        except Exception:  # noqa: BLE001 — master unreachable; next state
            pass           # application reconciles

    # ---------------- write path (replica side) ----------------

    def _on_replica_bulk(self, req) -> dict:
        p = req.payload
        inst = self.get_shard(p["index"], p["shard_id"])
        term = p["primary_term"]
        if term < inst.primary_term:
            raise PrimaryTermMismatchError(
                f"replication from deposed primary (term [{term}] < "
                f"[{inst.primary_term}])")
        ops_bytes = p.get("ops_bytes") or _ops_bytes(p["ops"])
        with self.indexing_pressure.replica(ops_bytes), inst.lock:
            inst.primary_term = max(inst.primary_term, term)
            for op in p["ops"]:
                if op["op"] == "index":
                    inst.engine.index(op["id"], op["source"],
                                      seq_no=op["seq_no"],
                                      op_primary_term=term)
                else:
                    inst.engine.delete(op["id"], seq_no=op["seq_no"],
                                       op_primary_term=term)
            inst.known_global_checkpoint = max(
                inst.known_global_checkpoint,
                p.get("global_checkpoint", NO_OPS_PERFORMED))
            return {"local_checkpoint": inst.engine.local_checkpoint}

    # ---------------- peer recovery: source handlers ----------------

    def _on_recovery_prepare(self, req) -> dict:
        p = req.payload
        inst = self.get_shard(p["index"], p["shard_id"])
        if not inst.primary:
            raise ShardNotFoundError("recovery source must be the primary")
        with inst.lock:
            # phase0: track the target so concurrent writes reach it from
            # now on (ref: RecoverySourceHandler add to replication group)
            inst.tracker.add_tracking(p["target_allocation_id"])
            return {"primary_term": inst.primary_term,
                    "global_checkpoint": inst.tracker.global_checkpoint}

    def _on_recovery_segments(self, req) -> dict:
        p = req.payload
        inst = self.get_shard(p["index"], p["shard_id"])
        with inst.lock:
            # snapshot under the shard lock so the blob + live mask + the
            # max_seq_no it is stamped with form one consistent point in
            # time (a concurrent bulk holds the same lock)
            payloads, max_seq_no = inst.engine.segment_payloads()
        segments = []
        for blob, live in payloads:
            # the advertised hash is computed BEFORE the wire: an injected
            # `segment_transfer` clause damages the payload after it (bit
            # rot in transit), so the hash stays pristine and the TARGET
            # must detect the mismatch and re-fetch
            digest = blob_hash(blob)
            if corruption_fires(self.node_name, site="segment_transfer"):
                blob = integrity.bitflip(blob)
            segments.append({"blob": base64.b64encode(blob).decode("ascii"),
                             "live": live.tolist(), "hash": digest})
        return {"segments": segments, "max_seq_no": max_seq_no}

    def _on_recovery_ops(self, req) -> dict:
        p = req.payload
        inst = self.get_shard(p["index"], p["shard_id"])
        with inst.lock:
            # same consistency argument as segments: the op tail and the
            # max_seq_no / term shipped with it must agree
            out = {"ops": inst.engine.changes_since(p["above_seq_no"]),
                   "max_seq_no": inst.engine.max_seq_no,
                   "primary_term": inst.primary_term}
            if p.get("divergent"):
                # a restarted target is rolling its divergent tail back to
                # the global checkpoint (same machinery as primary-failover
                # resync): ship the authoritative state of each such doc
                out["doc_states"] = {d: inst.engine.doc_resync_state(d)
                                     for d in p["divergent"]}
            return out

    def _on_recovery_cancel(self, req) -> dict:
        """A recovery target died or gave up mid-flight: drop its tracking
        so the global checkpoint is not pinned by a ghost copy forever
        (ref: RecoverySourceHandler cancel + ReplicationTracker's removal of
        failed/relocated copies). Idempotent; in-sync copies are never
        touched — those are the master's to fail."""
        p = req.payload
        inst = self.get_shard(p["index"], p["shard_id"])
        aid = p["target_allocation_id"]
        with inst.lock:
            cleaned = (aid in inst.tracker.tracked_ids
                       and aid not in inst.tracker.in_sync_ids)
            if cleaned:
                inst.tracker.remove_tracking(aid)
                _count("ghost_cleanups")
            return {"cleaned": cleaned}

    def _on_recovery_finalize(self, req) -> dict:
        p = req.payload
        inst = self.get_shard(p["index"], p["shard_id"])
        with inst.lock:
            # the lock is the linearization point: any write that failed to
            # reach the (not-yet-in-sync) target is visible here as a gap
            # above the target's checkpoint; ship it before marking in-sync
            gap_ops = inst.engine.changes_since(p["local_checkpoint"])
            inst.tracker.update_local_checkpoint(
                p["target_allocation_id"], p["local_checkpoint"])
            inst.tracker.mark_in_sync(p["target_allocation_id"])
            return {"gap_ops": gap_ops,
                    "max_seq_no": inst.engine.max_seq_no,
                    "primary_term": inst.primary_term,
                    "global_checkpoint": inst.tracker.global_checkpoint}

    # ---------------- peer recovery: target routine ----------------

    def recover_replica(self, inst: ShardInstance) -> None:
        """Pull-based replica bootstrap from the primary node (ref:
        indices/recovery/PeerRecoveryTargetService.java doRecovery).
        Raises on failure; caller may retry (every step is idempotent).

        Failure after prepare sends a best-effort recovery/cancel to the
        source so the tracking added for this copy does not linger as a
        ghost pinning the primary's global checkpoint."""
        state = self.state
        primary = state.primary_of(inst.index, inst.shard_id)
        # a RELOCATING primary is still the serving copy (and the only
        # legal recovery source while its own move is in flight)
        if primary is None or primary.node_id is None \
                or not primary.serving:
            raise ShardNotFoundError(
                f"no started primary for [{inst.index}][{inst.shard_id}]")
        source = primary.node_id
        shard_ref = {"index": inst.index, "shard_id": inst.shard_id}
        _count("recoveries_started")
        prep = self.channels.request(
            source, "internal:index/shard/recovery/prepare",
            {**shard_ref, "target_allocation_id": inst.allocation_id,
             "target_node": self.node_name})
        try:
            self._recover_replica_tracked(inst, source, shard_ref, prep)
        except Exception:
            _count("recoveries_failed")
            try:
                self.channels.request(
                    source, "internal:index/shard/recovery/cancel",
                    {**shard_ref,
                     "target_allocation_id": inst.allocation_id})
            except Exception:  # noqa: BLE001 — best effort; if the source
                pass           # is gone its tracker died with it
            raise

    def _recover_replica_tracked(self, inst: ShardInstance, source: str,
                                 shard_ref: dict, prep: dict) -> None:
        """The phases that run while the source tracks this copy."""
        # captured BEFORE phase1: a freshly installed snapshot raises
        # max_seq_no above the shipped global checkpoint without any
        # divergence — only pre-existing local history can diverge
        was_empty = inst.engine.max_seq_no == NO_OPS_PERFORMED
        inst.primary_term = max(inst.primary_term, prep["primary_term"])
        inst.engine.advance_primary_term(prep["primary_term"])
        # phase1 (file phase): install the segment snapshot when this copy
        # is empty — segments are the recovery files
        if was_empty:
            seg_resp = self._fetch_verified_segments(source, shard_ref)
            for seg in seg_resp["segments"]:
                inst.engine.install_segment(
                    base64.b64decode(seg["blob"]), seg["live"])
            inst.engine.fill_seqno_gaps(seg_resp["max_seq_no"])
        if not was_empty \
                and inst.engine.max_seq_no > prep["global_checkpoint"]:
            # a restarted copy may hold a divergent tail: ops above the
            # global checkpoint acked by a deposed primary but absent from
            # the current one. Roll back to the checkpoint with the SAME
            # machinery promotion resync uses, then replay forward.
            gcp = prep["global_checkpoint"]
            divergent = inst.engine.docs_above(gcp)
            replay_from = min(gcp, inst.engine.local_checkpoint)
            ops_resp = self.channels.request(
                source, "internal:index/shard/recovery/ops",
                {**shard_ref, "above_seq_no": replay_from,
                 "divergent": divergent})
            with inst.lock:
                resync_target_apply(
                    inst.engine, prep["primary_term"],
                    ops_resp.get("doc_states", {}), replay_from,
                    ops_resp["ops"], ops_resp["max_seq_no"])
        else:
            # phase2 (ops phase): replay history above what we hold
            ops_resp = self.channels.request(
                source, "internal:index/shard/recovery/ops",
                {**shard_ref, "above_seq_no": inst.engine.local_checkpoint})
            self._apply_recovery_ops(inst, ops_resp["ops"],
                                     ops_resp["primary_term"])
            inst.engine.fill_seqno_gaps(ops_resp["max_seq_no"])
        # finalize: source marks us in-sync and ships any writes that missed
        # us while we were not yet required
        fin = self.channels.request(
            source, "internal:index/shard/recovery/finalize",
            {**shard_ref, "target_allocation_id": inst.allocation_id,
             "local_checkpoint": inst.engine.local_checkpoint})
        self._apply_recovery_ops(inst, fin["gap_ops"], fin["primary_term"])
        inst.engine.fill_seqno_gaps(fin["max_seq_no"])
        inst.known_global_checkpoint = max(
            inst.known_global_checkpoint, fin["global_checkpoint"])
        inst.engine.flush()

    def _fetch_verified_segments(self, source: str, shard_ref: dict) -> dict:
        """Phase1 fetch with in-flight verification: every segment payload
        is re-hashed against the hash the source advertised (computed on
        the source BEFORE the wire). A mismatch means transfer corruption —
        re-fetch immediately, bounded by `ES_TPU_RECOVERY_RETRIES`, counted
        under `transfer_retries` (SEPARATE from the node-unavailable retry
        loop in cluster_state_service, which handles dead sources)."""
        retries = max(0, int(knob("ES_TPU_RECOVERY_RETRIES")))
        attempt = 0
        while True:
            resp = self.channels.request(
                source, "internal:index/shard/recovery/segments", shard_ref)
            clean = True
            for seg in resp["segments"]:
                want = seg.get("hash")
                if want is None:
                    continue   # pre-integrity source: nothing to check
                if blob_hash(base64.b64decode(seg["blob"])) != want:
                    clean = False
                    break
                integrity.count("transfer_hashes_verified")
            if clean:
                return resp
            integrity.count("transfer_corruptions")
            if attempt >= retries:
                raise SegmentCorruptedError(
                    f"recovery segment payload from [{source}] failed hash "
                    f"verification {attempt + 1}x (transfer corruption)")
            attempt += 1
            integrity.count("transfer_retries")

    @staticmethod
    def _apply_recovery_ops(inst: ShardInstance, ops: List[dict],
                            term: int) -> None:
        for op in ops:
            if op["op"] == "index":
                inst.engine.index(op["id"], op.get("source"),
                                  seq_no=op["seq_no"], op_primary_term=term)
            else:
                inst.engine.delete(op["id"], seq_no=op["seq_no"],
                                   op_primary_term=term)

    # ---------------- relocation: warm HBM handoff ----------------

    class _WarmView:
        """Minimal index-service view over one shard instance, shaped like
        the search action's _ShardView so the ServingContext built here is
        the SAME object the query path reuses after the swap."""

        def __init__(self, inst):
            self.shards = [inst.engine]
            self.mapper = inst.mapper
            self.name = inst.index

    def _on_relocation_warm_info(self, req) -> dict:
        """Relocation source side: report which fields this copy actually
        served (the per-field engines its serving snapshot built) and the
        process's hot dispatch shapes from the compile-cache introspection,
        so the target can prime before taking traffic."""
        from elasticsearch_tpu.common import hbm_ledger

        p = req.payload
        inst = self.get_shard(p["index"], p["shard_id"])
        fields: List[str] = []
        sparse_terms: Dict[str, List[str]] = {}
        ctx = getattr(inst, "_serving_ctx", None)
        snap = getattr(ctx, "_snapshot", None) if ctx is not None else None
        if snap is not None:
            fields = sorted(getattr(snap, "_bm", {}))
            # the hot cold-tier: terms with resident eager-sparse slices,
            # so the target can pre-slice them instead of rebuilding under
            # first-query latency
            for field in fields:
                eng = snap.engine(field)
                if eng is not None and hasattr(eng, "sparse_hot_terms"):
                    terms = eng.sparse_hot_terms()
                    if terms:
                        sparse_terms[field] = terms
        return {"fields": fields, "shapes": hbm_ledger.hot_shapes(),
                "sparse_terms": sparse_terms}

    def warm_relocation_handoff(self, inst: ShardInstance,
                                source_node: str) -> None:
        """Target side, after recovery and before shard-started: register
        the engine, upload columns, and prime the compile cache with the
        source's hot shapes (extend_qc_sizes), so the relocated shard never
        serves its first query cold. Best-effort — any failure leaves the
        relocation correct-but-cold (ES_TPU_RELOC_WARM=0 skips it
        entirely)."""
        from elasticsearch_tpu.common.relocation import count as _rcount
        from elasticsearch_tpu.common.settings import knob

        if not knob("ES_TPU_RELOC_WARM"):
            return
        t0 = time.monotonic()
        try:
            info = self.channels.request(
                source_node, "internal:index/shard/relocation/warm_info",
                {"index": inst.index, "shard_id": inst.shard_id})
            from elasticsearch_tpu.search.serving import ServingContext

            ctx = getattr(inst, "_serving_ctx", None)
            if ctx is None:
                ctx = ServingContext(self._WarmView(inst))
                inst._serving_ctx = ctx
            snap = ctx.snapshot()
            sizes = sorted({s for sizes in info["shapes"].values()
                            for s in sizes})
            warmed = 0
            primed = 0
            for field in info["fields"]:
                eng = snap.engine(field)
                if eng is None:
                    continue
                warmed += 1
                if sizes and hasattr(eng, "extend_qc_sizes"):
                    eng.extend_qc_sizes(sizes)
                    primed += len(sizes)
                terms = info.get("sparse_terms", {}).get(field)
                if terms and hasattr(eng, "prewarm_sparse"):
                    _rcount("sparse_prewarms", eng.prewarm_sparse(terms))
            _rcount("warm_handoffs")
            _rcount("fields_warmed", warmed)
            _rcount("shapes_primed", primed)
        except Exception:  # noqa: BLE001 — warming is best-effort; the
            _rcount("warm_failures")   # move itself must not fail on it
        finally:
            _rcount("warm_ms",
                    max(0, int((time.monotonic() - t0) * 1000)))

    # ---------------- primary promotion + resync ----------------

    def promote_to_primary(self, inst: ShardInstance, new_term: int) -> None:
        """This copy was promoted by the master: fence, fill gaps, build the
        primary-side tracker, then resync every surviving copy over the
        transport (ref: IndexShard primary promotion +
        PrimaryReplicaSyncer.java)."""
        with inst.lock:
            gcp = inst.known_global_checkpoint
            inst.engine.advance_primary_term(new_term)
            inst.engine.fill_seqno_gaps(inst.engine.max_seq_no)
            inst.primary = True
            inst.primary_term = new_term
            inst.tracker = ReplicationTracker(inst.allocation_id)
            inst.tracker.update_local_checkpoint(
                inst.allocation_id, inst.engine.local_checkpoint)
        state = self.state
        for r in state.shard_copies(inst.index, inst.shard_id):
            if r.allocation_id == inst.allocation_id or r.node_id is None:
                continue
            # RELOCATING replicas are serving copies and must be resynced
            # like STARTED ones; INITIALIZING/UNASSIGNED are not yet ours
            if not r.serving:
                continue
            try:
                self._resync_copy(inst, r, gcp, new_term)
            except Exception as e:  # noqa: BLE001
                self._report_shard_failed(inst.index, inst.shard_id,
                                          r.allocation_id, str(e))

    def _resync_copy(self, inst: ShardInstance, r: ShardRouting,
                     gcp: int, new_term: int) -> None:
        shard_ref = {"index": inst.index, "shard_id": inst.shard_id}
        prep = self.channels.request(
            r.node_id, "internal:index/shard/resync/prepare",
            {**shard_ref, "primary_term": new_term, "above_seq_no": gcp})
        doc_states = {d: inst.engine.doc_resync_state(d)
                      for d in prep["divergent"]}
        replay_from = min(gcp, prep["local_checkpoint"])
        ops = inst.engine.changes_since(replay_from)
        resp = self.channels.request(
            r.node_id, "internal:index/shard/resync/apply",
            {**shard_ref, "primary_term": new_term,
             "doc_states": doc_states, "replay_from": replay_from,
             "ops": ops, "max_seq_no": inst.engine.max_seq_no})
        inst.tracker.add_tracking(r.allocation_id)
        inst.tracker.update_local_checkpoint(
            r.allocation_id, resp["local_checkpoint"])
        inst.tracker.mark_in_sync(r.allocation_id)

    def _on_resync_prepare(self, req) -> dict:
        p = req.payload
        inst = self.get_shard(p["index"], p["shard_id"])
        term = p["primary_term"]
        if term < inst.primary_term:
            raise PrimaryTermMismatchError(
                f"resync from deposed primary (term [{term}])")
        with inst.lock:
            inst.engine.advance_primary_term(term)
            inst.primary_term = term
            return {"divergent": inst.engine.docs_above(p["above_seq_no"]),
                    "local_checkpoint": inst.engine.local_checkpoint}

    def _on_resync_apply(self, req) -> dict:
        p = req.payload
        inst = self.get_shard(p["index"], p["shard_id"])
        with inst.lock:
            resync_target_apply(inst.engine, p["primary_term"],
                                p["doc_states"], p["replay_from"],
                                p["ops"], p["max_seq_no"])
            inst.primary_term = p["primary_term"]
            return {"local_checkpoint": inst.engine.local_checkpoint}

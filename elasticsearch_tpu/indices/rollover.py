"""Shared rollover mechanics (ref: cluster/metadata/
MetadataRolloverService.java) used by BOTH the single-node REST handler
(rest/handlers.rollover) and the distributed coordinator
(cluster_node.rollover) — one implementation of name sequencing,
condition evaluation and the alias swap, so the two paths cannot drift."""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError

_SEQ = re.compile(r"^(.*?)-(\d+)$")


def next_rollover_name(old_name: str) -> str:
    """logs-000001 -> logs-000002 (zero-padded to six, like the
    reference's generateRolloverIndexName)."""
    m = _SEQ.match(old_name)
    if not m:
        raise IllegalArgumentError(
            f"index name [{old_name}] does not match pattern '^.*-\\d+$' — "
            "specify the target index name")
    return f"{m.group(1)}-{int(m.group(2)) + 1:06d}"


def evaluate_rollover_conditions(conditions: dict,
                                 metrics: Dict[str, object]) -> Dict[str, bool]:
    """{condition: met} for the given metrics. metrics maps condition name
    -> current value (max_age expects age_ms, sizes expect bytes); a
    condition with no metric available on the calling path raises, so an
    unsupported condition can never silently pass."""
    met: Dict[str, bool] = {}
    for cond, want in (conditions or {}).items():
        if cond not in metrics:
            raise IllegalArgumentError(
                f"unknown rollover condition [{cond}]")
        value = metrics[cond]
        if cond == "max_age":
            from elasticsearch_tpu.tasks.task_manager import parse_timeout_ms

            met[cond] = float(value) >= (parse_timeout_ms(want) or 0)
        elif cond in ("max_size", "max_primary_shard_size"):
            met[cond] = float(value) >= _parse_bytes(want)
        else:                      # max_docs, max_primary_shard_docs
            met[cond] = float(value) >= int(want)
    return met


def _parse_bytes(v) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    for suffix, mult in (("pb", 1 << 50), ("tb", 1 << 40), ("gb", 1 << 30),
                         ("mb", 1 << 20), ("kb", 1 << 10), ("b", 1)):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)
    return int(float(s))


def rollover_alias_actions(alias: str, old_name: str, new_name: str,
                           old_spec: Optional[dict]) -> List[dict]:
    """The alias swap as _aliases-style actions: a write-index managed
    alias stays on the old index demoted to is_write_index false; a plain
    alias moves entirely."""
    spec = dict(old_spec or {})
    if spec.get("is_write_index"):
        return [
            {"add": {"index": old_name, "alias": alias,
                     **{**spec, "is_write_index": False}}},
            {"add": {"index": new_name, "alias": alias,
                     **{**spec, "is_write_index": True}}},
        ]
    return [{"remove": {"index": old_name, "alias": alias}},
            {"add": {"index": new_name, "alias": alias, **spec}}]

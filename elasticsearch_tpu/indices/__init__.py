"""Node-level distributed shard management (ref: server/.../indices/)."""

from elasticsearch_tpu.indices.shard_service import (
    DistributedShardService, ShardInstance, ShardNotFoundError,
)
from elasticsearch_tpu.indices.cluster_state_service import (
    IndicesClusterStateService,
)

__all__ = [
    "DistributedShardService", "ShardInstance", "ShardNotFoundError",
    "IndicesClusterStateService",
]

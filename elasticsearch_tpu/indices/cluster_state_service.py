"""IndicesClusterStateService analog: cluster state drives local shards.

The reference applies every committed cluster state on every node and
reconciles local shard instances against it (ref:
indices/cluster/IndicesClusterStateService.java:200 applyClusterState —
deletes indices, removes shards, creates/updates shards, starts recoveries,
notifies the master when shards start or fail). This is the piece round-2
review called the missing spine: consensus commits states, and THIS makes
them mean something on data nodes.

Reconciliation per applied state:
  * shards whose index/allocation vanished from routing -> close + remove;
  * new assignments to this node -> create engine; fresh primaries report
    started immediately; replicas run pull-based peer recovery from the
    primary node, then report started;
  * a replica whose routing turned primary -> promote (term bump + fence +
    transport resync of survivors);
  * master notifications (shard started/failed) go through the master
    client and come back as new cluster states.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster.state import ClusterState
from elasticsearch_tpu.common import integrity
from elasticsearch_tpu.common.integrity import SegmentCorruptedError
from elasticsearch_tpu.common.settings import knob
from elasticsearch_tpu.indices.shard_service import DistributedShardService


class IndicesClusterStateService:
    def __init__(self, node_name: str,
                 shard_service: DistributedShardService,
                 master_client: Callable[[str, dict], dict]):
        self.node_name = node_name
        self.shards = shard_service
        self.master_client = master_client
        self._apply_lock = threading.Lock()
        # actions deferred to after apply returns (a state update must never
        # be submitted from inside the applier — ref: ClusterApplierService
        # appliers run before listeners exactly to avoid this reentrancy)
        self._post_apply: List[Callable[[], None]] = []

    def apply_cluster_state(self, state: ClusterState) -> None:
        with self._apply_lock:
            self.shards.state = state
            self._remove_unassigned_shards(state)
            self._create_or_update_shards(state)
            actions, self._post_apply = self._post_apply, []
        for fn in actions:
            try:
                fn()
            except Exception:  # noqa: BLE001 — reports are retried by
                pass           # the next state application

    # ---- removal (ref: IndicesClusterStateService.removeIndices/Shards) ----

    def _remove_unassigned_shards(self, state: ClusterState) -> None:
        for (index, shard_id), inst in list(self.shards.shards.items()):
            keep = False
            for r in state.routing.get(index, []):
                if (r.shard_id == shard_id and r.node_id == self.node_name
                        and r.allocation_id == inst.allocation_id):
                    keep = True
            if not keep:
                self.shards.remove_shard(index, shard_id)

    # ---- creation / role changes ----

    def _create_or_update_shards(self, state: ClusterState) -> None:
        for r in state.entries_on_node(self.node_name):
            meta = state.indices.get(r.index)
            if meta is None:
                continue
            inst = self.shards.shards.get((r.index, r.shard_id))
            if inst is None:
                try:
                    self._create_local_shard(meta, r)
                except SegmentCorruptedError as e:
                    # corruption fails the COPY, never the applier: the
                    # corrupted-* marker (written where the verify failed)
                    # blocks this store from serving again, and the
                    # deferred shard-failed report routes through the same
                    # seam every other copy failure uses — the master
                    # reallocates from a healthy peer
                    integrity.count("shards_failed_corrupt")
                    self.shards.remove_shard(r.index, r.shard_id)
                    self._defer_report_failed(r, f"corrupted: {e}")
            else:
                new_term = meta.primary_term(r.shard_id)
                still_reloc_target = (r.state == "INITIALIZING"
                                      and r.relocating_node_id is not None)
                if r.primary and not inst.primary and not still_reloc_target:
                    # promotion (ref: IndexShard term bump on new routing);
                    # for a relocation swap the term is unchanged — the
                    # same primary context moves, no bump. A still-
                    # recovering relocation target must NOT promote yet.
                    self.shards.promote_to_primary(inst, new_term)
                inst.state = r.state if r.state != "INITIALIZING" \
                    else inst.state
                if inst.primary and inst.tracker is not None:
                    self._sync_tracker(inst, state, meta)

    def _create_local_shard(self, meta, r) -> None:
        """One new assignment: build the engine and schedule whatever must
        happen before the copy reports started. Raises
        `SegmentCorruptedError` when the store cannot serve (marker, failed
        checksum on commit load, or a failed startup scan)."""
        if r.state == "INITIALIZING" and r.relocating_node_id is not None:
            # relocation target: even when routing carries the
            # primary flag, the source keeps the primary context
            # until the swap — this copy recovers as a replica
            # (peer recovery from the serving primary), warms its
            # HBM/compile caches, then reports started
            from dataclasses import replace as _replace

            inst = self.shards.create_shard(
                meta, _replace(r, primary=False))
            self._defer_recovery(
                inst, relocation_source=r.relocating_node_id)
        elif r.primary:
            inst = self.shards.create_shard(meta, r)
            # fresh (or locally-recovered) primary: started
            inst.state = "STARTED" if r.state == "STARTED" \
                else "INITIALIZING"
            if r.state == "INITIALIZING":
                self._verify_on_startup(inst)
                self._defer_report_started(inst)
                inst.state = "STARTED"
        else:
            inst = self.shards.create_shard(meta, r)
            self._defer_recovery(inst)

    def _verify_on_startup(self, inst) -> None:
        """ES_TPU_CHECK_ON_STARTUP: full-store checksum scan BEFORE the
        copy reports started (ref: index.shard.check_on_startup) — the
        commit load only re-reads blobs it rebuilds, this re-reads all of
        them, so bit rot under an already-loaded store is caught here
        instead of at the next recovery."""
        if not knob("ES_TPU_CHECK_ON_STARTUP"):
            return
        integrity.count("startup_checks")
        try:
            inst.engine.verify_store()
        except SegmentCorruptedError:
            integrity.count("startup_failures")
            raise

    def _sync_tracker(self, inst, state: ClusterState, meta) -> None:
        """Keep the primary's replication tracker consistent with the
        published in-sync set (ref: ReplicationTracker
        updateFromMaster)."""
        present = {r.allocation_id
                   for r in state.shard_copies(inst.index, inst.shard_id)}
        for aid in list(inst.tracker.in_sync_ids):
            if aid != inst.allocation_id and aid not in present:
                inst.tracker.remove_tracking(aid)

    # ---- deferred actions ----

    def _defer_report_started(self, inst) -> None:
        payload = {"index": inst.index, "shard_id": inst.shard_id,
                   "allocation_id": inst.allocation_id}

        def report():
            self.master_client("internal:cluster/shard/started", payload)

        self._post_apply.append(report)

    def _defer_report_failed(self, r, reason: str) -> None:
        payload = {"index": r.index, "shard_id": r.shard_id,
                   "allocation_id": r.allocation_id, "reason": reason}

        def report():
            self.master_client("internal:cluster/shard/failed", payload)

        self._post_apply.append(report)

    def _defer_recovery(self, inst,
                        relocation_source: Optional[str] = None) -> None:
        def recover():
            import time

            from elasticsearch_tpu.common.durability import count
            from elasticsearch_tpu.common.settings import knob

            # a dying source or an injected transport blip must not cost
            # the copy outright: every recovery step is idempotent, so
            # retry with exponential backoff before telling the master
            # (ref: PeerRecoveryTargetService retryRecovery)
            attempts = max(1, knob("ES_TPU_RECOVERY_RETRIES"))
            backoff = knob("ES_TPU_RECOVERY_BACKOFF_MS") / 1000.0
            last_err: Optional[Exception] = None
            for attempt in range(attempts):
                if attempt:
                    ov = getattr(self.shards, "overload", None)
                    if ov is not None and not ov.retry_allowed("recovery"):
                        # node-wide retry budget exhausted: report the
                        # organic error to the master now instead of
                        # piling recovery retries onto a browned-out peer
                        break
                    count("recoveries_retried")
                    time.sleep(backoff * (2 ** (attempt - 1)))
                try:
                    self.shards.recover_replica(inst)
                    last_err = None
                    break
                except Exception as e:  # noqa: BLE001
                    last_err = e
            if last_err is None:
                try:
                    # the freshly recovered (and flushed) store replaces
                    # whatever corruption got this copy here: any marker
                    # left in the data path is stale now
                    if inst.engine.data_path is not None:
                        integrity.clear_corruption_markers(
                            inst.engine.data_path)
                    self._verify_on_startup(inst)
                except SegmentCorruptedError as e:
                    integrity.count("shards_failed_corrupt")
                    last_err = e
            if last_err is not None:
                self.master_client(
                    "internal:cluster/shard/failed",
                    {"index": inst.index, "shard_id": inst.shard_id,
                     "allocation_id": inst.allocation_id,
                     "reason": f"recovery failed: {last_err}"})
                return
            if relocation_source is not None:
                # warm HBM handoff before shard-started: the moved copy
                # must not serve its first query cold (best-effort inside)
                self.shards.warm_relocation_handoff(inst, relocation_source)
            inst.state = "STARTED"
            self.master_client(
                "internal:cluster/shard/started",
                {"index": inst.index, "shard_id": inst.shard_id,
                 "allocation_id": inst.allocation_id})

        self._post_apply.append(recover)

"""REST API handlers: the user-facing surface.

Implements the core of the reference's REST API (ref: the 138 Rest*Action
handlers under rest/action/ and the 144 specs in
rest-api-spec/src/main/resources/rest-api-spec/api/): document CRUD, bulk,
search/msearch/count, index admin, cluster/cat/nodes monitoring, analyze,
mget, update, delete-by-query, aliases. Response shapes follow the reference
so existing clients can switch over.
"""

from __future__ import annotations

import json
import secrets
import time
from typing import Any, Dict, List

from elasticsearch_tpu import __version__
from elasticsearch_tpu.common.errors import (
    DocumentMissingError,
    ElasticsearchTpuError,
    IllegalArgumentError,
    IndexNotFoundError,
    ParsingError,
    VersionConflictError,
)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.controller import (
    RestController, RestRequest, RestResponse, _error_body,
)
from elasticsearch_tpu.search.queries import parse_query

_START_TIME = time.time()


def register_handlers(node: Node, rc: RestController) -> None:
    h = _Handlers(node)
    r = rc.register

    # security action filter (ref: SecurityActionFilter): installed only
    # when xpack.security.enabled — otherwise the node stays open exactly
    # as before
    sec = getattr(node, "security", None)
    if sec is not None and sec.enabled:
        rc.security_filter = sec.rest_filter

    # overload admission (common/overload.py): shed data-path requests at
    # the front door before any body parse or handler work — bulk tier at
    # YELLOW, interactive too at RED. Management/snapshot requests are
    # always admitted so stats and health stay reachable mid-brownout.
    if getattr(node, "overload", None) is not None:
        rc.admission = _overload_admission(node)

    r("GET", "/", h.root)
    # security management
    r("GET", "/_security/_authenticate", h.security_authenticate)
    r("PUT", "/_security/user/{username}", h.security_put_user)
    r("POST", "/_security/user/{username}", h.security_put_user)
    r("DELETE", "/_security/user/{username}", h.security_delete_user)
    r("PUT", "/_security/role/{role}", h.security_put_role)
    r("POST", "/_security/role/{role}", h.security_put_role)
    r("GET", "/_security/role/{role}", h.security_get_role)
    r("DELETE", "/_security/role/{role}", h.security_delete_role)
    r("POST", "/_security/api_key", h.security_create_api_key)
    r("DELETE", "/_security/api_key", h.security_invalidate_api_key)
    # index admin
    r("PUT", "/{index}", h.create_index)
    r("DELETE", "/{index}", h.delete_index)
    r("GET", "/{index}", h.get_index)
    r("HEAD", "/{index}", h.head_index)
    r("GET", "/{index}/_mapping", h.get_mapping)
    r("GET", "/_mapping", h.get_mapping)
    r("GET", "/_settings", h.get_settings)
    r("PUT", "/{index}/_mapping", h.put_mapping)
    r("GET", "/{index}/_settings", h.get_settings)
    r("PUT", "/{index}/_settings", h.put_settings)
    r("POST", "/{index}/_refresh", h.refresh)
    r("GET", "/{index}/_refresh", h.refresh)
    r("POST", "/_refresh", h.refresh_all)
    r("POST", "/{index}/_flush", h.flush)
    r("POST", "/_flush", h.flush_all)
    r("POST", "/{index}/_forcemerge", h.forcemerge)
    r("GET", "/{index}/_stats", h.index_stats)
    r("GET", "/_stats", h.all_stats)
    r("GET", "/{index}/_count", h.count)
    r("POST", "/{index}/_count", h.count)
    r("GET", "/_count", h.count_all)
    r("POST", "/_count", h.count_all)
    # documents
    r("PUT", "/{index}/_doc/{id}", h.index_doc)
    r("POST", "/{index}/_doc/{id}", h.index_doc)
    r("POST", "/{index}/_doc", h.index_doc_auto_id)
    r("PUT", "/{index}/_create/{id}", h.create_doc)
    r("POST", "/{index}/_create/{id}", h.create_doc)
    r("GET", "/{index}/_doc/{id}", h.get_doc)
    r("HEAD", "/{index}/_doc/{id}", h.head_doc)
    r("GET", "/{index}/_source/{id}", h.get_source)
    r("DELETE", "/{index}/_doc/{id}", h.delete_doc)
    r("POST", "/{index}/_update/{id}", h.update_doc)
    r("GET", "/_mget", h.mget)
    r("POST", "/_mget", h.mget)
    r("GET", "/{index}/_mget", h.mget)
    r("POST", "/{index}/_mget", h.mget)
    # bulk
    r("POST", "/_bulk", h.bulk)
    r("PUT", "/_bulk", h.bulk)
    r("POST", "/{index}/_bulk", h.bulk)
    # search
    r("GET", "/{index}/_search", h.search)
    r("POST", "/{index}/_search", h.search)
    r("GET", "/_search", h.search_all)
    r("POST", "/_search", h.search_all)
    r("GET", "/_search/scroll", h.scroll_next)
    r("POST", "/_search/scroll", h.scroll_next)
    r("DELETE", "/_search/scroll", h.scroll_clear)
    r("POST", "/{index}/_pit", h.open_pit)
    r("DELETE", "/_pit", h.close_pit)
    r("POST", "/_reindex", h.reindex)
    r("GET", "/{index}/_termvectors/{id}", h.termvectors)
    r("POST", "/{index}/_termvectors/{id}", h.termvectors)
    r("POST", "/_render/template", h.render_template)
    r("GET", "/{index}/_search/template", h.search_template)
    r("POST", "/{index}/_search/template", h.search_template)
    r("GET", "/{index}/_rank_eval", h.rank_eval)
    r("POST", "/{index}/_rank_eval", h.rank_eval)
    r("POST", "/{index}/_async_search", h.async_search_submit)
    r("GET", "/_async_search/{id}", h.async_search_get)
    r("DELETE", "/_async_search/{id}", h.async_search_delete)
    r("GET", "/_field_caps", h.field_caps)
    r("POST", "/_field_caps", h.field_caps)
    r("GET", "/{index}/_field_caps", h.field_caps)
    r("POST", "/{index}/_field_caps", h.field_caps)
    r("GET", "/{index}/_explain/{id}", h.explain)
    r("POST", "/{index}/_explain/{id}", h.explain)
    # ingest pipelines (ref: RestPutPipelineAction, RestSimulatePipelineAction)
    r("PUT", "/_ingest/pipeline/{id}", h.put_pipeline)
    r("GET", "/_ingest/pipeline/{id}", h.get_pipeline)
    r("GET", "/_ingest/pipeline", h.get_pipelines)
    r("DELETE", "/_ingest/pipeline/{id}", h.delete_pipeline)
    r("POST", "/_ingest/pipeline/{id}/_simulate", h.simulate_pipeline)
    r("GET", "/_ingest/pipeline/{id}/_simulate", h.simulate_pipeline)
    r("POST", "/_ingest/pipeline/_simulate", h.simulate_pipeline)
    # snapshots (ref: RestPutRepositoryAction, RestCreateSnapshotAction,
    # RestRestoreSnapshotAction, RestDeleteSnapshotAction)
    r("PUT", "/_snapshot/{repo}", h.put_repository)
    r("POST", "/_snapshot/{repo}/_verify", h.verify_repository)
    r("GET", "/_snapshot/{repo}", h.get_repository)
    r("PUT", "/_snapshot/{repo}/{snapshot}", h.create_snapshot)
    r("POST", "/_snapshot/{repo}/{snapshot}", h.create_snapshot)
    r("GET", "/_snapshot/{repo}/{snapshot}", h.get_snapshot)
    r("DELETE", "/_snapshot/{repo}/{snapshot}", h.delete_snapshot)
    r("POST", "/_snapshot/{repo}/{snapshot}/_restore", h.restore_snapshot)
    r("GET", "/_tasks", h.list_tasks)
    r("POST", "/_tasks/_cancel", h.cancel_tasks)
    r("GET", "/_tasks/{task_id}", h.get_task)
    r("POST", "/_tasks/{task_id}/_cancel", h.cancel_task)
    r("POST", "/_msearch", h.msearch)
    r("GET", "/_msearch", h.msearch)
    r("POST", "/{index}/_msearch", h.msearch)
    r("POST", "/{index}/_delete_by_query", h.delete_by_query)
    r("POST", "/{index}/_update_by_query", h.update_by_query)
    # analyze
    r("GET", "/_analyze", h.analyze)
    r("POST", "/_analyze", h.analyze)
    r("GET", "/{index}/_analyze", h.analyze)
    r("POST", "/{index}/_analyze", h.analyze)
    # cluster / monitoring
    r("PUT", "/_index_template/{name}", h.put_index_template)
    r("GET", "/_index_template/{name}", h.get_index_template)
    r("GET", "/_index_template", h.get_index_templates)
    r("DELETE", "/_index_template/{name}", h.delete_index_template)
    r("GET", "/_cluster/settings", h.get_cluster_settings)
    r("PUT", "/_cluster/settings", h.put_cluster_settings)
    r("GET", "/_cluster/health", h.cluster_health)
    r("GET", "/_cluster/state", h.cluster_state)
    r("GET", "/_cluster/stats", h.cluster_stats)
    r("POST", "/_cluster/reroute", h.cluster_reroute)
    r("GET", "/_nodes", h.nodes_info)
    r("GET", "/_nodes/stats", h.nodes_stats)
    r("GET", "/_nodes/hot_threads", h.hot_threads)
    # cross-cluster plane (PR 20)
    r("GET", "/_remote/info", h.remote_info)
    r("PUT", "/{index}/_ccr/follow", h.ccr_follow)
    r("POST", "/{index}/_ccr/follow", h.ccr_follow)
    r("POST", "/{index}/_ccr/pause_follow", h.ccr_pause_follow)
    r("POST", "/{index}/_ccr/resume_follow", h.ccr_resume_follow)
    r("GET", "/{index}/_ccr/stats", h.ccr_stats)
    # search flight recorder (PR 9)
    r("GET", "/_tpu/slowlog", h.tpu_slowlog)
    r("GET", "/_tpu/trace", h.tpu_traces)
    # device telemetry plane (PR 12)
    r("GET", "/_tpu/metrics", h.tpu_metrics)
    r("GET", "/_tpu/metrics/history", h.tpu_metrics_history)
    # lifecycle admin
    r("POST", "/{index}/_close", h.close_index)
    r("POST", "/{index}/_open", h.open_index)
    r("POST", "/{alias}/_rollover", h.rollover)
    r("POST", "/{alias}/_rollover/{new_index}", h.rollover)
    r("PUT", "/{index}/_shrink/{target}", h.resize_shrink)
    r("POST", "/{index}/_shrink/{target}", h.resize_shrink)
    r("PUT", "/{index}/_split/{target}", h.resize_split)
    r("POST", "/{index}/_split/{target}", h.resize_split)
    r("PUT", "/{index}/_clone/{target}", h.resize_clone)
    r("POST", "/{index}/_clone/{target}", h.resize_clone)
    # aliases
    r("POST", "/_aliases", h.update_aliases)
    r("GET", "/_alias", h.get_aliases)
    r("GET", "/_alias/{name}", h.get_aliases)
    r("GET", "/{index}/_alias", h.get_aliases)
    r("GET", "/{index}/_alias/{name}", h.get_aliases)
    r("PUT", "/{index}/_alias/{name}", h.put_alias)
    r("POST", "/{index}/_alias/{name}", h.put_alias)
    r("PUT", "/{index}/_aliases/{name}", h.put_alias)
    r("DELETE", "/{index}/_alias/{name}", h.delete_alias)
    r("DELETE", "/{index}/_aliases/{name}", h.delete_alias)
    r("HEAD", "/{index}/_alias/{name}", h.head_alias)
    r("HEAD", "/_alias/{name}", h.head_alias)
    # legacy (v1) index templates
    r("PUT", "/_template/{name}", h.put_legacy_template)
    r("POST", "/_template/{name}", h.put_legacy_template)
    r("GET", "/_template/{name}", h.get_legacy_template)
    r("GET", "/_template", h.get_legacy_templates)
    r("DELETE", "/_template/{name}", h.delete_legacy_template)
    r("HEAD", "/_template/{name}", h.head_legacy_template)
    # field-level mapping
    r("GET", "/{index}/_mapping/field/{fields}", h.get_field_mapping)
    r("GET", "/_mapping/field/{fields}", h.get_field_mapping)
    # cat
    r("GET", "/_cat/indices", h.cat_indices)
    r("GET", "/_cat/health", h.cat_health)
    r("GET", "/_cat/shards", h.cat_shards)
    r("GET", "/_cat/count", h.cat_count)
    r("GET", "/_cat/nodes", h.cat_nodes)
    r("GET", "/_cat/segments", h.cat_segments)
    r("GET", "/_cat/segments/{index}", h.cat_segments)
    r("GET", "/_cat/aliases", h.cat_aliases)
    r("GET", "/_cat/allocation", h.cat_allocation)
    r("GET", "/_cat/templates", h.cat_templates)
    r("GET", "/_cat/thread_pool", h.cat_thread_pool)
    r("GET", "/_cat/thread_pool/{name}", h.cat_thread_pool)
    r("GET", "/_cat/tasks", h.cat_tasks)


def _render_search_template(source, params: dict):
    """Mustache subset: {{var}} substitution + {{#toJson}}var{{/toJson}}
    (the two forms that cover the vast majority of real templates)."""
    import re as _re

    if isinstance(source, dict):
        source = json.dumps(source)
    if not isinstance(source, str):
        raise IllegalArgumentError("[source] template is required")
    out = _re.sub(
        r'"\{\{#toJson\}\}(\w+)\{\{/toJson\}\}"',
        lambda m: json.dumps(params.get(m.group(1))), source)
    out = _re.sub(
        r"\{\{(\w+)\}\}",
        lambda m: json.dumps(str(params.get(m.group(1), "")))[1:-1], out)
    try:
        return json.loads(out)
    except json.JSONDecodeError as e:
        raise IllegalArgumentError(f"failed to render template: {e}")


def _ok(body, status=200) -> RestResponse:
    return RestResponse(status=status, body=body)


class _Handlers:
    def __init__(self, node: Node):
        self.node = node
        # the telemetry plane answers stats RPCs with this node's full
        # REST sections rather than the module-global default set
        tp = getattr(node, "telemetry_plane", None)
        if tp is not None:
            tp.local_stats_fn = self._local_node_stats

    # ---------- info ----------

    def root(self, req: RestRequest) -> RestResponse:
        return _ok({
            "name": self.node.node_name,
            "cluster_name": self.node.cluster_state.cluster_name,
            "cluster_uuid": self.node.node_id,
            "version": {
                "number": __version__,
                "build_flavor": "tpu",
                "lucene_version": "none (tpu-native segments)",
            },
            "tagline": "You Know, for Search",
        })

    # ---------- security (ref: x-pack security REST actions) ----------

    def _sec(self):
        sec = getattr(self.node, "security", None)
        if sec is None:
            raise IllegalArgumentError("security is not available")
        return sec

    def security_authenticate(self, req: RestRequest) -> RestResponse:
        sec = self._sec()
        if not sec.enabled:
            authn = None
        else:
            authn = sec.authenticate(req.headers)
        username = authn.username if authn else "_anonymous"
        roles = [r.name for r in authn.roles] if authn else ["superuser"]
        return _ok({"username": username, "roles": roles,
                    "enabled": True,
                    "authentication_type":
                        authn.auth_type if authn else "anonymous"})

    def security_put_user(self, req: RestRequest) -> RestResponse:
        body = req.body or {}
        self._sec().put_user(req.param("username"), body.get("password"),
                             body.get("roles", []))
        return _ok({"created": True})

    def security_delete_user(self, req: RestRequest) -> RestResponse:
        found = self._sec().delete_user(req.param("username"))
        return _ok({"found": found}, 200 if found else 404)

    def security_put_role(self, req: RestRequest) -> RestResponse:
        self._sec().put_role(req.param("role"), req.body or {})
        return _ok({"role": {"created": True}})

    def security_get_role(self, req: RestRequest) -> RestResponse:
        sec = self._sec()
        role = sec.roles.get(req.param("role"))
        if role is None:
            return _ok({}, 404)
        return _ok({role.name: {"cluster": role.cluster,
                                "indices": role.indices}})

    def security_delete_role(self, req: RestRequest) -> RestResponse:
        found = self._sec().delete_role(req.param("role"))
        return _ok({"found": found}, 200 if found else 404)

    def security_create_api_key(self, req: RestRequest) -> RestResponse:
        body = req.body or {}
        user = req.param("_authn_user", "elastic")
        roles = None
        owned = []
        if body.get("role_descriptors"):
            # inline role descriptors register as key-OWNED ad-hoc roles,
            # removed with the key on invalidation
            sec = self._sec()
            roles = []
            for rname, rbody in body["role_descriptors"].items():
                full = f"_api_key_{rname}_{secrets.token_hex(4)}"
                sec.put_role(full, rbody)
                roles.append(full)
            owned = list(roles)
        out = self._sec().create_api_key(user, body.get("name", ""), roles,
                                         owned_roles=owned)
        return _ok(out)

    def security_invalidate_api_key(self, req: RestRequest) -> RestResponse:
        body = req.body or {}
        ids = body.get("ids") or ([body["id"]] if body.get("id") else [])
        invalidated = [i for i in ids if self._sec().invalidate_api_key(i)]
        return _ok({"invalidated_api_keys": invalidated,
                    "error_count": len(ids) - len(invalidated)})

    # ---------- index admin ----------

    def create_index(self, req: RestRequest) -> RestResponse:
        name = req.param("index")
        meta = self.node.create_index(name, req.body or {})
        return _ok({"acknowledged": True, "shards_acknowledged": True, "index": name})

    def delete_index(self, req: RestRequest) -> RestResponse:
        for name in self._resolve(req.param("index"), require=True):
            self.node.delete_index(name)
        return _ok({"acknowledged": True})

    # ---- lifecycle admin (ref: action/admin/indices/{close,open,shrink,
    #      rollover}; MetadataRolloverService.java; VERDICT r4 item 7) ----

    def close_index(self, req: RestRequest) -> RestResponse:
        from dataclasses import replace

        names = self._resolve(req.param("index"), require=True)
        for name in names:
            svc = self.node.indices.get(name)
            svc.closed = True
            meta = self.node.cluster_state.indices[name]
            new_meta = replace(meta, state="close", version=meta.version + 1)
            routing = self.node.cluster_state.routing[name]
            self.node.update_state(lambda s, m=new_meta, r=routing:
                                   s.with_index(m, r))
        return _ok({"acknowledged": True, "shards_acknowledged": True,
                    "indices": {n: {"closed": True} for n in names}})

    def open_index(self, req: RestRequest) -> RestResponse:
        from dataclasses import replace

        for name in self._resolve(req.param("index"), require=True):
            svc = self.node.indices.get(name)
            svc.closed = False
            meta = self.node.cluster_state.indices[name]
            new_meta = replace(meta, state="open", version=meta.version + 1)
            routing = self.node.cluster_state.routing[name]
            self.node.update_state(lambda s, m=new_meta, r=routing:
                                   s.with_index(m, r))
        return _ok({"acknowledged": True, "shards_acknowledged": True})

    def rollover(self, req: RestRequest) -> RestResponse:
        """POST /{alias}/_rollover[/{new_index}] (ref:
        MetadataRolloverService.rolloverClusterState; shared mechanics in
        indices/rollover.py): evaluate conditions on the alias's write
        index; when met, create the next index in the -NNNNNN sequence and
        swap the alias."""
        from elasticsearch_tpu.indices.rollover import (
            evaluate_rollover_conditions, next_rollover_name,
            rollover_alias_actions,
        )

        alias = req.param("alias")
        body = req.body or {}
        cs = self.node.cluster_state
        holders = [(n, cs.indices[n].aliases[alias])
                   for n in sorted(cs.indices) if alias in cs.indices[n].aliases]
        if not holders:
            raise IllegalArgumentError(
                f"rollover target [{alias}] does not point to any index")
        writers = [h for h in holders if h[1].get("is_write_index")]
        if len(holders) > 1 and len(writers) != 1:
            raise IllegalArgumentError(
                f"rollover target [{alias}] points to multiple indices "
                "without one write index")
        old_name, old_spec = writers[0] if writers else holders[0]
        svc = self.node.indices.get(old_name)
        meta = cs.indices[old_name]

        conditions = body.get("conditions", {}) or {}
        metrics = {
            "max_docs": svc.doc_count(),
            "max_age": int(time.time() * 1000) - meta.creation_date,
            "max_size": svc.store_size_bytes(),
            "max_primary_shard_size": svc.store_size_bytes()
            // max(len(svc.shards), 1),
            "max_primary_shard_docs": max(
                (e.doc_count() for e in svc.shards), default=0),
        }
        met = evaluate_rollover_conditions(conditions, metrics)
        rolled = (not conditions) or any(met.values())

        new_name = (req.param("new_index") or body.get("new_index")
                    or next_rollover_name(old_name))
        resp = {"acknowledged": False, "shards_acknowledged": False,
                "old_index": old_name, "new_index": new_name,
                "rolled_over": False, "dry_run": bool(body.get("dry_run")),
                "conditions": {f"[{c}: {conditions[c]}]": v
                               for c, v in met.items()}}
        if body.get("dry_run") or not rolled:
            return _ok(resp)

        create_body = {k: v for k, v in body.items()
                       if k in ("settings", "mappings", "aliases")}
        self.node.create_index(new_name, create_body)
        for action in rollover_alias_actions(alias, old_name, new_name,
                                             old_spec):
            op, spec = next(iter(action.items()))
            target = spec["index"]
            payload = None if op == "remove" else {
                k: v for k, v in spec.items() if k not in ("index", "alias")}
            self._set_alias(target, alias, payload)
        resp.update({"acknowledged": True, "shards_acknowledged": True,
                     "rolled_over": True})
        return _ok(resp)

    def resize_shrink(self, req: RestRequest) -> RestResponse:
        return self._resize(req, "shrink")

    def resize_split(self, req: RestRequest) -> RestResponse:
        return self._resize(req, "split")

    def resize_clone(self, req: RestRequest) -> RestResponse:
        return self._resize(req, "clone")

    def _resize(self, req: RestRequest, mode: str) -> RestResponse:
        """_shrink/_split/_clone (ref: action/admin/indices/shrink/
        TransportResizeAction.java): create the target with the adjusted
        shard count and re-route every live doc. TPU segments are HBM/host
        arrays, not files — rebuilding the columnar layout IS the resize
        (there is no hard-link shortcut to preserve), and the murmur3 _id
        routing re-partitions exactly. Custom ?routing values are not
        persisted per doc, so resized copies of custom-routed docs route
        by _id (documented divergence); a doc without a stored _source
        cannot be replayed and fails the resize up front."""
        source = req.param("index")
        target = req.param("target")
        svc = self.node.indices.get(source)
        if self.node.indices.has(target):
            from elasticsearch_tpu.common.errors import (
                ResourceAlreadyExistsError,
            )

            raise ResourceAlreadyExistsError(
                f"index [{target}] already exists")
        body = req.body or {}
        src_meta = self.node.cluster_state.indices[source]
        src_n = src_meta.number_of_shards
        tgt_settings = dict((body.get("settings") or {}))
        tgt_n = int(tgt_settings.get(
            "index.number_of_shards",
            tgt_settings.get("number_of_shards",
                             src_n if mode != "shrink" else 1)))
        if mode == "shrink" and src_n % tgt_n != 0:
            raise IllegalArgumentError(
                f"the number of source shards [{src_n}] must be a multiple "
                f"of [{tgt_n}]")
        if mode == "split" and tgt_n % src_n != 0:
            raise IllegalArgumentError(
                f"the number of target shards [{tgt_n}] must be a multiple "
                f"of [{src_n}]")
        if mode == "clone" and tgt_n != src_n:
            raise IllegalArgumentError(
                "clone must keep the source's shard count")
        tgt_settings["index.number_of_shards"] = tgt_n
        self.node.create_index(target, {
            "settings": tgt_settings,
            "mappings": src_meta.mappings,
            "aliases": body.get("aliases", {}),
        })
        tgt_svc = self.node.indices.get(target)
        for engine in svc.shards:
            searcher = engine.acquire_searcher()
            for v in searcher.views:
                seg = v.segment
                for ord_ in range(seg.n_docs):
                    if not bool(v.live[ord_]):
                        continue
                    src_doc = seg.sources[ord_]
                    if src_doc is None:
                        raise IllegalArgumentError(
                            f"cannot resize [{source}]: doc "
                            f"[{seg.doc_ids[ord_]}] has no _source to "
                            "replay")
                    tgt_svc.index_doc(seg.doc_ids[ord_], src_doc)
        tgt_svc.refresh()
        return _ok({"acknowledged": True, "shards_acknowledged": True,
                    "index": target})

    def get_index(self, req: RestRequest) -> RestResponse:
        out = {}
        for name in self._resolve(req.param("index"), require=True):
            svc = self.node.indices.get(name)
            meta = self.node.cluster_state.indices[name]
            out[name] = {
                "aliases": meta.aliases,
                "mappings": svc.mapper.mapping(),
                "settings": {"index": {
                    "number_of_shards": str(meta.number_of_shards),
                    "number_of_replicas": str(meta.number_of_replicas),
                    "uuid": meta.uuid,
                    "creation_date": str(meta.creation_date),
                    "provided_name": name,
                }},
            }
        return _ok(out)

    def head_index(self, req: RestRequest) -> RestResponse:
        exists = all(self.node.indices.has(n) for n in
                     self._resolve(req.param("index"))) and \
            bool(self._resolve(req.param("index")))
        return RestResponse(status=200 if exists else 404, body={})

    def get_mapping(self, req: RestRequest) -> RestResponse:
        out = {}
        require = not req.param_bool("ignore_unavailable")
        for name in self._resolve(req.param("index"), require=require):
            svc = self.node.indices.get(name)
            svc.check_metadata_allowed()
            out[name] = {"mappings": svc.mapper.mapping()}
        return _ok(out)

    def put_mapping(self, req: RestRequest) -> RestResponse:
        for name in self._resolve(req.param("index"), require=True):
            svc = self.node.indices.get(name)
            svc.check_metadata_allowed()
            svc.mapper.merge(req.body or {})
        return _ok({"acknowledged": True})

    def put_settings(self, req: RestRequest) -> RestResponse:
        """ref: RestUpdateSettingsAction — DYNAMIC index settings update,
        validated, committed through the cluster state (version bump) so
        readers, replication and persistence all see it; replica-count
        changes rebuild the index's replica routing entries."""
        import dataclasses as _dc
        import uuid as _uuid

        from elasticsearch_tpu.cluster.state import ShardRouting
        from elasticsearch_tpu.common.settings import Settings as _S
        from elasticsearch_tpu.tasks.task_manager import parse_timeout_ms

        body = dict(req.body or {})
        updates = _S(body.get("settings", body))
        flat = {}
        for k in updates:
            key = k if k.startswith("index.") else f"index.{k}"
            raw = updates.raw(k)
            if key == "index.number_of_replicas":
                try:
                    if int(raw) < 0:
                        raise ValueError
                except (TypeError, ValueError):
                    raise IllegalArgumentError(
                        f"Failed to parse value [{raw}] for setting [{key}]")
            elif key == "index.default_pipeline":
                if not isinstance(raw, str):
                    raise IllegalArgumentError(
                        f"[{key}] must be a pipeline name")
            elif key.startswith("index.search.slowlog."):
                try:
                    parse_timeout_ms(raw)
                except (TypeError, ValueError):
                    raise IllegalArgumentError(
                        f"Failed to parse value [{raw}] for setting [{key}]")
            elif key in ("index.blocks.write", "index.blocks.read",
                         "index.blocks.read_only", "index.blocks.metadata",
                         "index.max_terms_count",
                         "index.max_result_window",
                         "index.refresh_interval"):
                pass          # enforced by IndexService.check_*_allowed
            else:
                raise IllegalArgumentError(
                    f"Can't update non dynamic setting [{key}]")
            flat[key] = raw

        # The metadata block rejects settings updates UNLESS the request
        # only toggles index.blocks.* itself — otherwise a metadata block
        # could never be removed (ref: TransportUpdateSettingsAction
        # .checkBlock skips the block for all-blocks requests).
        only_blocks = all(k.startswith("index.blocks.") for k in flat)
        for name in self._resolve(req.param("index"), require=True):
            svc = self.node.indices.get(name)
            if not only_blocks:
                svc.check_metadata_allowed()
            new_meta = _dc.replace(
                svc.meta, settings=svc.meta.settings.with_updates(flat))
            svc.meta = new_meta

            def updater(state, name=name, new_meta=new_meta):
                routing = list(state.routing.get(name, []))
                if "index.number_of_replicas" in flat:
                    want = int(flat["index.number_of_replicas"])
                    primaries = [r for r in routing if r.primary]
                    replicas = {r.shard_id: [x for x in routing
                                             if not x.primary
                                             and x.shard_id == r.shard_id]
                                for r in primaries}
                    routing = list(primaries)
                    for p in primaries:
                        have = replicas.get(p.shard_id, [])
                        routing.extend(have[:want])
                        for _ in range(want - len(have)):
                            routing.append(ShardRouting(
                                index=name, shard_id=p.shard_id,
                                node_id=None, primary=False,
                                state="UNASSIGNED"))
                return state.with_index(new_meta, routing)

            self.node.update_state(updater)
        return _ok({"acknowledged": True})

    def get_settings(self, req: RestRequest) -> RestResponse:
        out = {}
        for name in self._resolve(req.param("index"), require=True):
            self.node.indices.get(name).check_metadata_allowed()
            meta = self.node.cluster_state.indices[name]
            out[name] = {"settings": {"index": {
                "number_of_shards": str(meta.number_of_shards),
                "number_of_replicas": str(meta.number_of_replicas),
                "uuid": meta.uuid,
            }}}
        return _ok(out)

    def refresh(self, req: RestRequest) -> RestResponse:
        names = self._resolve(req.param("index"), require=True)
        for name in names:
            self.node.indices.get(name).refresh()
        n = sum(len(self.node.indices.get(x).shards) for x in names)
        return _ok({"_shards": {"total": n, "successful": n, "failed": 0}})

    def refresh_all(self, req: RestRequest) -> RestResponse:
        req.params["index"] = "_all"
        return self.refresh(req)

    def flush(self, req: RestRequest) -> RestResponse:
        names = self._resolve(req.param("index"), require=True)
        for name in names:
            self.node.indices.get(name).flush()
        n = sum(len(self.node.indices.get(x).shards) for x in names)
        return _ok({"_shards": {"total": n, "successful": n, "failed": 0}})

    def flush_all(self, req: RestRequest) -> RestResponse:
        req.params["index"] = "_all"
        return self.flush(req)

    def forcemerge(self, req: RestRequest) -> RestResponse:
        max_segs = req.param_int("max_num_segments", 1)
        for name in self._resolve(req.param("index"), require=True):
            self.node.indices.get(name).force_merge(max_segs)
        return _ok({"_shards": {"total": 1, "successful": 1, "failed": 0}})

    def index_stats(self, req: RestRequest) -> RestResponse:
        out = {"indices": {}}
        total = {"docs": {"count": 0, "deleted": 0}, "store": {"size_in_bytes": 0}}
        for name in self._resolve(req.param("index"), require=True):
            stats = self.node.indices.get(name).stats()
            out["indices"][name] = {"primaries": stats, "total": stats}
            total["docs"]["count"] += stats["docs"]["count"]
            total["store"]["size_in_bytes"] += stats["store"]["size_in_bytes"]
        out["_all"] = {"primaries": total, "total": total}
        n_sh = sum(self.node.cluster_state.indices[n].number_of_shards
                   for n in out["indices"]
                   if n in self.node.cluster_state.indices)
        out["_shards"] = {"total": n_sh, "successful": n_sh, "failed": 0}
        return _ok(out)

    def all_stats(self, req: RestRequest) -> RestResponse:
        req.params["index"] = "_all"
        return self.index_stats(req)

    # ---------- documents ----------

    def index_doc(self, req: RestRequest) -> RestResponse:
        return self._do_index(req, req.param("id"), op_type=req.param("op_type", "index"))

    def index_doc_auto_id(self, req: RestRequest) -> RestResponse:
        import uuid as _uuid

        return self._do_index(req, _uuid.uuid4().hex[:20], op_type="create")

    def create_doc(self, req: RestRequest) -> RestResponse:
        return self._do_index(req, req.param("id"), op_type="create")

    def _auto_create(self, name: str) -> None:
        if self.node.indices.has(name):
            return
        if not getattr(self.node, "auto_create_index", True):
            raise IndexNotFoundError(name)
        self.node.create_index(name, {})  # auto-create (ref: TransportBulkAction)

    def _resolve_write(self, name: str) -> str:
        """Write-target resolution (ref: IndexNameExpressionResolver
        concreteWriteIndex): a concrete index is itself; an alias resolves
        to its single index or, among several, the one flagged
        is_write_index; ambiguous aliases are a 400."""
        if self.node.indices.has(name):
            return name
        cs = self.node.cluster_state
        holders = [(n, cs.indices[n].aliases[name])
                   for n in sorted(cs.indices)
                   if name in cs.indices[n].aliases]
        if not holders:
            return name            # unknown name: auto-create path decides
        if len(holders) == 1:
            return holders[0][0]
        writers = [n for n, spec in holders if spec.get("is_write_index")]
        if len(writers) != 1:
            raise IllegalArgumentError(
                f"no write index is defined for alias [{name}]. The write "
                "index may be explicitly disabled using is_write_index="
                "false or the alias points to multiple indices without one "
                "being designated as a write index")
        return writers[0]

    def _do_index(self, req: RestRequest, doc_id: str, op_type: str) -> RestResponse:
        name = self._resolve_write(req.param("index"))
        self._auto_create(name)
        svc = self.node.indices.get(name)
        kw = {}
        if req.param("if_seq_no") is not None:
            kw["if_seq_no"] = req.param_int("if_seq_no")
            kw["if_primary_term"] = req.param_int("if_primary_term")
        routed = self._run_pipeline(name, doc_id, req.body or {},
                                    req.param("pipeline"))
        if routed is None:   # dropped by the pipeline
            return _ok({"_index": name, "_id": doc_id, "result": "noop",
                        "_shards": {"total": 0, "successful": 0, "failed": 0}})
        source, name, doc_id = routed
        if not self.node.indices.has(name):
            self.node.create_index(name, {})   # pipeline rerouted the doc
        svc = self.node.indices.get(name)
        result = svc.index_doc(doc_id, source, op_type=op_type, **kw)
        resp = self._write_response(name, result)
        if req.param("refresh") in ("true", "", "wait_for"):
            svc.refresh()
            resp["forced_refresh"] = True
        status = 201 if result.result == "created" else 200
        return _ok(resp, status)

    def _write_response(self, index: str, result) -> dict:
        return {
            "_index": index,
            "_id": result.doc_id,
            "_version": result.version,
            "result": result.result,
            "_shards": {"total": 1, "successful": 1, "failed": 0},
            "_seq_no": result.seq_no,
            "_primary_term": result.primary_term,
        }

    def get_doc(self, req: RestRequest) -> RestResponse:
        svc = self.node.indices.get(req.param("index"))
        doc = svc.get_doc(req.param("id"), routing=req.param("routing"))
        if doc is None:
            return _ok({"_index": req.param("index"), "_id": req.param("id"), "found": False}, 404)
        out = {"_index": req.param("index"), **doc, "found": True}
        return _ok(out)

    def head_doc(self, req: RestRequest) -> RestResponse:
        svc = self.node.indices.get(req.param("index"))
        doc = svc.get_doc(req.param("id"))
        return RestResponse(status=200 if doc else 404, body={})

    def get_source(self, req: RestRequest) -> RestResponse:
        svc = self.node.indices.get(req.param("index"))
        doc = svc.get_doc(req.param("id"))
        if doc is None:
            raise DocumentMissingError(f"[{req.param('id')}]: document missing")
        return _ok(doc["_source"])

    def delete_doc(self, req: RestRequest) -> RestResponse:
        name = req.param("index")
        svc = self.node.indices.get(name)
        kw = {}
        if req.param("if_seq_no") is not None:
            kw["if_seq_no"] = req.param_int("if_seq_no")
            kw["if_primary_term"] = req.param_int("if_primary_term")
        result = svc.delete_doc(req.param("id"), **kw)
        if req.param("refresh") in ("true", "", "wait_for"):
            svc.refresh()
        status = 200 if result.result == "deleted" else 404
        return _ok(self._write_response(name, result), status)

    def update_doc(self, req: RestRequest) -> RestResponse:
        """Partial update: doc merge + doc_as_upsert/upsert
        (ref: action/update/UpdateHelper.java)."""
        name = req.param("index")
        body = req.body or {}
        if not self.node.indices.has(name) and (
                "upsert" in body or body.get("doc_as_upsert")):
            self._auto_create(name)
        svc = self.node.indices.get(name)
        doc_id = req.param("id")
        existing = svc.get_doc(doc_id)
        if existing is None:
            if body.get("doc_as_upsert") and "doc" in body:
                source = body["doc"]
            elif "upsert" in body:
                source = body["upsert"]
            else:
                raise DocumentMissingError(f"[{doc_id}]: document missing")
            result = svc.index_doc(doc_id, source)
        else:
            if "doc" not in body:
                raise IllegalArgumentError("failed to parse update request: expected [doc]")
            merged = _deep_merge(dict(existing["_source"]), body["doc"])
            if merged == existing["_source"] and not body.get("detect_noop") is False:
                return _ok({
                    "_index": name, "_id": doc_id, "_version": existing["_version"],
                    "result": "noop",
                    "_shards": {"total": 0, "successful": 0, "failed": 0},
                    "_seq_no": existing["_seq_no"], "_primary_term": existing["_primary_term"],
                })
            result = svc.index_doc(doc_id, merged)
        if req.param("refresh") in ("true", "", "wait_for"):
            svc.refresh()
        return _ok(self._write_response(name, result))

    def mget(self, req: RestRequest) -> RestResponse:
        body = req.body or {}
        docs_spec = body.get("docs")
        if docs_spec is None and "ids" in body:
            docs_spec = [{"_id": str(i), "_index": req.param("index")}
                         for i in body["ids"]]
        out = []
        for spec in docs_spec or []:
            index = spec.get("_index", req.param("index"))
            doc_id = str(spec["_id"])
            try:
                svc = self.node.indices.get(index)
                doc = svc.get_doc(doc_id)
            except IndexNotFoundError:
                doc = None
            if doc is None:
                out.append({"_index": index, "_id": doc_id, "found": False})
            else:
                out.append({"_index": index, **doc, "found": True})
        return _ok({"docs": out})

    # ---------- bulk ----------

    def bulk(self, req: RestRequest) -> RestResponse:
        """NDJSON bulk (ref: action/bulk/TransportBulkAction.java:164).
        The whole request's bytes are reserved on the node's
        IndexingPressure for the bulk's lifetime — a flood bounces with
        429 instead of buffering unbounded (ref: IndexingPressure.java)."""
        from elasticsearch_tpu.tasks import task_manager as _taskmgr

        with self.node.indexing_pressure.coordinating(len(req.raw_body)):
            if _taskmgr.current_task() is None:
                with self.node.tasks.task(
                        "indices:data/write/bulk",
                        f"bulk bytes[{len(req.raw_body)}]"):
                    return self._bulk_inner(req)
            return self._bulk_inner(req)

    def _bulk_inner(self, req: RestRequest) -> RestResponse:
        default_index = req.param("index")
        lines = [ln for ln in req.raw_body.decode("utf-8").split("\n") if ln.strip()]
        items: List[dict] = []
        errors = False
        start = time.monotonic()
        i = 0
        touched = set()
        while i < len(lines):
            try:
                action_line = json.loads(lines[i])
            except json.JSONDecodeError:
                raise ParsingError(f"Malformed action/metadata line [{i + 1}]")
            if len(action_line) != 1:
                raise ParsingError(f"Malformed action/metadata line [{i + 1}]")
            op, meta = next(iter(action_line.items()))
            raw_index = meta.get("_index", default_index)
            if raw_index is None:
                raise ParsingError(
                    f"Validation Failed: 1: index is missing for action "
                    f"line [{i + 1}];")
            index = self._resolve_write(str(raw_index))
            doc_id = meta.get("_id")
            if doc_id is not None:
                doc_id = str(doc_id)
            i += 1
            source = None
            if op in ("index", "create", "update"):
                if i >= len(lines):
                    raise ParsingError("Validation Failed: missing source for bulk op")
                source = json.loads(lines[i])
                i += 1
            try:
                self._auto_create(index)
                svc = self.node.indices.get(index)
                touched.add(index)
                if op in ("index", "create"):
                    if doc_id is None:
                        import uuid as _uuid

                        doc_id = _uuid.uuid4().hex[:20]
                    routed = self._run_pipeline(
                        index, doc_id, source,
                        meta.get("pipeline", req.param("pipeline")))
                    if routed is None:   # dropped by the pipeline
                        items.append({op: {"_index": index, "_id": doc_id,
                                           "result": "noop", "status": 200}})
                        continue
                    source, index, doc_id = routed
                    if not self.node.indices.has(index):
                        self.node.create_index(index, {})
                    svc = self.node.indices.get(index)
                    touched.add(index)
                    result = svc.index_doc(doc_id, source,
                                           op_type="create" if op == "create" else "index")
                    items.append({op: {**self._write_response(index, result),
                                       "status": 201 if result.result == "created" else 200}})
                elif op == "delete":
                    result = svc.delete_doc(doc_id)
                    items.append({op: {**self._write_response(index, result),
                                       "status": 200 if result.result == "deleted" else 404}})
                elif op == "update":
                    sub = RestRequest("POST", "", {"index": index, "id": doc_id}, source)
                    resp = self.update_doc(sub)
                    items.append({op: {**resp.body, "status": resp.status}})
                else:
                    raise ParsingError(f"Malformed action [{op}]")
            except ElasticsearchTpuError as e:
                errors = True
                items.append({op: {"_index": index, "_id": doc_id, "status": e.status,
                                   "error": e.to_dict()}})
        if req.param("refresh") in ("true", "", "wait_for"):
            for name in touched:
                self.node.indices.get(name).refresh()
        took = int((time.monotonic() - start) * 1000)
        return _ok({"took": took, "errors": errors, "items": items})

    # ---------- search ----------

    def _ok_search(self, req: RestRequest, resp: dict, status: int = 200):
        """Search-family envelope: rest_total_hits_as_int renders hits.total
        as the pre-7.0 integer (ref: RestSearchAction TOTAL_HITS_AS_INT)."""
        if req.param_bool("rest_total_hits_as_int"):
            def fix(r):
                hits = r.get("hits") if isinstance(r, dict) else None
                if isinstance(hits, dict) and isinstance(hits.get("total"),
                                                         dict):
                    hits["total"] = hits["total"]["value"]
            fix(resp)
            for sub in resp.get("responses", []) or []:
                fix(sub)
        return _ok(resp, status)

    def _trace_enabled(self, req: RestRequest, body: dict) -> bool:
        """Flight-recorder enablement for one search: profile requests,
        every-Nth sampling (ES_TPU_TRACE_SAMPLE), or any target index with
        a slowlog threshold configured (a slow query must carry phase
        attribution when it lands in the slowlog)."""
        from elasticsearch_tpu.common import tracing

        if body.get("profile"):
            return True
        if tracing.should_sample():
            return True
        try:
            names = self._resolve(req.param("index"))
        except ElasticsearchTpuError:
            return False
        for n in names or ():
            try:
                th = self.node.indices.get(n).effective_slowlog_thresholds()
            except Exception:  # noqa: BLE001 — enablement never fails a search
                continue
            if any(v is not None for per in th.values()
                   for v in per.values()):
                return True
        return False

    def search(self, req: RestRequest) -> RestResponse:
        """Search entry: wraps the phase runner in a per-request
        TraceContext when the flight recorder is on (the `rest_total`
        histogram records regardless). Traced profile responses gain a
        `profile.tpu` section with the trace id and per-phase totals."""
        from elasticsearch_tpu.common import metrics, tracing
        from elasticsearch_tpu.threadpool import (
            activate_tier, tier_for_request,
        )

        body_view = req.body if isinstance(req.body, dict) else {}
        tc = None
        if tracing.current() is None and self._trace_enabled(req, body_view):
            tc = tracing.TraceContext(
                opaque_id=req.headers.get("x-opaque-id"),
                node=self.node.node_name, kind="rest")
        t0 = time.monotonic()
        # SLA tier for the dispatch scheduler: classifier + optional
        # `sla` request param, bound for the whole request like the trace
        tier = tier_for_request(req.method, req.path, req.params)
        with tracing.activate(tc), activate_tier(tier):
            rr = self._search_inner(req)
        total_ms = (time.monotonic() - t0) * 1e3
        metrics.observe("rest_total", total_ms)
        if tc is not None:
            tc.add_span("rest_total", total_ms, path=req.path)
            tracing.record_trace(tc)
            if isinstance(rr.body, dict) and isinstance(
                    rr.body.get("profile"), dict):
                from elasticsearch_tpu.common import hbm_ledger

                # routing explainability (PR 12): why this index's engine
                # selection went turbo or not, with the byte arithmetic
                routing = hbm_ledger.last_routing()
                tpu_profile = {
                    "trace_id": tc.trace_id, "opaque_id": tc.opaque_id,
                    "node": self.node.node_name,
                    "phases": tc.phase_totals()}
                if routing is not None:
                    tpu_profile["routing_reason"] = routing["reason"]
                    tpu_profile["routing"] = routing
                rr.body["profile"].setdefault("tpu", tpu_profile)
        return rr

    def _search_inner(self, req: RestRequest) -> RestResponse:
        from elasticsearch_tpu.index.index_service import parse_keep_alive

        body = dict(req.body or {})
        # url params mirror body fields (ref: RestSearchAction)
        if req.param("q") is not None:
            body["query"] = {"match": {"_all": req.param("q")}}  # minimal q= support
        for p in ("size", "from"):
            if req.param(p) is not None:
                body[p] = req.param_int(p)
        if req.param("timeout") is not None:
            body["timeout"] = req.param("timeout")
        if req.param("allow_partial_search_results") is not None:
            body["allow_partial_search_results"] = \
                req.param_bool("allow_partial_search_results")
        # point-in-time searches carry their index inside the pinned context
        pit = body.get("pit")
        if pit:
            ctx = self.node.indices.contexts.get(pit["id"])
            if pit.get("keep_alive"):
                ctx.keep_alive_s = parse_keep_alive(pit["keep_alive"])
            clean = {k: v for k, v in body.items() if k != "pit"}
            svc = self.node.indices.get(ctx.index)
            with self.node.tasks.task("indices:data/read/search",
                                      f"pit[{ctx.index}]") as task:
                resp = svc.search(clean, searchers=ctx.extra["searchers"],
                                  task=task)
            resp["pit_id"] = pit["id"]
            return self._ok_search(req, resp)
        # cross-cluster fan-out (PR 20): `remote:index` parts peel off into
        # one search RPC per registered remote; stays off the hot path for
        # expressions with no ':' or an empty remote registry
        index_expr = req.param("index")
        if self.node.remotes.has_remote_parts(index_expr):
            return self._ok_search(req, self._ccs_search(index_expr, body))
        names = self._resolve(index_expr, require=True)
        search_type = req.param("search_type", "query_then_fetch")
        # every search runs under a registered cancellable task
        # (ref: tasks/TaskManager.java:71 via TransportAction.execute)
        with self.node.tasks.task("indices:data/read/search",
                                  f"indices[{','.join(names)}]") as task:
            if req.param("scroll") is not None:
                if len(names) != 1:
                    raise IllegalArgumentError("scroll requires a single index")
                keep = parse_keep_alive(req.param("scroll"))
                return self._ok_search(req, self.node.indices.scroll_start(
                    names[0], body, keep, task=task))
            if len(names) == 1:
                return self._ok_search(req, self.node.indices.get(
                    names[0]).search(body, search_type, task=task))
            return self._ok_search(req, self._multi_index_search(
                names, body, search_type, task=task))

    def scroll_next(self, req: RestRequest) -> RestResponse:
        from elasticsearch_tpu.index.index_service import parse_keep_alive

        body = dict(req.body or {})
        scroll_id = body.get("scroll_id") or req.param("scroll_id")
        if not scroll_id:
            raise IllegalArgumentError("scroll_id is required")
        keep = parse_keep_alive(body.get("scroll") or req.param("scroll"),
                                0.0) or None
        with self.node.tasks.task("indices:data/read/scroll",
                                  f"scroll[{scroll_id[:8]}]") as task:
            return self._ok_search(req, self.node.indices.scroll_continue(
                scroll_id, keep, task=task))

    def scroll_clear(self, req: RestRequest) -> RestResponse:
        body = dict(req.body or {})
        ids = body.get("scroll_id", [])
        if isinstance(ids, str):
            ids = [ids]
        freed = sum(1 for i in ids if self.node.indices.contexts.release(i))
        return _ok({"succeeded": True, "num_freed": freed})

    def open_pit(self, req: RestRequest) -> RestResponse:
        from elasticsearch_tpu.index.index_service import parse_keep_alive

        names = self._resolve(req.param("index"), require=True)
        if len(names) != 1:
            raise IllegalArgumentError("PIT requires a single index")
        keep = parse_keep_alive(req.param("keep_alive"))
        pit_id = self.node.indices.open_pit(names[0], keep)
        return _ok({"id": pit_id})

    def close_pit(self, req: RestRequest) -> RestResponse:
        body = dict(req.body or {})
        ok = self.node.indices.close_pit(body.get("id", ""))
        return _ok({"succeeded": ok, "num_freed": int(ok)})

    def hot_threads(self, req: RestRequest) -> RestResponse:
        """ref: RestNodesHotThreadsAction — two-sample stack diff per node,
        fanned out across the cluster by the task plane; idle pool workers
        whose stacks didn't move between samples are elided."""
        return RestResponse(status=200,
                            body=self.node.task_plane.hot_threads(),
                            content_type="text/plain")

    # ---------- termvectors / templates(search) ----------

    def termvectors(self, req: RestRequest) -> RestResponse:
        """ref: RestTermVectorsAction — per-field term/freq/position stats
        for one document. REALTIME: the stored source is re-analyzed
        through the mapper (exactly what indexing did), so unrefreshed
        docs work and cost is O(doc terms), not O(vocabulary); df/ttf
        term statistics come from the postings for just the doc's terms."""
        names = self._resolve(req.param("index"), require=True)
        if len(names) != 1:
            raise IllegalArgumentError(
                "_termvectors requires exactly one concrete index")
        name = names[0]
        doc_id = req.param("id")
        svc = self.node.indices.get(name)
        source = svc.get_doc(doc_id)          # realtime (version map)
        if source is None:
            raise DocumentMissingError(f"[{doc_id}]: document missing")
        body = dict(req.body or {})
        want = body.get("fields") or req.param("fields")
        if isinstance(want, str):
            want = want.split(",")
        parsed = svc.mapper.parse(doc_id, source["_source"]
                                  if "_source" in source else source)
        engine = svc.shard_for(doc_id)
        searcher = engine.acquire_searcher()
        tv = {}
        field_terms = dict(parsed.inverted)
        for fname, values in parsed.keyword.items():
            field_terms.setdefault(fname, [(v, [0]) for v in values])
        for fname, entries in field_terms.items():
            if want and fname not in want:
                continue
            merged: Dict[str, list] = {}
            for term, positions in entries:
                merged.setdefault(term, []).extend(positions)
            terms_out = {}
            for t, positions in sorted(merged.items()):
                entry: Dict[str, Any] = {"term_freq": len(positions)}
                entry["tokens"] = [{"position": int(p)}
                                   for p in sorted(positions)]
                if body.get("term_statistics"):
                    df = ttf = 0
                    for v in searcher.views:
                        d, f = v.segment.term_stats(fname, t)
                        df += d
                        ttf += f
                    entry["doc_freq"] = df
                    entry["ttf"] = ttf
                terms_out[t] = entry
            if terms_out:
                stats = {}
                for v in searcher.views:
                    fp = v.segment.postings.get(fname)
                    if fp is None:
                        continue
                    stats["sum_doc_freq"] = stats.get("sum_doc_freq", 0) + \
                        int(fp.doc_freq.sum())
                    stats["sum_ttf"] = stats.get("sum_ttf", 0) + \
                        int(fp.total_term_freq.sum())
                    stats["doc_count"] = stats.get("doc_count", 0) + \
                        int((fp.doc_len > 0).sum())
                tv[fname] = {
                    "field_statistics": {
                        "sum_doc_freq": stats.get("sum_doc_freq", 0),
                        "doc_count": stats.get("doc_count", 0),
                        "sum_ttf": stats.get("sum_ttf", 0),
                    },
                    "terms": terms_out,
                }
        return _ok({"_index": name, "_id": doc_id, "found": True,
                    "term_vectors": tv})

    def render_template(self, req: RestRequest) -> RestResponse:
        body = dict(req.body or {})
        rendered = _render_search_template(
            body.get("source"), body.get("params") or {})
        return _ok({"template_output": rendered})

    def search_template(self, req: RestRequest) -> RestResponse:
        """ref: RestSearchTemplateAction (mustache module) — render the
        source template with params, then execute as a normal search."""
        body = dict(req.body or {})
        rendered = _render_search_template(
            body.get("source"), body.get("params") or {})
        sub = RestRequest("POST", "", dict(req.params), rendered)
        return self.search(sub)

    # ---------- index templates / cluster settings ----------

    def put_index_template(self, req: RestRequest) -> RestResponse:
        self.node.indices.put_template(req.param("name"),
                                       dict(req.body or {}))
        return _ok({"acknowledged": True})

    def get_index_template(self, req: RestRequest) -> RestResponse:
        import fnmatch as _fn

        name = req.param("name")
        out = [{"name": n, "index_template": t}
               for n, t in self.node.indices.templates.items()
               if _fn.fnmatchcase(n, name)]
        if not out and "*" not in name:
            e = ElasticsearchTpuError(
                f"index template matching [{name}] not found")
            e.status = 404
            raise e
        return _ok({"index_templates": out})

    def get_index_templates(self, req: RestRequest) -> RestResponse:
        return _ok({"index_templates": [
            {"name": n, "index_template": t}
            for n, t in self.node.indices.templates.items()]})

    def delete_index_template(self, req: RestRequest) -> RestResponse:
        self.node.indices.delete_template(req.param("name"))
        return _ok({"acknowledged": True})

    def get_cluster_settings(self, req: RestRequest) -> RestResponse:
        from elasticsearch_tpu.common.settings import Settings as _S

        out = {"persistent": _S(self.node._persistent_settings).as_nested_dict(),
               "transient": _S(self.node._transient_settings).as_nested_dict()}
        if req.param("include_defaults") == "true":
            out["defaults"] = {
                s.key: s.get(self.node.cluster_settings.settings)
                for s in self.node.cluster_settings._registered.values()}
        return _ok(out)

    def put_cluster_settings(self, req: RestRequest) -> RestResponse:
        """ref: RestClusterUpdateSettingsAction — validated against the
        registered dynamic settings; persistent/transient tracked apart."""
        body = dict(req.body or {})
        from elasticsearch_tpu.common.settings import Settings as _S

        # validate EVERYTHING before committing anything (the reference
        # rejects the whole request; partial commits would lie)
        all_updates = {}
        for scope in ("persistent", "transient"):
            flat = _S(body.get(scope) or {})
            all_updates[scope] = {k: flat.raw(k) for k in flat}
        for scope, updates in all_updates.items():
            for key in updates:
                if key not in self.node.cluster_settings._registered:
                    raise IllegalArgumentError(
                        f"{scope} setting [{key}], not recognized")
        for scope in ("persistent", "transient"):
            updates = all_updates[scope]
            if not updates:
                continue
            self.node.cluster_settings.apply(updates)
            store = (self.node._persistent_settings if scope == "persistent"
                     else self.node._transient_settings)
            for k, v in updates.items():
                if v is None:
                    store.pop(k, None)
                else:
                    store[k] = v
        return _ok({"acknowledged": True,
                    "persistent": _S(self.node._persistent_settings).as_nested_dict(),
                    "transient": _S(self.node._transient_settings).as_nested_dict()})

    def cluster_reroute(self, req: RestRequest) -> RestResponse:
        """POST /_cluster/reroute (ref: RestClusterRerouteAction) —
        explicit `move` commands through the same allocation step the
        drain/rebalance deciders use; `dry_run` plans and discards. On a
        standalone node every move is explained-and-rejected (there is no
        second node), which is exactly what the reference answers too."""
        from elasticsearch_tpu.cluster.allocation import AllocationService

        body = dict(req.body or {})
        commands = list(body.get("commands", []))
        dry_run = req.param_bool("dry_run") or bool(body.get("dry_run"))
        alloc = AllocationService()

        def plan(state, explain):
            st = state
            # commands address nodes by id OR name (the reference resolves
            # both in DiscoveryNodes#resolveNode)
            by_name = {n.name: nid for nid, n in st.nodes.items()}
            for cmd in commands:
                move = cmd.get("move")
                if not move:
                    if explain is not None:
                        explain.append({
                            "command": sorted(cmd)[0] if cmd else "?",
                            "accepted": False,
                            "reason": "only the move command is supported"})
                    continue
                index = move["index"]
                sid = int(move["shard"])
                frm, to = move["from_node"], move["to_node"]
                frm = frm if frm in st.nodes else by_name.get(frm, frm)
                to = to if to in st.nodes else by_name.get(to, to)
                src = next(
                    (r for r in st.routing.get(index, [])
                     if r.shard_id == sid and r.node_id == frm
                     and r.state == "STARTED"), None)
                if src is None:
                    if explain is not None:
                        explain.append({
                            "command": "move", "index": index, "shard": sid,
                            "accepted": False,
                            "reason": f"no STARTED copy of [{index}][{sid}] "
                                      f"on [{frm}]"})
                    continue
                moved = alloc.initiate_relocation(
                    st, index, sid, src.allocation_id, to)
                if explain is not None:
                    explain.append({
                        "command": "move", "index": index, "shard": sid,
                        "from_node": frm, "to_node": to,
                        "accepted": moved is not st,
                        **({} if moved is not st else
                           {"reason": "move rejected: target unknown, same "
                                      "node, or already holds a copy"})})
                st = moved
            return st

        explanations: list = []
        plan(self.node.cluster_state, explanations)
        if not dry_run:
            self.node.update_state(lambda st: alloc.reroute(plan(st, None)))
        return _ok({"acknowledged": True, "dry_run": dry_run,
                    "explanations": explanations,
                    "state": {"version": self.node.cluster_state.version}})

    # ---------- rank_eval (ref: modules/rank-eval RankEvalPlugin) ----------

    def rank_eval(self, req: RestRequest) -> RestResponse:
        body = dict(req.body or {})
        names = self._resolve(req.param("index"), require=True)
        metric_spec = body.get("metric", {"precision": {}})
        if not isinstance(metric_spec, dict) or len(metric_spec) != 1:
            raise IllegalArgumentError(
                "[metric] must name exactly one metric")
        (mname, mparams), = metric_spec.items()
        mparams = mparams or {}
        k = int(mparams.get("k", 10))
        details = {}
        scores = []
        for r in body.get("requests", []):
            rid = r["id"]
            rated = {(d["_index"], d["_id"]): int(d["rating"])
                     for d in r.get("ratings", [])}
            request = dict(r.get("request") or {})
            request.setdefault("size", k)
            if len(names) == 1:
                resp = self.node.indices.get(names[0]).search(request)
            else:
                resp = self._multi_index_search(names, request,
                                                "query_then_fetch")
            hits = resp["hits"]["hits"][:k]
            hit_rated = [rated.get((h["_index"], h["_id"]), None)
                         for h in hits]
            rel_thresh = int(mparams.get("relevant_rating_threshold", 1))
            relevant = [x is not None and x >= rel_thresh for x in hit_rated]
            if mname == "precision":
                denom = len(hits) if not mparams.get(
                    "ignore_unlabeled") else sum(
                    1 for x in hit_rated if x is not None)
                score = (sum(relevant) / denom) if denom else 0.0
            elif mname == "recall":
                total_rel = sum(1 for v in rated.values() if v >= rel_thresh)
                score = (sum(relevant) / total_rel) if total_rel else 0.0
            elif mname == "mean_reciprocal_rank":
                score = 0.0
                for i, ok in enumerate(relevant):
                    if ok:
                        score = 1.0 / (i + 1)
                        break
            elif mname == "dcg":
                import math

                # ref: DiscountedCumulativeGain — exponential gain
                score = sum((2 ** (x or 0) - 1) / math.log2(i + 2)
                            for i, x in enumerate(hit_rated))
            else:
                raise IllegalArgumentError(f"unknown metric [{mname}]")
            scores.append(score)
            details[rid] = {
                "metric_score": score,
                "unrated_docs": [{"_index": h["_index"], "_id": h["_id"]}
                                 for h, x in zip(hits, hit_rated)
                                 if x is None],
                "hits": [{"hit": {"_index": h["_index"], "_id": h["_id"],
                                  "_score": h.get("_score")},
                          "rating": x} for h, x in zip(hits, hit_rated)],
            }
        return _ok({"metric_score": (sum(scores) / len(scores)) if scores
                    else 0.0, "details": details, "failures": {}})

    # ---------- async search (ref: x-pack async-search) ----------

    _ASYNC_KEEP_S = 300.0
    _ASYNC_MAX = 100

    def _async_store(self):
        """Created eagerly in Node.__init__ (lazy creation would race under
        the threaded HTTP server); completed entries expire after keep-alive
        and the store is size-capped (the reference expires via keep_alive)."""
        import time as _time

        store = self.node._async_searches
        now = _time.monotonic()
        dead = [k for k, v in list(store.items())
                if not v["is_running"] and v.get("expires_at", 0) < now]
        for k in dead:
            store.pop(k, None)
        while len(store) > self._ASYNC_MAX:
            store.pop(next(iter(store)), None)
        return store

    def async_search_submit(self, req: RestRequest) -> RestResponse:
        import threading as _t
        import time as _time
        import uuid as _uuid

        names = self._resolve(req.param("index"), require=True)
        body = dict(req.body or {})
        wait_ms = 0
        if req.param("wait_for_completion_timeout") is not None:
            from elasticsearch_tpu.tasks.task_manager import parse_timeout_ms

            wait_ms = parse_timeout_ms(
                req.param("wait_for_completion_timeout")) or 0
        sid = _uuid.uuid4().hex
        task = self.node.tasks.register("indices:data/read/async_search",
                                        f"async[{','.join(names)}]")
        entry = {"is_running": True, "is_partial": True, "response": None,
                 "error": None, "start": int(_time.time() * 1000),
                 "task": task, "done": _t.Event()}
        self._async_store()[sid] = entry

        def run():
            try:
                if len(names) == 1:
                    entry["response"] = self.node.indices.get(
                        names[0]).search(body, task=task)
                else:
                    entry["response"] = self._multi_index_search(
                        names, body, "query_then_fetch", task=task)
            except ElasticsearchTpuError as e:
                entry["error"] = e
            except Exception as e:  # noqa: BLE001 — a failed search must
                err = ElasticsearchTpuError(str(e))   # never report success
                err.status = 500
                entry["error"] = err
            finally:
                import time as _tt

                entry["is_running"] = False
                entry["is_partial"] = entry["response"] is None
                entry["expires_at"] = _tt.monotonic() + self._ASYNC_KEEP_S
                self.node.tasks.unregister(task)
                entry["done"].set()

        _t.Thread(target=run, daemon=True,
                  name=f"async-search-{sid[:8]}").start()
        if wait_ms:
            entry["done"].wait(wait_ms / 1000.0)
        return self._async_render(sid, entry)

    def _async_render(self, sid, entry) -> RestResponse:
        if entry["error"] is not None:
            e = entry["error"]
            return RestResponse(status=e.status,
                               body={"error": e.to_dict(), "id": sid})
        return _ok({
            "id": sid,
            "is_running": entry["is_running"],
            "is_partial": entry["is_running"] or entry["response"] is None,
            "start_time_in_millis": entry["start"],
            "response": entry["response"] or {
                "hits": {"total": {"value": 0, "relation": "gte"},
                         "hits": []}},
        })

    def async_search_get(self, req: RestRequest) -> RestResponse:
        entry = self._async_store().get(req.param("id"))
        if entry is None:
            e = ElasticsearchTpuError(
                f"async search [{req.param('id')}] not found")
            e.status = 404
            raise e
        return self._async_render(req.param("id"), entry)

    def async_search_delete(self, req: RestRequest) -> RestResponse:
        entry = self._async_store().pop(req.param("id"), None)
        if entry is None:
            e = ElasticsearchTpuError("not found")
            e.status = 404
            raise e
        if entry["is_running"]:
            entry["task"].cancel("async search deleted")
        return _ok({"acknowledged": True})

    # ---------- reindex / field_caps / explain ----------

    def reindex(self, req: RestRequest) -> RestResponse:
        """Server-side scan + bulk copy (ref: RestReindexAction /
        reindex module): source index (+ optional query) into dest,
        optionally through an ingest pipeline."""
        body = dict(req.body or {})
        src_spec = body.get("source") or {}
        dest_spec = body.get("dest") or {}
        src_names = self._resolve(src_spec.get("index"), require=True)
        dest = dest_spec.get("index")
        if not dest:
            raise IllegalArgumentError("[dest.index] is required")
        pipeline = dest_spec.get("pipeline")
        op_type = dest_spec.get("op_type", "index")
        query = src_spec.get("query", {"match_all": {}})
        start = time.monotonic()
        created = updated = noops = conflicts = 0
        failures: list = []
        with self.node.tasks.task("indices:data/write/reindex",
                                  f"reindex to [{dest}]") as task:
            if not self.node.indices.has(dest):
                self.node.create_index(dest, {})
            dsvc = self.node.indices.get(dest)
            for name in src_names:
                svc = self.node.indices.get(name)
                # scan via the cursor machinery (stable under writes)
                body_q = {"query": query, "size": 500, "_want_cursor": True}
                resp = svc._search_dense(dict(body_q), task=task)
                while True:
                    hits = resp["hits"]["hits"]
                    if not hits:
                        break
                    for h in hits:
                        task.check()
                        source = h.get("_source", {})
                        doc_id = h["_id"]
                        routed = self._run_pipeline(dest, doc_id, source,
                                                    pipeline)
                        if routed is None:
                            noops += 1
                            continue
                        source, d_index, doc_id = routed
                        target = dsvc if d_index == dest else None
                        if target is None:
                            if not self.node.indices.has(d_index):
                                self.node.create_index(d_index, {})
                            target = self.node.indices.get(d_index)
                        try:
                            r = target.index_doc(doc_id, source,
                                                 op_type=op_type)
                            if r.result == "created":
                                created += 1
                            else:
                                updated += 1
                        except VersionConflictError:
                            conflicts += 1
                        except ElasticsearchTpuError as e:
                            # non-conflict errors (mapping conflicts etc.)
                            # must surface in `failures`, not masquerade as
                            # version_conflicts (ref: reindex module's
                            # BulkByScrollResponse; ADVICE r3)
                            failures.append({
                                "index": d_index, "id": doc_id,
                                "cause": {"type": e.error_type,
                                          "reason": str(e)},
                                "status": e.status})
                    cursor = resp.get("_cursor")
                    if cursor is None:
                        break
                    resp = svc._search_dense({**body_q, "_after_full": cursor},
                                             task=task)
            dsvc.refresh()
        return _ok({"took": int((time.monotonic() - start) * 1000),
                    "timed_out": False, "total": created + updated + noops,
                    "created": created, "updated": updated, "noops": noops,
                    "failures": failures, "batches": 1,
                    "version_conflicts": conflicts})

    def field_caps(self, req: RestRequest) -> RestResponse:
        """ref: RestFieldCapabilitiesAction — per-field type/searchable/
        aggregatable union across the target indices."""
        import fnmatch as _fn

        body = dict(req.body or {})
        pattern = req.param("fields") or body.get("fields", "*")
        if isinstance(pattern, str):
            pattern = pattern.split(",")
        names = self._resolve(req.param("index", "_all"), require=True)
        fields: Dict[str, dict] = {}
        for name in names:
            mapper = self.node.indices.get(name).mapper
            for fname in mapper.field_names():
                ft = mapper.field_type(fname)
                if not any(_fn.fnmatchcase(fname, p) for p in pattern):
                    continue
                type_ = ft.params.get("type", "object")
                caps = fields.setdefault(fname, {}).setdefault(type_, {
                    "type": type_,
                    "metadata_field": False,
                    "searchable": ft.searchable,
                    "aggregatable": ft.has_doc_values,
                })
        return _ok({"indices": names, "fields": fields})

    def explain(self, req: RestRequest) -> RestResponse:
        """ref: RestExplainAction — does this doc match, and with what
        score? Executed by filtering the query to the single document."""
        name = self._resolve(req.param("index"), require=True)[0]
        doc_id = req.param("id")
        svc = self.node.indices.get(name)
        if svc.get_doc(doc_id) is None:
            from elasticsearch_tpu.common.errors import DocumentMissingError

            raise DocumentMissingError(f"[{doc_id}]: document missing")
        body = dict(req.body or {})
        query = body.get("query", {"match_all": {}})
        r = svc.search({"query": {"bool": {
            "must": [query], "filter": [{"ids": {"values": [doc_id]}}]}},
            "size": 1})
        hits = r["hits"]["hits"]
        matched = bool(hits) and hits[0]["_id"] == doc_id
        score = hits[0]["_score"] if matched else 0.0
        return _ok({"_index": name, "_id": doc_id, "matched": matched,
                    "explanation": {
                        "value": score,
                        "description": "score, computed as the sum of the "
                                       "matching clauses' BM25 contributions",
                        "details": [],
                    } if matched else {"value": 0.0,
                                       "description": "no matching term",
                                       "details": []}})

    # ---------- ingest ----------

    def _run_pipeline(self, index: str, doc_id: str, source: dict,
                      pipeline_param):
        """Apply ?pipeline= or the index's default_pipeline; None means
        the document was DROPPED (ref: IngestService drop handling)."""
        pid = pipeline_param
        if pid is None and self.node.indices.has(index):
            meta = self.node.indices.get(index).meta
            pid = meta.settings.raw("index.default_pipeline")
        if not pid or pid == "_none":
            return source, index, doc_id
        return self.node.ingest.process(pid, source, index=index,
                                        doc_id=doc_id or "")

    def put_pipeline(self, req: RestRequest) -> RestResponse:
        self.node.ingest.put_pipeline(req.param("id"), dict(req.body or {}))
        return _ok({"acknowledged": True})

    def get_pipeline(self, req: RestRequest) -> RestResponse:
        p = self.node.ingest.get_pipeline(req.param("id"))
        return _ok({p.id: p.body})

    def get_pipelines(self, req: RestRequest) -> RestResponse:
        return _ok(self.node.ingest.pipelines())

    def delete_pipeline(self, req: RestRequest) -> RestResponse:
        self.node.ingest.delete_pipeline(req.param("id"))
        return _ok({"acknowledged": True})

    def simulate_pipeline(self, req: RestRequest) -> RestResponse:
        body = dict(req.body or {})
        if req.param("id"):
            pipeline_body = self.node.ingest.get_pipeline(req.param("id")).body
        else:
            pipeline_body = body.get("pipeline", {})
        docs = self.node.ingest.simulate(pipeline_body, body.get("docs", []))
        return _ok({"docs": docs})

    # ---------- snapshots ----------

    def put_repository(self, req: RestRequest) -> RestResponse:
        body = dict(req.body or {})
        self.node.snapshots.put_repository(
            req.param("repo"), body.get("type", ""),
            body.get("settings", {}))
        return _ok({"acknowledged": True})

    def get_repository(self, req: RestRequest) -> RestResponse:
        repo = self.node.snapshots.repository(req.param("repo"))
        return _ok({repo.name: {"type": "fs",
                                "settings": {"location": repo.location}}})

    def verify_repository(self, req: RestRequest) -> RestResponse:
        """POST /_snapshot/{repo}/_verify — probe round-trip plus a full
        re-hash of every referenced segment blob (integrity plane, PR 15);
        corrupt blobs come back as per-index lists, not a bare boolean."""
        return _ok(self.node.snapshots.verify_repository(req.param("repo")))

    def create_snapshot(self, req: RestRequest) -> RestResponse:
        body = dict(req.body or {})
        indices = body.get("indices")
        if isinstance(indices, str):
            indices = [i for n in indices.split(",")
                       for i in self._resolve(n, require=True)]
        meta = self.node.snapshots.create(
            req.param("repo"), req.param("snapshot"), indices)
        return _ok({"snapshot": meta})

    def get_snapshot(self, req: RestRequest) -> RestResponse:
        import fnmatch

        snap = req.param("snapshot")
        if snap == "_all" or "*" in snap:
            snaps = self.node.snapshots.list(req.param("repo"))
            if snap != "_all":
                snaps = [s for s in snaps
                         if fnmatch.fnmatchcase(s["snapshot"], snap)]
            return _ok({"snapshots": snaps})
        return _ok({"snapshots": [
            self.node.snapshots.get(req.param("repo"), snap)]})

    def delete_snapshot(self, req: RestRequest) -> RestResponse:
        self.node.snapshots.delete(req.param("repo"), req.param("snapshot"))
        return _ok({"acknowledged": True})

    def restore_snapshot(self, req: RestRequest) -> RestResponse:
        body = dict(req.body or {})
        indices = body.get("indices")
        if isinstance(indices, str):
            indices = indices.split(",")
        return _ok(self.node.snapshots.restore(
            req.param("repo"), req.param("snapshot"), indices,
            body.get("rename_pattern"), body.get("rename_replacement")))

    # ---------- tasks (ref: RestListTasksAction, RestCancelTasksAction) ----------

    def list_tasks(self, req: RestRequest) -> RestResponse:
        """Cluster-wide listing via the task plane: fans out over every
        cluster node, degrades to partial results + `node_failures` when
        a peer is dead (ref: TransportListTasksAction)."""
        return _ok(self.node.task_plane.list(
            actions=req.param("actions"),
            nodes=req.param("nodes"),
            parent_task_id=req.param("parent_task_id"),
            detailed=req.param_bool("detailed"),
            group_by=req.param("group_by", "nodes")))

    def get_task(self, req: RestRequest) -> RestResponse:
        # routed by the `{node}:{id}` prefix — a remote owner answers over
        # the transport; an unknown/dead owner 404s (malformed ids 400)
        return _ok(self.node.task_plane.get(req.param("task_id", "")))

    def cancel_task(self, req: RestRequest) -> RestResponse:
        from elasticsearch_tpu.tasks.task_manager import parse_timeout_ms

        return _ok(self.node.task_plane.cancel(
            req.param("task_id", ""),
            wait_for_completion=req.param_bool("wait_for_completion"),
            timeout_ms=parse_timeout_ms(req.param("timeout"))))

    def cancel_tasks(self, req: RestRequest) -> RestResponse:
        actions = req.param("actions", "*")
        cancelled = self.node.tasks.cancel_matching(actions)
        return _ok({"nodes": {self.node.tasks.node_id: {
            "tasks": {f"{t.node}:{t.id}": t.to_dict() for t in cancelled}}}})

    def search_all(self, req: RestRequest) -> RestResponse:
        req.params.setdefault("index", "_all")
        return self.search(req)

    def _multi_index_search(self, names: List[str], body: dict, search_type: str,
                            task=None) -> dict:
        if task is None:
            from elasticsearch_tpu.tasks import task_manager as _taskmgr

            task = _taskmgr.current_task()
        responses = [(n, self.node.indices.get(n).search(body, search_type, task=task))
                     for n in names]
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        all_hits = []
        total = 0
        max_score = None
        timed_out = False
        shards_total = 0
        shards_ok = 0
        shards_skipped = 0
        shards_failed = 0
        shard_failures: List[dict] = []
        for name, r in responses:
            total += r["hits"]["total"]["value"]
            # a partially-timed-out or partially-failed member index must
            # not be laundered into a clean merged header (ref:
            # SearchResponseMerger.java — ORs timeouts, sums shard counts)
            timed_out = timed_out or bool(r.get("timed_out"))
            sh = r.get("_shards", {})
            shards_total += sh.get("total", 0)
            shards_ok += sh.get("successful", 0)
            shards_skipped += sh.get("skipped", 0)
            shards_failed += sh.get("failed", 0)
            shard_failures.extend(sh.get("failures", []))
            if r["hits"]["max_score"] is not None:
                max_score = max(max_score or float("-inf"), r["hits"]["max_score"])
            all_hits.extend(r["hits"]["hits"])
        if body.get("sort"):
            all_hits.sort(key=lambda h: h.get("sort", []))
        else:
            all_hits.sort(key=lambda h: -(h.get("_score") or 0.0))
        shards: dict = {"total": shards_total, "successful": shards_ok,
                        "skipped": shards_skipped, "failed": shards_failed}
        if shard_failures:
            shards["failures"] = shard_failures
        return {
            "took": sum(r["took"] for _, r in responses),
            "timed_out": timed_out,
            "_shards": shards,
            "hits": {"total": {"value": total, "relation": "eq"},
                     "max_score": max_score,
                     "hits": all_hits[from_: from_ + size]},
        }

    def _ccs_search(self, index_expr: str, body: dict) -> dict:
        """Cross-cluster fan-out for the standalone node (PR 20): peel the
        `remote:pattern` parts off the expression and let the remote
        registry run one leg per cluster; the purely-local parts re-enter
        the ordinary single-/multi-index path as the local leg."""
        local_parts, remote_groups = \
            self.node.remotes.split_expression(index_expr)

        def local_search(expr: str, sub: dict) -> dict:
            names = self._resolve(expr, require=True)
            if len(names) == 1:
                return self.node.indices.get(names[0]).search(dict(sub))
            return self._multi_index_search(names, dict(sub),
                                            "query_then_fetch")

        with self.node.tasks.task("indices:data/read/search",
                                  f"ccs[{index_expr}]"):
            return self.node.remotes.cross_cluster_search(
                body, local_parts, remote_groups, local_search)

    def msearch(self, req: RestRequest) -> RestResponse:
        from elasticsearch_tpu.threadpool import (
            activate_tier, tier_for_request,
        )

        with activate_tier(tier_for_request(req.method, req.path,
                                            req.params)):
            with self.node.tasks.task(
                    "indices:data/read/msearch",
                    f"msearch bytes[{len(req.raw_body)}]"):
                return self._msearch_inner(req)

    def _msearch_inner(self, req: RestRequest) -> RestResponse:
        lines = [ln for ln in req.raw_body.decode().split("\n") if ln.strip()]
        slots = []   # (index_names | None, body, error | None)
        ccs_exprs: dict = {}   # slot -> `remote:pattern` expression (PR 20)
        i = 0
        while i + 1 <= len(lines) - 1 or (i < len(lines)):
            header = json.loads(lines[i])
            body = json.loads(lines[i + 1]) if i + 1 < len(lines) else {}
            i += 2
            index = header.get("index", req.param("index", "_all"))
            # a `remote:index` line fans out per cluster instead of
            # resolving locally — a line targeting only dead
            # skip_unavailable remotes must come back empty-but-well-formed
            # (`_clusters.skipped` counted), never as an error entry
            if self.node.remotes.has_remote_parts(index):
                ccs_exprs[len(slots)] = index
                slots.append((None, body, None))
                continue
            try:
                slots.append((self._resolve(index, require=True), body, None))
            except ElasticsearchTpuError as e:
                slots.append((None, body, e))
        # single-index bodies group into per-index batches so eligible flat
        # queries share one device dispatch (ref P8 batched _msearch)
        by_index: dict = {}
        for si, (names, body, err) in enumerate(slots):
            if err is None and names is not None and len(names) == 1:
                by_index.setdefault(names[0], []).append(si)
        batched: dict = {}
        for name, idxs in by_index.items():
            try:
                rs = self.node.indices.get(name).msearch([slots[i][1] for i in idxs])
                for si, r in zip(idxs, rs):
                    if isinstance(r, ElasticsearchTpuError):
                        batched[si] = {"error": r.to_dict(), "status": r.status}
                    else:
                        batched[si] = {**r, "status": 200}
            except ElasticsearchTpuError as e:
                for si in idxs:
                    batched[si] = {"error": e.to_dict(), "status": e.status}
        responses = []
        for si, (names, body, err) in enumerate(slots):
            if si in ccs_exprs:
                try:
                    responses.append({**self._ccs_search(ccs_exprs[si],
                                                         body),
                                      "status": 200})
                except ElasticsearchTpuError as e:
                    responses.append({"error": e.to_dict(),
                                      "status": e.status})
            elif err is not None:
                responses.append({"error": err.to_dict(), "status": err.status})
            elif si in batched:
                responses.append(batched[si])
            else:
                try:
                    responses.append({**self._multi_index_search(names, body, "query_then_fetch"),
                                      "status": 200})
                except ElasticsearchTpuError as e:
                    responses.append({"error": e.to_dict(), "status": e.status})
        return self._ok_search(req, {
            "took": sum(r.get("took", 0) for r in responses),
            "responses": responses})

    def count(self, req: RestRequest) -> RestResponse:
        body = dict(req.body or {})
        body["size"] = 0
        body["track_total_hits"] = True
        names = self._resolve(req.param("index"), require=True)
        total = 0
        for n in names:
            total += self.node.indices.get(n).search(body)["hits"]["total"]["value"]
        return _ok({"count": total,
                    "_shards": {"total": len(names), "successful": len(names),
                                "skipped": 0, "failed": 0}})

    def count_all(self, req: RestRequest) -> RestResponse:
        req.params.setdefault("index", "_all")
        return self.count(req)

    def delete_by_query(self, req: RestRequest) -> RestResponse:
        """Scroll-free delete-by-query (ref: reindex module's
        DeleteByQueryRequest — client-side search+delete loop)."""
        names = self._resolve(req.param("index"), require=True)
        body = dict(req.body or {})
        body["size"] = 10000
        body["_source"] = False
        deleted = 0
        start = time.monotonic()
        for n in names:
            svc = self.node.indices.get(n)
            svc.refresh()
            r = svc.search(body)
            for h in r["hits"]["hits"]:
                result = svc.delete_doc(h["_id"])
                if result.result == "deleted":
                    deleted += 1
            svc.refresh()
        return _ok({"took": int((time.monotonic() - start) * 1000), "timed_out": False,
                    "total": deleted, "deleted": deleted, "batches": 1,
                    "version_conflicts": 0, "noops": 0, "failures": []})

    def update_by_query(self, req: RestRequest) -> RestResponse:
        """Re-indexes matching docs in place (no script support yet)."""
        names = self._resolve(req.param("index"), require=True)
        body = dict(req.body or {})
        if "script" in body:
            raise IllegalArgumentError("script in update_by_query is not yet supported")
        body["size"] = 10000
        updated = 0
        start = time.monotonic()
        for n in names:
            svc = self.node.indices.get(n)
            svc.refresh()
            r = svc.search(body)
            for h in r["hits"]["hits"]:
                svc.index_doc(h["_id"], h["_source"])
                updated += 1
            svc.refresh()
        return _ok({"took": int((time.monotonic() - start) * 1000), "timed_out": False,
                    "total": updated, "updated": updated, "batches": 1,
                    "version_conflicts": 0, "noops": 0, "failures": []})

    # ---------- analyze ----------

    def analyze(self, req: RestRequest) -> RestResponse:
        body = req.body or {}
        text = body.get("text", "")
        texts = text if isinstance(text, list) else [text]
        index = req.param("index")
        if index and self.node.indices.has(index):
            registry = self.node.indices.get(index).analysis
            svc = self.node.indices.get(index)
            if "field" in body:
                ft = svc.mapper.field_type(body["field"])
                analyzer = svc.mapper.analyzer_for(ft) if ft is not None else registry.get("standard")
            else:
                analyzer = registry.get(body.get("analyzer", "standard"))
        else:
            from elasticsearch_tpu.analysis import AnalysisRegistry

            analyzer = AnalysisRegistry().get(body.get("analyzer", "standard"))
        tokens = []
        for i, t in enumerate(texts):
            for tok in analyzer.tokenize(t):
                tokens.append({
                    "token": tok.term,
                    "start_offset": tok.start_offset,
                    "end_offset": tok.end_offset,
                    "type": "<ALPHANUM>",
                    "position": tok.position,
                })
        return _ok({"tokens": tokens})

    # ---------- cluster / monitoring ----------

    def cluster_health(self, req: RestRequest) -> RestResponse:
        """GET /_cluster/health — with the maintenance-plane wait params
        (ref: RestClusterHealthAction): `wait_for_status` blocks until the
        cluster is at least that healthy, `wait_for_no_relocating_shards`
        until every move has completed; both are a bounded poll that
        reports `timed_out: true` rather than erroring on expiry."""
        from elasticsearch_tpu.tasks.task_manager import parse_timeout_ms

        want_status = req.param("wait_for_status")
        want_no_reloc = req.param_bool("wait_for_no_relocating_shards")
        health = self.node.cluster_state.health()
        if want_status is None and not want_no_reloc:
            return _ok(health)
        rank = {"green": 0, "yellow": 1, "red": 2}
        if want_status is not None and want_status not in rank:
            raise IllegalArgumentError(
                f"unknown wait_for_status [{want_status}]")
        timeout_ms = parse_timeout_ms(req.param("timeout")) or 30_000.0
        deadline = time.monotonic() + timeout_ms / 1000.0

        def satisfied(h: dict) -> bool:
            if want_status is not None \
                    and rank[h["status"]] > rank[want_status]:
                return False
            if want_no_reloc and h["relocating_shards"] > 0:
                return False
            return True

        while not satisfied(health):
            if time.monotonic() >= deadline:
                health["timed_out"] = True
                return _ok(health)
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
            health = self.node.cluster_state.health()
        return _ok(health)

    def cluster_state(self, req: RestRequest) -> RestResponse:
        cs = self.node.cluster_state
        return _ok({
            "cluster_name": cs.cluster_name,
            "cluster_uuid": self.node.node_id,
            "version": cs.version,
            "state_uuid": f"v{cs.version}",
            "blocks": {},
            "master_node": cs.master_node_id,
            "nodes": {nid: {"name": n.name, "transport_address": n.address,
                            "roles": list(n.roles)} for nid, n in cs.nodes.items()},
            "metadata": {"indices": {
                name: {"state": m.state, "settings": {"index": {
                    "number_of_shards": str(m.number_of_shards),
                    "number_of_replicas": str(m.number_of_replicas)}},
                    "aliases": sorted(m.aliases)}
                for name, m in cs.indices.items()}},
        })

    def cluster_stats(self, req: RestRequest) -> RestResponse:
        total_docs = sum(self.node.indices.get(n).doc_count()
                         for n in self.node.indices.names())
        return _ok({
            "cluster_name": self.node.cluster_state.cluster_name,
            "status": self.node.cluster_state.health()["status"],
            "indices": {"count": len(self.node.indices.names()),
                        "docs": {"count": total_docs, "deleted": 0}},
            "nodes": {"count": {"total": len(self.node.cluster_state.nodes)}},
        })

    def nodes_info(self, req: RestRequest) -> RestResponse:
        import jax

        cs = self.node.cluster_state
        return _ok({
            "_nodes": {"total": len(cs.nodes), "successful": len(cs.nodes), "failed": 0},
            "cluster_name": cs.cluster_name,
            "nodes": {nid: {
                "name": n.name,
                "transport_address": n.address,
                "version": __version__,
                "roles": list(n.roles),
                "accelerators": [str(d) for d in jax.devices()],
            } for nid, n in cs.nodes.items()},
        })

    # ---- cross-cluster plane (PR 20) ----

    def remote_info(self, req: RestRequest) -> RestResponse:
        """GET /_remote/info (ref: RestRemoteClusterInfoAction)."""
        return _ok(self.node.remotes.remote_info())

    def ccr_follow(self, req: RestRequest) -> RestResponse:
        """PUT /{index}/_ccr/follow (ref: RestPutFollowAction)."""
        body = dict(req.body or {})
        remote_cluster = body.get("remote_cluster")
        leader_index = body.get("leader_index")
        if not remote_cluster or not leader_index:
            raise IllegalArgumentError(
                "_ccr/follow requires [remote_cluster] and [leader_index]")
        return _ok(self.node.ccr.follow(
            req.param("index"), remote_cluster, leader_index,
            settings=body.get("settings")))

    def ccr_pause_follow(self, req: RestRequest) -> RestResponse:
        return _ok(self.node.ccr.pause_follow(req.param("index")))

    def ccr_resume_follow(self, req: RestRequest) -> RestResponse:
        return _ok(self.node.ccr.resume_follow(req.param("index")))

    def ccr_stats(self, req: RestRequest) -> RestResponse:
        """GET /{index}/_ccr/stats (ref: RestFollowStatsAction)."""
        return _ok(self.node.ccr.follower_stats(req.param("index")))

    def _local_node_stats(self) -> dict:
        """This node's full stats sections — the REST body for a
        single-node cluster and the telemetry plane's RPC answer when a
        peer coordinator fans out (cluster/telemetry_plane.py)."""
        return {
            "name": self.node.node_name,
            "indices": {"docs": {"count": sum(
                self.node.indices.get(n).doc_count() for n in self.node.indices.names())}},
            "breakers": self.node.breakers.stats(),
            "indexing_pressure": self.node.indexing_pressure.stats(),
            "thread_pool": self.node.thread_pool.stats(),
            "tpu_coalescer": _default_coalescer_stats(),
            "tpu_scheduler": _default_scheduler_stats(),
            "tpu_turbo": _turbo_merge_stats(),
            "tpu_health": _tpu_health_stats(),
            "tpu_coordinator": _tpu_coordinator_stats(),
            "tpu_durability": _tpu_durability_stats(),
            "tpu_search_latency": _tpu_search_latency_stats(),
            "tpu_settings": _tpu_settings_stats(),
            "tpu_hbm": _tpu_hbm_stats(),
            "tpu_agg": _tpu_agg_stats(),
            "tpu_knn": _tpu_knn_stats(),
            "tpu_compile": _tpu_compile_stats(),
            "tpu_tasks": self.node.tasks.stats(),
            "tpu_overload": self.node.overload.stats(),
            "tpu_relocation": _tpu_relocation_stats(),
            "tpu_integrity": _tpu_integrity_stats(),
            "tpu_ccs": self.node.remotes.stats(),
            "tpu_ccr": self.node.ccr.stats(),
            "jvm": {"uptime_in_millis": int((time.time() - _START_TIME) * 1000)},
        }

    def nodes_stats(self, req: RestRequest) -> RestResponse:
        """GET /_nodes/stats — cluster fan-out through the telemetry
        plane: a dead peer degrades to a `node_failures` entry and
        partial stats, never a failed response (PR 11 /_tasks
        semantics)."""
        cs = self.node.cluster_state
        per_node, failures = self.node.telemetry_plane.nodes_stats()
        nodes = {}
        for name, stats in per_node.items():
            # the local node keeps its id key (response-shape compat);
            # peers key by the name the channels layer routes on
            key = self.node.node_id if name == self.node.node_name else name
            nodes[key] = stats
        out = {
            "_nodes": {"total": len(per_node) + len(failures),
                       "successful": len(per_node),
                       "failed": len(failures)},
            "cluster_name": cs.cluster_name,
            "nodes": nodes,
        }
        if failures:
            out["_nodes"]["failures"] = failures
            out["node_failures"] = failures
        return _ok(out)

    def tpu_metrics(self, req: RestRequest) -> RestResponse:
        """GET /_tpu/metrics — every declared counter/gauge/histogram from
        all live nodes as one Prometheus text exposition (histograms in
        cumulative-`le` form); dead peers degrade to es_tpu_node_up 0."""
        text, _failures = self.node.telemetry_plane.prometheus()
        return RestResponse(body=text,
                            content_type="text/plain; version=0.0.4")

    def tpu_metrics_history(self, req: RestRequest) -> RestResponse:
        """GET /_tpu/metrics/history — the sampler ring: periodic
        counter/gauge snapshots (ES_TPU_METRICS_SAMPLE_S) plus provider
        sections like the scheduler's per-lane busy fraction, so rates
        are computable without an external scraper."""
        from elasticsearch_tpu.common import metrics as _m
        from elasticsearch_tpu.common.settings import knob

        samples = _m.metrics_history()
        return _ok({"interval_s": knob("ES_TPU_METRICS_SAMPLE_S"),
                    "capacity": knob("ES_TPU_METRICS_HISTORY"),
                    "sampler_running": _m.maybe_start_sampler(),
                    "samples": samples})

    def tpu_slowlog(self, req: RestRequest) -> RestResponse:
        """GET /_tpu/slowlog — the bounded in-memory search slowlog ring:
        structured over-threshold records (phase, level, index, took_ms,
        query source, trace id + per-phase breakdown when traced), newest
        last, plus the cumulative per-level counters."""
        from elasticsearch_tpu.common import tracing

        return _ok({"slowlog": tracing.slowlog_entries(),
                    **tracing.slowlog_stats()})

    def tpu_traces(self, req: RestRequest) -> RestResponse:
        """GET /_tpu/trace — the flight-recorder ring: recently completed
        traced requests with their spans (bounded by ES_TPU_TRACE_RING)."""
        from elasticsearch_tpu.common import tracing

        return _ok({"traces": tracing.recent_traces()})

    # ---------- aliases ----------

    def update_aliases(self, req: RestRequest) -> RestResponse:
        from dataclasses import replace

        for action in (req.body or {}).get("actions", []):
            op, spec = next(iter(action.items()))
            index = spec["index"]
            alias = spec["alias"]
            meta = self.node.cluster_state.indices.get(index)
            if meta is None:
                raise IndexNotFoundError(index)
            aliases = dict(meta.aliases)
            if op == "add":
                aliases[alias] = {k: v for k, v in spec.items() if k not in ("index", "alias")}
            elif op == "remove":
                aliases.pop(alias, None)
            else:
                raise IllegalArgumentError(f"unsupported alias action [{op}]")
            new_meta = replace(meta, aliases=aliases, version=meta.version + 1)
            routing = self.node.cluster_state.routing[index]
            self.node.update_state(lambda s: s.with_index(new_meta, routing))
        return _ok({"acknowledged": True})

    def get_aliases(self, req: RestRequest) -> RestResponse:
        want = req.param("name")
        out = {}
        for name in self._resolve(req.param("index", "_all"), require=False):
            meta = self.node.cluster_state.indices[name]
            aliases = meta.aliases
            if want is not None:
                import fnmatch as _fn

                pats = [p.strip() for p in want.split(",")]
                aliases = {a: spec for a, spec in aliases.items()
                           if any(_fn.fnmatchcase(a, p) for p in pats)}
                if not aliases:
                    continue
            out[name] = {"aliases": aliases}
        if want is not None and not out:
            return _ok({"error": f"alias [{want}] missing", "status": 404},
                       404)
        return _ok(out)

    def _set_alias(self, index: str, alias: str, spec: dict) -> None:
        from dataclasses import replace

        meta = self.node.cluster_state.indices.get(index)
        if meta is None:
            raise IndexNotFoundError(index)
        aliases = dict(meta.aliases)
        if spec is None:
            aliases.pop(alias, None)
        else:
            aliases[alias] = spec
        new_meta = replace(meta, aliases=aliases, version=meta.version + 1)
        routing = self.node.cluster_state.routing[index]
        self.node.update_state(lambda s, m=new_meta, r=routing:
                               s.with_index(m, r))

    def put_alias(self, req: RestRequest) -> RestResponse:
        spec = {k: v for k, v in (req.body or {}).items()}
        for name in self._resolve(req.param("index"), require=True):
            self._set_alias(name, req.param("name"), spec)
        return _ok({"acknowledged": True})

    def delete_alias(self, req: RestRequest) -> RestResponse:
        found = False
        for name in self._resolve(req.param("index"), require=True):
            if req.param("name") in self.node.cluster_state.indices[name].aliases:
                found = True
            self._set_alias(name, req.param("name"), None)
        if not found:
            return _ok({"error": "aliases missing", "status": 404}, 404)
        return _ok({"acknowledged": True})

    def head_alias(self, req: RestRequest) -> RestResponse:
        import fnmatch as _fn

        want = req.param("name", "")
        pats = [p.strip() for p in want.split(",")]
        names = self._resolve(req.param("index", "_all"), require=False)
        for name in names:
            for a in self.node.cluster_state.indices[name].aliases:
                if any(_fn.fnmatchcase(a, p) for p in pats):
                    return RestResponse(status=200, body={})
        return RestResponse(status=404, body={})

    # ---------- legacy (v1) index templates (ref:
    #            MetadataIndexTemplateService legacy put/get) ----------

    def _legacy_templates(self) -> dict:
        if not hasattr(self.node, "_legacy_templates"):
            self.node._legacy_templates = {}
        return self.node._legacy_templates

    def put_legacy_template(self, req: RestRequest) -> RestResponse:
        body = req.body or {}
        if "index_patterns" not in body and "template" not in body:
            raise IllegalArgumentError(
                "index_template [missing index_patterns]")
        name = req.param("name")
        stored = dict(body)
        pats = stored.get("index_patterns")
        if isinstance(pats, str):
            stored["index_patterns"] = [pats]
        self._legacy_templates()[name] = stored
        # bridge onto the composable registry so creation-time application
        # uses one mechanism
        patterns = body.get("index_patterns") or [body.get("template")]
        if isinstance(patterns, str):
            patterns = [patterns]
        self.node.indices.put_template("__legacy__" + name, {
            "index_patterns": patterns,
            "template": {k: v for k, v in body.items()
                         if k in ("settings", "mappings", "aliases")},
            "priority": int(body.get("order", 0)),
        })
        return _ok({"acknowledged": True})

    def get_legacy_template(self, req: RestRequest) -> RestResponse:
        import fnmatch as _fn

        want = req.param("name")
        store = self._legacy_templates()
        pats = [p.strip() for p in want.split(",")] if want else ["*"]
        out = {n: b for n, b in store.items()
               if any(_fn.fnmatchcase(n, p) for p in pats)}
        if want and not any("*" in p for p in pats) and not out:
            return _ok({"error": f"template [{want}] missing",
                        "status": 404}, 404)
        return _ok(out)

    def get_legacy_templates(self, req: RestRequest) -> RestResponse:
        return _ok(dict(self._legacy_templates()))

    def delete_legacy_template(self, req: RestRequest) -> RestResponse:
        name = req.param("name")
        if name not in self._legacy_templates():
            return _ok({"error": f"index_template [{name}] missing",
                        "status": 404}, 404)
        del self._legacy_templates()[name]
        try:
            self.node.indices.delete_template("__legacy__" + name)
        except Exception:  # noqa: BLE001 — bridge entry may be absent
            pass
        return _ok({"acknowledged": True})

    def head_legacy_template(self, req: RestRequest) -> RestResponse:
        ok = req.param("name") in self._legacy_templates()
        return RestResponse(status=200 if ok else 404, body={})

    def get_field_mapping(self, req: RestRequest) -> RestResponse:
        """GET /{index}/_mapping/field/{fields} (ref:
        TransportGetFieldMappingsAction)."""
        import fnmatch as _fn

        fields = [f.strip() for f in req.param("fields", "").split(",")]
        out = {}
        for name in self._resolve(req.param("index", "_all"), require=False):
            svc = self.node.indices.get(name)
            props = svc.mapper.mapping().get("properties", {})
            matched = {}
            for fname, fdef in props.items():
                if any(_fn.fnmatchcase(fname, p) for p in fields):
                    matched[fname] = {"full_name": fname,
                                      "mapping": {fname.split(".")[-1]: fdef}}
            out[name] = {"mappings": matched}
        return _ok(out)

    # ---------- cat ----------

    def cat_indices(self, req: RestRequest) -> RestResponse:
        rows = []
        cs = self.node.cluster_state
        for name in self.node.indices.names():
            svc = self.node.indices.get(name)
            meta = cs.indices[name]
            health = "yellow" if meta.number_of_replicas > 0 else "green"
            rows.append(f"{health} open {name} {meta.uuid} {meta.number_of_shards} "
                        f"{meta.number_of_replicas} {svc.doc_count()} 0 0b 0b")
        return RestResponse(body="\n".join(rows) + ("\n" if rows else ""),
                            content_type="text/plain")

    def cat_health(self, req: RestRequest) -> RestResponse:
        h = self.node.cluster_state.health()
        line = (f"{int(time.time())} {time.strftime('%H:%M:%S')} {h['cluster_name']} "
                f"{h['status']} {h['number_of_nodes']} {h['number_of_data_nodes']} "
                f"{h['active_shards']} {h['active_primary_shards']} 0 0 "
                f"{h['unassigned_shards']} 0 - "
                f"{h['active_shards_percent_as_number']:.1f}%\n")
        return RestResponse(body=line, content_type="text/plain")

    def cat_shards(self, req: RestRequest) -> RestResponse:
        cs = self.node.cluster_state

        def node_name(nid):
            n = cs.nodes.get(nid)
            return n.name if n is not None else (nid or "")

        rows = []
        for index, shards in cs.routing.items():
            if not self.node.indices.has(index):
                continue
            svc = self.node.indices.get(index)
            for s in shards:
                kind = "p" if s.primary else "r"
                docs = svc.shards[s.shard_id].doc_count() if s.primary else 0
                node = node_name(s.node_id) if s.node_id else ""
                # a moving copy renders `source -> target` (ref: the cat
                # shards RELOCATING row); its INITIALIZING other half shows
                # where the bytes are coming from
                if s.state == "RELOCATING" and s.relocating_node_id:
                    node = f"{node} -> {node_name(s.relocating_node_id)}"
                rows.append(f"{index} {s.shard_id} {kind} {s.state} {docs} 0b "
                            f"{'127.0.0.1' if s.node_id else ''} {node}")
        return RestResponse(body="\n".join(rows) + ("\n" if rows else ""),
                            content_type="text/plain")

    def cat_allocation(self, req: RestRequest) -> RestResponse:
        n_shards = sum(1 for shards in self.node.cluster_state.routing.values()
                       for r in shards if r.state == "STARTED")
        return RestResponse(
            status=200,
            body=f"{n_shards} {self.node.node_name}\n",
            content_type="text/plain")

    def cat_count(self, req: RestRequest) -> RestResponse:
        total = sum(self.node.indices.get(n).doc_count() for n in self.node.indices.names())
        return RestResponse(body=f"{int(time.time())} {time.strftime('%H:%M:%S')} {total}\n",
                            content_type="text/plain")

    def cat_segments(self, req: RestRequest) -> RestResponse:
        lines = []
        for name in self._resolve(req.param("index", "_all")):
            svc = self.node.indices.get(name)
            for sid, engine in enumerate(svc.shards):
                se = engine.acquire_searcher()
                for v in se.views:
                    lines.append(
                        f"{name} {sid} _{v.segment.seg_id} "
                        f"{int(v.live.sum())} "
                        f"{v.segment.n_docs - int(v.live.sum())} "
                        f"{v.segment.ram_bytes()}")
        return RestResponse(status=200, body="\n".join(lines) + "\n",
                            content_type="text/plain")

    def cat_aliases(self, req: RestRequest) -> RestResponse:
        lines = []
        for name, meta in self.node.cluster_state.indices.items():
            for alias in meta.aliases:
                lines.append(f"{alias} {name} - - - -")
        return RestResponse(status=200, body="\n".join(lines) + "\n",
                            content_type="text/plain")

    def cat_templates(self, req: RestRequest) -> RestResponse:
        lines = [f"{n} [{','.join(t['index_patterns'])}] {t['priority']}"
                 for n, t in self.node.indices.templates.items()]
        return RestResponse(status=200, body="\n".join(lines) + "\n",
                            content_type="text/plain")

    def cat_nodes(self, req: RestRequest) -> RestResponse:
        rows = [f"127.0.0.1 0 0 - cdfhilmrstw * {self.node.node_name}"]
        return RestResponse(body="\n".join(rows) + "\n", content_type="text/plain")

    def cat_thread_pool(self, req: RestRequest) -> RestResponse:
        """GET /_cat/thread_pool[/{name}] — the reference's default
        columns (node_name name active queue rejected) extended with the
        flight recorder's queue-wait view: the smoothed queue-wait EWMA
        and the queue-wait histogram p99 per pool (PR 9)."""
        import fnmatch as _fn

        from elasticsearch_tpu.common import metrics

        want = req.param("name")
        pats = [p.strip() for p in want.split(",")] if want else None
        rows = []
        for name, st in sorted(self.node.thread_pool.stats().items()):
            if pats and not any(_fn.fnmatchcase(name, p) for p in pats):
                continue
            s = metrics.summary(f"queue_wait.{name}")
            p99 = s["p99"] if s else 0.0
            rows.append(f"{self.node.node_name} {name} {st['active']} "
                        f"{st['queue']} {st['rejected']} "
                        f"{st['queue_ewma_ms']} {p99}")
        return RestResponse(body="\n".join(rows) + ("\n" if rows else ""),
                            content_type="text/plain")

    def cat_tasks(self, req: RestRequest) -> RestResponse:
        """GET /_cat/tasks — cluster-wide flat task rows via the task
        plane's fan-out (ref: RestCatTasksAction default columns)."""
        rows = self.node.task_plane.cat_rows(
            detailed=req.param_bool("detailed"))
        return RestResponse(body="\n".join(rows) + ("\n" if rows else ""),
                            content_type="text/plain")

    # ---------- helpers ----------

    def _resolve(self, expression: str | None, require: bool = False) -> List[str]:
        expression = expression or "_all"
        names = self.node.cluster_state.resolve_indices(expression)
        if require and not names and expression not in ("_all", "*"):
            raise IndexNotFoundError(expression)
        return names


def _default_coalescer_stats() -> dict:
    from elasticsearch_tpu.threadpool.coalescer import default_coalescer

    return default_coalescer().stats()


def _default_scheduler_stats() -> dict:
    from elasticsearch_tpu.threadpool.scheduler import scheduler_stats

    return scheduler_stats()


def _turbo_merge_stats() -> dict:
    """Node-wide Turbo partition-merge counters (PR 4): fused S > 1
    device dispatches, per-partition dispatch units they covered, and
    how many batch merges ran on device vs through the host _merge3."""
    from elasticsearch_tpu.search.serving import turbo_node_stats

    return turbo_node_stats()


def _tpu_health_stats() -> dict:
    """Node-wide device-health section (PR 5): per-engine circuit state
    + cumulative fault/fallback counters, plus the serving layer's
    containment counters (recovered shards, fast-path rejections/timeouts)
    and the coalescer's poison-batch retries."""
    from elasticsearch_tpu.common.health import node_health_stats
    from elasticsearch_tpu.search.serving import serving_fault_stats
    from elasticsearch_tpu.threadpool.coalescer import default_coalescer

    out = node_health_stats()
    out.update(serving_fault_stats())
    out["coalesce_batch_retries"] = \
        default_coalescer().stats()["coalesce_batch_retries"]
    return out


def _tpu_search_latency_stats() -> dict:
    """Search flight-recorder section (PR 9): per-phase latency histogram
    summaries (queue wait per pool, coalesce wait, device, demux, fetch,
    query, merge, rest_total — p50/p90/p99/max over log-spaced buckets),
    the coalescer's batch-size/pad-ratio distributions, and the slowlog
    ring counters. Always on: histograms record whether or not any
    request is traced."""
    from elasticsearch_tpu.common import metrics, tracing

    out = metrics.search_latency_stats()
    out["slowlog"] = tracing.slowlog_stats()
    return out


def _tpu_coordinator_stats() -> dict:
    """Coordinator resilience section (PR 6): shard failover retries, open
    node-transport circuits, abandoned RPCs, fetch-phase drops, plus the
    per-edge transport circuit states."""
    from elasticsearch_tpu.action.search_action import coordinator_stats

    return coordinator_stats()


def _tpu_durability_stats() -> dict:
    """Write-path durability section (PR 8): translog fsync failures and
    syncs, injected corruptions, segment-commit failures, crash-replay
    counts, replication retries/failures, peer-recovery outcomes, ghost
    cleanups, and the live async-durability exposure window — one flat
    section so a chaos run's acked-write accounting is auditable with a
    single GET."""
    from elasticsearch_tpu.common.durability import durability_stats

    return durability_stats()


def _tpu_settings_stats() -> dict:
    """Effective ES_TPU_* knob values (PR 7): every declared knob with its
    parsed value and whether it came from the environment or the default —
    so a chaos/bench run's exact configuration is observable, not inferred
    from shell history."""
    from elasticsearch_tpu.common.settings import effective_knobs

    return effective_knobs()


def _tpu_hbm_stats() -> dict:
    """HBM residency section (PR 12): per-engine device-byte occupancy
    (byte-identical to the engines' own hbm_bytes()), high watermark,
    eviction/churn counters, protected-slot pressure, budget headroom vs
    ES_TPU_TURBO_HBM, and the turbo_eligible routing log."""
    from elasticsearch_tpu.common import hbm_ledger

    return hbm_ledger.hbm_stats()


def _overload_admission(node):
    """REST front-door admission check for `RestController.admission`:
    returns a 429 RestResponse with Retry-After when the node's overload
    controller sheds this request, None to admit."""
    from elasticsearch_tpu.threadpool import (
        EsRejectedExecutionError, pool_for_request, tier_for_request,
    )

    def admission(method: str, path: str, params: Dict[str, str]):
        if pool_for_request(method, path) not in ("search", "write", "get"):
            return None
        tier = tier_for_request(method, path, params)
        retry_after = node.overload.admit(tier)
        if retry_after is None:
            return None
        err = EsRejectedExecutionError(
            f"[{node.node_name}] overload shed "
            f"({node.overload.stats()['level']}): {tier}-tier request on "
            f"[{path}]", tier=tier, retry_after_s=retry_after)
        return RestResponse(status=err.status, body=_error_body(err),
                            headers={"Retry-After":
                                     str(max(1, int(retry_after)))})

    return admission


def _tpu_agg_stats() -> dict:
    """Device analytics section (PR 18): collects served on device,
    fused dispatches, host fallbacks, and the HBM bytes held by the
    engine's precomputed agg columns (reconciles with tpu_hbm's `agg`
    engine entry byte-for-byte)."""
    from elasticsearch_tpu.search import agg_device

    return agg_device.agg_stats()


def _tpu_knn_stats() -> dict:
    """Quantized kNN section (PR 19): queries, int8 first-pass
    dispatches, rescored candidates, certificate misses, host fallbacks,
    and the HBM bytes held by the quantized shards + centroids
    (reconciles with tpu_hbm's `knn` engine entry byte-for-byte)."""
    from elasticsearch_tpu.parallel import knn

    return knn.knn_node_stats()


def _tpu_compile_stats() -> dict:
    """Compile-cache section (PR 12): primed dispatch shapes, per-dispatch
    hit/miss counters, unplanned retraces, warmup coverage ratio, and the
    recent first-trace events with wall cost — the cold-start cliff and
    the scheduler bucket ladder, measured."""
    from elasticsearch_tpu.common import hbm_ledger

    return hbm_ledger.compile_stats()


def _tpu_relocation_stats() -> dict:
    """Maintenance-plane section (PR 14): completed moves, cancelled
    relocations, and the warm-HBM-handoff accounting (handoffs run, wall
    ms, fields warmed, qc shapes primed, best-effort failures)."""
    from elasticsearch_tpu.common.relocation import relocation_stats

    return relocation_stats()


def _tpu_integrity_stats() -> dict:
    """Data-integrity plane section (PR 15): segments verified/corrupted at
    rest, transfer hash verifications and retried transfers, corruption
    markers written/cleared, shard copies failed or quarantined for
    corruption, HBM scrub outcomes (ticks, mismatches, repairs, yields),
    repository verifies, and restore cleanups — the audit surface for the
    three integrity legs."""
    from elasticsearch_tpu.common.integrity import integrity_stats

    return integrity_stats()


def _deep_merge(base: dict, patch: dict) -> dict:
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            base[k] = _deep_merge(dict(base[k]), v)
        else:
            base[k] = v
    return base

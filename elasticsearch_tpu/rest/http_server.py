"""HTTP frontend: stdlib threaded server hosting the RestController.

The analog of the reference's Netty4HttpServerTransport
(ref: http/AbstractHttpServerTransport.java:59, modules/transport-netty4) —
the HTTP layer is deliberately thin: parse method/path/query/body, dispatch,
encode. Heavy lifting (search execution) releases the GIL inside XLA, so a
threaded server keeps the device busy under concurrent clients.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlsplit

from elasticsearch_tpu.rest.controller import RestController


class HttpServer:
    def __init__(self, controller: RestController, host: str = "127.0.0.1", port: int = 9200):
        self.controller = controller
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _dispatch(self):
                parts = urlsplit(self.path)
                params = dict(parse_qsl(parts.query, keep_blank_values=True))
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else None
                resp = outer.controller.dispatch(self.command, parts.path,
                                                 params, body,
                                                 headers=dict(self.headers))
                data = resp.encode()
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-elastic-product", "Elasticsearch")
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(data)

            do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _dispatch

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]

    def start(self) -> None:
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

"""HTTP frontend: stdlib threaded server hosting the RestController.

The analog of the reference's Netty4HttpServerTransport
(ref: http/AbstractHttpServerTransport.java:59, modules/transport-netty4) —
the HTTP layer is deliberately thin: parse method/path/query/body, dispatch,
encode. Heavy lifting (search execution) releases the GIL inside XLA, so a
threaded server keeps the device busy under concurrent clients.

When a `ThreadPool` is attached, requests do NOT execute on the accept
threads: each request is classified to a named stage pool (search / write /
get / management / snapshot) and submitted there, so concurrency per stage
is bounded and a saturated pool sheds load with 429
`es_rejected_execution_exception` instead of queueing unboundedly
(ref: the reference's per-action executor dispatch out of the Netty event
loop).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlsplit

from elasticsearch_tpu.rest.controller import (
    RestController, RestResponse, _backoff_headers, _error_body,
)


class HttpServer:
    def __init__(self, controller: RestController, host: str = "127.0.0.1",
                 port: int = 9200, thread_pool=None):
        self.controller = controller
        self.thread_pool = thread_pool
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _dispatch(self):
                parts = urlsplit(self.path)
                params = dict(parse_qsl(parts.query, keep_blank_values=True))
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else None
                if outer.thread_pool is None:
                    resp = outer.controller.dispatch(
                        self.command, parts.path, params, body,
                        headers=dict(self.headers))
                else:
                    from elasticsearch_tpu.threadpool import (
                        EsRejectedExecutionError, pool_for_request,
                    )

                    pool = pool_for_request(self.command, parts.path)
                    try:
                        resp = outer.thread_pool.execute(
                            pool, outer.controller.dispatch,
                            self.command, parts.path, params, body,
                            headers=dict(self.headers))
                    except EsRejectedExecutionError as e:
                        resp = RestResponse(status=e.status,
                                            body=_error_body(e),
                                            headers=_backoff_headers(e))
                data = resp.encode()
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-elastic-product", "Elasticsearch")
                for name, value in resp.headers.items():
                    self.send_header(name, value)
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(data)

            do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _dispatch

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]

    def start(self) -> None:
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

from elasticsearch_tpu.rest.controller import RestController, RestRequest, RestResponse
from elasticsearch_tpu.rest.handlers import register_handlers
from elasticsearch_tpu.rest.http_server import HttpServer

__all__ = ["RestController", "RestRequest", "RestResponse", "register_handlers", "HttpServer"]

"""REST dispatch: method+path-pattern routing to handlers.

Re-designs the reference RestController's path trie
(ref: rest/RestController.java:153 registerHandler — patterns like
"/{index}/_search") with the same placeholder syntax. Handlers receive a
RestRequest (params from placeholders + query string, parsed JSON body) and
return a RestResponse. Exceptions map to ES-shaped error bodies with the
status from the error class (ref: ElasticsearchException.status()).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import ElasticsearchTpuError, JsonParseError


@dataclass
class RestRequest:
    method: str
    path: str
    params: Dict[str, str] = field(default_factory=dict)
    body: Any = None
    raw_body: bytes = b""
    headers: Dict[str, str] = field(default_factory=dict)

    def param(self, name: str, default=None):
        return self.params.get(name, default)

    def param_bool(self, name: str, default: bool = False) -> bool:
        v = self.params.get(name)
        if v is None:
            return default
        return str(v).lower() in ("true", "1", "")

    def param_int(self, name: str, default: int = 0) -> int:
        v = self.params.get(name)
        return default if v is None else int(v)


@dataclass
class RestResponse:
    status: int = 200
    body: Any = None
    content_type: str = "application/json"
    headers: Dict[str, str] = field(default_factory=dict)

    def encode(self) -> bytes:
        if isinstance(self.body, (bytes,)):
            return self.body
        if isinstance(self.body, str):
            return self.body.encode()
        return json.dumps(self.body, default=_json_default).encode()


def _json_default(o):
    """Numpy scalars leak into responses from columnar code (sort values,
    doc values); serialize them as their Python equivalents."""
    import numpy as np

    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.bool_):
        return bool(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"Object of type {type(o).__name__} is not JSON serializable")


Handler = Callable[[RestRequest], RestResponse]


class _Route:
    __slots__ = ("segments", "handler")

    def __init__(self, pattern: str, handler: Handler):
        self.segments = [s for s in pattern.split("/") if s]
        self.handler = handler

    def match(self, parts: List[str]) -> Optional[Dict[str, str]]:
        if len(parts) != len(self.segments):
            return None
        params: Dict[str, str] = {}
        for seg, part in zip(self.segments, parts):
            if seg.startswith("{") and seg.endswith("}"):
                params[seg[1:-1]] = part
            elif seg != part:
                return None
        return params

    @property
    def specificity(self) -> tuple:
        # literal segments beat placeholders position-by-position
        return tuple(0 if s.startswith("{") else 1 for s in self.segments)


class RestController:
    def __init__(self):
        self._routes: Dict[str, List[_Route]] = {}
        # authn/authz action filter (security/service.py) — runs before
        # every handler when security is enabled (ref: the reference's
        # SecurityActionFilter wrapping the action chain)
        self.security_filter = None
        # overload admission hook (common/overload.py) — called with
        # (method, path, params) before body parse; a non-None RestResponse
        # sheds the request (429 + Retry-After) without running the handler
        self.admission = None

    def register(self, method: str, pattern: str, handler: Handler) -> None:
        self._routes.setdefault(method.upper(), []).append(_Route(pattern, handler))
        self._routes[method.upper()].sort(key=lambda r: r.specificity, reverse=True)

    def dispatch(self, method: str, path: str, params: Dict[str, str] | None = None,
                 body: bytes | str | None = None,
                 headers: Dict[str, str] | None = None) -> RestResponse:
        parts = [p for p in path.split("?")[0].split("/") if p]
        routes = self._routes.get(method.upper(), [])
        for route in routes:
            matched = route.match(parts)
            if matched is not None:
                req_params = dict(params or {})
                req_params.update(matched)
                if self.admission is not None:
                    shed = self.admission(method.upper(), path, req_params)
                    if shed is not None:
                        return shed
                parsed, raw, parse_error = _parse_body(body)
                if parse_error and not _is_ndjson_endpoint(parts):
                    err = JsonParseError("request body is not valid JSON")
                    return RestResponse(status=err.status, body=_error_body(err))
                req = RestRequest(method=method.upper(), path=path, params=req_params,
                                  body=parsed, raw_body=raw,
                                  headers={k.lower(): v for k, v in
                                           (headers or {}).items()})
                try:
                    if self.security_filter is not None:
                        self.security_filter(req, parts)
                    return route.handler(req)
                except ElasticsearchTpuError as e:
                    return RestResponse(status=e.status, body=_error_body(e),
                                        headers=_backoff_headers(e))
                except Exception as e:  # noqa: BLE001 — REST boundary
                    err = ElasticsearchTpuError(str(e))
                    return RestResponse(status=500, body=_error_body(err))
        if method.upper() == "HEAD":
            return RestResponse(status=404, body={})
        return RestResponse(
            status=400,
            body={"error": f"no handler found for uri [{path}] and method [{method.upper()}]"},
        )


def _is_ndjson_endpoint(parts: List[str]) -> bool:
    """bulk/_msearch bodies are newline-delimited JSON, parsed downstream."""
    return any(p in ("_bulk", "_msearch") for p in parts)


def _parse_body(body) -> Tuple[Any, bytes, bool]:
    if body is None:
        return None, b"", False
    raw = body.encode() if isinstance(body, str) else body
    if not raw.strip():
        return None, raw, False
    try:
        return json.loads(raw), raw, False
    except json.JSONDecodeError:
        return None, raw, True


def _error_body(e: ElasticsearchTpuError) -> dict:
    cause = e.to_dict()
    return {"error": {"root_cause": [cause], **cause}, "status": e.status}


def _backoff_headers(e: ElasticsearchTpuError) -> Dict[str, str]:
    """429s carry a Retry-After derived from the rejecting layer's hint
    (pool queue EWMA or the overload controller's backoff)."""
    ra = e.metadata.get("retry_after_s")
    if ra is None:
        return {}
    return {"Retry-After": str(max(1, int(ra)))}

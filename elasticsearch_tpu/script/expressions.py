"""Sandboxed expression scripting — the painless analog.

The reference sandboxes scripts by compiling a custom language to JVM
bytecode against per-context allowlists (ref: modules/lang-painless
Compiler.java, ScriptContext allowlists). Without a JVM the TPU build gets
the same guarantee by *structural* sandboxing: scripts are parsed with
Python's `ast` module and only an explicitly allowlisted node set is
interpreted — no attribute access, no calls except allowlisted functions,
no imports, no subscripts except on provided mappings, no comprehensions.
Everything else raises at compile time, like painless' compile-time
allowlist errors.

Contexts (score, aggs, update, ingest, …) differ only in the variables they
bind (`_score`, `doc`, `ctx`, `params`, bucket paths), matching the
reference's ScriptContext design (ref: script/ScriptContext.java).
"""

from __future__ import annotations

import ast
import math
import re
from typing import Any, Callable, Dict, Mapping

from elasticsearch_tpu.common.errors import ElasticsearchTpuError


class ScriptException(ElasticsearchTpuError):
    status = 400
    error_type = "script_exception"


_ALLOWED_FUNCS: Dict[str, Callable] = {
    "abs": abs, "min": min, "max": max, "round": round, "len": len,
    "floor": math.floor, "ceil": math.ceil, "sqrt": math.sqrt,
    "log": math.log, "log10": math.log10, "exp": math.exp,
    "sin": math.sin, "cos": math.cos, "tan": math.tan,
    "saturation": lambda v, k: v / (v + k),
    "sigmoid": lambda v, k, a: v ** a / (k ** a + v ** a),
}

_ALLOWED_NODES = (
    ast.Expression, ast.BinOp, ast.UnaryOp, ast.BoolOp, ast.Compare,
    ast.IfExp, ast.Constant, ast.Name, ast.Load, ast.Call, ast.Subscript,
    ast.Index, ast.Tuple, ast.List,
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow,
    ast.USub, ast.UAdd, ast.Not, ast.And, ast.Or,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE, ast.In, ast.NotIn,
    ast.Attribute,  # validated separately: only .value / .length on doc fields
)

_ALLOWED_ATTRS = {"value", "values", "length", "empty"}


def _safe_pow(a, b):
    """Bounded exponentiation: painless-style compute limiting — an eval'd
    expression cannot be interrupted, so astronomically-large powers are
    rejected up front."""
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        if abs(b) > 1024 or (isinstance(a, int) and abs(a) > 1 and abs(b) > 256):
            raise ScriptException("power operand too large")
    return a ** b


def _safe_mult(a, b):
    """Bounded multiplication: rejects huge sequence repetition."""
    for seq, n in ((a, b), (b, a)):
        if isinstance(seq, (str, list, tuple)) and isinstance(n, int):
            if len(seq) * max(n, 0) > 100_000:
                raise ScriptException("sequence repetition too large")
    return a * b


# pow() must go through the same compute bound as the ** operator — the raw
# builtin would let pow(2, 10**9) bypass the _GuardOps rewrite entirely
_ALLOWED_FUNCS["pow"] = _safe_pow


class _GuardOps(ast.NodeTransformer):
    """Rewrite Pow/Mult into guarded calls at compile time."""

    _MAP = {ast.Pow: "__safe_pow__", ast.Mult: "__safe_mult__"}

    def visit_BinOp(self, node):
        self.generic_visit(node)
        fname = self._MAP.get(type(node.op))
        if fname is None:
            return node
        return ast.copy_location(
            ast.Call(func=ast.Name(id=fname, ctx=ast.Load()),
                     args=[node.left, node.right], keywords=[]), node)


class _AttrDict(dict):
    """params dict supporting both params['x'] and painless params.x."""

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError:
            raise ScriptException(f"missing script parameter [{name}]") from None


_STRING_RE = re.compile(r"'[^']*'|\"[^\"]*\"")


def _normalize_code(code: str) -> str:
    for pat, py in ((r"&&", " and "), (r"\|\|", " or "), (r"!(?!=)", " not "),
                    (r"\?:", " or "), (r"\bnull\b", "None"), (r"\btrue\b", "True"),
                    (r"\bfalse\b", "False"), (r"\bMath\.", "")):
        code = re.sub(pat, py, code)
    return code


def _normalize(source: str) -> str:
    """Translate the painless-isms that appear in common scripts.

    Rewrites only code outside string literals, on word boundaries, so field
    names or strings containing e.g. "null" are untouched.
    """
    src = source.strip().rstrip(";")
    out = []
    last = 0
    for m in _STRING_RE.finditer(src):
        out.append(_normalize_code(src[last: m.start()]))
        out.append(m.group(0))
        last = m.end()
    out.append(_normalize_code(src[last:]))
    return "".join(out)


class ExpressionScript:
    """A compiled, structurally-sandboxed expression."""

    def __init__(self, source: str):
        self.source = source
        normalized = _normalize(source)
        try:
            tree = ast.parse(normalized, mode="eval")
        except SyntaxError as e:
            raise ScriptException(f"compile error in script [{source}]: {e}") from None
        for node in ast.walk(tree):
            if not isinstance(node, _ALLOWED_NODES):
                raise ScriptException(
                    f"illegal construct [{type(node).__name__}] in script [{source}]")
            if isinstance(node, ast.Attribute) and node.attr not in _ALLOWED_ATTRS:
                # painless params.x is allowed; all other attributes are not
                if not (isinstance(node.value, ast.Name) and node.value.id == "params"):
                    raise ScriptException(
                        f"unknown attribute [.{node.attr}] in script [{source}]")
            if isinstance(node, ast.Call):
                if not isinstance(node.func, ast.Name) or node.func.id not in _ALLOWED_FUNCS:
                    raise ScriptException(
                        f"unknown function in script [{source}]")
        tree = ast.fix_missing_locations(_GuardOps().visit(tree))
        self._code = compile(tree, "<script>", "eval")

    def execute(self, variables: Mapping[str, Any] | None = None) -> Any:
        env: Dict[str, Any] = dict(_ALLOWED_FUNCS)
        env["None"] = None
        env["__safe_pow__"] = _safe_pow
        env["__safe_mult__"] = _safe_mult
        if variables:
            env.update(variables)
        if isinstance(env.get("params"), dict):
            env["params"] = _AttrDict(env["params"])
        try:
            return eval(self._code, {"__builtins__": {}}, env)  # noqa: S307 — AST-allowlisted
        except ScriptException:
            raise
        except Exception as e:  # noqa: BLE001 — runtime errors surface as script errors
            raise ScriptException(f"runtime error in script [{self.source}]: {e}") from None


class _DocField:
    """painless-style doc['field'] accessor."""

    __slots__ = ("_values",)

    def __init__(self, values):
        self._values = values if isinstance(values, list) else (
            [] if values is None else [values])

    @property
    def value(self):
        if not self._values:
            raise ScriptException("A document doesn't have a value for a field")
        return self._values[0]

    @property
    def values(self):
        return self._values

    @property
    def length(self):
        return len(self._values)

    @property
    def empty(self):
        return not self._values

    def __getitem__(self, i):
        return self._values[i]


def doc_map(field_values: Mapping[str, Any]) -> Dict[str, _DocField]:
    return {f: _DocField(v) for f, v in field_values.items()}


_cache: Dict[str, ExpressionScript] = {}


def compile_script(spec) -> ExpressionScript:
    """Compile {"source": ...} | str, with a compile cache
    (ref: script/ScriptService.java compile-rate limiting + cache)."""
    source = spec.get("source") if isinstance(spec, dict) else spec
    if not isinstance(source, str):
        raise ScriptException("script source must be a string")
    script = _cache.get(source)
    if script is None:
        script = ExpressionScript(source)
        if len(_cache) > 2048:
            _cache.clear()
        _cache[source] = script
    return script

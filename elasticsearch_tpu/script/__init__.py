from elasticsearch_tpu.script.expressions import ExpressionScript, compile_script

__all__ = ["ExpressionScript", "compile_script"]

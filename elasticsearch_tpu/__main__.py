"""`python -m elasticsearch_tpu` — start a single node with the HTTP frontend.

The analog of the reference's bin/elasticsearch -> Elasticsearch.main ->
Bootstrap.init -> Node.start (ref: bootstrap/Elasticsearch.java:64,
bootstrap/Bootstrap.java:327).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="elasticsearch-tpu")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9200)
    ap.add_argument("--data", default=None, help="data path (translog/commits); in-memory if unset")
    ap.add_argument("--name", default="node-0")
    ap.add_argument("--cluster-name", default="elasticsearch-tpu")
    args = ap.parse_args(argv)

    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest import HttpServer, RestController, register_handlers

    node = Node(Settings({"cluster.name": args.cluster_name}),
                data_path=args.data, node_name=args.name)
    rc = RestController()
    register_handlers(node, rc)
    from elasticsearch_tpu.plugins import load_plugins

    loaded = load_plugins(node, rc)
    if loaded:
        print(f"[{args.name}] plugins loaded: {', '.join(loaded)}", flush=True)
    server = HttpServer(rc, host=args.host, port=args.port,
                        thread_pool=node.thread_pool)
    server.start()
    print(f"[{args.name}] started, http on {args.host}:{server.port}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

from elasticsearch_tpu.testing.deterministic import DeterministicTaskQueue
from elasticsearch_tpu.testing.linearizability import LinearizabilityChecker

__all__ = ["DeterministicTaskQueue", "LinearizabilityChecker"]

"""Disruptable in-memory transport over the deterministic task queue.

Port of the testing idea in the reference's
test/disruption/DisruptableMockTransport.java: message delivery is a
scheduled task with configurable delay, and a rule table can blackhole or
delay traffic between node pairs to simulate partitions — two-sided,
bridge, or isolate-one.

Failure taxonomy: a dropped delivery surfaces through `on_error` as the
SAME `NodeUnavailableError` the transport layer raises for killed or
partitioned nodes (transport/channels.py) — so coordination code exercises
the identical recovery path here as under live fault injection. Legacy
zero-arg `on_error` callbacks keep working; callbacks that accept one
argument receive the error.
"""

from __future__ import annotations

import inspect
from typing import Callable, Dict, Optional, Set, Tuple

from elasticsearch_tpu.testing.deterministic import DeterministicTaskQueue


def _invoke_on_error(on_error: Callable, sender: str, to: str) -> None:
    """Call `on_error`, passing a `NodeUnavailableError` when the callback
    accepts an argument (new taxonomy) and nothing when it doesn't (legacy
    zero-arg callbacks, e.g. cluster/coordination.py's lambdas)."""
    try:
        accepts_arg = bool(inspect.signature(on_error).parameters)
    except (TypeError, ValueError):
        accepts_arg = False
    if accepts_arg:
        from elasticsearch_tpu.transport.channels import NodeUnavailableError

        on_error(NodeUnavailableError(
            f"no route from [{sender}] to [{to}] (disruption)"))
    else:
        on_error()


class DisruptableTransport:
    def __init__(self, queue: DeterministicTaskQueue,
                 base_delay_ms: float = 5.0, jitter_ms: float = 10.0):
        self.queue = queue
        self.base_delay_ms = base_delay_ms
        self.jitter_ms = jitter_ms
        self.handlers: Dict[str, Callable] = {}     # node -> handle_message
        self.blackholed: Set[Tuple[str, str]] = set()
        self.disconnected: Set[str] = set()

    def register(self, node_id: str, handler: Callable) -> None:
        """handler(sender, msg, reply_fn)"""
        self.handlers[node_id] = handler

    # ---- disruption rules ----

    def partition(self, side_a: Set[str], side_b: Set[str]) -> None:
        for a in side_a:
            for b in side_b:
                self.blackholed.add((a, b))
                self.blackholed.add((b, a))

    def isolate(self, node_id: str) -> None:
        for other in self.handlers:
            if other != node_id:
                self.blackholed.add((node_id, other))
                self.blackholed.add((other, node_id))

    def heal(self) -> None:
        self.blackholed.clear()
        self.disconnected.clear()

    def _delivery_ok(self, a: str, b: str) -> bool:
        return ((a, b) not in self.blackholed
                and a not in self.disconnected and b not in self.disconnected)

    # ---- the transport API coordinators use ----

    def send(self, sender: str, to: str, msg: dict,
             on_reply: Callable[[dict], None],
             on_error: Optional[Callable[[], None]] = None) -> None:
        delay = self.base_delay_ms + self.queue.random.random() * self.jitter_ms

        def deliver():
            if not self._delivery_ok(sender, to) or to not in self.handlers:
                # silent drop models a blackhole; on_error models a connection
                # error, scheduled so timeouts still apply realistically
                if on_error is not None:
                    self.queue.schedule_at(
                        delay, lambda: _invoke_on_error(on_error, sender, to))
                return

            def reply_fn(reply_msg: dict) -> None:
                rdelay = self.base_delay_ms + self.queue.random.random() * self.jitter_ms

                def deliver_reply():
                    if self._delivery_ok(to, sender):
                        on_reply(reply_msg)
                    elif on_error is not None:
                        _invoke_on_error(on_error, to, sender)

                self.queue.schedule_at(rdelay, deliver_reply)

            self.handlers[to](sender, msg, reply_fn)

        self.queue.schedule_at(delay, deliver)

"""Crash–restart chaos harness for the write path (PR 8).

Two pieces the durability ladder needs beyond testing/disruptable_transport:

* `CrashRestartCluster` — a `form_local_cluster` wrapper whose `crash(node)`
  models real node death (channels cut, applier detached, in-memory engines
  abandoned WITHOUT flushing — whatever was not fsynced is gone as far as
  any reopened file can see) and whose `restart(node)` brings the same name
  back over the same `data_path`: engines reload the last commit and replay
  the translog (`recover_from_disk`), then the copy rejoins via node-join +
  peer recovery, including the divergent-tail rollback for a copy that was
  ahead of the promoted primary when it died.

  CPython detail the model depends on: a garbage-collected file object
  flushes its buffer, which would RESURRECT bytes the crash should have
  destroyed. Crashed node objects are therefore stashed in `_wreckage` for
  the harness's lifetime; a separate `open()` of the same path observes
  only what was explicitly flushed/fsynced — the correct crash semantics.

* `AckedWriteHistory` — a per-document invoke/response history with the
  acked-write durability rule expressed as linearizability against a
  last-writer-wins register spec: a write whose ack was observed MUST be
  readable afterwards (losing it fails the check); a write that never
  acked may or may not survive (both are legal); reads record what they
  actually observed. Per-doc histories keep the Wing & Gong search tiny.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster_node import (
    ClusterNode, _register_refresh_handler, form_local_cluster,
)
from elasticsearch_tpu.parallel.routing import shard_for_id
from elasticsearch_tpu.testing.linearizability import (
    Event, LinearizabilityChecker, SequentialSpec,
)


class CrashRestartCluster:
    """An in-process cluster whose nodes can die and come back from disk."""

    def __init__(self, names: List[str], data_path: str,
                 roles: Optional[Dict[str, Tuple[str, ...]]] = None):
        self.names = list(names)
        self.data_path = data_path
        self.roles = roles or {}
        self.nodes, self.store, self.channels = form_local_cluster(
            names, data_path, roles)
        self.by_name: Dict[str, ClusterNode] = {
            n.node_name: n for n in self.nodes}
        # crashed node objects, kept ALIVE: dropping them would let file
        # GC flush translog buffers the crash is supposed to destroy
        self._wreckage: List[ClusterNode] = []

    def node(self, name: str) -> ClusterNode:
        return self.by_name[name]

    def master(self) -> ClusterNode:
        return self.by_name[self.store.master_node()]

    def crash(self, name: str, report: bool = True) -> None:
        """Kill `name` without any shutdown courtesy: no flush, no fsync,
        no dying gasp to the master. With report=True a survivor notices
        (node-left -> promotion + reallocation); report=False models a
        restart faster than failure detection (the master never knew)."""
        node = self.by_name.pop(name)
        self.nodes = [n for n in self.nodes if n.node_name != name]
        self._wreckage.append(node)
        self.channels.kill(name)
        self.store.remove_applier(name)
        if report:
            survivor = self.master()
            survivor.report_node_left(name)

    def restart(self, name: str) -> ClusterNode:
        """Reopen `name` from its data_path and rejoin the cluster. The
        engines load the last segment commit and replay the translog above
        it; peer recovery then reconciles each copy with the current
        primary (rolling back a divergent tail where needed)."""
        path = f"{self.data_path}/{name}"
        roles = self.roles.get(name, ("master", "data"))
        node = ClusterNode(name, self.channels, self.store, data_path=path,
                          roles=roles)
        _register_refresh_handler(node)
        self.channels.register(name, node.transport)  # also un-kills
        node.shard_service.state = self.store.current()
        self.store.add_applier(name, node.apply_state)
        self.by_name[name] = node
        self.nodes.append(node)
        node.master_client(
            "internal:cluster/node/join",
            {"node": {"node_id": name, "name": name, "address": "",
                      "roles": list(roles)}})
        # the join is a no-op state-wise when the master never saw the
        # crash (report=False): reconcile explicitly so shards reopen
        node.apply_state(self.store.current())
        return node

    # ---- authoritative reads ----

    def primary_instance(self, index: str, doc_id: str):
        """The current primary's ShardInstance for the shard owning doc_id
        (None while the shard has no started primary)."""
        state = self.store.current()
        meta = state.indices[index]
        sid = shard_for_id(doc_id, meta.number_of_shards)
        primary = state.primary_of(index, sid)
        if primary is None or primary.node_id is None \
                or not primary.serving:
            return None
        holder = self.by_name.get(primary.node_id)
        if holder is None:
            return None
        return holder.shard_service.shards.get((index, sid))

    def read_doc(self, index: str, doc_id: str) -> Optional[dict]:
        """Realtime get through the current primary's engine — the
        authoritative answer for the durability check's final reads."""
        inst = self.primary_instance(index, doc_id)
        if inst is None:
            return None
        hit = inst.engine.get(doc_id)
        return None if hit is None else hit["_source"]


class AckedRegisterSpec(SequentialSpec):
    """Last-writer-wins register per document.

    Inputs are ("write", value) / ("delete", None) / ("read", None).
    A completed write/delete (ack observed) is always linearizable and sets
    the register; an incomplete one (out=None) is linearized optionally by
    the checker — covering both "took effect" and "lost before the WAL".
    A completed read's observed value — encoded ("observed", v), so a
    legitimate None document is distinguishable from the checker's marker
    for an incomplete op — must equal the register.
    """

    def initial_state(self) -> Any:
        return None

    def apply(self, state, inp, out):
        kind, arg = inp
        if kind == "read":
            if out is None:
                return True, state
            return (out[1] == state), state
        nstate = arg if kind == "write" else None
        return True, nstate


class AckedWriteHistory:
    """Concurrent per-doc histories + the acked-write durability check."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: Dict[str, List[Event]] = {}   # guarded by: _lock
        self._next_op = 0                           # guarded by: _lock

    def invoke(self, doc_id: str, kind: str, arg: Any = None) -> int:
        with self._lock:
            self._next_op += 1
            op_id = self._next_op
            self._events.setdefault(doc_id, []).append(
                Event("invoke", op_id, (kind, arg)))
            return op_id

    def respond(self, doc_id: str, op_id: int, out: Any = "ok") -> None:
        with self._lock:
            self._events[doc_id].append(Event("response", op_id, out))

    def record_read(self, doc_id: str, observed: Any) -> None:
        """A completed read observing `observed` (the document's current
        value, None when absent)."""
        op = self.invoke(doc_id, "read")
        self.respond(doc_id, op, ("observed", observed))

    def check(self) -> List[str]:
        """Run the linearizability check per document; return the doc ids
        whose history is NOT linearizable — i.e. where an acked write was
        lost or a read observed an impossible value. Empty list = pass."""
        checker = LinearizabilityChecker(AckedRegisterSpec())
        with self._lock:
            histories = {d: list(ev) for d, ev in self._events.items()}
        return [doc for doc, ev in sorted(histories.items())
                if not checker.is_linearizable(ev)]

"""Linearizability checker (Wing & Gong with Lowe's memoization).

Port of the testing *idea* in the reference's
cluster/coordination/LinearizabilityChecker.java (527 LoC): given a
sequential specification and a concurrent history of invoke/response event
pairs, search for a linearization — a total order of the operations,
consistent with real-time order, that the sequential spec accepts.

Used by the coordination tests to prove the cluster-state register is
linearizable under partitions, message loss, and leader churn.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple


@dataclass
class Event:
    kind: str        # "invoke" | "response"
    op_id: int
    value: Any       # input on invoke, output on response


class SequentialSpec:
    """Override: initial_state() and apply(state, input) -> (ok, output, next_state)."""

    def initial_state(self) -> Any:
        raise NotImplementedError

    def apply(self, state: Any, inp: Any, out: Any) -> Tuple[bool, Any]:
        """Return (accepted, next_state) for input/observed-output pair."""
        raise NotImplementedError

    def fingerprint(self, state: Any) -> Any:
        return state


class LinearizabilityChecker:
    def __init__(self, spec: SequentialSpec):
        self.spec = spec

    def is_linearizable(self, history: List[Event], max_steps: int = 2_000_000) -> bool:
        # pair up events
        invokes = {}
        responses = {}
        order = []
        for e in history:
            if e.kind == "invoke":
                invokes[e.op_id] = e
                order.append(e)
            else:
                responses[e.op_id] = e
                order.append(e)
        # ops with no response: may or may not have taken effect — model both
        # by treating them as completable at any later point (standard trick:
        # append synthetic responses at the end with unknown output = None)
        ops = {}
        for op_id, inv in invokes.items():
            resp = responses.get(op_id)
            ops[op_id] = (inv.value, resp.value if resp else None, resp is not None)

        # entries in real-time order: (op_id, invoke_index, response_index)
        idx_of = {}
        for i, e in enumerate(order):
            if e.kind == "invoke":
                idx_of[e.op_id] = [i, len(order)]
        for i, e in enumerate(order):
            if e.kind == "response":
                idx_of[e.op_id][1] = i

        pending = sorted(ops, key=lambda o: idx_of[o][0])
        completed_ops = frozenset(o for o in ops if ops[o][2])
        steps = [0]
        memo = set()

        def search(done: frozenset, state: Any) -> bool:
            steps[0] += 1
            if steps[0] > max_steps:
                raise RuntimeError("linearizability search exceeded budget")
            if completed_ops <= done:
                # incomplete ops are optional: not linearizing one models
                # "the op never took effect"
                return True
            key = (done, self.spec.fingerprint(state))
            if key in memo:
                return False
            # candidate ops: invoked before the earliest response of any
            # not-yet-linearized completed op (minimal-response rule)
            min_resp = min(idx_of[o][1] for o in completed_ops if o not in done)
            for op_id in pending:
                if op_id in done:
                    continue
                if idx_of[op_id][0] > min_resp:
                    break
                inp, out, completed = ops[op_id]
                accepted, nstate = self.spec.apply(state, inp, out if completed else None)
                if accepted and search(done | {op_id}, nstate):
                    return True
            memo.add(key)
            return False

        return search(frozenset(), self.spec.initial_state())


class CasRegisterSpec(SequentialSpec):
    """Compare-and-set register — the cluster-state model: an op is
    (op_kind, arg) with kinds write(v: (expected_version, value)) and read.

    write succeeds iff expected_version == current version; on success the
    register becomes (version+1, value). Reads return (version, value).
    """

    def initial_state(self):
        return (0, None)

    def apply(self, state, inp, out):
        version, value = state
        kind, arg = inp
        if kind == "read":
            if out is None:        # incomplete read: allowed, no state change
                return True, state
            return (out == state), state
        expected, new_value = arg
        ok = expected == version
        nstate = (version + 1, new_value) if ok else state
        if out is None:            # incomplete write: either effect is possible
            return True, nstate if ok else state
        return (out == ok), nstate

"""Deterministic task queue: virtual time + seeded interleaving.

The spine of the distributed-simulation test tier (ref:
test/framework/.../cluster/coordination/DeterministicTaskQueue.java — 499
LoC of virtual time that lets Raft-grade properties run in milliseconds).
Every scheduled action in a simulated cluster goes through one of these;
"now" only advances when no runnable task remains, and runnable tasks
execute in seeded-random order to explore interleavings.
"""

from __future__ import annotations

import heapq
import random
from typing import Callable, List, Optional, Tuple


class DeterministicTaskQueue:
    def __init__(self, seed: int = 0):
        self.random = random.Random(seed)
        self.now_ms: float = 0.0
        self._runnable: List[Tuple[int, Callable]] = []
        self._deferred: List[Tuple[float, int, Callable]] = []   # heap by time
        self._seq = 0

    # ---- scheduling API (what simulated nodes see) ----

    def schedule_now(self, fn: Callable) -> None:
        self._seq += 1
        self._runnable.append((self._seq, fn))

    def schedule_at(self, delay_ms: float, fn: Callable) -> "ScheduledHandle":
        self._seq += 1
        handle = ScheduledHandle(fn)
        heapq.heappush(self._deferred, (self.now_ms + delay_ms, self._seq, handle))
        return handle

    # ---- driving the simulation ----

    def has_runnable(self) -> bool:
        return bool(self._runnable)

    def has_deferred(self) -> bool:
        return bool(self._deferred)

    def run_one(self) -> bool:
        """Run one runnable task (seeded-random choice). False if none."""
        if not self._runnable:
            return False
        i = self.random.randrange(len(self._runnable))
        _, fn = self._runnable.pop(i)
        fn()
        return True

    def advance_time(self) -> bool:
        """Jump virtual time to the next deferred task; promote all tasks due."""
        if not self._deferred:
            return False
        self.now_ms = max(self.now_ms, self._deferred[0][0])
        while self._deferred and self._deferred[0][0] <= self.now_ms:
            _, seq, handle = heapq.heappop(self._deferred)
            if not handle.cancelled:
                self._runnable.append((seq, handle.fn))
        return True

    def run_all_runnable(self, limit: int = 100_000) -> None:
        n = 0
        while self.run_one():
            n += 1
            if n > limit:
                raise RuntimeError("runnable task storm: possible livelock")

    def run_until(self, deadline_ms: float, limit: int = 1_000_000) -> None:
        """Advance virtual time to `deadline_ms`, draining tasks on the way."""
        n = 0
        while True:
            if self._runnable:
                self.run_one()
            elif self._deferred and self._deferred[0][0] <= deadline_ms:
                self.advance_time()
            else:
                break
            n += 1
            if n > limit:
                raise RuntimeError("simulation did not quiesce")
        self.now_ms = max(self.now_ms, deadline_ms)

    def run_until_quiet(self, max_time_ms: float = 10 * 60 * 1000,
                        limit: int = 1_000_000) -> None:
        """Run until no runnable and no deferred tasks remain (or time cap)."""
        n = 0
        while (self._runnable or self._deferred) and self.now_ms <= max_time_ms:
            if not self.run_one():
                if not self.advance_time():
                    break
            n += 1
            if n > limit:
                raise RuntimeError("simulation did not quiesce")


class ScheduledHandle:
    __slots__ = ("fn", "cancelled")

    def __init__(self, fn: Callable):
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def __lt__(self, other):  # heap tie-break stability
        return id(self) < id(other)

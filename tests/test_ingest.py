"""Ingest pipelines (VERDICT r2 missing #6): processors, on_failure chains,
drop, bulk integration, default_pipeline, _simulate."""

import json

import pytest

from elasticsearch_tpu.ingest import (
    IngestDocument, IngestProcessorError, IngestService, PipelineMissingError,
)


@pytest.fixture()
def svc():
    return IngestService()


def run(svc, processors, source, **kw):
    svc.put_pipeline("p", {"processors": processors})
    r = svc.process("p", source, **kw)
    return None if r is None else r[0]


def test_set_remove_rename(svc):
    out = run(svc, [
        {"set": {"field": "env", "value": "prod"}},
        {"set": {"field": "greeting", "value": "hi {{user.name}}"}},
        {"rename": {"field": "old", "target_field": "new"}},
        {"remove": {"field": "junk"}},
    ], {"user": {"name": "kim"}, "old": 1, "junk": True})
    assert out == {"user": {"name": "kim"}, "env": "prod",
                   "greeting": "hi kim", "new": 1}


def test_convert_and_string_processors(svc):
    out = run(svc, [
        {"convert": {"field": "n", "type": "integer"}},
        {"convert": {"field": "flag", "type": "boolean"}},
        {"lowercase": {"field": "tag"}},
        {"trim": {"field": "pad"}},
        {"split": {"field": "csv", "separator": ","}},
        {"gsub": {"field": "phone", "pattern": r"[-\s]", "replacement": ""}},
        {"append": {"field": "tags", "value": ["b", "c"]}},
    ], {"n": "42", "flag": "TRUE", "tag": "HOT", "pad": "  x ",
        "csv": "a,b", "phone": "1-800 555", "tags": "a"})
    assert out["n"] == 42 and out["flag"] is True
    assert out["tag"] == "hot" and out["pad"] == "x"
    assert out["csv"] == ["a", "b"] and out["phone"] == "1800555"
    assert out["tags"] == ["a", "b", "c"]


def test_date_processor(svc):
    out = run(svc, [{"date": {"field": "ts", "formats": ["UNIX"]}}],
              {"ts": "1700000000"})
    assert out["@timestamp"].startswith("2023-11-14T")
    out = run(svc, [{"date": {"field": "d", "formats": ["%d/%m/%Y"],
                              "target_field": "when"}}], {"d": "02/01/2020"})
    assert out["when"].startswith("2020-01-02T")
    with pytest.raises(IngestProcessorError):
        run(svc, [{"date": {"field": "d", "formats": ["%Y"]}}],
            {"d": "not a date"})


def test_dissect(svc):
    out = run(svc, [{"dissect": {
        "field": "msg", "pattern": "%{client} - %{verb} %{path}"}}],
        {"msg": "1.2.3.4 - GET /index.html"})
    assert out["client"] == "1.2.3.4"
    assert out["verb"] == "GET" and out["path"] == "/index.html"


def test_drop_and_fail(svc):
    assert run(svc, [{"drop": {}}], {"x": 1}) is None
    svc.put_pipeline("f", {"processors": [
        {"fail": {"message": "bad doc {{id}}"}}]})
    with pytest.raises(IngestProcessorError, match="bad doc 7"):
        svc.process("f", {"id": 7})


def test_on_failure_chains(svc):
    out = run(svc, [
        {"convert": {"field": "n", "type": "integer",
                     "on_failure": [{"set": {"field": "n", "value": -1}}]}},
    ], {"n": "not-a-number"})
    assert out["n"] == -1
    # processor-level ignore_failure
    out = run(svc, [
        {"convert": {"field": "n", "type": "integer", "ignore_failure": True}},
        {"set": {"field": "ok", "value": 1}},
    ], {"n": "nope"})
    assert out["n"] == "nope" and out["ok"] == 1
    # pipeline-level on_failure
    svc.put_pipeline("pf", {
        "processors": [{"fail": {"message": "boom"}}],
        "on_failure": [{"set": {"field": "failed", "value": True}}]})
    assert svc.process("pf", {})[0]["failed"] is True


def test_unknown_processor_and_missing_pipeline(svc):
    with pytest.raises(IngestProcessorError):
        svc.put_pipeline("x", {"processors": [{"nope": {}}]})
    with pytest.raises(PipelineMissingError):
        svc.get_pipeline("ghost")


def test_pipeline_reroutes_via_meta(svc):
    svc.put_pipeline("route", {"processors": [
        {"set": {"field": "_index", "value": "logs-2026"}}]})
    out = svc.process("route", {"x": 1}, index="logs", doc_id="7")
    assert out == ({"x": 1}, "logs-2026", "7")


def test_simulate(svc):
    docs = svc.simulate(
        {"processors": [{"uppercase": {"field": "a"}}]},
        [{"_source": {"a": "x"}}, {"_source": {"b": 1}}])
    assert docs[0]["doc"]["_source"]["a"] == "X"
    assert "error" in docs[1]


def test_bulk_and_default_pipeline_integration():
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest import RestController, register_handlers

    node = Node()
    rc = RestController()
    register_handlers(node, rc)

    def call(method, path, body=None, raw=None, params=None):
        data = raw if raw is not None else (
            json.dumps(body).encode() if body is not None else None)
        resp = rc.dispatch(method, path, params or {}, data)
        return resp.status, json.loads(resp.encode() or b"{}")

    call("PUT", "/_ingest/pipeline/clean", {"processors": [
        {"lowercase": {"field": "tag"}},
        {"set": {"field": "via", "value": "clean"}},
    ]})
    call("PUT", "/pipes", {"settings": {
        "index": {"default_pipeline": "clean"}}})
    # default pipeline applies without ?pipeline=
    st, body = call("PUT", "/pipes/_doc/1", {"tag": "HOT"})
    assert st in (200, 201)
    call("POST", "/pipes/_refresh")
    st, doc = call("GET", "/pipes/_doc/1")
    assert doc["_source"] == {"tag": "hot", "via": "clean"}
    # bulk with per-action pipeline + a drop pipeline
    call("PUT", "/_ingest/pipeline/dropper", {"processors": [{"drop": {}}]})
    lines = [
        json.dumps({"index": {"_index": "pipes", "_id": "2",
                              "pipeline": "dropper"}}),
        json.dumps({"tag": "GONE"}),
        json.dumps({"index": {"_index": "pipes", "_id": "3",
                              "pipeline": "clean"}}),
        json.dumps({"tag": "WARM"}),
    ]
    st, body = call("POST", "/_bulk", raw=("\n".join(lines) + "\n").encode())
    assert body["items"][0]["index"]["result"] == "noop"
    call("POST", "/pipes/_refresh")
    st, _ = call("GET", "/pipes/_doc/2")
    assert st == 404
    st, doc = call("GET", "/pipes/_doc/3")
    assert doc["_source"]["tag"] == "warm"
    node.close()

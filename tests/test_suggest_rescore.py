"""Suggesters (term/phrase/completion) + rescore phase (VERDICT r4 item 4).

Differential where possible: rescore results are checked against a
manually-computed combination of the two queries' scores; suggesters
against hand-computable corpora (ref: the reference's
TermSuggestionBuilderTests / phrase + completion suggester semantics)."""

import numpy as np
import pytest

from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture(scope="module")
def svc():
    meta = IndexMetadata(
        index="sugg", uuid="u_sg", settings=Settings({}),
        mappings={"properties": {
            "body": {"type": "text"},
            "title": {"type": "text"},
            "sugg": {"type": "completion"},
            "n": {"type": "integer"},
        }})
    svc = IndexService(meta)
    docs = [
        ("hello world again", "alpha", {"input": ["Hotel Berlin", "Berlin"],
                                        "weight": 10}),
        ("hello there world", "beta", {"input": "Hotel Amsterdam",
                                       "weight": 5}),
        ("the quick brown fox jumps", "gamma", "Hostel Paris"),
        ("quick brown foxes leap high", "delta", ["Hotel Paris", "Paris"]),
        ("hello hello world peace", "alpha beta", {"input": "Hot Dog Stand",
                                                   "weight": 2}),
        ("world peace now", "gamma delta", "Hotelier"),
    ]
    for i, (body, title, sugg) in enumerate(docs):
        svc.index_doc(str(i), {"body": body, "title": title, "sugg": sugg,
                               "n": i})
    svc.refresh()
    yield svc
    svc.close()


# ---------------------------------------------------------------- term ----


def test_term_suggester_corrects_typo(svc):
    r = svc.search({"suggest": {
        "fix": {"text": "helol wrold", "term": {"field": "body"}}}})
    entries = r["suggest"]["fix"]
    assert [e["text"] for e in entries] == ["helol", "wrold"]
    assert entries[0]["options"][0]["text"] == "hello"
    assert entries[0]["options"][0]["freq"] == 3       # docs containing hello
    assert entries[1]["options"][0]["text"] == "world"
    assert entries[1]["offset"] == 6


def test_term_suggester_missing_mode_skips_known_words(svc):
    r = svc.search({"suggest": {
        "fix": {"text": "hello wrold", "term": {"field": "body"}}}})
    entries = r["suggest"]["fix"]
    assert entries[0]["options"] == []     # "hello" exists -> no suggestions
    assert entries[1]["options"][0]["text"] == "world"


def test_term_suggester_always_and_sort_frequency(svc):
    r = svc.search({"suggest": {
        "fix": {"text": "quick", "term": {
            "field": "body", "suggest_mode": "always",
            "sort": "frequency", "max_edits": 2,
            "min_word_length": 3}}}})
    opts = r["suggest"]["fix"][0]["options"]
    assert all(o["freq"] >= 1 for o in opts)


# -------------------------------------------------------------- phrase ----


def test_phrase_suggester_corrects_sequence(svc):
    r = svc.search({"suggest": {
        "ph": {"text": "helo world",
               "phrase": {"field": "body", "max_errors": 2.0,
                          "confidence": 0.0}}}})
    entry = r["suggest"]["ph"][0]
    assert entry["text"] == "helo world"
    assert any(o["text"] == "hello world" for o in entry["options"])


def test_phrase_suggester_highlight(svc):
    r = svc.search({"suggest": {
        "ph": {"text": "helo world",
               "phrase": {"field": "body", "max_errors": 2.0,
                          "confidence": 0.0,
                          "highlight": {"pre_tag": "<em>",
                                        "post_tag": "</em>"}}}}})
    opts = r["suggest"]["ph"][0]["options"]
    target = [o for o in opts if o["text"] == "hello world"]
    assert target and target[0]["highlighted"] == "<em>hello</em> world"


# ---------------------------------------------------------- completion ----


def test_completion_prefix_and_weight_order(svc):
    r = svc.search({"suggest": {
        "c": {"prefix": "hot", "completion": {"field": "sugg"}}}})
    opts = r["suggest"]["c"][0]["options"]
    texts = [o["text"] for o in opts]
    # weight-ranked: Hotel Berlin (10) first, then Hotel Amsterdam (5)
    assert texts[0] == "Hotel Berlin"
    assert texts[1] == "Hotel Amsterdam"
    assert all(t.lower().startswith("hot") for t in texts)


def test_completion_respects_deletes(svc):
    meta = IndexMetadata(
        index="sugg2", uuid="u_sg2", settings=Settings({}),
        mappings={"properties": {"sugg": {"type": "completion"}}})
    s2 = IndexService(meta)
    s2.index_doc("1", {"sugg": {"input": "apple", "weight": 9}})
    s2.index_doc("2", {"sugg": {"input": "apricot", "weight": 1}})
    s2.refresh()
    s2.delete_doc("1")
    s2.refresh()
    r = s2.search({"suggest": {
        "c": {"prefix": "ap", "completion": {"field": "sugg"}}}})
    texts = [o["text"] for o in r["suggest"]["c"][0]["options"]]
    assert texts == ["apricot"]
    s2.close()


def test_suggest_only_body_and_global_text(svc):
    r = svc.search({"size": 0, "suggest": {
        "text": "wrold",
        "a": {"term": {"field": "body"}},
        "b": {"term": {"field": "title"}}}})
    assert r["suggest"]["a"][0]["options"][0]["text"] == "world"
    assert r["hits"]["hits"] == []


def test_suggest_unknown_kind_rejected(svc):
    with pytest.raises(IllegalArgumentError):
        svc.search({"suggest": {"x": {"text": "a", "bogus": {}}}})


# ------------------------------------------------------------- rescore ----


def _score_of(svc, body, doc_id):
    r = svc.search(body)
    for h in r["hits"]["hits"]:
        if h["_id"] == doc_id:
            return h["_score"]
    return None


def test_rescore_total_combines_scores(svc):
    base = {"query": {"match": {"body": "world"}}, "size": 10}
    plain = svc.search(base)
    resc = svc.search({**base, "rescore": {
        "window_size": 10,
        "query": {"rescore_query": {"match": {"body": "hello"}},
                  "query_weight": 1.0, "rescore_query_weight": 2.0}}})
    # every rescored hit's score == orig + 2 * hello-score (or orig alone)
    hello_scores = {h["_id"]: h["_score"] for h in
                    svc.search({"query": {"match": {"body": "hello"}},
                                "size": 20})["hits"]["hits"]}
    plain_scores = {h["_id"]: h["_score"] for h in plain["hits"]["hits"]}
    for h in resc["hits"]["hits"]:
        expect = plain_scores[h["_id"]] + 2.0 * hello_scores.get(h["_id"], 0.0)
        assert abs(h["_score"] - expect) < 1e-4, h["_id"]
    # and the order follows the combined score
    scores = [h["_score"] for h in resc["hits"]["hits"]]
    assert scores == sorted(scores, reverse=True)


def test_rescore_window_limits_reranking(svc):
    base = {"query": {"match": {"body": "world"}}, "size": 10}
    resc = svc.search({**base, "rescore": {
        "window_size": 1,
        "query": {"rescore_query": {"match": {"body": "peace"}},
                  "rescore_query_weight": 100.0}}})
    plain = svc.search(base)
    # only the top-1 doc could change score; tail order preserved
    assert [h["_id"] for h in resc["hits"]["hits"][1:]] == \
        [h["_id"] for h in plain["hits"]["hits"][1:]]


def test_rescore_score_modes(svc):
    base = {"query": {"match": {"body": "world"}}, "size": 10}
    for mode in ("total", "multiply", "avg", "max", "min"):
        r = svc.search({**base, "rescore": {
            "window_size": 10,
            "query": {"rescore_query": {"match": {"body": "hello"}},
                      "score_mode": mode}}})
        assert r["hits"]["hits"], mode


def test_rescore_rejects_field_sort(svc):
    with pytest.raises(IllegalArgumentError):
        svc.search({"query": {"match": {"body": "world"}},
                    "sort": [{"n": "asc"}],
                    "rescore": {"query": {
                        "rescore_query": {"match": {"body": "hello"}}}}})


def test_rescore_multiple_passes(svc):
    base = {"query": {"match": {"body": "world"}}, "size": 10}
    r = svc.search({**base, "rescore": [
        {"window_size": 10, "query": {
            "rescore_query": {"match": {"body": "hello"}}}},
        {"window_size": 5, "query": {
            "rescore_query": {"match": {"body": "peace"}},
            "rescore_query_weight": 3.0}},
    ]})
    scores = [h["_score"] for h in r["hits"]["hits"][:5]]
    assert scores == sorted(scores, reverse=True)

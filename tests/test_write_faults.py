"""Write-path fault ladder units (PR 8): grammar coverage for the new
sites, translog fsync/corruption behavior, the async-durability exposure
bound, the engine's failed-state latch, replication retry classification,
and the knob surface backing the coordinator bulk retry loop.
"""

import os

import pytest

from elasticsearch_tpu.common import faults
from elasticsearch_tpu.common.durability import (
    durability_stats, reset_for_tests,
)
from elasticsearch_tpu.common.faults import (
    DURABILITY_SITES, DurabilityFaultError, FaultSpecError, corruption_fires,
    durability_fault_point, inject, parse_spec, transport_fault_point,
)
from elasticsearch_tpu.common.settings import ENV_KNOBS, knob
from elasticsearch_tpu.index.engine import InternalEngine
from elasticsearch_tpu.index.translog import (
    Translog, TranslogCorruptedError, TranslogFsyncError,
)
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.transport.channels import (
    _RPC_FAULT_SITES, NodeUnavailableError,
)

pytestmark = pytest.mark.faults

MAPPING = {"properties": {"body": {"type": "text"},
                          "n": {"type": "long"}}}

NEW_SITES = ("rpc_bulk", "rpc_replica_bulk", "rpc_recovery", "rpc_resync",
             "translog_fsync", "translog_corrupt", "segment_commit")


@pytest.fixture(autouse=True)
def _clean_counters():
    reset_for_tests()
    yield
    faults.clear()
    reset_for_tests()


def make_engine(path=None):
    return InternalEngine(MapperService(dict(MAPPING)), data_path=path)


# ---------------------------------------------------------------- grammar


def test_all_new_sites_parse():
    spec = ";".join(f"{s}:raise" for s in NEW_SITES)
    clauses = parse_spec(spec)
    assert [c.site for c in clauses] == list(NEW_SITES)


def test_rpc_bulk_accepts_node_name_part():
    (c,) = parse_spec("rpc_bulk#d1:raise@2x3")
    assert (c.site, c.part, c.nth, c.count) == ("rpc_bulk", "d1", 2, 3)


def test_durability_site_rejects_node_name_part():
    # durability sites take integer parts only — a node-name selector on
    # translog_fsync is a spec typo, and typos fail LOUD
    with pytest.raises(FaultSpecError):
        parse_spec("translog_fsync#x:raise")


def test_translog_fsync_nth_count_markers():
    (c,) = parse_spec("translog_fsync:raise@2x3")
    assert (c.nth, c.count) == (2, 3)
    assert faults._fire_mode("translog_fsync", None) is None  # call 1
    faults.install("translog_fsync:raise@2x3")
    try:
        hits = [faults._fire_mode("translog_fsync", None) is not None
                for _ in range(6)]
        assert hits == [False, True, True, True, False, False]
    finally:
        faults.clear()


def test_every_write_rpc_action_maps_to_a_site():
    for action, site in {
            "indices:data/write/bulk[s]": "rpc_bulk",
            "indices:data/write/bulk[s][r]": "rpc_replica_bulk",
            "internal:index/shard/recovery/prepare": "rpc_recovery",
            "internal:index/shard/recovery/segments": "rpc_recovery",
            "internal:index/shard/recovery/ops": "rpc_recovery",
            "internal:index/shard/recovery/finalize": "rpc_recovery",
            "internal:index/shard/recovery/cancel": "rpc_recovery",
            "internal:index/shard/resync/prepare": "rpc_resync",
            "internal:index/shard/resync/apply": "rpc_resync"}.items():
        assert _RPC_FAULT_SITES[action] == site


# ------------------------------------------------------------------ fire


def test_durability_fault_point_fires_as_oserror():
    with inject("translog_fsync:raise@1x1"):
        with pytest.raises(DurabilityFaultError) as ei:
            durability_fault_point("translog_fsync")
        assert isinstance(ei.value, OSError)
        durability_fault_point("translog_fsync")  # x1 consumed


def test_transport_site_fires_node_unavailable():
    with inject("rpc_bulk#d1:raise@1x1"):
        transport_fault_point("rpc_bulk", "d2")  # wrong node: no fire
        with pytest.raises(NodeUnavailableError):
            transport_fault_point("rpc_bulk", "d1")


def test_corruption_fires_is_consumable():
    with inject("translog_corrupt:raise@1x1"):
        assert corruption_fires() is True
        assert corruption_fires() is False


# -------------------------------------------------------------- translog


def test_fsync_fault_raises_and_counts(tmp_path):
    t = Translog(str(tmp_path / "t"))
    t.add({"op": "index", "id": "a", "seq_no": 0})
    with inject("translog_fsync:raise@1x1"):
        with pytest.raises(TranslogFsyncError):
            t.add({"op": "index", "id": "b", "seq_no": 1})
    assert durability_stats()["fsync_failures"] == 1
    # the site recovered: the next append syncs fine
    t.add({"op": "index", "id": "c", "seq_no": 2})


def test_async_durability_window_is_bounded(tmp_path, monkeypatch):
    monkeypatch.setenv("ES_TPU_TRANSLOG_SYNC_OPS", "4")
    t = Translog(str(tmp_path / "t"), durability="async")
    for i in range(3):
        t.add({"op": "index", "id": str(i), "seq_no": i})
    assert t.ops_since_sync == 3
    assert durability_stats()["max_ops_since_sync"] == 3
    t.add({"op": "index", "id": "3", "seq_no": 3})  # hits the bound
    assert t.ops_since_sync == 0


def test_interior_corruption_surfaces_at_replay(tmp_path):
    t = Translog(str(tmp_path / "t"))
    with inject("translog_corrupt:raise@1x1"):
        t.add({"op": "index", "id": "a", "seq_no": 0})  # written, CRC broken
    t.add({"op": "index", "id": "b", "seq_no": 1})      # makes it interior
    assert durability_stats()["translog_corruptions"] == 1
    with pytest.raises(TranslogCorruptedError):
        list(t.read_ops())


def test_corrupt_tail_record_is_a_torn_write(tmp_path):
    t = Translog(str(tmp_path / "t"))
    t.add({"op": "index", "id": "a", "seq_no": 0})
    with inject("translog_corrupt:raise@1x1"):
        t.add({"op": "index", "id": "b", "seq_no": 1})  # last record
    ops = list(t.read_ops())
    assert [op["id"] for op in ops] == ["a"]


# ---------------------------------------------------------------- engine


def test_engine_latches_failed_after_fsync_fault(tmp_path):
    e = make_engine(str(tmp_path / "s"))
    e.index("a", {"body": "x", "n": 1})
    with inject("translog_fsync:raise@1x1"):
        with pytest.raises(TranslogFsyncError):
            e.index("b", {"body": "y", "n": 2})
    assert e.failed_reason is not None
    # the latch holds after the fault clears: a failed copy must be
    # reallocated, never written into
    with pytest.raises(TranslogFsyncError):
        e.index("c", {"body": "z", "n": 3})


def test_segment_commit_fault_counts_and_raises(tmp_path):
    e = make_engine(str(tmp_path / "s"))
    e.index("a", {"body": "x", "n": 1})
    with inject("segment_commit:raise@1x1"):
        with pytest.raises(OSError):
            e.flush()
    assert durability_stats()["segment_commit_failures"] == 1
    e.flush()  # recovered


def test_recover_from_disk_counts_replays(tmp_path):
    path = str(tmp_path / "s")
    e1 = make_engine(path)
    e1.index("a", {"body": "x", "n": 1})
    e1.index("b", {"body": "y", "n": 2})
    # no flush: a second engine over the same path replays the WAL
    e2 = make_engine(path)
    assert e2.get("a") is not None and e2.get("b") is not None
    stats = durability_stats()
    assert stats["translog_replays"] >= 1
    assert stats["translog_replayed_ops"] >= 2
    del e1  # keep the first engine alive until after the replay check


# ----------------------------------------------------------------- knobs


def test_write_path_knobs_are_declared():
    for name, default in (("ES_TPU_TRANSLOG_SYNC_OPS", 128),
                          ("ES_TPU_BULK_RETRIES", 20),
                          ("ES_TPU_BULK_RETRY_MS", 100),
                          ("ES_TPU_BULK_TIMEOUT_MS", 0),
                          ("ES_TPU_RECOVERY_RETRIES", 3),
                          ("ES_TPU_RECOVERY_BACKOFF_MS", 50)):
        assert name in ENV_KNOBS
        if os.environ.get(name) in (None, ""):
            assert knob(name) == default


def test_durability_sites_are_known():
    assert DURABILITY_SITES <= faults.KNOWN_SITES

"""Rolling maintenance plane (PR 14): live shard relocation with warm HBM
handoff, node drain, delayed allocation, rebalancing.

Three layers, mirroring the plane's own structure:

* pure state-transition tests over AllocationService — the relocation
  state machine (initiate/complete/cancel), the drain + rebalance
  deciders, the concurrent-relocations cap, and delayed allocation with
  a FAKE clock (the timer merely submits; the decision is a pure
  function of state + now_ms);
* live in-process cluster tests — a real move over the transport (peer
  recovery + in-sync swap + warm handoff), drain via
  PUT /_cluster/settings, delayed allocation around a crash/restart
  bounce, and the rpc_relocation fault site;
* the chaos lane's rolling-restart scenario: drain -> relocations
  complete -> crash -> restart -> rejoin -> rebalance, with zero acked
  writes lost and admitted searches agreeing before and after.
"""

import time as _time

import pytest

from elasticsearch_tpu.cluster.allocation import (
    AllocationService, CONCURRENT_RELOC_SETTING, EXCLUDE_NAME_SETTING,
)
from elasticsearch_tpu.cluster.state import (
    ClusterState, DiscoveryNode, IndexMetadata, ShardRouting,
)
from elasticsearch_tpu.cluster_node import form_local_cluster
from elasticsearch_tpu.common import relocation as reloc_counters
from elasticsearch_tpu.common.settings import Settings

MAPPINGS = {"properties": {"n": {"type": "integer"},
                           "body": {"type": "text"}}}


@pytest.fixture(autouse=True)
def _fresh_counters():
    reloc_counters.reset_for_tests()
    yield
    reloc_counters.reset_for_tests()


# ---------------------------------------------------------------------------
# pure state transitions
# ---------------------------------------------------------------------------

def make_state(n_nodes=3, shards=1, replicas=0, placements=None):
    """A hand-built state: nodes n0..nK, one index, explicit placements
    {(shard_id, primary): node} (default: round-robin STARTED copies)."""
    nodes = {f"n{i}": DiscoveryNode(node_id=f"n{i}", name=f"n{i}")
             for i in range(n_nodes)}
    routing = []
    in_sync = {}
    aid = [0]

    def new_aid():
        aid[0] += 1
        return f"aid{aid[0]:03d}"

    for sid in range(shards):
        copies = [(sid, True)] + [(sid, False)] * replicas
        for j, (s, primary) in enumerate(copies):
            if placements is not None:
                node = placements.get((s, primary))
            else:
                node = f"n{(s + j) % n_nodes}"
            a = new_aid()
            routing.append(ShardRouting(
                index="idx", shard_id=s, node_id=node, primary=primary,
                state="STARTED", allocation_id=a))
            in_sync.setdefault(s, []).append(a)
    meta = IndexMetadata(
        index="idx", uuid="u1",
        settings=Settings({"index.number_of_shards": shards,
                           "index.number_of_replicas": replicas}),
        mappings=MAPPINGS,
        primary_terms=tuple([1] * shards),
        in_sync_allocations={s: tuple(v) for s, v in in_sync.items()})
    return ClusterState(master_node_id="n0", nodes=nodes,
                        indices={"idx": meta}, routing={"idx": routing})


def copies(state, sid=0):
    return state.shard_copies("idx", sid)


def by_state(state, want, sid=0):
    return [r for r in copies(state, sid) if r.state == want]


def test_initiate_relocation_creates_linked_pair():
    alloc = AllocationService()
    st = make_state(n_nodes=2, placements={(0, True): "n0"})
    src = copies(st)[0]
    out = alloc.initiate_relocation(st, "idx", 0, src.allocation_id, "n1")
    assert out is not st
    (source,) = by_state(out, "RELOCATING")
    (target,) = by_state(out, "INITIALIZING")
    assert source.node_id == "n0" and source.relocating_node_id == "n1"
    assert target.node_id == "n1" and target.relocating_node_id == "n0"
    assert target.primary == source.primary
    assert target.allocation_id not in ("", source.allocation_id)
    # the source keeps serving mid-move
    assert source.serving and not target.serving
    assert out.primary_of("idx", 0) is source
    # in-sync is untouched until the target actually starts
    assert out.indices["idx"].in_sync_allocations[0] \
        == st.indices["idx"].in_sync_allocations[0]


def test_initiate_relocation_rejects_illegal_moves():
    alloc = AllocationService()
    st = make_state(n_nodes=2, replicas=1,
                    placements={(0, True): "n0", (0, False): "n1"})
    src = st.primary_of("idx", 0)
    # same-shard rule: n1 already holds a copy
    assert alloc.initiate_relocation(
        st, "idx", 0, src.allocation_id, "n1") is st
    # unknown target node
    assert alloc.initiate_relocation(
        st, "idx", 0, src.allocation_id, "n9") is st
    # source == target
    assert alloc.initiate_relocation(
        st, "idx", 0, src.allocation_id, "n0") is st


def test_relocation_complete_swaps_in_sync_and_removes_source():
    alloc = AllocationService()
    st = make_state(n_nodes=2, placements={(0, True): "n0"})
    src = copies(st)[0]
    st = alloc.initiate_relocation(st, "idx", 0, src.allocation_id, "n1")
    (target,) = by_state(st, "INITIALIZING")
    out = alloc.apply_started_shard(st, "idx", 0, target.allocation_id)
    assert len(copies(out)) == 1
    (started,) = copies(out)
    assert started.node_id == "n1" and started.state == "STARTED"
    assert started.primary and started.relocating_node_id is None
    in_sync = set(out.indices["idx"].in_sync_allocations[0])
    assert in_sync == {target.allocation_id}
    assert src.allocation_id not in in_sync
    # same primary context moved: NO term bump on a relocation swap
    assert out.indices["idx"].primary_term(0) == 1
    assert reloc_counters.relocation_stats()["moves"] == 1
    h = out.health()
    assert h["status"] == "green" and h["relocating_shards"] == 0


def test_relocation_target_failure_cancels_cleanly():
    alloc = AllocationService()
    st = make_state(n_nodes=2, placements={(0, True): "n0"})
    src = copies(st)[0]
    st = alloc.initiate_relocation(st, "idx", 0, src.allocation_id, "n1")
    (target,) = by_state(st, "INITIALIZING")
    out = alloc.apply_failed_shard(st, "idx", 0, target.allocation_id)
    (back,) = copies(out)
    assert back.state == "STARTED" and back.node_id == "n0"
    assert back.relocating_node_id is None
    assert back.allocation_id == src.allocation_id
    # no replacement UNASSIGNED copy appears: nothing was lost
    assert not by_state(out, "UNASSIGNED")
    assert set(out.indices["idx"].in_sync_allocations[0]) \
        == {src.allocation_id}
    assert reloc_counters.relocation_stats()["cancels"] == 1


def test_dead_target_node_reverts_source():
    alloc = AllocationService()
    st = make_state(n_nodes=2, placements={(0, True): "n0"})
    src = copies(st)[0]
    st = alloc.initiate_relocation(st, "idx", 0, src.allocation_id, "n1")
    out = alloc.disassociate_dead_nodes(st, {"n1"}, delayed_ms=0)
    (back,) = copies(out)
    assert back.state == "STARTED" and back.node_id == "n0"
    assert reloc_counters.relocation_stats()["cancels"] == 1
    assert out.health()["status"] == "green"


def test_dead_source_node_promotes_and_drops_target():
    """Killing the source mid-transfer takes the half-built target with it;
    an in-sync replica is promoted so the shard stays served."""
    alloc = AllocationService()
    st = make_state(n_nodes=3, replicas=1,
                    placements={(0, True): "n0", (0, False): "n1"})
    src = st.primary_of("idx", 0)
    replica = next(r for r in copies(st) if not r.primary)
    st = alloc.initiate_relocation(st, "idx", 0, src.allocation_id, "n2")
    (target,) = by_state(st, "INITIALIZING")
    out = alloc.disassociate_dead_nodes(st, {"n0"}, delayed_ms=0)
    promoted = out.primary_of("idx", 0)
    assert promoted is not None and promoted.node_id == "n1"
    assert promoted.allocation_id == replica.allocation_id
    assert out.indices["idx"].primary_term(0) == 2  # real failover: bump
    in_sync = set(out.indices["idx"].in_sync_allocations[0])
    assert target.allocation_id not in in_sync
    alive_nodes = {r.node_id for r in copies(out)}
    assert "n0" not in alive_nodes
    # the orphaned target is gone too (it could never finish recovering)
    assert all(r.relocating_node_id is None for r in copies(out))


def test_drain_via_exclude_setting_bounded_by_cap():
    alloc = AllocationService()
    st = make_state(n_nodes=3, shards=4, placements={
        (0, True): "n0", (1, True): "n0", (2, True): "n0", (3, True): "n1"})
    st = st.with_settings({EXCLUDE_NAME_SETTING: "n0",
                           CONCURRENT_RELOC_SETTING: "2"})
    out = alloc.reroute(st)
    moving = [r for shards in out.routing.values() for r in shards
              if r.state == "RELOCATING"]
    assert len(moving) == 2          # cap, not all three at once
    assert all(r.node_id == "n0" for r in moving)
    assert all(r.relocating_node_id != "n0" for r in moving)
    # completing one move frees budget for the next drain step
    tgt = next(r for r in by_state(out, "INITIALIZING",
                                   sid=moving[0].shard_id))
    out2 = alloc.reroute(alloc.apply_started_shard(
        out, "idx", moving[0].shard_id, tgt.allocation_id))
    moving2 = [r for shards in out2.routing.values() for r in shards
               if r.state == "RELOCATING"]
    assert len(moving2) == 2


def test_drain_respects_same_shard_rule():
    """A drained primary whose only other nodes hold the replica stays put
    rather than doubling up."""
    alloc = AllocationService()
    st = make_state(n_nodes=2, replicas=1,
                    placements={(0, True): "n0", (0, False): "n1"})
    st = st.with_settings({EXCLUDE_NAME_SETTING: "n0"})
    out = alloc.reroute(st)
    assert not by_state(out, "RELOCATING")
    assert out.primary_of("idx", 0).node_id == "n0"


def test_rebalance_moves_onto_new_node():
    alloc = AllocationService()
    st = make_state(n_nodes=2, shards=4, placements={
        (0, True): "n0", (1, True): "n0", (2, True): "n1", (3, True): "n1"})
    st = st.with_node(DiscoveryNode(node_id="n2", name="n2"))
    out = alloc.reroute(st)
    moving = [r for shards in out.routing.values() for r in shards
              if r.state == "RELOCATING"]
    assert moving, "an empty joiner must attract copies"
    targets = [r.relocating_node_id for r in moving]
    assert all(t == "n2" for t in targets)
    # spread >= 2 rule: a 2-vs-1 split does not thrash
    for r in moving:
        tgt = next(t for t in by_state(out, "INITIALIZING", sid=r.shard_id))
        out = alloc.apply_started_shard(out, "idx", r.shard_id,
                                        tgt.allocation_id)
    settled = alloc.reroute(out)
    still = [r for shards in settled.routing.values() for r in shards
             if r.state == "RELOCATING"]
    assert not still


def test_delayed_allocation_fake_clock_window_then_expiry():
    clock = [1_000_000]
    alloc = AllocationService(clock=lambda: clock[0])
    st = make_state(n_nodes=3, replicas=1,
                    placements={(0, True): "n0", (0, False): "n1"})
    out = alloc.disassociate_dead_nodes(st, {"n1"}, delayed_ms=30_000)
    (repl,) = by_state(out, "UNASSIGNED")
    assert repl.delayed_until_ms == 1_030_000
    assert repl.last_node_id == "n1"
    h = out.health(now_ms=clock[0])
    assert h["delayed_unassigned_shards"] == 1
    assert h["status"] == "yellow"
    # inside the window: reroute must NOT build a replacement elsewhere
    inside = alloc.reroute(out, now_ms=1_010_000)
    assert by_state(inside, "UNASSIGNED")
    assert not by_state(inside, "INITIALIZING")
    # past the deadline: the replacement allocates (exactly once)
    clock[0] = 1_030_001
    expired = alloc.reroute(out)
    (init,) = by_state(expired, "INITIALIZING")
    assert init.node_id == "n2"   # n0 holds the primary; same-shard rule
    assert init.delayed_until_ms is None
    assert expired.health(now_ms=clock[0])["delayed_unassigned_shards"] == 0


def test_delayed_allocation_rejoin_reclaims_own_copy():
    clock = [500_000]
    alloc = AllocationService(clock=lambda: clock[0])
    st = make_state(n_nodes=3, replicas=1,
                    placements={(0, True): "n0", (0, False): "n1"})
    out = alloc.disassociate_dead_nodes(st, {"n1"}, delayed_ms=60_000)
    # the node bounces back inside the window
    back = out.with_node(DiscoveryNode(node_id="n1", name="n1"))
    rejoined = alloc.reroute(back, now_ms=510_000)
    (init,) = by_state(rejoined, "INITIALIZING")
    assert init.node_id == "n1"   # its own copy, not a stranger's


# ---------------------------------------------------------------------------
# live in-process cluster
# ---------------------------------------------------------------------------

def make_cluster(n_data=3, data_path=None):
    names = ["m0"] + [f"d{i}" for i in range(n_data)]
    roles = {"m0": ("master",)}
    return form_local_cluster(names, data_path=data_path, roles=roles)


def index_body(shards=1, replicas=0):
    return {"settings": {"number_of_shards": shards,
                         "number_of_replicas": replicas},
            "mappings": MAPPINGS}


def bulk_ops(start, count):
    return [{"op": "index", "id": str(i),
             "source": {"n": i, "body": f"word{i % 7} common text"}}
            for i in range(start, start + count)]


def wait_until(pred, timeout=10.0):
    deadline = _time.monotonic() + timeout
    while _time.monotonic() < deadline:
        if pred():
            return True
        _time.sleep(0.02)
    return pred()


def nodes_holding(store, index, sid):
    return {r.node_id for r in store.current().shard_copies(index, sid)
            if r.node_id is not None}


def test_live_move_command_relocates_and_preserves_results():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(1, 0))
    a.bulk("docs", bulk_ops(0, 40))
    a.refresh("docs")
    before = b.search("docs", {"query": {"match": {"body": "common"}},
                               "size": 10, "track_total_hits": True})
    src = store.current().primary_of("docs", 0).node_id
    free = next(n for n in ("d0", "d1", "d2") if n != src)
    resp = a.cluster_reroute([{"move": {
        "index": "docs", "shard": 0, "from_node": src, "to_node": free}}])
    assert resp["acknowledged"]
    assert wait_until(lambda: nodes_holding(store, "docs", 0) == {free})
    assert wait_until(
        lambda: store.current().health()["relocating_shards"] == 0)
    h = store.current().health()
    assert h["status"] == "green"
    after = c.search("docs", {"query": {"match": {"body": "common"}},
                              "size": 10, "track_total_hits": True})
    assert after["hits"]["total"]["value"] \
        == before["hits"]["total"]["value"] == 40
    assert [x["_id"] for x in after["hits"]["hits"]] \
        == [x["_id"] for x in before["hits"]["hits"]]
    assert reloc_counters.relocation_stats()["moves"] == 1
    # writes keep flowing through the moved primary
    r2 = a.bulk("docs", bulk_ops(40, 10))
    assert not r2["errors"]


def test_live_move_dry_run_changes_nothing():
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(1, 0))
    src = store.current().primary_of("docs", 0).node_id
    free = next(n for n in ("d0", "d1", "d2") if n != src)
    v0 = store.current().version
    resp = a.cluster_reroute(
        [{"move": {"index": "docs", "shard": 0,
                   "from_node": src, "to_node": free}},
         {"cancel": {}}], dry_run=True)
    assert resp["dry_run"]
    assert resp["explanations"][0]["accepted"] is True
    assert resp["explanations"][1]["accepted"] is False
    assert store.current().version == v0
    assert nodes_holding(store, "docs", 0) == {src}


def test_live_drain_then_rebalance_on_clear(tmp_path):
    nodes, store, channels = make_cluster(data_path=str(tmp_path))
    master, a, b, c = nodes
    a.create_index("docs", index_body(2, 1))
    a.bulk("docs", bulk_ops(0, 30))
    a.refresh("docs")
    # drain d0: every copy must leave, bounded by the cap under the hood
    a.update_cluster_settings({EXCLUDE_NAME_SETTING: "d0"})
    assert wait_until(lambda: not store.current().entries_on_node("d0"))
    assert wait_until(
        lambda: store.current().health()["relocating_shards"] == 0)
    assert store.current().health()["status"] == "green"
    r = b.search("docs", {"query": {"match": {"body": "common"}},
                          "size": 5, "track_total_hits": True})
    assert r["hits"]["total"]["value"] == 30
    # clearing the filter lets the rebalancer repopulate the empty node
    a.update_cluster_settings({EXCLUDE_NAME_SETTING: None})
    assert wait_until(lambda: bool(store.current().entries_on_node("d0")))
    assert wait_until(
        lambda: store.current().health()["relocating_shards"] == 0)
    assert store.current().health()["status"] == "green"


def test_warm_handoff_primes_target(monkeypatch):
    """ES_TPU_RELOC_WARM=1 (default): the moved copy's per-field engines
    and qc bucket ladder are primed BEFORE shard-started, measured by the
    tpu_relocation counters; =0 leaves the move correct but cold."""
    monkeypatch.setenv("ES_TPU_FORCE_TURBO", "1")
    from elasticsearch_tpu.common import hbm_ledger
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(1, 0))
    a.bulk("docs", bulk_ops(0, 60))
    a.refresh("docs")
    # serve queries so the source builds its per-field engine and the
    # ledger records hot dispatch shapes (what the handoff transfers)
    for _ in range(2):
        b.search("docs", {"query": {"match": {"body": "common"}}, "size": 5})
    assert hbm_ledger.hot_shapes(), "searches must leave hot shapes behind"
    src = store.current().primary_of("docs", 0).node_id
    free = next(n for n in ("d0", "d1", "d2") if n != src)

    monkeypatch.setenv("ES_TPU_RELOC_WARM", "0")
    a.cluster_reroute([{"move": {"index": "docs", "shard": 0,
                                 "from_node": src, "to_node": free}}])
    assert wait_until(lambda: nodes_holding(store, "docs", 0) == {free})
    cold = reloc_counters.relocation_stats()
    assert cold["moves"] == 1 and cold["warm_handoffs"] == 0
    assert cold["shapes_primed"] == 0
    # kill switch off -> the move is correct anyway
    r = c.search("docs", {"query": {"match": {"body": "common"}},
                          "size": 5, "track_total_hits": True})
    assert r["hits"]["total"]["value"] == 60

    monkeypatch.setenv("ES_TPU_RELOC_WARM", "1")
    b.search("docs", {"query": {"match": {"body": "common"}}, "size": 5})
    src2, free2 = free, src
    a.cluster_reroute([{"move": {"index": "docs", "shard": 0,
                                 "from_node": src2, "to_node": free2}}])
    assert wait_until(lambda: nodes_holding(store, "docs", 0) == {free2})
    warm = reloc_counters.relocation_stats()
    assert warm["moves"] == 2
    assert warm["warm_handoffs"] == 1
    assert warm["fields_warmed"] >= 1      # the body engine was pre-built
    assert warm["shapes_primed"] > 0       # qc ladder covered hot widths
    assert warm["warm_failures"] == 0
    retraces_before = hbm_ledger.compile_stats()["retraces"]
    r = c.search("docs", {"query": {"match": {"body": "common"}},
                          "size": 5, "track_total_hits": True})
    assert r["hits"]["total"]["value"] == 60
    # first post-move query dispatches at a primed shape: no new retrace
    assert hbm_ledger.compile_stats()["retraces"] == retraces_before


def test_rpc_relocation_fault_leaves_move_correct_but_cold():
    """Faulting the warm-info RPC (site rpc_relocation, #node selector
    reused from rpc_recovery) must not fail the move — warming is
    best-effort, and the failure is counted."""
    from elasticsearch_tpu.common import faults
    nodes, store, channels = make_cluster()
    master, a, b, c = nodes
    a.create_index("docs", index_body(1, 0))
    a.bulk("docs", bulk_ops(0, 20))
    a.refresh("docs")
    b.search("docs", {"query": {"match": {"body": "common"}}, "size": 5})
    src = store.current().primary_of("docs", 0).node_id
    free = next(n for n in ("d0", "d1", "d2") if n != src)
    with faults.inject(f"rpc_relocation#{src}:raise"):
        a.cluster_reroute([{"move": {"index": "docs", "shard": 0,
                                     "from_node": src, "to_node": free}}])
        assert wait_until(lambda: nodes_holding(store, "docs", 0) == {free})
    stats = reloc_counters.relocation_stats()
    assert stats["moves"] == 1
    assert stats["warm_failures"] == 1
    assert stats["warm_handoffs"] == 0
    assert store.current().health()["status"] == "green"
    r = c.search("docs", {"query": {"match": {"body": "common"}},
                          "size": 5, "track_total_hits": True})
    assert r["hits"]["total"]["value"] == 20


def test_live_delayed_allocation_bounce_inside_window(tmp_path, monkeypatch):
    """A node bouncing inside ES_TPU_DELAYED_ALLOC_MS rejoins and recovers
    its own copies: zero replacement copies are built elsewhere, and the
    wait shows up in delayed_unassigned_shards."""
    from elasticsearch_tpu.testing.chaos import CrashRestartCluster
    monkeypatch.setenv("ES_TPU_DELAYED_ALLOC_MS", "60000")
    cluster = CrashRestartCluster(
        ["m0", "d0", "d1"], str(tmp_path), roles={"m0": ("master",)})
    m = cluster.node("m0")
    m.create_index("docs", index_body(1, 1))
    cluster.node("d0").bulk("docs", bulk_ops(0, 25))
    m2 = cluster.master()
    replica = next(r for r in cluster.store.current().shard_copies("docs", 0)
                   if not r.primary)
    victim = replica.node_id
    cluster.crash(victim, report=True)
    st = cluster.store.current()
    h = st.health()
    assert h["delayed_unassigned_shards"] == 1
    assert h["status"] == "yellow"
    (unassigned,) = [r for r in st.shard_copies("docs", 0)
                     if r.state == "UNASSIGNED"]
    assert unassigned.last_node_id == victim
    # no replacement sprouted on the surviving data node
    survivor = "d0" if victim == "d1" else "d1"
    assert len([r for r in st.shard_copies("docs", 0)
                if r.node_id == survivor]) <= 1
    cluster.restart(victim)
    assert wait_until(
        lambda: cluster.store.current().health()["status"] == "green")
    st = cluster.store.current()
    (back,) = [r for r in st.shard_copies("docs", 0)
               if r.node_id == victim]
    assert back.state == "STARTED"
    assert st.health()["delayed_unassigned_shards"] == 0


def test_live_delayed_allocation_expiry_allocates_exactly_once(
        tmp_path, monkeypatch):
    from elasticsearch_tpu.testing.chaos import CrashRestartCluster
    monkeypatch.setenv("ES_TPU_DELAYED_ALLOC_MS", "150")
    cluster = CrashRestartCluster(
        ["m0", "d0", "d1", "d2"], str(tmp_path), roles={"m0": ("master",)})
    m = cluster.node("m0")
    m.create_index("docs", index_body(1, 1))
    cluster.node("d0").bulk("docs", bulk_ops(0, 10))
    replica = next(r for r in cluster.store.current().shard_copies("docs", 0)
                   if not r.primary)
    victim = replica.node_id
    cluster.crash(victim, report=True)
    assert cluster.store.current().health()["delayed_unassigned_shards"] == 1
    # the master's timer fires after the window and reroutes: the
    # replacement builds on a remaining node, exactly once
    assert wait_until(
        lambda: cluster.store.current().health()["status"] == "green",
        timeout=8.0)
    st = cluster.store.current()
    cps = st.shard_copies("docs", 0)
    assert len(cps) == 2
    assert {r.state for r in cps} == {"STARTED"}
    assert victim not in {r.node_id for r in cps}


# ---------------------------------------------------------------------------
# chaos lane: the rolling-restart scenario
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_rolling_restart_drain_crash_rejoin_rebalance(tmp_path, monkeypatch):
    """The maintenance window end-to-end: drain d0 -> every copy moves off
    -> crash d0 (as a reboot would) -> restart + rejoin -> clear the
    filter -> rebalance repopulates it. No acked write is lost (checked
    via the linearizability harness), admitted searches agree bit-for-bit
    before and after, and the cluster ends green with zero relocating
    shards."""
    from elasticsearch_tpu.testing.chaos import (
        AckedWriteHistory, CrashRestartCluster,
    )
    monkeypatch.setenv("ES_TPU_DELAYED_ALLOC_MS", "0")
    cluster = CrashRestartCluster(
        ["m0", "d0", "d1", "d2"], str(tmp_path), roles={"m0": ("master",)})
    m = cluster.node("m0")
    m.create_index("docs", index_body(2, 1))
    history = AckedWriteHistory()

    def write(doc_id, n, via="d1"):
        # the register value is the scalar n (the checker's state must be
        # hashable); the documents carry the full source
        op = history.invoke(doc_id, "write", n)
        try:
            r = cluster.node(via).bulk(
                "docs", [{"op": "index", "id": doc_id,
                          "source": {"n": n,
                                     "body": f"word{n % 7} common text"}}],
                retries=3)
            if not r["errors"]:
                history.respond(doc_id, op)
        except Exception:  # noqa: BLE001 — unacked: either outcome legal
            pass

    for i in range(30):
        write(str(i), i)
    cluster.node("d1").refresh("docs")
    before = cluster.node("d1").search(
        "docs", {"query": {"match": {"body": "common"}},
                 "size": 10, "track_total_hits": True,
                 "sort": [{"n": "asc"}]})

    # 1. drain: exclude d0, wait for zero copies + no relocations
    cluster.master().update_cluster_settings({EXCLUDE_NAME_SETTING: "d0"})
    assert wait_until(
        lambda: not cluster.store.current().entries_on_node("d0"))
    assert wait_until(
        lambda: cluster.store.current().health()["relocating_shards"] == 0)
    assert cluster.store.current().health()["status"] == "green"
    for i in range(30, 45):
        write(str(i), i)

    # 2. the maintenance reboot: crash, then restart from the same path
    cluster.crash("d0", report=True)
    assert cluster.store.current().health()["status"] == "green"
    for i in range(45, 60):
        write(str(i), i)
    cluster.restart("d0")

    # 3. clear the filter: the rebalancer repopulates the rejoined node
    cluster.master().update_cluster_settings({EXCLUDE_NAME_SETTING: None})
    assert wait_until(
        lambda: bool(cluster.store.current().entries_on_node("d0")))
    assert wait_until(
        lambda: cluster.store.current().health()["relocating_shards"] == 0)
    h = cluster.store.current().health()
    assert h["status"] == "green"
    assert h["relocating_shards"] == 0

    # durability: every acked write is readable through the final primaries
    for i in range(60):
        source = cluster.read_doc("docs", str(i))
        history.record_read(str(i), None if source is None else source["n"])
    assert history.check() == []
    # admitted searches agree bit-for-bit with the pre-maintenance answer
    cluster.node("d1").refresh("docs")
    after = cluster.node("d1").search(
        "docs", {"query": {"match": {"body": "common"}},
                 "size": 10, "track_total_hits": True,
                 "sort": [{"n": "asc"}]})
    assert [(x["_id"], x["sort"]) for x in after["hits"]["hits"]] \
        == [(x["_id"], x["sort"]) for x in before["hits"]["hits"]]
    assert reloc_counters.relocation_stats()["moves"] >= 3

"""Percolator: store queries, match documents against them (VERDICT r4
item 6; ref: modules/percolator/ candidate-prefilter + memory-index
replay)."""

import pytest

from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.index_service import IndexService


@pytest.fixture(scope="module")
def svc():
    meta = IndexMetadata(
        index="perc", uuid="u_pc", settings=Settings({}),
        mappings={"properties": {
            "query": {"type": "percolator"},
            "body": {"type": "text"},
            "tag": {"type": "keyword"},
            "n": {"type": "integer"},
        }})
    svc = IndexService(meta)
    stored = [
        ("q_match", {"match": {"body": "quick fox"}}),
        ("q_term", {"term": {"tag": "urgent"}}),
        ("q_bool", {"bool": {"must": [{"match": {"body": "brown"}}],
                             "filter": [{"term": {"tag": "news"}}]}}),
        ("q_range", {"range": {"n": {"gte": 100}}}),      # no terms: ALWAYS
        ("q_phrase", {"match_phrase": {"body": "lazy dog"}}),
        ("q_none", {"match_none": {}}),
    ]
    for qid, body in stored:
        svc.index_doc(qid, {"query": body})
    svc.refresh()
    yield svc
    svc.close()


def _ids(r):
    return sorted(h["_id"] for h in r["hits"]["hits"])


def test_percolate_match_and_term(svc):
    r = svc.search({"query": {"percolate": {
        "field": "query",
        "document": {"body": "the quick brown fox", "tag": "news",
                     "n": 5}}}})
    assert _ids(r) == ["q_bool", "q_match"]


def test_percolate_range_always_verified(svc):
    r = svc.search({"query": {"percolate": {
        "field": "query", "document": {"n": 150}}}})
    assert _ids(r) == ["q_range"]
    r2 = svc.search({"query": {"percolate": {
        "field": "query", "document": {"n": 50}}}})
    assert _ids(r2) == []


def test_percolate_phrase_needs_order(svc):
    hit = svc.search({"query": {"percolate": {
        "field": "query", "document": {"body": "such a lazy dog here"}}}})
    assert _ids(hit) == ["q_phrase"]
    miss = svc.search({"query": {"percolate": {
        "field": "query", "document": {"body": "dog lazy"}}}})
    assert _ids(miss) == []


def test_percolate_multiple_documents_any_match(svc):
    r = svc.search({"query": {"percolate": {
        "field": "query",
        "documents": [{"body": "nothing relevant"},
                      {"tag": "urgent"}]}}})
    assert _ids(r) == ["q_term"]


def test_percolate_in_bool_filter(svc):
    r = svc.search({"query": {"bool": {
        "must": [{"percolate": {"field": "query",
                                "document": {"tag": "urgent"}}}],
        "filter": [{"ids": {"values": ["q_term", "q_match"]}}]}}})
    assert _ids(r) == ["q_term"]


def test_percolate_respects_deletes(svc):
    meta = IndexMetadata(
        index="perc2", uuid="u_pc2", settings=Settings({}),
        mappings={"properties": {"query": {"type": "percolator"},
                                 "body": {"type": "text"}}})
    s2 = IndexService(meta)
    s2.index_doc("a", {"query": {"match": {"body": "apple"}}})
    s2.index_doc("b", {"query": {"match": {"body": "apple banana"}}})
    s2.refresh()
    s2.delete_doc("a")
    s2.refresh()
    r = s2.search({"query": {"percolate": {
        "field": "query", "document": {"body": "apple"}}}})
    assert _ids(r) == ["b"]
    s2.close()


def test_invalid_stored_query_rejected_at_index_time(svc):
    from elasticsearch_tpu.common.errors import ElasticsearchTpuError

    with pytest.raises(ElasticsearchTpuError):
        svc.index_doc("bad", {"query": {"no_such_query": {}}})

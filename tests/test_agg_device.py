"""Differential suite for the device analytics tier (PR 18).

The host aggregators are the exact reference: every device-served
response must match the host path BIT-FOR-BIT — including rendered
float metrics, under injected `agg_reduce` faults (containment → host
fallback), with `ES_TPU_AGG=0` (verbatim host path, zero device
counters), and after an `hbm_region` scrub repair of a flipped agg
column. Device routing is forced by shrinking AGG_DEVICE_MIN_DOCS, the
same seam the old terms-count kernel test used.
"""

import numpy as np
import pytest

import elasticsearch_tpu.search.aggregations as agg_mod
from elasticsearch_tpu.cluster.state import IndexMetadata
from elasticsearch_tpu.common import integrity, metrics
from elasticsearch_tpu.common.faults import clear as clear_faults, inject
from elasticsearch_tpu.common.settings import Settings, knob
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.search import agg_device

BASE_MS = 1_600_000_000_000        # 2020-09-13T12:26:40Z


@pytest.fixture(autouse=True)
def _clean_faults():
    clear_faults()
    yield
    clear_faults()


def _make_service(n=2500, seed=7):
    meta = IndexMetadata(
        index="agg", uuid="u", settings=Settings({}), mappings={
            "properties": {"tag": {"type": "keyword"},
                           "body": {"type": "text"},
                           "price": {"type": "float"},
                           "ts": {"type": "long"}}})
    svc = IndexService(meta)
    rng = np.random.default_rng(seed)
    for i in range(n):
        tags = [f"t{rng.integers(0, 40)}"]
        if i % 3 == 0:
            tags.append(f"t{rng.integers(0, 40)}")   # multi-valued docs
        doc = {"tag": tags, "body": "w" + str(i % 7),
               "ts": BASE_MS + int(rng.integers(0, 90 * 86_400_000))}
        if i % 5 != 0:                               # price gaps: exists
            doc["price"] = float(np.round(rng.normal(40, 12), 2))
        svc.index_doc(str(i), doc)
    svc.refresh()
    return svc


def _ab(svc, body, monkeypatch):
    """(device response, host response) for one search body."""
    monkeypatch.setattr(agg_mod, "AGG_DEVICE_MIN_DOCS", 1)
    dev = svc._search_dense(body)["aggregations"]
    monkeypatch.setattr(agg_mod, "AGG_DEVICE_MIN_DOCS", 1 << 60)
    host = svc._search_dense(body)["aggregations"]
    return dev, host


def _counts():
    with agg_device._COUNTS_LOCK:
        return dict(agg_device._COUNTS)


# ---------------------------------------------------------------------------
# bit-identity across agg shapes
# ---------------------------------------------------------------------------


def test_terms_device_matches_host(monkeypatch):
    svc = _make_service()
    before = _counts()
    body = {"query": {"match": {"body": "w3"}}, "size": 0,
            "aggs": {"tags": {"terms": {"field": "tag", "size": 50}}}}
    dev, host = _ab(svc, body, monkeypatch)
    assert dev == host
    assert sum(b["doc_count"] for b in dev["tags"]["buckets"]) > 0
    after = _counts()
    assert after["agg_queries"] == before["agg_queries"] + 1
    assert after["agg_device_dispatches"] > before["agg_device_dispatches"]
    svc.close()


def test_date_histogram_offset_format_and_calendar(monkeypatch):
    svc = _make_service()
    for body in [
        {"size": 0, "aggs": {"d": {"date_histogram": {
            "field": "ts", "fixed_interval": "7d",
            "offset": 10_800_000}}}},                 # +3h offset
        {"size": 0, "aggs": {"d": {"date_histogram": {
            "field": "ts", "calendar_interval": "month"}}}},
        {"size": 0, "aggs": {"d": {"date_histogram": {
            "field": "ts", "fixed_interval": "12h"}}}},
    ]:
        dev, host = _ab(svc, body, monkeypatch)
        assert dev == host                  # includes key_as_string render
        assert len(dev["d"]["buckets"]) > 1
    svc.close()


def test_stats_under_terms_subagg_bit_identical(monkeypatch):
    svc = _make_service()
    body = {"query": {"match": {"body": "w1"}}, "size": 0,
            "aggs": {"tags": {
                "terms": {"field": "tag", "size": 50},
                "aggs": {"p": {"stats": {"field": "price"}},
                         "a": {"avg": {"field": "price"}},
                         "lo": {"min": {"field": "price"}},
                         "nv": {"value_count": {"field": "price"}}}}}}
    dev, host = _ab(svc, body, monkeypatch)
    assert dev == host        # float sums reduced in host order: bitwise
    svc.close()


def test_histogram_and_date_histogram_subaggs(monkeypatch):
    svc = _make_service()
    for body in [
        {"size": 0, "aggs": {"h": {
            "histogram": {"field": "price", "interval": 7.5},
            "aggs": {"s": {"stats": {"field": "price"}}}}}},
        {"size": 0, "aggs": {"d": {
            "date_histogram": {"field": "ts", "calendar_interval": "month"},
            "aggs": {"s": {"extended_stats": {"field": "price"}}}}}},
    ]:
        dev, host = _ab(svc, body, monkeypatch)
        assert dev == host
    svc.close()


def test_empty_mask_matches_host(monkeypatch):
    svc = _make_service(n=1200)
    body = {"query": {"match": {"body": "nosuchtoken"}}, "size": 0,
            "aggs": {"tags": {"terms": {"field": "tag"}},
                     "h": {"histogram": {"field": "price", "interval": 5}}}}
    dev, host = _ab(svc, body, monkeypatch)
    assert dev == host
    assert dev["tags"]["buckets"] == []
    svc.close()


# ---------------------------------------------------------------------------
# fallback + A/B + faults
# ---------------------------------------------------------------------------


def test_over_budget_layouts_fall_back_to_host(monkeypatch):
    """ES_TPU_AGG_HBM_FRAC=0 refuses every layout: the collect is served
    by the host aggregators (identical response), counted as fallback."""
    monkeypatch.setenv("ES_TPU_AGG_HBM_FRAC", "0.0")
    svc = _make_service(n=1200, seed=11)
    before = _counts()
    body = {"size": 0, "aggs": {"tags": {"terms": {"field": "tag"}}}}
    dev, host = _ab(svc, body, monkeypatch)
    assert dev == host
    after = _counts()
    assert after["agg_host_fallbacks"] > before["agg_host_fallbacks"]
    assert after["agg_device_dispatches"] == before["agg_device_dispatches"]
    assert after["agg_bytes"] == before["agg_bytes"]
    svc.close()


def test_agg_flag_off_restores_host_path_verbatim(monkeypatch):
    svc = _make_service(n=1500, seed=3)
    body = {"size": 0, "aggs": {
        "tags": {"terms": {"field": "tag", "size": 50},
                 "aggs": {"s": {"stats": {"field": "price"}}}}}}
    monkeypatch.setattr(agg_mod, "AGG_DEVICE_MIN_DOCS", 1)
    on = svc._search_dense(body)["aggregations"]

    monkeypatch.setenv("ES_TPU_AGG", "0")
    assert not knob("ES_TPU_AGG")
    before = _counts()
    off = svc._search_dense(body)["aggregations"]
    after = _counts()
    assert off == on
    # knob off = the host path verbatim: no device counters move at all
    assert after == before

    monkeypatch.delenv("ES_TPU_AGG")
    before = _counts()
    on2 = svc._search_dense(body)["aggregations"]
    assert on2 == on
    assert _counts()["agg_queries"] == before["agg_queries"] + 1
    svc.close()


def test_agg_reduce_fault_contained_with_host_fallback(monkeypatch):
    """An injected agg_reduce fault poisons only that dispatch: the
    collect falls back to the host aggregator and the response stays
    bit-identical; the next dispatch runs on device again."""
    svc = _make_service(n=1500, seed=5)
    body = {"size": 0, "aggs": {"tags": {"terms": {"field": "tag"}}}}
    monkeypatch.setattr(agg_mod, "AGG_DEVICE_MIN_DOCS", 1)
    want = svc._search_dense(body)["aggregations"]       # builds the layout

    eng = agg_device.default_engine()
    serials = [s for n, s in eng.layout_serials().items()
               if n.endswith("_terms")]
    assert serials
    before = _counts()
    with inject(f"agg_reduce#{max(serials)}:raise@1"):
        got = svc._search_dense(body)["aggregations"]
    assert got == want
    after = _counts()
    assert after["agg_host_fallbacks"] == before["agg_host_fallbacks"] + 1

    # containment: the fault did not poison the engine or the layout
    before = _counts()
    again = svc._search_dense(body)["aggregations"]
    assert again == want
    assert _counts()["agg_queries"] == before["agg_queries"] + 1
    svc.close()


def test_hbm_scrub_repairs_flipped_agg_column(monkeypatch):
    """A bitflipped device agg column is detected by the PR-15 scrubber,
    repaired from the host copy, and the repaired column serves
    bit-identical results."""
    integrity.reset_scrub_for_tests()
    svc = _make_service(n=1500, seed=13)
    body = {"size": 0, "aggs": {"tags": {"terms": {"field": "tag"}}}}
    monkeypatch.setattr(agg_mod, "AGG_DEVICE_MIN_DOCS", 1)
    want = svc._search_dense(body)["aggregations"]

    eng = agg_device.default_engine()
    # newest terms layout = the one this service just built (older tests'
    # layouts may still be alive but were dropped from the scrub registry
    # by the reset above)
    region = max((n for n in eng.layout_serials() if n.endswith("_terms")),
                 key=lambda n: eng.layout_serials()[n])
    base = integrity.integrity_stats()["scrub_repairs"]
    with inject(f"hbm_region#{region}:raise@1x1"):
        results = [integrity.scrub_once()
                   for _ in range(integrity.scrub_registry_size())]
    hit = [r for r in results if r and r["result"] == "mismatch"]
    assert len(hit) == 1 and hit[0]["region"].endswith(region)
    assert integrity.integrity_stats()["scrub_repairs"] == base + 1

    got = svc._search_dense(body)["aggregations"]
    assert got == want
    svc.close()


# ---------------------------------------------------------------------------
# scheduler tiering + accounting
# ---------------------------------------------------------------------------


def test_agg_collects_ride_bulk_tier(monkeypatch):
    """Agg dispatches are bulk-tier scheduler work: the bulk counter
    moves, the interactive counter does not."""
    from elasticsearch_tpu.threadpool.scheduler import scheduler_stats

    svc = _make_service(n=1200, seed=17)
    body = {"size": 0, "aggs": {"tags": {"terms": {"field": "tag"}}}}
    monkeypatch.setattr(agg_mod, "AGG_DEVICE_MIN_DOCS", 1)

    def tiers():
        t = scheduler_stats().get("tiers", {})
        return (t.get("bulk", {}).get("dispatches", 0),
                t.get("interactive", {}).get("dispatches", 0))

    svc._search_dense(body)                  # warm: layout build + trace
    b0, i0 = tiers()
    svc._search_dense(body)
    b1, i1 = tiers()
    assert b1 > b0
    assert i1 == i0
    svc.close()


def test_ledger_reconciles_and_knobs_declared(monkeypatch):
    """tpu_hbm's agg engine bytes == the engine's own accounting == the
    tpu_agg stats section; knobs come from the typed registry."""
    assert knob("ES_TPU_AGG") is True
    assert knob("ES_TPU_AGG_HBM_FRAC") == 0.25

    svc = _make_service(n=1200, seed=19)
    monkeypatch.setattr(agg_mod, "AGG_DEVICE_MIN_DOCS", 1)
    svc._search_dense(
        {"size": 0, "aggs": {"tags": {"terms": {"field": "tag"}},
                             "h": {"histogram": {"field": "price",
                                                 "interval": 4}}}})
    eng = agg_device.default_engine()
    assert eng.hbm_bytes() > 0
    assert eng.hbm_bytes() == eng.ledger_bytes()
    assert agg_device.agg_stats()["hbm_bytes"] == eng.hbm_bytes()

    # counters are declared (TPU005): Prometheus sees them even at zero
    vals = metrics.counter_values()
    for name in ("agg_queries", "agg_device_dispatches",
                 "agg_host_fallbacks", "agg_bytes"):
        assert name in vals

    from elasticsearch_tpu.rest.handlers import _tpu_agg_stats
    section = _tpu_agg_stats()
    for key in ("agg_queries", "agg_device_dispatches",
                "agg_host_fallbacks", "agg_bytes", "hbm_bytes",
                "enabled", "layouts"):
        assert key in section
    svc.close()

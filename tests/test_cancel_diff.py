"""Cancellation differential suite (PR 11).

The task plane's boundary-only cancellation contract, verified
differentially against never-cancelled references:

- a cancelled task parked inside a scheduler lane raises
  TaskCancelledError at the flush boundary — it never fails the batch;
- the co-batched peers of a cancelled waiter (scheduler AND legacy
  coalescer) produce rows BIT-identical to solo execution;
- re-running the cancelled query under a fresh task matches the
  never-cancelled reference exactly;
- a mixed round — injected ES_TPU_FAULTS device faults + a mid-park
  cancel — stays green: the fault is contained (PR 5), the cancel kills
  exactly one waiter, everyone else is bit-identical.
"""

import threading
import time

import numpy as np
import pytest

from elasticsearch_tpu.common import faults
from elasticsearch_tpu.tasks import TaskCancelledError, TaskManager
from elasticsearch_tpu.tasks import task_manager as _taskmgr
from elasticsearch_tpu.threadpool.coalescer import DispatchCoalescer
from elasticsearch_tpu.threadpool.scheduler import AdaptiveDispatchScheduler

pytestmark = [pytest.mark.multidevice]

WORDS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta",
         "theta", "iota", "kappa", "lam", "mu", "nu", "xi", "omicron", "pi"]

QUERIES = [["alpha"], ["beta", "gamma"], ["delta"], ["pi", "omicron"]]


@pytest.fixture(scope="module")
def svc():
    import os

    from elasticsearch_tpu.cluster.state import IndexMetadata
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index.index_service import IndexService

    os.environ["ES_TPU_FORCE_TURBO"] = "1"
    os.environ["ES_TPU_TURBO_COLD_DF"] = "8"
    try:
        meta = IndexMetadata(
            index="cdiff", uuid="u_cdiff", settings=Settings({}),
            mappings={"properties": {"body": {"type": "text"}}})
        svc = IndexService(meta)
        rng = np.random.default_rng(17)
        for i in range(280):
            words = rng.choice(WORDS, size=int(rng.integers(3, 14)))
            svc.index_doc(str(i), {"body": " ".join(words)})
            if i == 130:
                svc.refresh()
        svc.refresh()
        yield svc
        svc.close()
    finally:
        os.environ.pop("ES_TPU_FORCE_TURBO", None)
        os.environ.pop("ES_TPU_TURBO_COLD_DF", None)


@pytest.fixture(scope="module")
def eng(svc):
    return svc.serving.snapshot().engine("body")


@pytest.fixture(scope="module")
def solo(eng):
    return [eng.search_many([[q]], k=10)[0] for q in QUERIES]


def _rows_equal(got, want, label):
    gs, gp, go = got
    ws, wp, wo = want
    assert np.array_equal(np.asarray(gs), np.asarray(ws)), f"{label}: scores"
    assert np.array_equal(np.asarray(gp), np.asarray(wp)), f"{label}: parts"
    assert np.array_equal(np.asarray(go), np.asarray(wo)), f"{label}: ords"


def _run_round(dispatcher, eng, tm, cancel_idx=None, cancel_delay_s=0.05,
               k=10):
    """All QUERIES on their own threads under registered tasks, released
    together; optionally cancel one task after it parks. Returns
    (results, errors, tasks) aligned with QUERIES."""
    n = len(QUERIES)
    results, errors = [None] * n, [None] * n
    tasks = [tm.register("indices:data/read/search", f"q{i}")
             for i in range(n)]
    barrier = threading.Barrier(n + (1 if cancel_idx is not None else 0))

    def worker(i):
        try:
            with _taskmgr.activate(tasks[i]):
                barrier.wait(timeout=10)
                results[i] = dispatcher.dispatch(eng, [QUERIES[i]], k)
        except BaseException as e:  # noqa: BLE001 — asserted by callers
            errors[i] = e
        finally:
            tm.unregister(tasks[i])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    if cancel_idx is not None:
        barrier.wait(timeout=10)
        time.sleep(cancel_delay_s)      # let the waiters park in the lane
        tm.cancel(tasks[cancel_idx].id, "differential test")
    for t in threads:
        t.join(timeout=60)
    return results, errors, tasks


def _window_sched():
    # a wide flush budget AND a bucket the round can't fill, so every
    # waiter genuinely parks long enough for the canceller to fire
    return AdaptiveDispatchScheduler(buckets=(8,), interactive_us=250000.0,
                                     bulk_us=250000.0)


def test_precancelled_task_refused_at_dispatch_entry(eng, monkeypatch):
    monkeypatch.setenv("ES_TPU_COALESCE_US", "250000")
    tm = TaskManager("n")
    t = tm.register("indices:data/read/search", "pre")
    t.cancel("before dispatch")
    sched = _window_sched()
    with _taskmgr.activate(t):
        with pytest.raises(TaskCancelledError):
            sched.dispatch(eng, [QUERIES[0]], 10)
    assert sched.stats()["sched_dispatches"] == 0
    assert sched.stats()["direct_dispatches"] == 0


def test_cancel_parked_scheduler_waiter_spares_peers(eng, solo, monkeypatch):
    monkeypatch.setenv("ES_TPU_COALESCE_US", "250000")
    tm = TaskManager("n")
    results, errors, _ = _run_round(_window_sched(), eng, tm, cancel_idx=2)
    assert isinstance(errors[2], TaskCancelledError)
    assert results[2] is None
    for i in (0, 1, 3):
        assert errors[i] is None, f"peer {i} must survive the cancel"
        _rows_equal(results[i], solo[i], f"peer {i}")
    st = tm.stats()
    # `completed` counts every unregister; `cancelled` is the subset
    assert st["cancelled"] == 1 and st["completed"] == 4
    assert st["current"] == {}


def test_cancel_in_flight_coalesced_batch_member(eng, solo, monkeypatch):
    monkeypatch.setenv("ES_TPU_COALESCE_US", "250000")
    tm = TaskManager("n")
    co = DispatchCoalescer(window_us=250000.0)
    results, errors, _ = _run_round(co, eng, tm, cancel_idx=1)
    assert isinstance(errors[1], TaskCancelledError)
    for i in (0, 2, 3):
        assert errors[i] is None
        _rows_equal(results[i], solo[i], f"coalesced peer {i}")


def test_rerun_after_cancel_matches_never_cancelled_reference(
        eng, solo, monkeypatch):
    monkeypatch.setenv("ES_TPU_COALESCE_US", "250000")
    tm = TaskManager("n")
    sched = _window_sched()
    _, errors, _ = _run_round(sched, eng, tm, cancel_idx=0)
    assert isinstance(errors[0], TaskCancelledError)
    # identical re-run under a fresh task: bit-identical to the quiet
    # reference — a cancel must leave no residue in the lane state
    t = tm.register("indices:data/read/search", "rerun")
    with _taskmgr.activate(t):
        got = sched.dispatch(eng, [QUERIES[0]], 10)
    tm.unregister(t)
    _rows_equal(got, solo[0], "rerun")


@pytest.mark.faults
def test_mixed_cancel_and_device_fault_round_green(eng, solo, monkeypatch):
    """One injected fused-dispatch fault (contained by PR 5 host
    re-score) AND one mid-park cancel in the same round: the cancelled
    waiter dies alone, every survivor is bit-identical."""
    monkeypatch.setenv("ES_TPU_COALESCE_US", "250000")
    tm = TaskManager("n")
    with faults.inject("fused_dispatch:raise@1;turbo_sweep:raisexinf"):
        results, errors, _ = _run_round(_window_sched(), eng, tm,
                                        cancel_idx=3)
    assert isinstance(errors[3], TaskCancelledError)
    for i in (0, 1, 2):
        assert errors[i] is None, f"fault leaked to waiter {i}: {errors[i]}"
        _rows_equal(results[i], solo[i], f"chaos survivor {i}")


def test_unrelated_cancel_leaves_search_bit_identical(svc):
    """End-to-end no-cancel purity: a search running while an UNRELATED
    task is cancelled returns exactly what a quiet run returns."""
    body = {"query": {"match": {"body": "alpha"}}, "size": 10,
            "track_total_hits": True}
    quiet = svc.search(body)
    tm = TaskManager("n")
    victim = tm.register("indices:data/read/search", "unrelated")
    tm.cancel(victim.id, "noise")
    noisy = svc.search(body)
    assert noisy["hits"] == quiet["hits"]
    assert noisy["_shards"] == quiet["_shards"]

"""Primary/replica replication groups: seqno acks, recovery, failover."""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import InternalEngine
from elasticsearch_tpu.index.replication import (
    ReplicationGroup, ShardCopy, new_allocation_id,
)
from elasticsearch_tpu.mapper import MapperService

MAPPING = {"properties": {"n": {"type": "integer"}, "body": {"type": "text"}}}


def copy(node="n0"):
    return ShardCopy(allocation_id=new_allocation_id(), node_id=node,
                     engine=InternalEngine(MapperService(dict(MAPPING))))


def doc_ids(engine):
    engine.refresh()
    s = engine.acquire_searcher()
    out = set()
    for v in s.views:
        for i, alive in enumerate(v.live):
            if alive:
                out.add(v.segment.doc_ids[i])
    return out


def test_writes_replicate_and_checkpoint_advances():
    group = ReplicationGroup(copy())
    r1 = copy("n1")
    group.add_replica(r1)
    for i in range(10):
        group.index(str(i), {"n": i, "body": f"doc {i}"})
    group.delete("3")
    assert doc_ids(group.primary.engine) == doc_ids(r1.engine)
    assert "3" not in doc_ids(r1.engine)
    assert group.global_checkpoint == 10  # seqnos 0..10 all acked everywhere
    assert r1.engine.local_checkpoint == group.primary.engine.local_checkpoint


def test_recovery_of_populated_primary():
    group = ReplicationGroup(copy())
    for i in range(20):
        group.index(str(i), {"n": i})
    group.delete("5")
    group.primary.engine.refresh()
    r1 = copy("n1")
    group.add_replica(r1)
    assert doc_ids(r1.engine) == doc_ids(group.primary.engine)
    assert r1.allocation_id in group.tracker.in_sync_ids
    # post-recovery writes keep flowing
    group.index("new", {"n": 99})
    assert "new" in doc_ids(r1.engine)


def test_stale_op_cannot_resurrect_deleted_doc():
    group = ReplicationGroup(copy())
    r1 = copy("n1")
    group.add_replica(r1)
    group.index("x", {"n": 1})
    group.delete("x")
    # replay the stale index op directly at the replica (out-of-order arrival)
    r1.engine.index("x", {"n": 1}, seq_no=0)
    assert "x" not in doc_ids(r1.engine)


def test_failed_replica_is_dropped_and_reported():
    failures = []
    group = ReplicationGroup(copy(), on_replica_failure=lambda aid, e: failures.append(aid))
    r1 = copy("n1")
    group.add_replica(r1)

    def boom(*a, **k):
        raise RuntimeError("disk died")

    r1.engine.index = boom
    group.index("a", {"n": 1})
    assert failures == [r1.allocation_id]
    assert r1.allocation_id not in group.tracker.in_sync_ids
    # subsequent writes succeed without the dead copy
    group.index("b", {"n": 2})
    assert "b" in doc_ids(group.primary.engine)


def test_promote_replica_resyncs_survivors():
    group = ReplicationGroup(copy())
    r1, r2 = copy("n1"), copy("n2")
    group.add_replica(r1)
    group.add_replica(r2)
    for i in range(8):
        group.index(str(i), {"n": i})
    old_term = group.primary.engine.primary_term
    # primary dies; promote r1
    new_group = group.promote(r1.allocation_id)
    assert new_group.primary is r1
    assert r1.engine.primary_term == old_term + 1
    assert r2.allocation_id in new_group.tracker.in_sync_ids
    new_group.index("after", {"n": 100})
    assert "after" in doc_ids(r1.engine)
    assert "after" in doc_ids(r2.engine)
    assert doc_ids(r1.engine) == doc_ids(r2.engine)


def test_promotion_divergent_replica_converges_on_new_primary():
    """Ops above the global checkpoint that only reached some copies must
    converge on the NEW primary's history after promotion."""
    group = ReplicationGroup(copy())
    r1, r2 = copy("n1"), copy("n2")
    group.add_replica(r1)
    group.add_replica(r2)
    group.index("a", {"n": 1})
    # a write that reaches r1 but not r2 (r2 temporarily fails, gets dropped)
    orig = r2.engine.index
    r2.engine.index = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("net"))
    group.index("b", {"n": 2})
    r2.engine.index = orig
    # promote r1 (which has 'b'); r2 must catch up to include it
    new_group = group.promote(r1.allocation_id)
    new_group.replicas[r2.allocation_id] = r2
    new_group.tracker.add_tracking(r2.allocation_id)
    ops = r1.engine.changes_since(r2.engine.local_checkpoint)
    for op in ops:
        new_group._apply_to_copy(r2, {"op": op["op"], "id": op["id"],
                                      "source": op.get("source"),
                                      "seq_no": op["seq_no"]})
    assert doc_ids(r2.engine) == doc_ids(r1.engine)


def test_concurrent_writes_during_recovery(monkeypatch):
    """Writes racing phase1 of recovery must not be lost: the copy is tracked
    before the snapshot streams, and stale-op checks dedupe the overlap."""
    group = ReplicationGroup(copy())
    for i in range(10):
        group.index(str(i), {"n": i})
    r1 = copy("n1")

    # interleave: after phase1 computes its snapshot, more writes land
    real_changes = group.primary.engine.changes_since
    state = {"injected": False}

    def racing_changes(min_seq):
        ops = real_changes(min_seq)
        if not state["injected"]:
            state["injected"] = True
            group.replicas[r1.allocation_id] = r1     # already tracked by add_replica
            group.index("racer", {"n": 777})          # concurrent write
        return ops

    monkeypatch.setattr(group.primary.engine, "changes_since", racing_changes)
    group.add_replica(r1)
    assert "racer" in doc_ids(r1.engine)
    assert doc_ids(r1.engine) == doc_ids(group.primary.engine)
    assert r1.allocation_id in group.tracker.in_sync_ids


def test_primary_term_fencing_blocks_deposed_primary():
    """A deposed primary's writes must be rejected by replicas that have
    adopted the new primary term (split-brain fencing)."""
    group = ReplicationGroup(copy())
    r1, r2 = copy("n1"), copy("n2")
    group.add_replica(r1)
    group.add_replica(r2)
    group.index("a", {"n": 1})
    new_group = group.promote(r1.allocation_id)
    # old group still references r2; its term-1 writes must bounce
    group.index("zombie", {"n": -1})
    assert "zombie" not in doc_ids(r2.engine)
    assert r2.allocation_id not in group.tracker.in_sync_ids  # dropped as failed
    # and the promoted group keeps working
    new_group.index("ok", {"n": 2})
    assert "ok" in doc_ids(r2.engine)


def test_resync_divergence_rollback_and_crash_durability(tmp_path):
    """A replica's divergent tail (replicated beyond the global checkpoint by
    a lost primary) is rolled back to the new primary's history on promote —
    and the rollback survives a crash-restart: the trim marker drops the
    divergent translog records and the re-logged resync state replays."""

    def durable_copy(node, path):
        return ShardCopy(allocation_id=new_allocation_id(), node_id=node,
                         engine=InternalEngine(MapperService(dict(MAPPING)),
                                               data_path=str(path)))

    primary = durable_copy("n0", tmp_path / "p")
    r1 = durable_copy("n1", tmp_path / "r1")
    r2 = durable_copy("n2", tmp_path / "r2")
    group = ReplicationGroup(primary)
    group.add_replica(r1)
    group.add_replica(r2)
    group.index("a", {"n": 1})
    gcp = group.global_checkpoint

    # the old primary replicates a write only to r2 (r1 missed it), then dies
    op = primary.engine.index("diverged", {"n": 2})
    r2.engine.index("diverged", {"n": 2}, seq_no=op.seq_no,
                    op_primary_term=op.primary_term)
    assert gcp < op.seq_no

    # drop the old primary; promote r1 (which never saw "diverged")
    group.replicas.pop(primary.allocation_id, None)
    new_group = group.promote(r1.allocation_id)
    assert "diverged" not in doc_ids(r2.engine)
    assert doc_ids(r2.engine) == doc_ids(r1.engine) == {"a"}

    # crash r2 and recover from disk: divergence must not resurrect
    r2.engine.close()
    recovered = InternalEngine(MapperService(dict(MAPPING)),
                               data_path=str(tmp_path / "r2"))
    assert doc_ids(recovered) == {"a"}
    assert recovered.get("diverged") is None
    # the surviving acked write is still durable
    assert recovered.get("a")["_source"] == {"n": 1}


def test_resync_rollback_of_flushed_divergence_survives_crash(tmp_path):
    """ADVICE r2 (medium): when the divergent op was already FLUSHED into a
    committed segment, rollback must not depend on the translog trim — the
    commit's live mask would resurrect the doc on crash recovery. Promote
    re-commits the rolled-back state, so restart converges."""

    def durable_copy(node, path):
        return ShardCopy(allocation_id=new_allocation_id(), node_id=node,
                         engine=InternalEngine(MapperService(dict(MAPPING)),
                                               data_path=str(path)))

    primary = durable_copy("n0", tmp_path / "p")
    r1 = durable_copy("n1", tmp_path / "r1")
    r2 = durable_copy("n2", tmp_path / "r2")
    group = ReplicationGroup(primary)
    group.add_replica(r1)
    group.add_replica(r2)
    group.index("a", {"n": 1})
    gcp = group.global_checkpoint

    # old primary replicates a write only to r2, which FLUSHES it into a
    # committed segment (live mask on disk now covers the divergent doc,
    # and its seqno is <= the committed local checkpoint)
    op = primary.engine.index("diverged", {"n": 2})
    r2.engine.index("diverged", {"n": 2}, seq_no=op.seq_no,
                    op_primary_term=op.primary_term)
    r2.engine.flush()
    assert gcp < op.seq_no

    group.replicas.pop(primary.allocation_id, None)
    new_group = group.promote(r1.allocation_id)
    assert doc_ids(r2.engine) == {"a"}

    # crash r2 and recover purely from disk: the divergent doc must stay dead
    r2.engine.close()
    recovered = InternalEngine(MapperService(dict(MAPPING)),
                               data_path=str(tmp_path / "r2"))
    assert doc_ids(recovered) == {"a"}
    assert recovered.get("diverged") is None
    assert recovered.get("a")["_source"] == {"n": 1}

    # post-promote writes on the recovered state still apply cleanly
    assert new_group.index("b", {"n": 3}).result == "created"
